"""fp16_utils / mlp / fused_dense tests (ref: ``tests/L0/run_fp16util``,
``tests/L0/run_mlp``, ``apex/fused_dense`` tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP
from apex_tpu.optimizers import FusedAdam, FusedSGD


def make_params():
    k = jax.random.PRNGKey(0)
    return {
        "dense": {"kernel": jax.random.normal(k, (16, 8)),
                  "bias": jnp.zeros((8,))},
        "layernorm": {"weight": jnp.ones((8,)), "bias": jnp.zeros((8,))},
        "step": jnp.int32(0),
    }


def test_network_to_half_keeps_norms_fp32():
    half = network_to_half(make_params())
    assert half["dense"]["kernel"].dtype == jnp.float16
    assert half["layernorm"]["weight"].dtype == jnp.float32
    assert half["step"].dtype == jnp.int32  # non-float untouched


def test_prep_and_roundtrip():
    model = network_to_half(make_params())
    model_p, master = prep_param_lists(model)
    assert master["dense"]["kernel"].dtype == jnp.float32
    back = master_params_to_model_params(model_p, master)
    assert back["dense"]["kernel"].dtype == jnp.float16
    g = model_grads_to_master_grads(
        jax.tree.map(lambda a: a.astype(jnp.float16)
                     if jnp.issubdtype(a.dtype, jnp.floating) else a,
                     make_params()))
    assert g["dense"]["kernel"].dtype == jnp.float32


def test_fp16_optimizer_matches_fp32_sgd():
    """Static scale 128: scaled-loss grads through FP16_Optimizer must
    track the plain fp32 SGD trajectory within fp16 tolerance."""
    model32 = {"w": jax.random.normal(jax.random.PRNGKey(1), (32, 4))}
    model16 = jax.tree.map(lambda a: a.astype(jnp.float16), model32)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 32))

    def loss_fn(p, dtype):
        return jnp.sum((x.astype(dtype) @ p["w"].astype(dtype))
                       .astype(jnp.float32) ** 2)

    opt = FP16_Optimizer(FusedSGD(lr=1e-3), static_loss_scale=128.0)
    st = opt.init(model16)
    ref = FusedSGD(lr=1e-3)
    ref_p, ref_st = model32, ref.init(model32)
    for _ in range(3):
        g = jax.grad(lambda p: opt.scale_loss(
            loss_fn(p, jnp.float16), st))(model16)
        assert g["w"].dtype == jnp.float16
        model16, st = opt.step(g, model16, st)
        ref_g = jax.grad(lambda p: loss_fn(p, jnp.float32))(ref_p)
        ref_p, ref_st = ref.step(ref_g, ref_p, ref_st)
    np.testing.assert_allclose(np.asarray(st.master["w"]),
                               np.asarray(ref_p["w"]), rtol=2e-2,
                               atol=2e-3)
    assert model16["w"].dtype == jnp.float16


def test_fp16_optimizer_dynamic_overflow_skips_and_halves():
    model16 = {"w": jnp.ones((4, 4), jnp.float16)}
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True)
    st = opt.init(model16)
    s0 = float(opt.loss_scale(st))
    bad = {"w": jnp.full((4, 4), jnp.inf, jnp.float16)}
    new_model, st = opt.step(bad, model16, st)
    assert float(opt.loss_scale(st)) == s0 / 2
    np.testing.assert_array_equal(np.asarray(new_model["w"]),
                                  np.asarray(model16["w"]))
    good = {"w": jnp.full((4, 4), 0.1, jnp.float16)}
    new_model, st = opt.step(good, model16, st)
    assert float(jnp.max(jnp.abs(new_model["w"] - model16["w"]))) > 0


def test_fp16_optimizer_state_dict_roundtrip():
    model16 = {"w": jnp.ones((4, 4), jnp.float16)}
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True)
    st = opt.init(model16)
    st2 = opt.load_state_dict(opt.state_dict(st))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), st, st2)


# -- mlp / fused_dense ------------------------------------------------------

@pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
def test_mlp_matches_manual_chain(activation):
    mlp = MLP([16, 32, 8], activation=activation)
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    want = x
    for p in params:
        want = want @ p["kernel"] + p["bias"]
        if activation == "relu":
            want = jax.nn.relu(want)
        elif activation == "sigmoid":
            want = jax.nn.sigmoid(want)
    np.testing.assert_allclose(np.asarray(mlp.apply(params, x)),
                               np.asarray(want), rtol=1e-6)


def test_mlp_remat_same_values_and_grads():
    mlp = MLP([16, 32, 8], remat=False)
    mlp_r = MLP([16, 32, 8], remat=True)
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    f = lambda m: jax.grad(  # noqa: E731
        lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6),
        f(mlp), f(mlp_r))


def test_mlp_validation():
    with pytest.raises(ValueError):
        MLP([16])
    with pytest.raises(ValueError):
        MLP([16, 8], activation="tanh")


def test_fused_dense():
    fd = FusedDense(16, 8)
    p = fd.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    np.testing.assert_allclose(
        np.asarray(fd.apply(p, x)),
        np.asarray(x @ p["kernel"] + p["bias"]), rtol=1e-6)


def test_fused_dense_gelu_dense():
    fdg = FusedDenseGeluDense(16, 32, 8)
    p = fdg.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    h = jax.nn.gelu(x @ p["fc1"]["kernel"] + p["fc1"]["bias"],
                    approximate=False)
    want = h @ p["fc2"]["kernel"] + p["fc2"]["bias"]
    np.testing.assert_allclose(np.asarray(fdg.apply(p, x)),
                               np.asarray(want), rtol=1e-6)


def test_autocast_flows_through_mlp_and_fused_dense():
    from apex_tpu.amp.autocast import autocast

    mlp = MLP([16, 8])
    fd = FusedDense(16, 8)
    pm, pf = mlp.init(jax.random.PRNGKey(0)), fd.init(jax.random.PRNGKey(1))
    x = jnp.ones((2, 16), jnp.float32)
    with autocast(jnp.bfloat16):
        assert mlp.apply(pm, x).dtype == jnp.bfloat16
        assert fd.apply(pf, x).dtype == jnp.bfloat16
    assert mlp.apply(pm, x).dtype == jnp.float32

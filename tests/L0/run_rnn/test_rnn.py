"""RNN cell/stack tests (ref: ``apex/RNN`` — the deprecated fp16 RNN
tier; golden comparisons against hand-rolled steps and torch-semantics
checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.RNN import (
    GRU, LSTM, RNN, gru_cell, init_gru_cell, init_lstm_cell,
    init_mlstm_cell, lstm_cell, mlstm_cell,
)

S, B, I, H = 6, 2, 5, 4


def test_lstm_cell_matches_manual():
    p = init_lstm_cell(jax.random.PRNGKey(0), I, H)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, I))
    h = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    c = jax.random.normal(jax.random.PRNGKey(3), (B, H))
    h2, c2 = lstm_cell(p, x, (h, c))

    g = x @ p["w_ih"] + h @ p["w_hh"] + p["b_ih"] + p["b_hh"]
    i_, f, g_, o = np.split(np.asarray(g), 4, axis=-1)
    sig = lambda z: 1 / (1 + np.exp(-z))  # noqa: E731
    c_want = sig(f) * np.asarray(c) + sig(i_) * np.tanh(g_)
    h_want = sig(o) * np.tanh(c_want)
    np.testing.assert_allclose(np.asarray(h2), h_want, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(c2), c_want, rtol=1e-5,
                               atol=1e-6)


def test_gru_cell_matches_manual():
    p = init_gru_cell(jax.random.PRNGKey(0), I, H)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, I))
    h = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    h2 = gru_cell(p, x, h)

    gi = np.asarray(x @ p["w_ih"] + p["b_ih"])
    gh = np.asarray(h @ p["w_hh"] + p["b_hh"])
    i_r, i_z, i_n = np.split(gi, 3, -1)
    h_r, h_z, h_n = np.split(gh, 3, -1)
    sig = lambda z: 1 / (1 + np.exp(-z))  # noqa: E731
    r, z = sig(i_r + h_r), sig(i_z + h_z)
    n = np.tanh(i_n + r * h_n)
    want = (1 - z) * n + z * np.asarray(h)
    np.testing.assert_allclose(np.asarray(h2), want, rtol=1e-5, atol=1e-6)


def test_lstm_stack_equals_unrolled_cells():
    model = LSTM(I, H, num_layers=2)
    params = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, I))
    out, finals = model.apply(params, xs)
    assert out.shape == (S, B, H) and len(finals) == 2

    # unroll by hand through both layers
    cur = np.asarray(xs)
    for layer in params:
        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        outs = []
        for t in range(S):
            h, c = lstm_cell(layer["fwd"], jnp.asarray(cur[t]),
                             (jnp.asarray(h), jnp.asarray(c)))
            h, c = np.asarray(h), np.asarray(c)
            outs.append(h)
        cur = np.stack(outs)
    np.testing.assert_allclose(np.asarray(out), cur, rtol=1e-5, atol=1e-6)


def test_bidirectional_concat_and_reverse():
    model = GRU(I, H, bidirectional=True)
    params = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, I))
    out, finals = model.apply(params, xs)
    assert out.shape == (S, B, 2 * H)
    # the backward half at time 0 is the bwd scan's LAST state
    fin_f, fin_b = finals[0]
    np.testing.assert_allclose(np.asarray(out[-1, :, :H]),
                               np.asarray(fin_f), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out[0, :, H:]),
                               np.asarray(fin_b), rtol=1e-6)


def test_mlstm_runs_and_differs_from_lstm():
    mp = init_mlstm_cell(jax.random.PRNGKey(0), I, H)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, I))
    h = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    c = jnp.zeros((B, H))
    h2, c2 = mlstm_cell(mp, x, (h, c))
    assert h2.shape == (B, H)
    lp = {k: mp[k] for k in ("w_ih", "w_hh", "b_ih", "b_hh")}
    h3, _ = lstm_cell(lp, x, (h, c))
    # nonzero h: the multiplicative m = (xWmx)⊙(hWmh) replaces h in the
    # gates, so the two cells diverge (at h=0 both see zeros)
    assert float(jnp.max(jnp.abs(h2 - h3))) > 0


def test_gradients_flow_and_dtype_held():
    model = LSTM(I, H, num_layers=2, dropout=0.1)
    params = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, I),
                           jnp.bfloat16)
    out, _ = model.apply(params, xs.astype(jnp.bfloat16),
                         dropout_rng=jax.random.PRNGKey(2))
    assert out.dtype == jnp.bfloat16  # gate math fp32, output dtype held
    g = jax.grad(lambda p: jnp.sum(
        model.apply(p, xs)[0].astype(jnp.float32)))(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert bool(jnp.all(jnp.isfinite(leaf)))


def test_mode_validation():
    with pytest.raises(ValueError, match="mode"):
        RNN("conv", I, H)

"""Weight-norm reparameterization tests (ref:
``apex/reparameterization`` — w == g·v/||v||, grads to both factors)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.reparameterization import (
    apply_weight_norm,
    compute_weight,
    remove_weight_norm,
)


def test_split_reconstructs_identity():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
    g, v = apply_weight_norm(w, dim=0)
    assert g.shape == (8, 1)
    np.testing.assert_allclose(np.asarray(compute_weight(g, v, 0)),
                               np.asarray(w), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(remove_weight_norm(g, v, 0)),
                               np.asarray(w), rtol=1e-6)


def test_direction_invariance():
    """Scaling v leaves w unchanged (the reparameterization's point)."""
    w = jax.random.normal(jax.random.PRNGKey(1), (4, 6))
    g, v = apply_weight_norm(w)
    np.testing.assert_allclose(
        np.asarray(compute_weight(g, 7.5 * v)),
        np.asarray(compute_weight(g, v)), rtol=1e-5)


def test_gradients_match_autodiff_of_definition():
    w0 = jax.random.normal(jax.random.PRNGKey(2), (4, 6))
    g0, v0 = apply_weight_norm(w0)

    def loss(g, v):
        return jnp.sum(jnp.sin(compute_weight(g, v)))

    def loss_manual(g, v):
        norm = jnp.sqrt(jnp.sum(v * v, axis=1, keepdims=True))
        return jnp.sum(jnp.sin(g * v / norm))

    got = jax.grad(loss, argnums=(0, 1))(g0, v0)
    want = jax.grad(loss_manual, argnums=(0, 1))(g0, v0)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_fp16_safe_norm():
    """Norms that overflow fp16 (the apex motivation): values near the
    f16 max must not inf out — the norm runs in fp32."""
    v = jnp.full((2, 1024), 200.0, jnp.float16)  # ssq ~ 4e7 >> f16 max
    g = jnp.ones((2, 1), jnp.float16)
    w = compute_weight(g, v)
    assert w.dtype == jnp.float16
    assert bool(jnp.all(jnp.isfinite(w.astype(jnp.float32))))

"""Opt-level preset and casting tests (ref: tests/L0/run_amp casting suite)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp


def _params():
    return {
        "dense": {"kernel": jnp.ones((4, 4), jnp.float32)},
        "batch_norm": {
            "scale": jnp.ones((4,), jnp.float32),
            "bias": jnp.zeros((4,), jnp.float32),
        },
    }


def test_o0_is_fp32():
    h = amp.initialize("O0", verbosity=0)
    p = h.cast_model(_params())
    assert p["dense"]["kernel"].dtype == jnp.float32
    assert float(h.init_state().loss_scale) == 1.0


def test_o2_casts_model_keeps_norms_fp32():
    h = amp.initialize("O2", verbosity=0)
    p = h.cast_model(_params())
    assert p["dense"]["kernel"].dtype == jnp.bfloat16
    assert p["batch_norm"]["scale"].dtype == jnp.float32
    assert h.properties.master_weights
    assert h.scaler.dynamic


def test_o3_casts_everything():
    h = amp.initialize("O3", verbosity=0)
    p = h.cast_model(_params())
    assert p["batch_norm"]["scale"].dtype == jnp.bfloat16
    assert not h.scaler.dynamic


def test_fp16_override():
    h = amp.initialize("O2", cast_model_type=jnp.float16, verbosity=0)
    p = h.cast_model(_params())
    assert p["dense"]["kernel"].dtype == jnp.float16


def test_bad_opt_level_raises():
    with pytest.raises(ValueError):
        amp.initialize("O4")


def test_o1_autocast_policy():
    h = amp.initialize("O1", verbosity=0)
    x = jnp.ones((2, 2), jnp.float32)
    with h.autocast():
        (mm_x,) = amp.cast_args("matmul", x)
        assert mm_x.dtype == jnp.bfloat16
        (sm_x,) = amp.cast_args("softmax", x.astype(jnp.bfloat16))
        assert sm_x.dtype == jnp.float32
        a, b = amp.cast_args("add", x, x.astype(jnp.bfloat16))
        assert a.dtype == b.dtype == jnp.float32  # promote to widest
    # outside the context: passthrough
    (y,) = amp.cast_args("matmul", x)
    assert y.dtype == jnp.float32


def test_o2_end_to_end_train_step_matches_fp32_direction():
    """O2 master-weight step must track the fp32 step closely (golden-model
    pattern of the reference's L0 suite)."""
    h = amp.initialize("O2", verbosity=0)
    params = {"w": jnp.asarray([[1.0, 2.0], [3.0, 4.0]], jnp.float32)}
    batch = {"x": jnp.asarray([[1.0, -1.0]]), "y": jnp.asarray([[0.5, 0.5]])}
    opt = optax.sgd(0.1)

    def loss_fn(p, b):
        pred = b["x"] @ p["w"].astype(jnp.float32)
        return jnp.mean((pred - b["y"]) ** 2)

    # fp32 reference step
    g_ref = jax.grad(loss_fn)(params, batch)
    ref_new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, g_ref)

    master = h.master_params(params)
    state = h.init_state()
    opt_state = opt.init(master)

    def amp_loss_fn(p, b):
        return loss_fn(p, b)

    @jax.jit
    def step(master, opt_state, state, b):
        model = h.cast_model(master)
        loss, grads, found_inf, state = h.value_and_grad(amp_loss_fn)(
            model, state, h.cast_input(b)
        )
        grads = jax.tree_util.tree_map(
            lambda g, m: g.astype(m.dtype), grads, master
        )
        updates, new_opt = opt.update(grads, opt_state, master)
        new_master = optax.apply_updates(master, updates)
        master = amp.apply_if_finite(new_master, master, found_inf)
        opt_state = amp.apply_if_finite(new_opt, opt_state, found_inf)
        return master, opt_state, state, loss

    master, opt_state, state, loss = step(master, opt_state, state, batch)
    np.testing.assert_allclose(
        np.asarray(master["w"]), np.asarray(ref_new["w"]), rtol=2e-2
    )
    assert jnp.isfinite(loss)
    # scale advanced one clean step
    assert int(state.unskipped) == 1


def test_o2_cast_model_consumes_precast():
    """``cast_model(precast=...)`` (optimizer fused cast-out): matching-
    dtype leaves are taken VERBATIM (same array object — no recast),
    keep-fp32 norm leaves still come from the master tree, and a
    mismatched precast leaf falls back to casting master."""
    h = amp.initialize("O2", verbosity=0)
    master = _params()
    pre = jax.tree.map(lambda x: (x + 1).astype(jnp.bfloat16), master)
    p = h.cast_model(master, precast=pre)
    # bf16 leaf consumed verbatim — the emitted values, not master's
    assert p["dense"]["kernel"] is pre["dense"]["kernel"]
    # norm leaves stay fp32 and come from master (precast dtype mismatch)
    assert p["batch_norm"]["scale"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(p["batch_norm"]["scale"]),
                                  np.asarray(master["batch_norm"]["scale"]))


def test_model_params_from_master_precast():
    from apex_tpu.amp import policy

    master = _params()
    like = {"dense": {"kernel": jnp.zeros((4, 4), jnp.bfloat16)},
            "batch_norm": {"scale": jnp.zeros((4,), jnp.float32),
                           "bias": jnp.zeros((4,), jnp.float32)}}
    pre = jax.tree.map(lambda x: (x * 2).astype(jnp.bfloat16), master)
    got = policy.model_params_from_master(master, like, precast=pre)
    assert got["dense"]["kernel"] is pre["dense"]["kernel"]
    assert got["batch_norm"]["scale"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(got["batch_norm"]["scale"]),
                                  np.asarray(master["batch_norm"]["scale"]))


def _dots_by_dtype(closed, dtype):
    """dot_general eqns (outside pallas bodies) with all operands in dtype."""
    from apex_tpu.lint.traced import jaxprlib as jl

    return [e for e in jl.all_eqns(closed, into_pallas=False)
            if e.primitive.name == "dot_general"
            and all(v.aval.dtype == dtype for v in e.invars)]


def test_o1_einsum_policy():
    h = amp.initialize("O1", verbosity=0)
    x = jnp.ones((2, 4), jnp.float32)
    with h.autocast():
        a, b = amp.cast_args("einsum", x, x)
        assert a.dtype == b.dtype == jnp.bfloat16
    a, b = amp.cast_args("einsum", x, x)
    assert a.dtype == jnp.float32  # passthrough outside the context


def test_o1_bert_unfused_attention_traces_bf16():
    """The unfused-attention einsums ride the O1 policy: every matmul in
    the traced forward runs bf16 under autocast and fp32 without."""
    import dataclasses

    from apex_tpu.models import bert

    cfg = dataclasses.replace(bert.bert_tiny(), fused_attention=False)
    params = bert.init_bert(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((2, 16), jnp.int32)

    # distinct lambdas: jax caches traces on function identity, and the
    # autocast context is trace-time state invisible to that cache
    h = amp.initialize("O1", verbosity=0)
    with h.autocast():
        hot = jax.make_jaxpr(
            lambda p: bert.apply_bert(p, cfg, ids)["mlm_logits"])(params)
    cold = jax.make_jaxpr(
        lambda p: bert.apply_bert(p, cfg, ids)["mlm_logits"])(params)

    # 2 attention einsums per layer, on top of the dense sites
    assert len(_dots_by_dtype(hot, jnp.bfloat16)) >= 2 * cfg.num_layers
    assert not _dots_by_dtype(cold, jnp.bfloat16)


def test_o1_gpt_logits_matmul_traces_bf16():
    from apex_tpu.models import gpt

    cfg = gpt.gpt_tiny()
    params = gpt.init_gpt(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((2, 16), jnp.int32)

    h = amp.initialize("O1", verbosity=0)
    with h.autocast():
        hot = jax.make_jaxpr(
            lambda p: gpt.gpt_loss_unsharded(p, cfg, ids, ids))(params)
    cold = jax.make_jaxpr(
        lambda p: gpt.gpt_loss_unsharded(p, cfg, ids, ids))(params)

    def logits_dots(closed, dtype):
        # the tied-embedding head: rhs is the transposed (h, vocab) table
        return [e for e in _dots_by_dtype(closed, dtype)
                if e.invars[1].aval.shape[-2:] == (cfg.hidden_size,
                                                   cfg.vocab_size)]

    assert logits_dots(hot, jnp.bfloat16)
    assert not logits_dots(cold, jnp.bfloat16)
    assert logits_dots(cold, jnp.float32)

"""Loss-scaler behavior tests.

Mirrors the reference's ``tests/L0/run_amp`` loss-scaler coverage: dynamic
backoff on overflow, growth after the clean-step window, skip-step
semantics, checkpoint round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp


def test_static_scale_is_constant():
    scaler = amp.LossScaler(loss_scale=128.0)
    s = scaler.init_state()
    assert float(s.loss_scale) == 128.0
    loss = jnp.asarray(2.0)
    assert float(scaler.scale(loss, s)) == 256.0
    s2 = scaler.update_scale(s, jnp.asarray(True))
    assert float(s2.loss_scale) == 128.0  # static never moves


def test_dynamic_backoff_on_overflow():
    scaler = amp.LossScaler(loss_scale="dynamic")
    s = scaler.init_state()
    assert float(s.loss_scale) == 2.0 ** 16
    grads = {"w": jnp.asarray([jnp.inf, 1.0])}
    _, found_inf = scaler.unscale(grads, s)
    assert bool(found_inf)
    s = scaler.update_scale(s, found_inf)
    assert float(s.loss_scale) == 2.0 ** 15
    assert int(s.overflows) == 1


def test_dynamic_growth_after_window():
    scaler = amp.LossScaler(loss_scale="dynamic", scale_window=4)
    s = scaler.init_state()
    clean = jnp.asarray(False)
    for _ in range(4):
        s = scaler.update_scale(s, clean)
    assert float(s.loss_scale) == 2.0 ** 17
    assert int(s.unskipped) == 0


def test_unscale_divides_by_scale():
    scaler = amp.LossScaler(loss_scale=4.0)
    s = scaler.init_state()
    grads = {"w": jnp.asarray([8.0, 4.0])}
    out, found_inf = scaler.unscale(grads, s)
    np.testing.assert_allclose(np.asarray(out["w"]), [2.0, 1.0])
    assert not bool(found_inf)


def test_apply_if_finite_skips_step():
    new = {"w": jnp.asarray([1.0])}
    old = {"w": jnp.asarray([0.0])}
    kept = amp.apply_if_finite(new, old, jnp.asarray(True))
    assert float(kept["w"][0]) == 0.0
    applied = amp.apply_if_finite(new, old, jnp.asarray(False))
    assert float(applied["w"][0]) == 1.0


def test_scaler_works_under_jit():
    scaler = amp.LossScaler(loss_scale="dynamic", scale_window=2)

    @jax.jit
    def step(state, g):
        unscaled, found_inf = scaler.unscale(g, state)
        return scaler.update_scale(state, found_inf), unscaled

    s = scaler.init_state()
    s, _ = step(s, {"w": jnp.asarray([1.0])})
    s, _ = step(s, {"w": jnp.asarray([jnp.nan])})
    assert float(s.loss_scale) == 2.0 ** 15


def test_state_dict_roundtrip():
    scaler = amp.LossScaler(loss_scale="dynamic")
    s = scaler.init_state()
    s = scaler.update_scale(s, jnp.asarray(True))
    d = scaler.state_dict(s)
    s2 = scaler.load_state_dict(d)
    assert float(s2.loss_scale) == float(s.loss_scale)
    assert int(s2.unskipped) == int(s.unskipped)


def test_amp_multi_loss_state_dict_roundtrip():
    """Reference parity: ``amp.initialize(num_losses=N)`` keeps N
    independent scalers and ``amp.state_dict`` carries all of them
    (``loss_scaler0..N-1``), not just scaler 0."""
    h = amp.initialize("O2", loss_scale="dynamic", num_losses=3,
                       verbosity=0)
    states = h.init_state()
    assert isinstance(states, tuple) and len(states) == 3
    # overflow only loss 1: its scale halves, the others stay put
    states = (states[0],
              h.update_scale(states[1], jnp.asarray(True)),
              states[2])
    d = h.state_dict(states)
    assert set(d) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}
    back = h.load_state_dict(d)
    assert float(back[1].loss_scale) == 2.0 ** 15
    assert float(back[0].loss_scale) == 2.0 ** 16

    # single-loss handles keep the flat shape both ways
    h1 = amp.initialize("O2", loss_scale="dynamic", verbosity=0)
    s = h1.init_state()
    assert not isinstance(s, tuple)
    assert set(h1.state_dict(s)) == {"loss_scaler0"}
    assert not isinstance(h1.load_state_dict(h1.state_dict(s)), tuple)


def test_amp_load_state_dict_count_mismatch_warns_and_loads_overlap():
    """A checkpoint whose loss_scaler count disagrees with num_losses
    must not brick the resume: warn, load the overlap, fresh-init the
    rest (reference apex silently truncates via zip; we keep the
    semantics and surface the warning)."""
    import warnings

    h3 = amp.initialize("O2", loss_scale="dynamic", num_losses=3,
                        verbosity=0)
    states = h3.init_state()
    states = (h3.update_scale(states[0], jnp.asarray(True)),) + states[1:]
    d3 = h3.state_dict(states)

    # fewer checkpoint entries than losses: overlap loads, rest fresh
    d1 = {"loss_scaler0": d3["loss_scaler0"]}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        back = h3.load_state_dict(d1)
    assert any("loss_scaler" in str(x.message) for x in w)
    assert isinstance(back, tuple) and len(back) == 3
    assert float(back[0].loss_scale) == 2.0 ** 15  # loaded (halved)
    assert float(back[1].loss_scale) == 2.0 ** 16  # fresh init

    # more checkpoint entries than losses: surplus ignored
    h1 = amp.initialize("O2", loss_scale="dynamic", verbosity=0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        s1 = h1.load_state_dict(d3)
    assert any("loss_scaler" in str(x.message) for x in w)
    assert not isinstance(s1, tuple)
    assert float(s1.loss_scale) == 2.0 ** 15  # scaler 0's state

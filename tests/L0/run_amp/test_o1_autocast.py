"""O1 op-policy autocast end-to-end (ref: ``apex/amp`` O1 — cached casts
installed over torch functions; here the op library consults
``amp.autocast.cast_args``). Asserts the dtype contract: matmuls/convs in
the compute dtype, norms/softmax fp32 inside, params untouched."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.amp.autocast import autocast, cast_args
from apex_tpu.models import layers as L


def test_cast_args_policies():
    x32 = jnp.ones((4, 4), jnp.float32)
    xb = jnp.ones((4, 4), jnp.bfloat16)
    # outside any context: identity
    assert cast_args("dense", x32)[0].dtype == jnp.float32
    with autocast(jnp.bfloat16):
        # fp16-list op: cast down
        assert cast_args("dense", x32)[0].dtype == jnp.bfloat16
        # fp32-list op: cast up
        assert cast_args("softmax", xb)[0].dtype == jnp.float32
        # promote: widest wins
        a, b = cast_args("add", xb, x32)
        assert a.dtype == b.dtype == jnp.float32
        # non-float args pass through
        ids = jnp.ones((4,), jnp.int32)
        assert cast_args("dense", ids)[0].dtype == jnp.int32
    with autocast(enabled=False):
        assert cast_args("dense", x32)[0].dtype == jnp.float32


def test_dense_runs_in_bf16_under_autocast():
    p = L.init_dense(jax.random.PRNGKey(0), 16, 8)  # fp32 params
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16), jnp.float32)
    assert L.dense(p, x).dtype == jnp.float32
    with autocast(jnp.bfloat16):
        y = L.dense(p, x)
    assert y.dtype == jnp.bfloat16
    assert p["kernel"].dtype == jnp.float32  # params untouched


def test_conv_under_autocast():
    p = L.init_conv(jax.random.PRNGKey(0), 3, 8, (3, 3))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    with autocast(jnp.bfloat16):
        assert L.conv(p, x).dtype == jnp.bfloat16
    assert L.conv(p, x).dtype == jnp.float32


def test_o1_handle_enables_autocast_o0_does_not():
    h1 = amp.initialize(opt_level="O1", verbosity=0)
    p = L.init_dense(jax.random.PRNGKey(0), 16, 8)
    x = jnp.ones((2, 16), jnp.float32)
    with h1.autocast():
        assert L.dense(p, x).dtype == jnp.bfloat16
    h0 = amp.initialize(opt_level="O0", verbosity=0)
    with h0.autocast():
        assert L.dense(p, x).dtype == jnp.float32


def test_o1_end_to_end_bert_step():
    """Full O1 train step on tiny BERT: fp32 master params, op-policy
    casting inside the loss, dynamic scaler — loss finite, close to the
    fp32 run, grads fp32 like the params."""
    from apex_tpu.models import apply_bert, bert_tiny, init_bert, mlm_loss

    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    mask = jnp.ones((2, 32), jnp.int32)

    def loss_fn(p):
        out = apply_bert(p, cfg, ids, mask)
        return mlm_loss(out["mlm_logits"], ids, mask)

    h = amp.initialize(opt_level="O1", loss_scale="dynamic", verbosity=0)
    state = h.init_state()
    with h.autocast():
        # O1 keeps master weights fp32 — no cast_model. jit'd: the
        # autocast interceptor acts at TRACE time, and eager per-op
        # dispatch of the whole fwd+bwd cost ~20 s on the 1-core host.
        loss, grads, found_inf, state = jax.jit(
            h.value_and_grad(loss_fn))(params, state)
    loss32 = jax.jit(loss_fn)(params)

    assert loss.dtype == jnp.float32
    assert not bool(found_inf)
    # bf16 matmuls: tolerance is bf16-sized, and the runs must differ
    # (proof the cast actually happened)
    np.testing.assert_allclose(float(loss), float(loss32), rtol=0.05)
    assert float(loss) != float(loss32)
    for g in jax.tree_util.tree_leaves(grads):
        assert g.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(g)))


def test_hidden_states_fp32_after_norms_dense_compute_bf16():
    """Reference O1 semantics: layer_norm is FP32-forced, so the residual
    stream re-emerges fp32 after every LN even though each dense casts its
    operands to bf16 (torch O1 behaves identically: linear returns fp16,
    the next layer_norm returns fp32)."""
    from apex_tpu.models import apply_bert, bert_tiny, init_bert
    from apex_tpu.models.layers import dense

    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    with autocast(jnp.bfloat16):
        out = apply_bert(params, cfg, ids, jnp.ones_like(ids))
        # the op-level contract that makes O1 fast: dense emits bf16
        q = dense(params["encoder"][0]["attention"]["qkv"], out["hidden"])
    assert out["hidden"].dtype == jnp.float32
    assert q.dtype == jnp.bfloat16
    assert out["mlm_logits"].dtype == jnp.float32  # loss head stays fp32

    bp, bs = L.init_batchnorm(4)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4), jnp.bfloat16)
    with autocast(jnp.bfloat16):
        y, new_state = L.batchnorm(bp, bs, x, train=True)
    assert new_state["mean"].dtype == jnp.float32
    assert new_state["var"].dtype == jnp.float32

"""Regression tests for bugs found during verification/review of the amp
subsystem."""

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.transformer import parallel_state as ps


def test_fp16_overflow_detected_under_jit():
    """XLA excess-precision folding (f32->f16->f32 elision) must not mask
    overflow detection (see amp/scaler.py :: _leaf_finite)."""
    h = amp.initialize("O2", cast_model_type=jnp.float16, verbosity=0)
    master = {"w": jnp.ones((8, 8), jnp.float32)}
    state = h.init_state()

    def loss_fn(p, x):
        return jnp.sum(x @ p["w"]) * 1e30

    @jax.jit
    def step(master, state, x):
        model = h.cast_model(master)
        _, grads, found_inf, state = h.value_and_grad(loss_fn)(
            model, state, x
        )
        return found_inf, state

    found_inf, state = step(master, state, jnp.ones((4, 8)))
    assert bool(found_inf)
    assert float(state.loss_scale) == 2.0 ** 15


def test_enabled_false_is_hard_off_switch():
    h = amp.initialize(
        "O2", loss_scale="dynamic", cast_model_type=jnp.bfloat16,
        enabled=False, verbosity=0,
    )
    assert h.properties.cast_model_type is None
    assert not h.scaler.dynamic
    p = h.cast_model({"w": jnp.ones((2,), jnp.float32)})
    assert p["w"].dtype == jnp.float32


def test_o0_casts_inputs_to_fp32():
    h = amp.initialize("O0", verbosity=0)
    batch = {"x": jnp.ones((2,), jnp.bfloat16)}
    assert h.cast_input(batch)["x"].dtype == jnp.float32


def test_virtual_pipeline_rank_reset_on_reinitialize():
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        virtual_pipeline_model_parallel_size_=2,
    )
    ps.set_virtual_pipeline_model_parallel_rank(1)
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        virtual_pipeline_model_parallel_size_=2,
    )
    assert ps.get_virtual_pipeline_model_parallel_rank() == 0
    ps.initialize_model_parallel(pipeline_model_parallel_size_=2)
    assert ps.get_virtual_pipeline_model_parallel_rank() is None

"""Trace summarizer (the parse-and-report half of the reference's
pyprof workflow — SURVEY §5 tracing row)."""

import io

import jax
import jax.numpy as jnp

from apex_tpu.utils.profiler import (
    annotate, print_summary, summarize_trace, trace,
)


def test_trace_and_summarize(tmp_path):
    d = str(tmp_path / "tb")

    @jax.jit
    def step(x):
        with annotate("matmul_region"):
            return x @ x

    x = jnp.ones((128, 128))
    step(x).block_until_ready()  # compile outside the trace
    with trace(d):
        step(x).block_until_ready()

    # CPU backend traces host lanes only — device_only=False covers it
    rows = summarize_trace(d, top=10, device_only=False)
    assert rows and all(r["total_us"] > 0 for r in rows)
    assert all(set(r) >= {"name", "process", "count", "total_us",
                          "avg_us"} for r in rows)

    buf = io.StringIO()
    print_summary(d, top=5, device_only=False, file=buf)
    out = buf.getvalue()
    assert "total_us" in out and len(out.splitlines()) >= 2


def test_device_only_on_host_trace_raises(tmp_path):
    import pytest

    d = str(tmp_path / "tb2")
    x = jnp.ones((64, 64))
    with trace(d):
        (x @ x).block_until_ready()
    with pytest.raises(ValueError, match="device_only=False"):
        summarize_trace(d)  # CPU trace has no device lanes

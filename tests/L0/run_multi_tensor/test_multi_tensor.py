"""Tests for the multi-tensor engine: flatten round-trip, list ops, and the
flat Pallas kernels vs jnp references."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.multi_tensor_apply import (
    MultiTensorApply,
    flatten_pytree,
    flatten_tensors,
    kernels,
    make_spec,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    unflatten_pytree,
    unflatten_tensors,
)


def _tensors():
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    return [
        jax.random.normal(ks[0], (33, 7), jnp.float32),
        jax.random.normal(ks[1], (129,), jnp.float32),
        jax.random.normal(ks[2], (4, 4, 4), jnp.bfloat16),
        jax.random.normal(ks[3], (2048,), jnp.float32),
    ]


def test_flatten_roundtrip():
    ts = _tensors()
    buf, spec = flatten_tensors(ts)
    assert buf.shape[1] == 128 and buf.dtype == jnp.float32
    back = unflatten_tensors(buf, spec)
    for t, b in zip(ts, back):
        assert t.dtype == b.dtype and t.shape == b.shape
        np.testing.assert_allclose(np.asarray(t, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-2, atol=1e-2)


def test_flatten_pytree_roundtrip():
    tree = {"a": jnp.arange(10, dtype=jnp.float32),
            "b": {"c": jnp.ones((3, 5), jnp.float32)}}
    buf, spec, treedef = flatten_pytree(tree)
    back = unflatten_pytree(buf, spec, treedef)
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), tree, back)


def test_tile_tensor_ids():
    ts = _tensors()
    spec = make_spec(ts)
    ids = spec.tile_tensor_ids(8)
    assert ids.shape[0] == spec.total_rows // 8
    assert ids[0] == 0 and ids[-1] == len(ts) - 1


def test_multi_tensor_scale_and_overflow():
    ts = _tensors()
    out, found_inf = multi_tensor_scale(ts, 0.5)
    assert not bool(found_inf)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.asarray(ts[0]) * 0.5, rtol=1e-6)
    bad = ts[:2] + [ts[2].astype(jnp.float32).at[0, 0, 0].set(jnp.inf)]
    _, found_inf = multi_tensor_scale(bad, 0.5)
    assert bool(found_inf)


def test_multi_tensor_l2norm():
    ts = [t.astype(jnp.float32) for t in _tensors()]
    total, per = multi_tensor_l2norm(ts, per_tensor=True)
    want = np.sqrt(sum(float(jnp.sum(t * t)) for t in ts))
    np.testing.assert_allclose(float(total), want, rtol=1e-5)
    np.testing.assert_allclose(
        float(per[1]), float(jnp.linalg.norm(ts[1])), rtol=1e-5)


def test_multi_tensor_axpby():
    xs = [jnp.ones((5,)), jnp.full((3, 3), 2.0)]
    ys = [jnp.full((5,), 3.0), jnp.ones((3, 3))]
    out, flag = multi_tensor_axpby(2.0, xs, -1.0, ys)
    np.testing.assert_allclose(np.asarray(out[0]), np.full(5, -1.0))
    np.testing.assert_allclose(np.asarray(out[1]), np.full((3, 3), 3.0))
    assert not bool(flag)


def test_applier_shim_apex_convention():
    applier = MultiTensorApply(2048)
    # apex: multi_tensor_applier(scale_op, noop_buf, [src, dst], scale) —
    # dst supplies the out dtypes, results are returned
    src = [jnp.ones((4,), jnp.bfloat16)]
    dst = [jnp.zeros((4,), jnp.float32)]
    out, flag = applier("scale", None, [src, dst], 2.0)
    assert out[0].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out[0]), np.full(4, 2.0))
    assert not bool(flag)

    # apex: applier(axpby_op, noop, [xs, ys, outs], a, b, ...)
    xs, ys = [jnp.ones((4,))], [jnp.full((4,), 2.0)]
    out, _ = applier("axpby", None, [xs, ys, ys], 3.0, 1.0)
    np.testing.assert_allclose(np.asarray(out[0]), np.full(4, 5.0))

    # single-list form still works for l2norm
    total = applier("l2norm", None, [[jnp.full((4,), 2.0)]])
    np.testing.assert_allclose(float(total), 4.0)


# -- flat Pallas kernels ----------------------------------------------------

def test_flat_scale_kernel():
    ts = [t.astype(jnp.float32) for t in _tensors()]
    buf, spec = flatten_tensors(ts)
    out, found_inf = kernels.flat_scale(buf, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(buf) * 0.25,
                               rtol=1e-6)
    assert not bool(found_inf)
    bad = buf.at[0, 0].set(jnp.nan)
    _, found_inf = kernels.flat_scale(bad, 0.25)
    assert bool(found_inf)


def test_flat_axpby_kernel():
    buf, _ = flatten_tensors([t.astype(jnp.float32) for t in _tensors()])
    out, _ = kernels.flat_axpby(2.0, buf, 0.5, buf * 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(buf) * 4,
                               rtol=1e-6)


def test_flat_l2norm_kernel_global_and_per_tensor():
    ts = [t.astype(jnp.float32) for t in _tensors()]
    buf, spec = flatten_tensors(ts)
    norm = kernels.flat_l2norm(buf)
    want = np.sqrt(sum(float(jnp.sum(t * t)) for t in ts))
    np.testing.assert_allclose(float(norm), want, rtol=1e-5)

    parts = kernels.flat_l2norm_partials(buf)
    ids = spec.tile_tensor_ids(8)
    # pad ids to match block-padded partials (pad partials are zero)
    ids = np.pad(ids, (0, parts.shape[0] - ids.shape[0]),
                 constant_values=len(ts) - 1)
    seg = jax.ops.segment_sum(parts, jnp.asarray(ids), num_segments=len(ts))
    for i, t in enumerate(ts):
        np.testing.assert_allclose(
            float(jnp.sqrt(seg[i])), float(jnp.linalg.norm(t)), rtol=1e-5)


def test_flat_adam_kernel_matches_manual():
    n = 5000
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
    p = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
    (gbuf, spec) = flatten_tensors([g])
    (pbuf, _) = flatten_tensors([p], spec)
    m = jnp.zeros_like(pbuf)
    v = jnp.zeros_like(pbuf)
    p1, m1, v1 = kernels.flat_adam(
        gbuf, pbuf, m, v, lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8,
        step=1, weight_decay=0.01, adam_w_mode=True)
    # manual
    mm = 0.1 * g
    vv = 0.001 * g * g
    mhat = mm / (1 - 0.9)
    vhat = vv / (1 - 0.999)
    want = p - 1e-2 * (mhat / (jnp.sqrt(vhat) + 1e-8) + 0.01 * p)
    got = unflatten_tensors(p1, spec)[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_applier_callable_reference_arity():
    # apex convention: applier(op, noop_flag, tensor_lists, *args) invokes
    # op(chunk_size, noop_flag, tensor_lists, *args) — the first two must
    # be forwarded, not dropped
    applier = MultiTensorApply(4096)
    seen = {}

    def op(chunk_size, noop_flag, tensor_lists, alpha):
        seen.update(chunk_size=chunk_size, noop_flag=noop_flag,
                    n_lists=len(tensor_lists), alpha=alpha)
        return [t * alpha for t in tensor_lists[0]]

    out = applier(op, "noop", [[jnp.ones(3)]], 2.0)
    assert seen == {"chunk_size": 4096, "noop_flag": "noop",
                    "n_lists": 1, "alpha": 2.0}
    np.testing.assert_allclose(np.asarray(out[0]), np.full(3, 2.0))


def test_flat_adam_kernel_bf16_moment_and_castout():
    """Kernel-level reduced-precision contract: bf16 m in/out with fp32
    accumulate (== round-to-nearest of the fp32 m), and the optional 4th
    output == the updated params cast to the emit dtype, bit for bit."""
    n = 5000
    g = jax.random.normal(jax.random.PRNGKey(3), (n,), jnp.float32)
    p = jax.random.normal(jax.random.PRNGKey(4), (n,), jnp.float32)
    (gbuf, spec) = flatten_tensors([g])
    (pbuf, _) = flatten_tensors([p], spec)
    kw = dict(lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8, step=3,
              weight_decay=0.01, adam_w_mode=True)

    m32 = jnp.full_like(pbuf, 0.25)
    v32 = jnp.full_like(pbuf, 0.5)
    p_ref, m_ref, v_ref = kernels.flat_adam(gbuf, pbuf, m32, v32, **kw)

    outs = kernels.flat_adam(gbuf, pbuf, m32.astype(jnp.bfloat16), v32,
                             emit_compute_dtype=jnp.bfloat16, **kw)
    assert len(outs) == 4
    p_bf, m_bf, v_bf, pc = outs
    assert m_bf.dtype == jnp.bfloat16 and v_bf.dtype == jnp.float32
    # m32 is bf16-exact, so the fp32-accumulated m must round to exactly
    # the fp32 path's m, and v must match bit for bit
    np.testing.assert_array_equal(
        np.asarray(m_bf, np.float32),
        np.asarray(m_ref.astype(jnp.bfloat16), np.float32))
    np.testing.assert_array_equal(np.asarray(v_bf), np.asarray(v_ref))
    np.testing.assert_allclose(np.asarray(p_bf), np.asarray(p_ref),
                               rtol=1e-5, atol=1e-6)
    # fused cast-out == cast of the kernel's own updated params
    assert pc.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(pc, np.float32),
        np.asarray(p_bf.astype(jnp.bfloat16), np.float32))

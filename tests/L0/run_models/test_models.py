"""Model-zoo tests: shapes, dtype policies, and a few-step loss decrease
(the reference's L1 convergence tests in miniature)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.models import (
    apply_bert, apply_resnet, bert_partition_specs, bert_tiny,
    cross_entropy_loss, init_bert, init_resnet, mlm_loss,
)
from apex_tpu.optimizers import FusedAdam, FusedSGD


def test_bert_forward_shapes():
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    out = apply_bert(params, cfg, ids)
    assert out["hidden"].shape == (2, 16, cfg.hidden_size)
    assert out["mlm_logits"].shape == (2, 16, cfg.vocab_size)
    assert out["pooled"].shape == (2, cfg.hidden_size)
    assert out["mlm_logits"].dtype == jnp.float32


def test_bert_bf16_compute():
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)
    ids = jnp.zeros((2, 16), jnp.int32)
    out = apply_bert(params, cfg, ids, compute_dtype=jnp.bfloat16)
    assert out["hidden"].dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(out["mlm_logits"], np.float32)))


def test_bert_mask_changes_output():
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 100)
    full = apply_bert(params, cfg, ids,
                      jnp.ones((2, 16), jnp.int32))["hidden"]
    half = apply_bert(params, cfg, ids,
                      jnp.concatenate([jnp.ones((2, 8), jnp.int32),
                                       jnp.zeros((2, 8), jnp.int32)], 1)
                      )["hidden"]
    assert not np.allclose(np.asarray(full[:, 0]), np.asarray(half[:, 0]),
                           atol=1e-5)


def test_bert_train_step_decreases_loss():
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0,
                             cfg.vocab_size)
    mask = jnp.ones((4, 32), jnp.int32)

    @jax.jit
    def step(params, state):
        def f(p):
            return mlm_loss(apply_bert(p, cfg, ids, mask)["mlm_logits"],
                            ids, mask)
        loss, grads = jax.value_and_grad(f)(params)
        params, state = opt.step(grads, params, state)
        return params, state, loss

    losses = []
    for _ in range(8):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_bert_amp_o2_train_step():
    cfg = bert_tiny()
    h = amp.initialize(opt_level="O2", loss_scale="dynamic")
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-3)
    opt_state = opt.init(params)
    sstate = h.init_state()
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                             cfg.vocab_size)
    mask = jnp.ones((2, 16), jnp.int32)

    @jax.jit
    def step(master, opt_state, sstate):
        p = h.cast_model(master)
        loss, grads, found_inf, sstate = h.value_and_grad(
            lambda p: mlm_loss(apply_bert(p, cfg, ids, mask,
                                          compute_dtype=jnp.bfloat16)
                               ["mlm_logits"], ids, mask))(p, sstate)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        return master, opt_state, sstate, loss, found_inf

    for _ in range(3):
        params, opt_state, sstate, loss, found_inf = step(
            params, opt_state, sstate)
    assert np.isfinite(float(loss)) and not bool(found_inf)
    # master params stay fp32
    assert params["encoder"][0]["attention"]["qkv"]["kernel"].dtype \
        == jnp.float32


def test_bert_partition_specs_cover_tree():
    from jax.sharding import PartitionSpec as P
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    specs = bert_partition_specs(params)
    flat_p = jax.tree_util.tree_leaves(params)
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert len(flat_p) == len(flat_s)
    qkv = specs["encoder"][0]["attention"]["qkv"]
    assert qkv["kernel"] == P(None, "model") and qkv["bias"] == P("model")
    assert specs["encoder"][0]["mlp"]["fc2"]["kernel"] == P("model", None)
    assert specs["embeddings"]["word"]["embedding"] == P("model", None)


def test_resnet18_forward_and_step():
    params, stats = init_resnet(jax.random.PRNGKey(0), 18, num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (2,), 0, 10)
    # jitted: eager per-op dispatch of the whole stack costs ~11 s on
    # the 1-core host; the compiled program lands in the persistent
    # test cache
    logits, new_stats = jax.jit(
        lambda p, s, x: apply_resnet(p, s, x, 18, train=True))(
        params, stats, x)
    assert logits.shape == (2, 10)
    # running stats updated
    assert not np.allclose(np.asarray(new_stats["stem_bn"]["mean"]),
                           np.asarray(stats["stem_bn"]["mean"]))
    # eval mode leaves stats untouched
    _, same = jax.jit(
        lambda p, s, x: apply_resnet(p, s, x, 18, train=False))(
        params, stats, x)
    np.testing.assert_array_equal(np.asarray(same["stem_bn"]["mean"]),
                                  np.asarray(stats["stem_bn"]["mean"]))

    opt = FusedSGD(lr=5e-3, momentum=0.9)
    state = opt.init(params)

    @jax.jit
    def step(params, stats, state):
        def f(p):
            logits, ns = apply_resnet(p, stats, x, 18, train=True)
            return cross_entropy_loss(logits, y), ns
        (loss, ns), grads = jax.value_and_grad(f, has_aux=True)(params)
        params, state = opt.step(grads, params, state)
        return params, ns, state, loss

    losses = []
    for _ in range(6):
        params, stats, state, loss = step(params, stats, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_resnet50_builds():
    params, stats = init_resnet(jax.random.PRNGKey(0), 50, num_classes=10)
    x = jnp.ones((1, 64, 64, 3))
    logits, _ = jax.jit(
        lambda p, s, x: apply_resnet(p, s, x, 50, train=False))(
        params, stats, x)
    assert logits.shape == (1, 10)

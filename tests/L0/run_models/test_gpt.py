"""GPT parity tests (ref: ``apex/transformer/testing/standalone_gpt.py``,
exercised upstream by ``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd``):
the TP=8 shard_map model must match the unsharded jnp golden path in loss
AND gradients; the pipeline adapter must match both."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import (
    GPTModel,
    gpt_loss_unsharded,
    gpt_partition_specs,
    gpt_pipeline_model,
    gpt_tiny,
    gpt_to_pipeline_params,
    init_gpt,
)
from apex_tpu.transformer import parallel_state as ps

# S=16 halves the attention/scan work of every config vs the original
# 32 and keeps the SP divisibility (tp=2 | S) intact (d=64 PR); B drops
# 4->2 with num_microbatches 4->2 (B must stay divisible — the
# schedules mask the extra warmup ticks, so M < pp is fine) — suite-time
# satellite of the optimizer-state PR. S can't shrink further: the cp=8
# ring needs 2 causal chunks per rank (16 | S).
B, S, MICROBATCHES = 2, 16, 2


def _data(cfg):
    k1, k2 = jax.random.split(jax.random.PRNGKey(42))
    ids = jax.random.randint(k1, (B, S), 0, cfg.vocab_size)
    labels = jax.random.randint(k2, (B, S), 0, cfg.vocab_size)
    return ids, labels


@pytest.mark.parametrize("use_rope,sequence_parallel", [
    (False, False), (True, False), (False, True)])
def test_tp8_loss_and_grads_match_unsharded(use_rope, sequence_parallel):
    cfg = gpt_tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "use_rope": use_rope,
                       "sequence_parallel": sequence_parallel})
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size_=8)
    model = GPTModel(cfg, tp_size=8)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)

    want_loss, want_grads = jax.value_and_grad(
        lambda p: gpt_loss_unsharded(p, cfg, ids, labels))(params)

    specs = model.partition_specs()

    def loss_and_grads(p, ids, labels):
        loss, grads = jax.value_and_grad(model.loss, argnums=0)(
            p, ids, labels)
        # SP: LN/Row-bias grads are per-rank partial sums (ref: Megatron
        # allreduces sequence-parallel grads after backward)
        return loss, model.allreduce_sequence_parallel_grads(grads)

    got_loss, got_grads = ps.shard_map(
        loss_and_grads,
        in_specs=(specs, P(), P()), out_specs=(P(), specs))(
        params, ids, labels)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        got_grads, want_grads)


def test_tp1_runs_without_sharding_surprises():
    """tp=1 mesh: the same TP code path must reproduce the golden loss."""
    cfg = gpt_tiny()
    ps.initialize_model_parallel(tensor_model_parallel_size_=1)
    model = GPTModel(cfg, tp_size=1)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)
    want = gpt_loss_unsharded(params, cfg, ids, labels)
    got = ps.shard_map(model.loss, in_specs=(model.partition_specs(),
                                             P(), P()),
                       out_specs=P())(params, ids, labels)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


@pytest.mark.parametrize("pp,vpp,tp,sp,rope", [
    (2, None, 1, False, False), (4, None, 1, False, False),
    (2, 2, 1, False, False), (2, None, 2, True, False),
    (2, None, 2, True, True)])
def test_pipeline_gpt_matches_unsharded(pp, vpp, tp, sp, rope):
    """GPT through the collective pipeline schedules — loss parity with
    the unsharded model and grad parity for the stages (incl. the
    tp=2 + sequence-parallel combination riding the pipe, with and
    without RoPE — the rotary table must span the GLOBAL sequence even
    though stage_fn sees the seq-sharded hidden)."""
    from apex_tpu.transformer.pipeline_parallel import schedules

    cfg = gpt_tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "sequence_parallel": sp,
                       "use_rope": rope})
    ps.initialize_model_parallel(
        tensor_model_parallel_size_=tp,
        pipeline_model_parallel_size_=pp,
        virtual_pipeline_model_parallel_size_=vpp)
    model = GPTModel(cfg, tp_size=tp)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)
    batch = {"input_ids": ids, "labels": labels}

    pipe_params = gpt_to_pipeline_params(params, cfg, pp, vpp)
    pipe_model = gpt_pipeline_model(model)
    fwd_bwd = (schedules.forward_backward_pipelining_with_interleaving
               if vpp else
               schedules.forward_backward_pipelining_without_interleaving)

    from apex_tpu.models.gpt import gpt_pipeline_partition_specs

    specs = gpt_pipeline_partition_specs(cfg, vpp)

    kw = {"virtual_pipeline_size": vpp} if vpp else {}

    def run(p, b):
        loss, grads = fwd_bwd(pipe_model, p, b,
                              num_microbatches=MICROBATCHES, **kw)
        return loss, model.allreduce_sequence_parallel_grads(grads)

    loss, grads = jax.jit(ps.shard_map(
        run, in_specs=(specs, P()), out_specs=(P(), specs)))(
        pipe_params, batch)

    # golden: microbatched unsharded loss (same microbatch mean-of-means)
    want_loss = gpt_loss_unsharded(params, cfg, ids, labels)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)

    # grads: tied embedding table accumulates from BOTH the embed lookup
    # and the LM head (the reference's shared-embedding allreduce adds the
    # two stage copies); everything else maps 1:1
    want_grads = jax.grad(
        lambda p: gpt_loss_unsharded(p, cfg, ids, labels))(params)
    want_pipe = gpt_to_pipeline_params(want_grads, cfg, pp, vpp)
    got_word = (grads["embed"]["word"]["embedding"]
                + grads["head"]["word"]["embedding"])
    np.testing.assert_allclose(
        np.asarray(got_word),
        np.asarray(want_pipe["embed"]["word"]["embedding"]),
        rtol=2e-4, atol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        grads["stages"], want_pipe["stages"])
    np.testing.assert_allclose(
        np.asarray(grads["head"]["final_ln"]["weight"]),
        np.asarray(want_grads["final_ln"]["weight"]),
        rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("use_rope,tp,cp,impl", [
    (False, 1, 8, "ring"), (True, 1, 8, "ring"), (False, 2, 4, "ring"),
    (True, 1, 8, "ulysses"), (False, 2, 4, "ulysses")])
def test_context_parallel_matches_unsharded(use_rope, tp, cp, impl):
    """Long-context GPT: ids/labels sequence-sharded over the context
    axis, ring OR Ulysses attention inside — loss AND grads must match
    the unsharded model (incl. composed with tp=2)."""
    cfg = gpt_tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "use_rope": use_rope,
                       "context_parallel": True,
                       "context_parallel_impl": impl})
    ps.initialize_model_parallel(tensor_model_parallel_size_=tp,
                                 context_parallel_size_=cp)
    model = GPTModel(cfg, tp_size=tp)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)

    want_loss, want_grads = jax.value_and_grad(
        lambda p: gpt_loss_unsharded(p, cfg, ids, labels))(params)

    specs = model.partition_specs()
    seq_sharded = P(None, ps.CONTEXT_AXIS)

    def run(p, ids, labels):
        loss, grads = jax.value_and_grad(model.loss, argnums=0)(
            p, ids, labels)
        # CP shards TOKENS the way DP shards the batch: each rank's AD
        # yields d(local token mean)/dp, so the closure is the standard
        # DDP one — pmean the grads over the context axis (psum alone
        # measured exactly cp× too big)
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(g, ps.CONTEXT_AXIS), grads)
        return loss, grads

    got_loss, got_grads = jax.jit(ps.shard_map(
        run, in_specs=(specs, seq_sharded, seq_sharded),
        out_specs=(P(), specs)))(params, ids, labels)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_grads, want_grads)


def test_pipeline_param_roundrobin_layout():
    """chunk c lives at [lane c//pp, dev c%pp] — reference round-robin."""
    cfg = type(gpt_tiny())(**{**gpt_tiny().__dict__, "num_layers": 8})
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    flat = params["layers"]["fc1"]["kernel"]  # (8, h, f)
    pp, vpp = 2, 2
    stacked = gpt_to_pipeline_params(params, cfg, pp, vpp)
    got = stacked["stages"]["fc1"]["kernel"]  # (vpp, pp, 2, h, f)
    # chunk 3 (= lane 1, dev 1) holds layers 6, 7
    np.testing.assert_array_equal(np.asarray(got[1, 1, 0]),
                                  np.asarray(flat[6]))
    np.testing.assert_array_equal(np.asarray(got[1, 1, 1]),
                                  np.asarray(flat[7]))


def test_dropout_active_and_deterministic():
    cfg = gpt_tiny()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)
    l1 = gpt_loss_unsharded(params, cfg, ids, labels,
                            dropout_rng=jax.random.PRNGKey(7))
    l2 = gpt_loss_unsharded(params, cfg, ids, labels,
                            dropout_rng=jax.random.PRNGKey(7))
    l3 = gpt_loss_unsharded(params, cfg, ids, labels,
                            dropout_rng=jax.random.PRNGKey(8))
    assert float(l1) == float(l2)
    assert float(l1) != float(l3)


def test_remat_policy_selective_matches_and_validates():
    """remat_policy='dots_saveable' (selective recompute) must be loss-
    AND grad-identical to full remat — jax.checkpoint changes only WHAT
    is stored, never the math; a bad policy name fails loudly."""
    cfg = gpt_tiny()
    full = type(cfg)(**{**cfg.__dict__, "remat": True})
    sel = type(cfg)(**{**cfg.__dict__, "remat": True,
                       "remat_policy": "dots_saveable"})
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids, labels = _data(cfg)

    def lg(c):
        return jax.value_and_grad(
            lambda p: gpt_loss_unsharded(p, c, ids, labels))(params)

    l1, g1 = lg(full)
    l2, g2 = lg(sel)
    assert float(l1) == float(l2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), g1, g2)

    bad = type(cfg)(**{**cfg.__dict__, "remat": True,
                       "remat_policy": "not_a_policy"})
    with pytest.raises(ValueError, match="not_a_policy"):
        gpt_loss_unsharded(params, bad, ids, labels)

    # factory members of jax.checkpoint_policies ARE callable but take
    # names/policies, not residuals — the allowlist must reject them at
    # config time, not let jax.checkpoint fail deep inside the scan
    factory = type(cfg)(**{**cfg.__dict__, "remat": True,
                           "remat_policy": "save_only_these_names"})
    with pytest.raises(ValueError, match="save_only_these_names"):
        gpt_loss_unsharded(params, factory, ids, labels)


def test_bench_hook_smoke():
    from apex_tpu.models.gpt import gpt_tp_bench

    # tp=2 keeps the hook-contract check ~4x cheaper than tp=8 on the
    # 1-core host; the tp=8 math itself is covered by the tp8 tests
    body, make_init, fetch, batch = gpt_tp_bench(False, 2)
    state = body(make_init())
    assert np.isfinite(float(fetch(state)))

"""Determinism + profiling-hook tests (SURVEY §5 rows 1–2: same seed ⇒
bitwise-equal training; named scopes visible to the tracer)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.models import (
    apply_bert, bert_tiny, gpt_loss_unsharded, gpt_tiny, init_bert,
    init_gpt, mlm_loss,
)
from apex_tpu.optimizers import FusedAdam


def _bert_train_step(seed):
    """One full amp-O2 + FusedAdam + dropout train step, from scratch."""
    cfg = bert_tiny()
    h = amp.initialize(opt_level="O2", loss_scale="dynamic", verbosity=0)
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-3)
    opt_state = opt.init(params)
    scaler_state = h.init_state()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    mask = jnp.ones_like(ids)

    @jax.jit
    def step(master, opt_state, scaler_state, rng):
        p = h.cast_model(master)

        def loss_fn(p):
            out = apply_bert(p, cfg, ids, mask, dropout_rng=rng)
            return mlm_loss(out["mlm_logits"], ids, mask)

        loss, grads, found_inf, scaler_state = h.value_and_grad(loss_fn)(
            p, scaler_state)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        return master, loss

    master, loss = step(params, opt_state, scaler_state,
                        jax.random.PRNGKey(seed))
    return np.asarray(loss), jax.tree.map(np.asarray, master)


def test_same_seed_bitwise_identical_train_step():
    loss_a, params_a = _bert_train_step(seed=7)
    loss_b, params_b = _bert_train_step(seed=7)
    assert loss_a.tobytes() == loss_b.tobytes()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b, strict=True),
        params_a, params_b)


def test_different_seed_differs():
    loss_a, _ = _bert_train_step(seed=7)
    loss_c, _ = _bert_train_step(seed=8)
    assert loss_a.tobytes() != loss_c.tobytes()


def test_gpt_dropout_bitwise_deterministic():
    cfg = gpt_tiny()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    f = jax.jit(lambda rng: gpt_loss_unsharded(
        params, cfg, ids, ids, dropout_rng=rng))
    a = np.asarray(f(jax.random.PRNGKey(3)))
    b = np.asarray(f(jax.random.PRNGKey(3)))
    assert a.tobytes() == b.tobytes()


def _hlo_with_metadata(lowered):
    """Text form of a lowered computation that still carries scope names.
    Newer jax exposes them on the Lowered (``debug_info=True``); older
    releases strip locs from ``as_text()`` and only the compiled HLO's
    op metadata keeps them."""
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:
        return lowered.compile().as_text()


def test_named_scopes_reach_hlo_metadata():
    """The profiler hooks are real: scope names survive into the lowered
    HLO's metadata (what the trace viewer attributes kernels to)."""
    cfg = bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((1, 16), jnp.int32)
    txt = _hlo_with_metadata(jax.jit(
        lambda p: apply_bert(p, cfg, ids, jnp.ones_like(ids))["hidden"]
    ).lower(params))
    assert "layer0/attention" in txt
    assert "layer0/mlp" in txt

    opt = FusedAdam(lr=1e-3)
    st = opt.init({"w": jnp.ones((4,))})
    txt = _hlo_with_metadata(jax.jit(
        lambda g, p, s: opt.step(g, p, s)
    ).lower({"w": jnp.ones((4,))}, {"w": jnp.ones((4,))}, st))
    assert "FusedAdam.step" in txt


def test_profiler_trace_writes_files(tmp_path):
    from apex_tpu.utils.profiler import annotate, trace

    with trace(str(tmp_path)):
        with annotate("traced_region"):
            jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))
                    ).block_until_ready()
    found = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                      recursive=True)
    assert found, f"no trace written under {tmp_path}"

"""Golden-model tests for Pallas LayerNorm/RMSNorm.

Mirrors the reference's ``tests/L0/run_fused_layer_norm/`` strategy: compare
the fused kernels against a plain framework implementation (here pure jnp in
fp32) under dtype-scaled tolerances, fwd and bwd, affine and plain, fp32 and
bf16, including shapes that don't divide the row tile.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)


def ref_layer_norm(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def ref_rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    return y.astype(x.dtype)


def tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(
        rtol=2e-5, atol=2e-5)


SHAPES = [((32, 256), 256), ((4, 17, 384), 384), ((3, 1024), 1024),
          # large H exercises the column-split backward (incl. a hidden
          # size that doesn't divide the column tile)
          ((12, 4096), 4096), ((9, 2816), 2816)]


@pytest.mark.parametrize("shape,h", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_affine_fwd_bwd(shape, h, dtype):
    k = jax.random.PRNGKey(0)
    kx, kw, kb, kg = jax.random.split(k, 4)
    x = jax.random.normal(kx, shape, dtype) * 2 + 1
    w = jax.random.normal(kw, (h,), jnp.float32) * 0.5 + 1
    b = jax.random.normal(kb, (h,), jnp.float32) * 0.1
    dy = jax.random.normal(kg, shape, dtype)

    got = fused_layer_norm_affine(x, w, b, h)
    want = ref_layer_norm(x, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **tol(dtype))

    def loss_f(f):
        def inner(x, w, b):
            return jnp.sum(f(x, w, b).astype(jnp.float32) * dy.astype(jnp.float32))
        return inner

    gx, gw, gb = jax.grad(loss_f(lambda x, w, b: fused_layer_norm_affine(x, w, b, h)),
                          argnums=(0, 1, 2))(x, w, b)
    rx, rw, rb = jax.grad(loss_f(lambda x, w, b: ref_layer_norm(x, w, b, 1e-5)),
                          argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), **tol(dtype))
    # weight grads sum over all rows — scale atol with the row count
    n_rows = int(np.prod(shape[:-1]))
    wtol = dict(rtol=2e-2, atol=1e-2 * max(1, n_rows) ** 0.5) \
        if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), **wtol)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), **wtol)


@pytest.mark.parametrize("shape,h", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_norm_affine_fwd_bwd(shape, h, dtype):
    k = jax.random.PRNGKey(1)
    kx, kw, kg = jax.random.split(k, 3)
    x = jax.random.normal(kx, shape, dtype)
    w = jax.random.normal(kw, (h,), jnp.float32) * 0.5 + 1
    dy = jax.random.normal(kg, shape, dtype)

    got = fused_rms_norm_affine(x, w, h)
    want = ref_rms_norm(x, w, 1e-5)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **tol(dtype))

    def mk(f):
        def inner(x, w):
            return jnp.sum(f(x, w).astype(jnp.float32) * dy.astype(jnp.float32))
        return inner

    gx, gw = jax.grad(mk(lambda x, w: fused_rms_norm_affine(x, w, h)),
                      argnums=(0, 1))(x, w)
    rx, rw = jax.grad(mk(lambda x, w: ref_rms_norm(x, w, 1e-5)),
                      argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), **tol(dtype))
    n_rows = int(np.prod(shape[:-1]))
    wtol = dict(rtol=2e-2, atol=1e-2 * max(1, n_rows) ** 0.5) \
        if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), **wtol)


def test_no_affine_variants():
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 128), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fused_layer_norm(x, 128)),
        np.asarray(ref_layer_norm(x, None, None, 1e-5)), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(fused_rms_norm(x, 128)),
        np.asarray(ref_rms_norm(x, None, 1e-5)), rtol=2e-5, atol=2e-5)
    # grads flow with no affine params
    g = jax.grad(lambda x: jnp.sum(fused_layer_norm(x, 128)))(x)
    r = jax.grad(lambda x: jnp.sum(ref_layer_norm(x, None, None, 1e-5)))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=2e-5, atol=2e-5)


def test_module_api():
    ln = FusedLayerNorm(256)
    p = ln.init()
    assert p["weight"].shape == (256,) and p["bias"].shape == (256,)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 256))
    np.testing.assert_allclose(
        np.asarray(ln.apply(p, x)),
        np.asarray(ref_layer_norm(x, p["weight"], p["bias"], 1e-5)),
        rtol=2e-5, atol=2e-5)

    rms = FusedRMSNorm(256)
    pr = rms.init()
    assert "bias" not in pr
    np.testing.assert_allclose(
        np.asarray(rms.apply(pr, x)),
        np.asarray(ref_rms_norm(x, pr["weight"], 1e-5)),
        rtol=2e-5, atol=2e-5)


def test_jit_and_multidim_normalized_shape():
    x = jax.random.normal(jax.random.PRNGKey(4), (6, 4, 64), jnp.float32)
    w = jnp.ones((4, 64)); b = jnp.zeros((4, 64))
    f = jax.jit(lambda x, w, b: fused_layer_norm_affine(x, w, b, (4, 64)))
    np.testing.assert_allclose(
        np.asarray(f(x, w, b)),
        np.asarray(ref_layer_norm(x.reshape(6, -1), w.reshape(-1),
                                  b.reshape(-1), 1e-5).reshape(x.shape)),
        rtol=2e-5, atol=2e-5)

"""ZeRO distributed-optimizer tests (ref:
``apex/contrib/test/optimizers/test_distributed_fused_adam.py`` — parity
of DistributedFusedAdam against single-process Adam, plus the sharded
state-memory claim)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.transformer import parallel_state as ps

DP = 8


def make_params(key):
    # sized to stay meaningful on the DP=8 mesh while keeping the suite
    # fast: emb (64x32 = 2048 elems = 16 flat 128-rows) still spans
    # several of the 8 shards (the trust-ratio and checkpoint tests
    # depend on that), "scale" stays deliberately non-128-aligned
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "dense": {"w": jax.random.normal(k1, (32, 16)),
                  "b": jnp.zeros((16,))},
        "emb": jax.random.normal(k2, (64, 32)) * 0.1,
        "scale": jax.random.normal(k3, (7,)),
    }


def per_rank_grads(key, params, n=DP):
    """(n, ...) stacked per-rank grads whose mean is the DDP gradient."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, len(leaves))
    stacked = [jax.random.normal(k, (n,) + l.shape) * 0.1
               for k, l in zip(keys, leaves)]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def dp_mesh():
    return ps.initialize_model_parallel()  # all 8 devices on the data axis


def _zero_step(opt, params, opt_state, grads_stacked, **kw):
    """Run opt.step inside a dp=8 shard_map; grads arrive rank-local.

    params/state/grads are ARGUMENTS of one jitted program cached per
    (opt, kw) — the previous shape closed over the current params, so
    every loop iteration traced and compiled a brand-new program with
    the params baked in as constants (~2/3 of this module's wall)."""
    key = (id(opt), tuple(sorted(kw.items())))
    step = _zero_step._cache.get(key)
    if step is None:
        sspec = opt.partition_spec()

        def body(g, p, st):
            return opt.step(g, p, st, **kw)

        pspec = jax.tree.map(lambda _: P(), params)
        step = jax.jit(ps.shard_map(
            body,
            in_specs=(jax.tree.map(lambda _: P(ps.DATA_AXIS),
                                   grads_stacked), pspec, sspec),
            out_specs=(pspec, sspec)))
        _zero_step._cache[key] = step
    return step(grads_stacked, params, opt_state)


_zero_step._cache = {}


@pytest.mark.parametrize("opt_cls,ref_cls,kw", [
    (DistributedFusedAdam, FusedAdam, dict(weight_decay=0.01)),
    (DistributedFusedAdam, FusedAdam, dict(adam_w_mode=False,
                                           weight_decay=0.1)),
    (DistributedFusedLAMB, FusedLAMB, dict(weight_decay=0.01)),
])
def test_matches_unsharded_reference(opt_cls, ref_cls, kw):
    """Several ZeRO steps == the replicated fused optimizer stepping on
    the rank-MEAN gradient (2 steps: step 2 exercises the nonzero-state
    recurrence, which is where a sharding bug would surface)."""
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    opt = opt_cls(lr=1e-2, dp_size=DP, **kw)
    ref = ref_cls(lr=1e-2, **kw)
    st = opt.init(params)
    ref_params, ref_st = params, ref.init(params)

    for i in range(2):
        gs = per_rank_grads(jax.random.PRNGKey(10 + i), params)
        new_params, st = _zero_step(opt, params, st, gs)
        mean_g = jax.tree.map(lambda a: a.mean(0), gs)
        if ref_cls is FusedLAMB:
            # the distributed grad-norm clip sees the mean grad too
            ref_params, ref_st = ref.step(mean_g, ref_params, ref_st)
        else:
            ref_params, ref_st = ref.step(mean_g, ref_params, ref_st)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
            new_params, ref_params)
        params = new_params


def test_overflow_skips_everywhere():
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, dp_size=DP)
    st = opt.init(params)
    gs = per_rank_grads(jax.random.PRNGKey(1), params)

    # found_inf True on ONE rank only must freeze params + state globally
    flags = jnp.arange(DP) == 3

    def body(g, f, st):
        return opt.step(g, params, st, found_inf=f[0])

    sspec = opt.partition_spec()
    new_params, new_st = ps.shard_map(
        body,
        in_specs=(jax.tree.map(lambda _: P(ps.DATA_AXIS), gs),
                  P(ps.DATA_AXIS), sspec),
        out_specs=(jax.tree.map(lambda _: P(), params), sspec))(
        gs, flags, st)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), new_params, params)
    assert int(new_st.step) == 0
    np.testing.assert_array_equal(np.asarray(new_st.m),
                                  np.asarray(st.m))


def test_state_is_sharded_at_rest():
    """device_put with partition_spec → each device stores ~1/dp of the
    optimizer state (the ZeRO memory claim, asserted in bytes)."""
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(dp_size=DP)
    st = opt.init(params)
    sharded_m = jax.device_put(
        st.m, NamedSharding(mesh, opt.partition_spec().m))
    shard_bytes = sharded_m.addressable_shards[0].data.nbytes
    assert shard_bytes * DP == st.m.nbytes
    assert opt.state_bytes_per_device(params) == 3 * shard_bytes


def test_grad_scale_unscales():
    """grad_scale=1/S on S-scaled grads == unscaled run (multiply
    convention)."""
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    gs = per_rank_grads(jax.random.PRNGKey(2), params)
    S = 2.0 ** 12

    opt = DistributedFusedAdam(lr=1e-2, dp_size=DP)
    p_plain, _ = _zero_step(opt, params, opt.init(params), gs)
    opt2 = DistributedFusedAdam(lr=1e-2, dp_size=DP)
    p_scaled, _ = _zero_step(
        opt2, params, opt2.init(params),
        jax.tree.map(lambda a: a * S, gs), grad_scale=1.0 / S)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        p_plain, p_scaled)


def test_lamb_trust_ratio_spans_shards():
    """A tensor bigger than one shard (emb: 64x32 = 16 flat rows over 8
    ranks) still gets ONE coherent trust ratio — compare against
    FusedLAMB where each leaf is a whole tensor."""
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(3))
    gs = per_rank_grads(jax.random.PRNGKey(4), params)
    opt = DistributedFusedLAMB(lr=5e-2, weight_decay=0.01, dp_size=DP)
    ref = FusedLAMB(lr=5e-2, weight_decay=0.01)
    got, _ = _zero_step(opt, params, opt.init(params), gs)
    want, _ = ref.step(jax.tree.map(lambda a: a.mean(0), gs), params,
                       ref.init(params))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6), got, want)


def test_sharded_checkpoint_resume(tmp_path):
    """ZeRO save/resume WITHOUT un-sharding (round-3 verdict missing #4):
    every stored shard of a sharded leaf is 1/dp of the leaf, and a run
    resumed from the sharded file continues bit-identically to an
    uninterrupted one."""
    import pickle

    from apex_tpu.utils.checkpoint import (
        load_sharded_checkpoint, save_sharded_checkpoint,
    )

    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, dp_size=DP)
    st = opt.init(params)
    # physically shard the state over the data axis (the at-rest layout)
    st = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
        if getattr(a, "ndim", 0) else a, st, opt.partition_spec())

    for i in range(2):
        params, st = _zero_step(
            opt, params, st, per_rank_grads(jax.random.PRNGKey(i), params))

    path = str(tmp_path / "zero.ckpt")
    save_sharded_checkpoint(path, st)

    # on-disk layout: sharded leaves stored as DP shards of 1/DP rows each
    recs = pickle.load(open(path, "rb"))
    sharded = [r for r in recs if r["kind"] == "sharded"]
    assert len(sharded) == 3  # master, m, v (step is a dense scalar)
    for r in sharded:
        assert len(r["shards"]) == DP
        for arr in r["shards"].values():
            assert arr.shape[0] == r["shape"][0] // DP

    # uninterrupted continuation
    g3 = per_rank_grads(jax.random.PRNGKey(99), params)
    want_params, want_st = _zero_step(opt, params, st, g3)

    # resumed continuation: template = a fresh sharded init
    st2 = opt.init(params)
    st2 = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
        if getattr(a, "ndim", 0) else a, st2, opt.partition_spec())
    st_resumed = load_sharded_checkpoint(path, st2)
    assert not st_resumed.m.sharding.is_fully_replicated
    got_params, _ = _zero_step(opt, params, st_resumed, g3)

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), got_params, want_params)


@pytest.mark.parametrize("opt_cls", [DistributedFusedAdam,
                                     DistributedFusedLAMB])
def test_bf16_moment_shard_tracks_fp32(opt_cls):
    """ZeRO with bf16 first moment: the per-device state formula drops to
    (4+4+2)/(4+4+4) of fp32, m is physically bf16 at rest, and the runs
    stay within bf16-moment tolerance of the fp32-state run."""
    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(5))
    opt32 = opt_cls(lr=1e-2, weight_decay=0.01, dp_size=DP)
    optbf = opt_cls(lr=1e-2, weight_decay=0.01, dp_size=DP,
                    m_dtype=jnp.bfloat16)
    assert optbf.state_bytes_per_device(params) * 12 == \
        opt32.state_bytes_per_device(params) * 10

    p32, st32 = params, opt32.init(params)
    pbf, stbf = params, optbf.init(params)
    assert stbf.m.dtype == jnp.bfloat16
    for i in range(2):
        gs = per_rank_grads(jax.random.PRNGKey(40 + i), params)
        p32, st32 = _zero_step(opt32, p32, st32, gs)
        pbf, stbf = _zero_step(optbf, pbf, stbf, gs)
    assert stbf.m.dtype == jnp.bfloat16
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4), pbf, p32)


def test_sharded_checkpoint_roundtrip_bf16_m(tmp_path):
    """The sharded checkpoint must preserve the bf16 m dtype through
    save/load and resume bit-identically (ISSUE: bf16 shards round-trip)."""
    from apex_tpu.utils.checkpoint import (
        load_sharded_checkpoint, save_sharded_checkpoint,
    )

    mesh = dp_mesh()
    params = make_params(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, dp_size=DP,
                               m_dtype=jnp.bfloat16)
    st = opt.init(params)
    st = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
        if getattr(a, "ndim", 0) else a, st, opt.partition_spec())
    for i in range(2):
        params, st = _zero_step(
            opt, params, st, per_rank_grads(jax.random.PRNGKey(i), params))

    path = str(tmp_path / "zero_bf16m.ckpt")
    save_sharded_checkpoint(path, st)

    st2 = opt.init(params)
    st2 = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
        if getattr(a, "ndim", 0) else a, st2, opt.partition_spec())
    st_resumed = load_sharded_checkpoint(path, st2)
    assert st_resumed.m.dtype == jnp.bfloat16
    assert not st_resumed.m.sharding.is_fully_replicated
    np.testing.assert_array_equal(
        np.asarray(st_resumed.m, np.float32), np.asarray(st.m, np.float32))

    g3 = per_rank_grads(jax.random.PRNGKey(99), params)
    want_params, _ = _zero_step(opt, params, st, g3)
    got_params, _ = _zero_step(opt, params, st_resumed, g3)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), got_params, want_params)

"""Golden-model optimizer tests (ref: ``tests/L0/run_optimizers`` compares
FusedAdam/LAMB against torch.optim within tolerances; here against optax
and manual formulas)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdagrad, FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD,
)


def make_params(key, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "dense": {"w": jax.random.normal(k1, (64, 32), dtype),
                  "b": jnp.zeros((32,), dtype)},
        "emb": jax.random.normal(k2, (100, 64), dtype) * 0.1,
        "scale": jax.random.normal(k3, (7,), dtype),
    }


def make_grads(key, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, l.shape, l.dtype)
                  for k, l in zip(keys, leaves)])


def run_steps(opt, params, n=5, seed=0, **kw):
    state = opt.init(params)
    for i in range(n):
        grads = make_grads(jax.random.PRNGKey(seed + i), params)
        params, state = opt.step(grads, params, state, **kw)
    return params, state


def run_optax(tx, params, n=5, seed=0):
    state = tx.init(params)
    for i in range(n):
        grads = make_grads(jax.random.PRNGKey(seed + i), params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x, np.float32), np.asarray(y, np.float32),
        rtol=rtol, atol=atol), a, b)


def test_fused_adam_matches_optax_adamw():
    params = make_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2, weight_decay=0.05, adam_w_mode=True)
    got, _ = run_steps(opt, params)
    want = run_optax(optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                                 weight_decay=0.05), params)
    assert_trees_close(got, want)


def test_fused_adam_l2_mode_matches_optax_adam_with_l2():
    params = make_params(jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-2, weight_decay=0.05, adam_w_mode=False)
    got, _ = run_steps(opt, params)
    want = run_optax(optax.chain(optax.add_decayed_weights(0.05),
                                 optax.scale_by_adam(),
                                 optax.scale(-1e-2)), params)
    assert_trees_close(got, want)


def test_fused_adam_flat_kernel_matches_tree_path():
    params = make_params(jax.random.PRNGKey(2))
    got, _ = run_steps(FusedAdam(lr=3e-3, weight_decay=0.01,
                                 use_flat_kernel=True), params)
    want, _ = run_steps(FusedAdam(lr=3e-3, weight_decay=0.01), params)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kw", [
    dict(momentum=0.9, weight_decay=1e-4),
    dict(momentum=0.9, nesterov=True),
    dict(momentum=0.9, weight_decay=1e-4, wd_after_momentum=True),
    dict(),  # plain SGD, no momentum
])
def test_fused_sgd_flat_kernel_matches_tree_path(kw):
    params = make_params(jax.random.PRNGKey(3))
    got, _ = run_steps(FusedSGD(lr=1e-2, use_flat_kernel=True, **kw),
                       params)
    want, _ = run_steps(FusedSGD(lr=1e-2, **kw), params)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kw", [
    dict(weight_decay=0.01),
    dict(weight_decay=0.01, adam_w_mode=False),
    dict(weight_decay=0.0, use_nvlamb=True),
    dict(weight_decay=0.01, max_grad_norm=0.05),  # clip engages
])
def test_fused_lamb_flat_kernel_matches_tree_path(kw):
    params = make_params(jax.random.PRNGKey(4))
    got, _ = run_steps(FusedLAMB(lr=1e-2, use_flat_kernel=True, **kw),
                       params)
    want, _ = run_steps(FusedLAMB(lr=1e-2, **kw), params)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kw", [
    dict(weight_decay=0.01),
    dict(weight_decay=0.01, reg_inside_moment=True),
    dict(weight_decay=0.0, grad_averaging=False),
    dict(weight_decay=0.01, init_zero=True),
])
def test_fused_novograd_flat_kernel_matches_tree_path(kw):
    params = make_params(jax.random.PRNGKey(5))
    got, _ = run_steps(FusedNovoGrad(lr=1e-2, use_flat_kernel=True, **kw),
                       params)
    want, _ = run_steps(FusedNovoGrad(lr=1e-2, **kw), params)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kw", [
    dict(weight_decay=0.01),
    dict(weight_decay=0.01, adagrad_w_mode=True),
])
def test_fused_adagrad_flat_kernel_matches_tree_path(kw):
    params = make_params(jax.random.PRNGKey(6))
    got, _ = run_steps(FusedAdagrad(lr=1e-2, use_flat_kernel=True, **kw),
                       params)
    want, _ = run_steps(FusedAdagrad(lr=1e-2, **kw), params)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


def test_fused_adam_skips_on_overflow():
    params = make_params(jax.random.PRNGKey(3))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    grads = make_grads(jax.random.PRNGKey(9), params)
    new_p, new_s = opt.step(grads, params, state,
                            found_inf=jnp.asarray(True))
    assert_trees_close(new_p, params, rtol=0, atol=0)
    assert int(new_s.step) == 0
    new_p, new_s = opt.step(grads, params, state,
                            found_inf=jnp.asarray(False))
    assert int(new_s.step) == 1
    with np.testing.assert_raises(AssertionError):
        assert_trees_close(new_p, params, rtol=0, atol=0)


def test_fused_sgd_matches_optax():
    params = make_params(jax.random.PRNGKey(4))
    got, _ = run_steps(FusedSGD(lr=0.1, momentum=0.9), params)
    # optax sgd with momentum: trace seeds buffer with grad on first step —
    # same as the reference/our first_run seeding
    want = run_optax(optax.sgd(0.1, momentum=0.9), params)
    assert_trees_close(got, want)


def test_fused_sgd_nesterov_and_wd():
    params = make_params(jax.random.PRNGKey(5))
    got, _ = run_steps(FusedSGD(lr=0.05, momentum=0.9, nesterov=True,
                                weight_decay=1e-4), params)
    want = run_optax(optax.chain(optax.add_decayed_weights(1e-4),
                                 optax.sgd(0.05, momentum=0.9,
                                           nesterov=True)), params)
    assert_trees_close(got, want)


def test_fused_lamb_matches_manual():
    """LAMB vs a straight-line manual implementation on one tensor."""
    p = jnp.asarray(np.random.RandomState(0).randn(32, 16), jnp.float32)
    g = jnp.asarray(np.random.RandomState(1).randn(32, 16), jnp.float32)

    opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=0.0,
                    grad_averaging=False)
    state = opt.init({"w": p})
    new_p, _ = opt.step({"w": g}, {"w": p}, state)

    b1, b2, eps, wd, lr = 0.9, 0.999, 1e-6, 0.01, 1e-2
    m = (1 - 0) * 0 + g  # grad_averaging=False => beta3=1
    m = b1 * 0 + 1.0 * g
    v = (1 - b2) * g * g
    mhat = m / (1 - b1)
    vhat = v / (1 - b2)
    u = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    ratio = jnp.linalg.norm(p) / jnp.linalg.norm(u)
    want = p - lr * ratio * u
    np.testing.assert_allclose(np.asarray(new_p["w"]), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_fused_lamb_grad_clipping():
    # A first Adam-style step normalizes uniform gradient scale away
    # (m_hat/sqrt(v_hat) is scale-invariant), so make clipping observable
    # through a large eps: update ~ g/(|g| + eps) differs strongly between
    # g ~ 100 (unclipped) and g ~ 0.125 (clipped to global norm 1).
    p = {"w": jnp.ones((8, 8), jnp.float32)}
    g = {"w": jnp.full((8, 8), 100.0, jnp.float32)}  # norm 800 >> 1.0
    opt = FusedLAMB(lr=1e-2, eps=1.0, max_grad_norm=1.0, weight_decay=0.0)
    clipped_p, _ = opt.step(g, p, opt.init(p))
    opt2 = FusedLAMB(lr=1e-2, eps=1.0, max_grad_norm=0.0, weight_decay=0.0)
    unclipped_p, _ = opt2.step(g, p, opt2.init(p))
    assert np.all(np.isfinite(np.asarray(clipped_p["w"])))
    assert not np.allclose(np.asarray(clipped_p["w"]),
                           np.asarray(unclipped_p["w"]))


def test_fused_novograd_manual_first_step():
    p = jnp.ones((4, 4), jnp.float32) * 2
    g = jnp.ones((4, 4), jnp.float32) * 0.5
    opt = FusedNovoGrad(lr=0.1, betas=(0.95, 0.98), weight_decay=0.1,
                        grad_averaging=False, bias_correction=False)
    state = opt.init({"w": p})
    new_p, new_s = opt.step({"w": g}, {"w": p}, state)
    v = float(jnp.sum(g * g))  # first-step seeding
    gn = g / (np.sqrt(v) + 1e-8)
    m = gn  # beta3 = 1, m0 = 0... m = b1*0 + 1*gn
    u = m + 0.1 * p
    want = p - 0.1 * u
    np.testing.assert_allclose(np.asarray(new_p["w"]), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(new_s.v["w"]), v, rtol=1e-5)


def test_fused_adagrad_matches_manual():
    # torch/apex adagrad puts eps OUTSIDE the sqrt (optax puts it inside),
    # so compare against the manual torch-semantics recurrence.
    params = make_params(jax.random.PRNGKey(6))
    got, _ = run_steps(FusedAdagrad(lr=0.05, eps=1e-10), params)

    want = params
    acc = jax.tree.map(jnp.zeros_like, params)
    for i in range(5):
        grads = make_grads(jax.random.PRNGKey(i), want)
        acc = jax.tree.map(lambda s, g: s + g * g, acc, grads)
        want = jax.tree.map(
            lambda p, g, s: p - 0.05 * g / (jnp.sqrt(s) + 1e-10),
            want, grads, acc)
    assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


def run_jit_steps(opt, params, n, seed=0, **kw):
    """n jitted steps (one compile) — makes the 100-step golden runs
    affordable on the CPU suite."""
    state = opt.init(params)
    step = jax.jit(lambda g, p, s: opt.step(g, p, s, **kw))
    for i in range(n):
        grads = make_grads(jax.random.PRNGKey(seed + i), params)
        params, state = step(grads, params, state)
    return params, state


@pytest.mark.parametrize("opt_cls,kw", [
    (FusedAdam, dict(weight_decay=0.01)),
    (FusedAdam, dict(weight_decay=0.01, use_flat_kernel=True)),
    (FusedLAMB, dict(weight_decay=0.01)),
    (FusedLAMB, dict(weight_decay=0.01, use_flat_kernel=True)),
])
def test_bf16_moment_tracks_fp32_golden_100_steps(opt_cls, kw):
    """bf16 first moment vs the fp32 golden run over >=100 steps: the
    round-to-nearest m store adds ~2^-9 relative noise per step; over
    100 steps the param drift stays inside mixed-precision tolerance
    (and far from the lr-scale divergence a broken accumulate gives)."""
    params = make_params(jax.random.PRNGKey(11))
    golden, gst = run_jit_steps(opt_cls(lr=1e-3, **kw), params, n=100)
    got, st = run_jit_steps(
        opt_cls(lr=1e-3, m_dtype=jnp.bfloat16, **kw), params, n=100)
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(st.m))
    assert_trees_close(got, golden, rtol=5e-3, atol=2e-3)
    # the runs must NOT be identical — proof the bf16 store really ran
    assert any(np.any(np.asarray(a) != np.asarray(b)) for a, b in zip(
        jax.tree.leaves(got), jax.tree.leaves(golden)))


@pytest.mark.parametrize("use_flat", [False, True])
def test_castout_bit_identical_to_master_cast(use_flat):
    """The fused cast-out must equal ``model_params_from_master`` BIT FOR
    BIT (both are one fp32->bf16 round-to-nearest of the same master),
    including mixed compute trees where some leaves stay fp32."""
    from apex_tpu.amp import policy

    params = make_params(jax.random.PRNGKey(12))
    compute = jax.tree_util.tree_map_with_path(
        lambda path, x: x if "scale" in str(path)
        else x.astype(jnp.bfloat16), params)
    opt = FusedAdam(lr=1e-2, weight_decay=0.01, use_flat_kernel=use_flat,
                    emit_compute_params=True)
    state = opt.init(params)
    for i in range(3):
        grads = make_grads(jax.random.PRNGKey(20 + i), params)
        params, state, compute = opt.step(
            grads, params, state, compute_params=compute)
        want = policy.model_params_from_master(params, compute)
        jax.tree.map(
            lambda c, w: np.testing.assert_array_equal(
                np.asarray(c, np.float32), np.asarray(w, np.float32)),
            compute, want)
        assert jax.tree.map(lambda c: c.dtype, compute) == \
            jax.tree.map(lambda c: c.dtype, want)


def test_castout_overflow_keeps_old_compute():
    params = make_params(jax.random.PRNGKey(13))
    opt = FusedAdam(lr=1e-2, emit_compute_params=True)
    state = opt.init(params)
    grads = make_grads(jax.random.PRNGKey(21), params)
    compute = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    new_p, new_s, new_c = opt.step(grads, params, state,
                                   compute_params=compute,
                                   found_inf=jnp.asarray(True))
    assert_trees_close(new_p, params, rtol=0, atol=0)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)),
        new_c, compute)


def test_bf16_params_keep_dtype():
    params = make_params(jax.random.PRNGKey(7), jnp.bfloat16)
    opt = FusedAdam(lr=1e-2)
    new_p, _ = run_steps(opt, params, n=2)
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(new_p))


def test_jit_step():
    params = make_params(jax.random.PRNGKey(8))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    grads = make_grads(jax.random.PRNGKey(10), params)

    @jax.jit
    def step(g, p, s, lr):
        return opt.step(g, p, s, lr=lr)

    p1, s1 = step(grads, params, state, 1e-3)
    p2, _ = opt.step(grads, params, state, lr=1e-3)
    assert_trees_close(p1, p2)
    assert int(s1.step) == 1

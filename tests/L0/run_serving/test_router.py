"""Chaos tier for the disaggregated serving tier (``serving.router``
+ ``serving.transfer``): two-replica prefill/decode split with
fault-tolerant page handoff, replica health, and failover.

The load-bearing contracts:

- FAULT-FREE IDENTITY — disaggregated committed streams are
  integer-identical to the colocated scheduler's (greedy and sampled,
  speculation on and off): the remote prefill runs the same jitted
  program and its pages ship verbatim, so there is nothing for the
  split to change;
- every injected transfer/replica fault yields a TYPED outcome and a
  recovered stream BIT-IDENTICAL to golden — retries, quarantines,
  colocated fallback and mid-stream failover are all invisible in the
  token streams (failover resumes via the preemption path: re-prefill
  from prompt + generated, keys fold token counts);
- corrupt payloads are quarantined at the checksum, never installed,
  never attended;
- the randomized multi-fault sweep replays bit-for-bit (outcomes,
  stats, injector counts, tick-clock event stream) under ``audit=True``.

``APEX_CHAOS_TRANSFER_SEED`` (comma-separated ints) overrides the
sweep's seed set — the CI chaos matrix fans one seed per leg and
uploads each leg's Perfetto dump.
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    ContinuousBatchingScheduler, DisaggregatedRouter, FaultInjector,
    PagedDecodeEngine, PageTransfer, Request, Tracer, TransferCorrupt,
    TransferFailed, FINISH_REASONS, transfer_checksum,
)
from apex_tpu.serving.paging import prefix_page_keys

pytestmark = pytest.mark.chaos

EOS = -1       # unreachable: healthy streams run to max_new_tokens
MAX_LEN = 32

#: The randomized sweep's seeds; the CI chaos matrix overrides this to
#: one seed per leg.
_TRANSFER_SEEDS = tuple(
    int(s) for s in os.environ.get("APEX_CHAOS_TRANSFER_SEED",
                                   "0,1,2").split(","))


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, injector=None, tracer=None, num_pages=20, **kw):
    cfg, params = model
    kw.setdefault("tracer", tracer if tracer is not None else Tracer())
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), injector=injector, **kw)


def _router(model, schedule=None, rates=None, seed=0, num_pages=20,
            spec_k=0, **kw):
    inj = FaultInjector(seed=seed, rates=rates, schedule=schedule)
    trc = Tracer()
    return DisaggregatedRouter(
        _engine(model, inj, trc, num_pages=num_pages, spec_k=spec_k),
        _engine(model, inj, trc, num_pages=num_pages, spec_k=spec_k),
        EOS, audit=True, **kw)


_REQS = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=8),
         Request(prompt=(6, 7, 8), max_new_tokens=6, temperature=0.8,
                 seed=7),
         Request(prompt=(9, 10, 11, 12), max_new_tokens=4,
                 temperature=1.1, seed=5)]


def _drive(sched, reqs=_REQS):
    for r in reqs:
        sched.submit(r)
    return sched.run()


def _golden(model, reqs=_REQS, spec_k=0):
    eng = _engine(model, spec_k=spec_k)
    return _drive(ContinuousBatchingScheduler(eng, eos_id=EOS,
                                              audit=True), reqs)


def _assert_all_ok_golden(router, golden):
    """Every request finished ok with its exact golden stream — the
    recovery paths are invisible in the committed tokens."""
    assert sorted(router.outcomes) == list(range(len(golden)))
    for rid, out in router.outcomes.items():
        assert out.reason in FINISH_REASONS and out.ok
        assert list(out.tokens) == golden[rid], f"request {rid} diverged"


# -- fault-free identity -----------------------------------------------------

@pytest.mark.parametrize("spec_k", [0, 2])
def test_fault_free_streams_match_colocated(model, spec_k):
    """The headline contract: greedy AND sampled streams, speculation
    on and off, all integer-identical to the colocated scheduler —
    with every admission actually served by the remote prefill
    replica."""
    golden = _golden(model, spec_k=spec_k)
    router = _router(model, spec_k=spec_k)
    assert _drive(router) == golden
    assert router.stats.remote_prefills == len(_REQS)
    assert router.stats.colocated_prefills == 0
    assert router.stats.failovers == 0
    assert all(h.state == "healthy" for h in router.health.values())
    _assert_all_ok_golden(router, golden)


def test_cross_replica_prefix_dedup(model):
    """Requests 0 and 1 share a full prompt page: the decode replica
    already holds it (registered at request 0's install), so request
    1's handoff ships one page fewer — content addressing IS the
    dedup, and the shared-page stream still matches golden."""
    reqs = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=6),
            Request(prompt=(1, 2, 3, 4, 9), max_new_tokens=6,
                    temperature=0.8, seed=7)]
    golden = _golden(model, reqs)
    router = _router(model)
    assert _drive(router, reqs) == golden
    assert router.stats.transfer_pages_deduped == 1
    assert router.stats.remote_prefills == 2


# -- one pinned fault per new site ------------------------------------------

def test_single_send_fault_retries_to_golden(model):
    """One dropped send: retried inside the same handoff, delivered on
    attempt 2, stream bit-identical."""
    golden = _golden(model)
    router = _router(model, schedule={"page_send": (0,)})
    assert _drive(router) == golden
    assert router.stats.transfer_retries == 1
    assert router.stats.transfer_failures == 0
    assert router.stats.remote_prefills == len(_REQS)
    _assert_all_ok_golden(router, golden)


def test_single_recv_corruption_quarantines_to_golden(model):
    """One in-flight byte flip: the checksum catches it, the payload
    is quarantined (never installed — golden equality is the proof
    that no corrupt page was ever attended), and the retry
    re-extracts clean tiles."""
    golden = _golden(model)
    router = _router(model, schedule={"page_recv": (0,)})
    assert _drive(router) == golden
    assert router.stats.transfer_corrupt == 1
    assert router.stats.transfer_retries == 1
    assert router.stats.transfer_failures == 0
    _assert_all_ok_golden(router, golden)


def test_single_health_probe_fault_recovers(model):
    """One failed probe degrades the replica (still routable); clean
    probes walk it back to healthy. No routing change, no stream
    change."""
    golden = _golden(model)
    router = _router(model, schedule={"replica_health": (0,)})
    assert _drive(router) == golden
    assert router.stats.remote_prefills == len(_REQS)
    assert router.stats.colocated_prefills == 0
    assert router.health["prefill"].state == "healthy"
    assert router.health["prefill"].transitions >= 2  # dip + recovery
    _assert_all_ok_golden(router, golden)


# -- degradation ladder ------------------------------------------------------

def test_transfer_budget_exhausted_falls_back_colocated(model):
    """Every attempt of the first handoff dropped: TransferFailed is
    raised, caught, and the admission is served colocated — the
    request never observes the fault and its stream is golden."""
    golden = _golden(model)
    router = _router(model, schedule={"page_send": (0, 1, 2)})
    assert _drive(router) == golden
    assert router.stats.transfer_failures == 1
    assert router.stats.colocated_prefills >= 1
    names = [e.name for e in router.tracer.events]
    assert "failover" in names  # the fallback instant, typed cause
    _assert_all_ok_golden(router, golden)


def test_transfer_corrupt_exhaustion_is_typed(model):
    """Driving the channel directly: persistent corruption exhausts
    the budget with a TYPED TransferCorrupt carrying attempts/pages —
    and the tiles never reached any cache (quarantine, not install)."""
    inj = FaultInjector(schedule={"page_recv": (0, 1, 2)})
    src = _engine(model, inj)
    src.prefill(0, [1, 2, 3, 4, 5])
    transfer = PageTransfer(injector=inj, tracer=src.tracer,
                            stats=src.stats, max_retries=2)
    with pytest.raises(TransferCorrupt) as ei:
        transfer.ship(src, [1, 2, 3, 4, 5], src._slot_pages[0],
                      replica="prefill")
    assert ei.value.attempts == 3 and ei.value.pages == 2
    assert src.stats.transfer_corrupt == 3
    assert src.stats.transfer_failures == 1
    # a clean channel still ships the same pages fine afterwards
    k_tile, v_tile, attempts = transfer.ship(
        src, [1, 2, 3, 4, 5], src._slot_pages[0], replica="prefill")
    assert attempts == 1 and k_tile.shape[1] == 2


def test_checksum_binds_payload_to_prompt(model):
    """The chain key is folded into the transfer checksum: a payload
    can only verify against the prompt whose pages it carries — a
    key mismatch is indistinguishable from corruption and quarantines
    the same way."""
    k = np.zeros((2, 1, 2, 4, 4), np.float32)
    v = np.ones_like(k)
    key_a = prefix_page_keys([1, 2, 3, 4], 4)[-1]
    key_b = prefix_page_keys([1, 2, 3, 9], 4)[-1]
    assert transfer_checksum(k, v, key_a) != transfer_checksum(k, v,
                                                               key_b)
    flipped = np.array(k, copy=True)
    flipped.reshape(-1).view(np.uint8)[3] ^= 0xFF
    assert transfer_checksum(k, v, key_a) != \
        transfer_checksum(flipped, v, key_a)


def test_remote_replica_down_routes_colocated(model):
    """Persistent probe failures take the prefill replica down (even
    probe indices hit it — fixed draw order); admissions after that
    are served colocated, streams stay golden, and nothing hangs."""
    golden = _golden(model)
    router = _router(
        model, schedule={"replica_health": tuple(range(0, 40, 2))})
    assert _drive(router) == golden
    assert router.health["prefill"].state == "down"
    assert router.stats.colocated_prefills >= 1
    assert router.stats.failovers == 0
    _assert_all_ok_golden(router, golden)


def test_active_replica_down_mid_stream_fails_over(model):
    """The DECODE (active) replica dies mid-stream (odd probe
    indices, two consecutive failures): every occupied slot drains
    back to the queue front, the replicas swap roles, and the resumed
    streams are integer-identical to golden — the failover is pure
    placement."""
    golden = _golden(model)
    router = _router(model, schedule={"replica_health": (1, 3)})
    assert _drive(router) == golden
    assert router.stats.failovers == 1
    assert router.engine.active_name == "prefill"  # roles swapped
    names = [e.name for e in router.tracer.events]
    assert "failover" in names and "preempted" in names
    _assert_all_ok_golden(router, golden)


def test_both_replicas_down_keeps_serving(model):
    """Both ladders bottom out — the REMOTE first (probe indices are
    per-tick pairs: even = prefill, odd = decode; prefill fails from
    tick 2 on, decode at ticks 3-4), so when the active replica dies
    there is no routable target and failover is refused: health gates
    ROUTING, not survival, and the incumbent keeps decoding. Streams
    golden, outcomes typed, no hang — and the decode ladder later
    climbs back up through clean probes."""
    reqs = _REQS[:2]  # both admitted tick 1; no later handoff boosts
    golden = _golden(model, reqs)
    schedule = {"replica_health": tuple(range(2, 32, 2)) + (5, 7)}
    router = _router(model, schedule=schedule)
    assert _drive(router, reqs) == golden
    assert router.stats.failovers == 0
    assert router.health["prefill"].state == "down"
    # decode walked healthy -> degraded -> down, then back up the
    # ladder through clean probes (the drain ends mid-climb)
    assert router.health["decode"].state in ("degraded", "healthy")
    assert router.health["decode"].transitions >= 3
    _assert_all_ok_golden(router, golden)


# -- construction contracts --------------------------------------------------

def test_router_validates_replica_pair(model):
    cfg, params = model
    inj, trc = FaultInjector(), Tracer()

    def eng(**kw):
        return _engine(model, kw.pop("injector", inj),
                       kw.pop("tracer", trc), **kw)

    with pytest.raises(ValueError, match="two engine instances"):
        e = eng()
        DisaggregatedRouter(e, e, EOS)
    with pytest.raises(ValueError, match="agree on page_size"):
        cfg2, params2 = model
        other = PagedDecodeEngine(params2, cfg2, num_slots=2,
                                  max_len=MAX_LEN, num_pages=20,
                                  page_size=8, buckets=(16, 32),
                                  injector=inj, tracer=trc)
        DisaggregatedRouter(other, eng(), EOS)
    with pytest.raises(ValueError, match="ONE FaultInjector"):
        DisaggregatedRouter(eng(injector=FaultInjector()), eng(), EOS)
    with pytest.raises(ValueError, match="ONE Tracer"):
        DisaggregatedRouter(eng(tracer=Tracer()), eng(), EOS)
    with pytest.raises(ValueError, match="chunked prefill"):
        DisaggregatedRouter(eng(), eng(), EOS, chunk_tokens=4)
    with pytest.raises(ValueError, match="paged engine"):
        from apex_tpu.serving import DecodeEngine
        dense = DecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             injector=inj, tracer=trc)
        DisaggregatedRouter(dense, eng(), EOS)


# -- randomized multi-fault sweep -------------------------------------------

@pytest.mark.parametrize("seed", _TRANSFER_SEEDS)
def test_multi_fault_chaos_replays_bit_for_bit(model, seed):
    """All three new sites armed at once (plus a legacy decode fault
    for cross-talk), audited every tick: every outcome typed, every
    ok stream exactly golden, every degraded stream a golden prefix
    — and the whole run replays bit-for-bit: outcomes, stats,
    injector counts, and the tick-clock event stream."""
    golden = _golden(model)
    rates = {"page_send": 0.25, "page_recv": 0.2,
             "replica_health": 0.12, "decode_exec": 0.05}

    def chaos_run():
        router = _router(model, rates=rates, seed=seed)
        _drive(router)
        return router

    router = chaos_run()
    assert sorted(router.outcomes) == list(range(len(_REQS)))
    for rid, out in router.outcomes.items():
        assert out.reason in FINISH_REASONS
        want = golden[rid]
        if out.ok:
            assert list(out.tokens) == want, f"request {rid} diverged"
        else:
            assert list(out.tokens) == want[:len(out.tokens)], \
                f"request {rid}: degraded stream not a golden prefix"
    replay = chaos_run()
    assert replay.outcomes == router.outcomes
    assert replay.stats.as_dict() == router.stats.as_dict()
    assert replay.engine.injector.counts == router.engine.injector.counts
    assert replay.tracer.tick_stream() == router.tracer.tick_stream()
    assert {h.state for h in replay.health.values()} \
        == {h.state for h in router.health.values()}
    # CI post-mortem artifact: one Perfetto dump per sweep seed,
    # uploaded by the chaos workflow legs
    out_path = os.environ.get("APEX_CHAOS_TRACE_OUT")
    if out_path:
        root, ext = os.path.splitext(out_path)
        router.tracer.dump_jsonl(
            f"{root}.transfer_seed{seed}{ext or '.jsonl'}")

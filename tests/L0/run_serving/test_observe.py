"""Observability layer: tracer determinism, metric math, flight
recorder, and export formats.

The tracer/registry/recorder are host-side hooks with the same inert
contract as the fault injector, so the load-bearing claims are:

- the TICK-CLOCK event stream is replay-exact (two runs at the same
  seed — fault-free or under a pinned fault schedule — produce equal
  ``tick_stream()``\\ s), while wall-clock stamps are explicitly
  outside that contract;
- enabling tracing never perturbs the committed token streams;
- histogram bucket math agrees with a brute-force quantile to within
  one bucket width;
- a forced livelock ships the flight-recorder ring in its typed
  error payload;
- the Perfetto dump is valid JSON-per-line with ``ph``/``ts``/``name``.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    ContinuousBatchingScheduler, FaultInjector, LivelockError,
    PagedDecodeEngine, Request, ServingStats, Tracer,
)
from apex_tpu.serving.observe import (
    LIFECYCLE, PHASES, FlightRecorder, Histogram, MetricsRegistry,
)

EOS = -1
MAX_LEN = 32


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, tracer=None, injector=None, spec_k=0, num_pages=20):
    cfg, params = model
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), spec_k=spec_k,
                             injector=injector, tracer=tracer)


def _drive(engine, n_reqs=3, max_new=6):
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS, audit=True)
    for s in range(n_reqs):
        sched.submit(Request(prompt=(7, 11, 13 + s), max_new_tokens=max_new,
                             temperature=0.7, seed=s))
    return sched, sched.run()


# -- metric math -------------------------------------------------------------

def test_histogram_quantile_matches_bruteforce():
    """Bucket-interpolated quantiles vs numpy's exact ones on a seeded
    workload: the estimate must land within one bucket width."""
    bounds = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)
    h = Histogram("ttft", buckets=bounds)
    rng = np.random.RandomState(42)
    vals = np.concatenate([rng.randint(1, 30, size=400),
                           rng.randint(30, 100, size=40)])
    for v in vals:
        h.observe(float(v))
    assert h.count == len(vals)
    assert h.sum == pytest.approx(float(vals.sum()))
    edges = [float(vals.min()), *bounds, float(vals.max())]
    for q in (0.1, 0.5, 0.9, 0.95, 0.99):
        est = h.quantile(q)
        true = float(np.percentile(vals, q * 100))
        # tolerance: the width of the bucket containing the true value
        idx = int(np.searchsorted(bounds, true))
        width = edges[idx + 1] - edges[idx] if idx < len(bounds) \
            else edges[-1] - edges[-2]
        assert abs(est - true) <= max(width, 1.0), (q, est, true)


def test_histogram_bucket_counts_are_cumulative_le():
    h = Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 2.0, 3.0, 100.0):
        h.observe(v)
    # le-semantics: v == bound lands IN that bucket
    assert h.counts == [2, 2, 1, 1]
    assert h.quantile(0.0) is not None
    assert h.quantile(1.0) == 100.0  # tail interpolates toward the max


def test_registry_get_or_create_and_type_conflict():
    r = MetricsRegistry()
    c = r.counter("x", help="h")
    assert r.counter("x") is c
    assert r.gauge("g", labels={"slot": 0}) \
        is not r.gauge("g", labels={"slot": 1})
    with pytest.raises(TypeError):
        r.gauge("x")


def test_prometheus_text_format():
    r = MetricsRegistry()
    r.counter("serving_retries_total", help="fault-path requeues").inc(3)
    r.gauge("serving_queue_depth").set(2)
    h = r.histogram("serving_ttft_ticks", buckets=(1.0, 4.0))
    h.observe(1.0)
    h.observe(9.0)
    text = r.to_prometheus()
    assert "# HELP serving_retries_total fault-path requeues" in text
    assert "# TYPE serving_retries_total counter" in text
    assert "serving_retries_total 3" in text
    assert "# TYPE serving_ttft_ticks histogram" in text
    assert 'serving_ttft_ticks_bucket{le="1.0"} 1' in text
    assert 'serving_ttft_ticks_bucket{le="+Inf"} 2' in text
    assert "serving_ttft_ticks_sum 10.0" in text
    assert "serving_ttft_ticks_count 2" in text


def test_servingstats_is_a_registry_view():
    """The legacy counter block and the registry share storage — a
    write through either face is visible through the other, so the
    exports can never drift from ``as_dict``."""
    stats = ServingStats()
    stats.retries += 2
    assert stats.registry.counter("serving_retries_total").value == 2
    stats.registry.counter("serving_retries_total").inc(1)
    assert stats.retries == 3
    stats.tokens_drafted = 10
    stats.tokens_accepted = 4
    d = stats.as_dict()
    assert d["retries"] == 3
    assert d["acceptance_rate"] == pytest.approx(0.4)
    with pytest.raises(TypeError):
        ServingStats(not_a_counter=1)
    with pytest.raises(AttributeError):
        stats.not_a_counter = 1


def test_flight_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=8)
    trc = Tracer(recorder=rec)
    for i in range(50):
        trc.set_tick(i)
        trc.instant("submitted", request_id=i)
    assert len(rec) == 8
    assert [e.request_id for e in rec.events()] == list(range(42, 50))
    assert len(trc.events) == 50  # the full event log is separate


# -- scheduler integration ---------------------------------------------------

pytest_chaos = pytest.mark.chaos


@pytest_chaos
def test_tracing_never_perturbs_streams(model):
    """Same seeds, tracer on vs off (and spec on): identical committed
    token streams — the hooks are host-side only."""
    _, bare = _drive(_engine(model))
    _, traced = _drive(_engine(model, tracer=Tracer()))
    assert traced == bare
    _, spec_traced = _drive(_engine(model, tracer=Tracer(), spec_k=2))
    assert spec_traced == bare


@pytest_chaos
@pytest.mark.parametrize("spec_k", [0, 2])
def test_tick_stream_is_replay_exact_under_pinned_faults(model, spec_k):
    """Two chaos runs at the same seed produce byte-identical
    tick-clock event streams; wall-clock stamps differ but are
    excluded from ``tick_key`` by construction."""
    rates = {"cow_clone": 0.2, "decode_exec": 0.1, "sample": 0.1}

    def go():
        trc = Tracer()
        _drive(_engine(model, tracer=trc, spec_k=spec_k,
                       injector=FaultInjector(seed=5, rates=rates),
                       num_pages=12))
        return trc

    a, b = go(), go()
    assert a.tick_stream() == b.tick_stream()
    assert len(a.tick_stream()) > 0
    walls_a = [e.wall for e in a.events]
    walls_b = [e.wall for e in b.events]
    assert walls_a != walls_b  # wall clock really is outside the key


@pytest_chaos
def test_event_taxonomy_and_metrics_after_run(model):
    trc = Tracer()
    sched, _ = _drive(_engine(model, tracer=trc, spec_k=2))
    names = {e.name for e in trc.events}
    assert {"submitted", "admitted", "first_token", "finished"} <= names
    assert {"prefill", "prepare_decode", "exec", "accept",
            "commit"} <= names
    assert names <= set(PHASES) | set(LIFECYCLE)
    reg = trc.registry
    assert reg.get("serving_ttft_ticks").count == 3
    assert reg.get("serving_itl_ticks").count > 0
    assert reg.get("serving_committed_tokens_per_tick").count > 0
    assert reg.get("serving_queue_depth") is not None
    # per-stream acceptance gauges exist for the speculating slots
    assert reg.get("serving_stream_acceptance_rate",
                   labels={"slot": 0}) is not None
    # the stats view and the registry agree by construction
    assert sched.stats.registry is reg
    assert reg.counter("serving_spec_ticks_total").value \
        == sched.stats.spec_ticks


@pytest_chaos
def test_pool_gauges_track_the_pool(model):
    trc = Tracer()
    sched, _ = _drive(_engine(model, tracer=trc))
    eng = sched.engine
    reg = trc.registry
    assert reg.get("serving_pages_free").value == eng.pool.num_free
    assert reg.get("serving_pages_cached").value == eng.pool.num_cached
    assert reg.get("serving_page_pool_occupancy").value \
        == pytest.approx(eng.pool.occupancy)
    assert 0.0 <= eng.pool.occupancy <= 1.0
    # dense/tier-less engines never create the host-tier gauges
    assert reg.get("serving_page_pool_host_pages") is None


def _drive_hierarchy(model, tracer):
    """Churn a hot prefix through a small pool so the host tier's
    spill AND promote paths both run under tracing."""
    from apex_tpu.serving import PrefixRegistry
    cfg, params = model
    tier = PrefixRegistry(1 << 20)
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                            num_pages=10, page_size=4, buckets=(16, 32),
                            tracer=tracer, host_tier=tier)
    sched = ContinuousBatchingScheduler(eng, eos_id=EOS, audit=True)
    hot = tuple(range(7, 15))
    for p in (hot, (101, 102, 103, 104, 105, 106, 107, 108),
              (201, 202, 203, 204, 205, 206, 207, 208),
              (301, 302, 303, 304, 305, 306, 307, 308), hot):
        sched.submit(Request(prompt=p, max_new_tokens=4))
    sched.run()
    return eng, tier, sched


def test_host_tier_gauges_track_both_tiers(model):
    """Host-tier engines grow the pool gauge family with per-tier
    breakdowns, and the values mirror ``PagePool.stats()`` exactly."""
    trc = Tracer()
    eng, tier, _ = _drive_hierarchy(model, trc)
    assert eng.stats.host_spills > 0 and eng.stats.host_promotes > 0
    reg, stats = trc.registry, eng.pool.stats()
    assert reg.get("serving_page_pool_hbm_used").value \
        == stats["hbm_used"]
    assert reg.get("serving_page_pool_host_pages").value \
        == stats["host_pages"] == tier.num_pages
    assert reg.get("serving_page_pool_host_bytes").value \
        == stats["host_bytes"] == tier.nbytes
    assert reg.get("serving_page_pool_host_hit_rate").value \
        == pytest.approx(stats["host_hit_rate"])
    assert stats["host_hit_rate"] > 0
    # the spill/promote lifecycle instants carry byte+tick payloads
    spills = [e for e in trc.events if e.name == "host_spill"]
    promotes = [e for e in trc.events if e.name == "host_promote"]
    assert spills and promotes
    assert all(dict(e.args).get("bytes", 0) > 0 for e in spills)
    assert any(dict(e.args).get("ticks", 0) >= 1 for e in promotes)


def test_host_tier_tick_stream_is_replay_exact(model):
    """The replay contract holds with the hierarchy live: two runs of
    the same pinned schedule produce byte-identical tick-clock event
    streams, spill/promote instants included."""
    a = Tracer()
    b = Tracer()
    _drive_hierarchy(model, a)
    _drive_hierarchy(model, b)
    assert a.tick_stream() == b.tick_stream()
    names = {e.name for e in a.events}
    assert {"host_spill", "host_promote"} <= names
    assert names <= set(PHASES) | set(LIFECYCLE)


@pytest_chaos
def test_request_outcome_carries_tick_latencies(model):
    sched, _ = _drive(_engine(model, tracer=Tracer()))
    for out in sched.outcomes.values():
        assert out.ttft_ticks is not None and out.ttft_ticks >= 1
        assert out.total_ticks >= out.ttft_ticks
    # and without a tracer the fields are still populated (they feed
    # the outcome record, not just the histograms)
    sched2, _ = _drive(_engine(model))
    assert all(o.ttft_ticks is not None
               for o in sched2.outcomes.values())


def _drive_chunked(engine, chunk_tokens=4, n_reqs=3, max_new=6):
    """_drive with chunked prefill on and prompts long enough that
    every admission really splits into several chunks."""
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS, audit=True,
                                        chunk_tokens=chunk_tokens)
    for s in range(n_reqs):
        sched.submit(Request(
            prompt=(7, 11, 13 + s, 17, 19, 23, 29 + s, 31, 37, 41),
            max_new_tokens=max_new, temperature=0.7, seed=s))
    return sched, sched.run()


@pytest_chaos
def test_chunked_tick_stream_is_replay_exact_under_pinned_faults(model):
    """The replay contract holds with chunked prefill on and the
    chunk_prefill_exec site armed: byte-identical tick-clock event
    streams across two runs at the same seed."""
    rates = {"cow_clone": 0.2, "chunk_prefill_exec": 0.2,
             "decode_exec": 0.1, "sample": 0.1}

    def go():
        trc = Tracer()
        _drive_chunked(_engine(model, tracer=trc,
                               injector=FaultInjector(seed=5,
                                                      rates=rates),
                               num_pages=12))
        return trc

    a, b = go(), go()
    assert a.tick_stream() == b.tick_stream()
    assert any(e.name == "chunk_prefill" for e in a.events)
    walls_a = [e.wall for e in a.events]
    walls_b = [e.wall for e in b.events]
    assert walls_a != walls_b  # wall clock stays outside the key


@pytest_chaos
def test_chunked_taxonomy_counters_and_outcomes(model):
    """Chunked runs stay inside the event taxonomy (chunk_prefill is a
    named phase), the chunk counter is a registry view of the stats
    block, and outcomes report how many ticks their prefill spanned."""
    trc = Tracer()
    sched, chunked = _drive_chunked(_engine(model, tracer=trc))
    names = {e.name for e in trc.events}
    assert "chunk_prefill" in names
    assert names <= set(PHASES) | set(LIFECYCLE)
    # 3 requests x 10-token prompts in 4-token chunks: 3 chunks each
    assert sched.stats.prefill_chunks == 9
    assert trc.registry.counter("serving_prefill_chunks_total").value \
        == sched.stats.prefill_chunks
    for out in sched.outcomes.values():
        assert out.prefill_ticks >= 2   # the prefill really spanned ticks
        assert out.ttft_ticks is not None and out.ttft_ticks >= 1
    # and tracing never perturbed the chunked streams
    _, bare = _drive_chunked(_engine(model))
    assert chunked == bare


@pytest_chaos
def test_livelock_error_carries_flight_recorder_ring(model):
    """The watchdog's LivelockError payload must include the stuck
    request's last trace events — the black box of the failure."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg, params = model
    trc = Tracer()
    eng = PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                            num_pages=2 + RESERVED_PAGES, page_size=4,
                            buckets=(16, 32), tracer=trc)
    eng.pool.needs_copy = lambda page: True   # the PR-8 bug, forced
    sched = ContinuousBatchingScheduler(eng, eos_id=EOS,
                                        watchdog_limit=8)
    sched.submit(Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=3))
    with pytest.raises(LivelockError) as exc:
        sched.run()
    payload = exc.value.payload
    assert payload["stuck"] == exc.value.stuck
    flight = payload["flight"]
    assert flight, "flight recorder ring missing from the payload"
    assert flight == trc.flight()
    # the stuck request's lifecycle is in the ring, and every entry is
    # a chrome event (JSON-safe: the payload must serialize)
    names = {e["name"] for e in flight}
    assert "preempted" in names or "prepare_decode" in names
    assert any(e["args"].get("request_id") == 0 for e in flight)
    json.dumps(flight)


def test_inert_tracer_contract(model):
    """An engine built without a tracer gets a disabled one: no events
    recorded, but the stats view still lives on a real registry (the
    hook sites cost one attribute check, like the inert injector)."""
    sched, _ = _drive(_engine(model))
    trc = sched.engine.tracer
    assert trc.enabled is False
    assert trc.events == []
    assert len(trc.recorder) == 0
    assert sched.stats.registry is trc.registry
    assert trc.registry.counter("serving_plain_ticks_total").value \
        == sched.stats.plain_ticks > 0


@pytest_chaos
def test_perfetto_jsonl_dump_is_valid(model, tmp_path):
    trc = Tracer()
    _drive(_engine(model, tracer=trc, spec_k=2))
    path = tmp_path / "trace.jsonl"
    n = trc.dump_jsonl(str(path))
    lines = path.read_text().splitlines()
    assert n == len(lines) == len(trc.events) > 0
    phs = set()
    for line in lines:
        d = json.loads(line)          # valid JSON per line
        assert {"ph", "ts", "name"} <= set(d)
        assert d["ts"] == d["args"]["tick"] * 1000
        assert "wall_s" in d["args"]
        phs.add(d["ph"])
        if d["ph"] == "X":
            assert d["dur"] >= 1
    assert phs == {"X", "i"}  # spans and instants both present


@pytest_chaos
def test_disagg_transfer_metrics_and_replay(model):
    """The disaggregated tier's observability surface: one
    ``page_transfer`` span per handoff, per-replica LABELED transfer
    counters, the replica health gauge, and the
    ``serving_transfer_ticks`` histogram — all inside the event
    taxonomy, and the whole tick-clock event stream replay-exact under
    a pinned transfer fault."""
    from apex_tpu.serving import DisaggregatedRouter
    from apex_tpu.serving.health import HEALTH_STATES

    def go():
        inj = FaultInjector(schedule={"page_send": (0,)})
        trc = Tracer()
        router = DisaggregatedRouter(_engine(model, trc, inj),
                                     _engine(model, trc, inj),
                                     EOS, audit=True)
        for s in range(3):
            router.submit(Request(prompt=(7, 11, 13 + s),
                                  max_new_tokens=6, temperature=0.7,
                                  seed=s))
        router.run()
        return router, trc

    router, trc = go()
    names = {e.name for e in trc.events}
    assert "page_transfer" in names
    assert names <= set(PHASES) | set(LIFECYCLE)
    spans = [e for e in trc.events if e.name == "page_transfer"]
    assert len(spans) == router.stats.remote_prefills == 3
    # the pinned send drop retried inside the FIRST span, delivered on
    # attempt 2 — never a second span, never a failure
    assert router.stats.transfer_retries == 1
    assert router.stats.transfer_failures == 0
    reg = trc.registry
    labels = {"replica": "prefill"}
    assert reg.get("serving_transfer_src_bytes_total",
                   labels=labels).value > 0
    assert reg.get("serving_transfer_src_retries_total",
                   labels=labels).value == 1
    assert reg.get("serving_transfer_src_failures_total",
                   labels=labels).value == 0
    hist = reg.get("serving_transfer_ticks", labels=labels)
    assert hist.count == 3  # one charged tick cost per delivered handoff
    # both replicas publish their health-state gauge; the one flaky
    # probe recovered, so both sit at the top of the ladder
    for replica in ("prefill", "decode"):
        g = reg.get("serving_replica_health",
                    labels={"replica": replica})
        assert g.value == HEALTH_STATES.index("healthy")
    # the stats view over the shared registry stays coherent: the
    # engines and the router share ONE counter block
    assert router.stats.registry is reg
    assert reg.counter("serving_transfers_total").value \
        == router.stats.transfers == 3
    # replay-exactness: same seed, same schedule -> byte-equal
    # tick-clock event stream, transfer spans included
    _, trc2 = go()
    assert trc.tick_stream() == trc2.tick_stream()


def test_pool_metrics_and_replay(model):
    """The pool tier's observability surface: one ``reshard`` span per
    device-to-device handoff, the per-replica
    ``serving_pool_replica_load`` gauge and per-reason
    ``serving_pool_routing_total`` counters, per-replica labeled
    reshard counters, and the ``rebalance`` lifecycle instant on a
    failover placement move — all inside the event taxonomy, and the
    whole tick-clock event stream replay-exact under a pinned fault
    schedule (a reshard drop AND a mid-stream decode failover)."""
    from apex_tpu.serving import PoolRouter
    from apex_tpu.serving.health import HEALTH_STATES

    def go():
        # reshard_send 0 -> first handoff retries inside its span;
        # replica_health 2,6 -> decode0 (probe order prefill0,
        # prefill1, decode0, decode1) dies and the slots move to
        # decode1
        inj = FaultInjector(schedule={"reshard_send": (0,),
                                      "replica_health": (2, 6)})
        trc = Tracer()
        pool = PoolRouter(
            [_engine(model, trc, inj), _engine(model, trc, inj)],
            [_engine(model, trc, inj), _engine(model, trc, inj)],
            EOS, audit=True)
        for s in range(3):
            pool.submit(Request(prompt=(7, 11, 13 + s),
                                max_new_tokens=6, temperature=0.7,
                                seed=s))
        pool.run()
        return pool, trc

    pool, trc = go()
    names = {e.name for e in trc.events}
    assert "reshard" in names
    assert "rebalance" in names
    assert names <= set(PHASES) | set(LIFECYCLE)
    spans = [e for e in trc.events if e.name == "reshard"]
    assert len(spans) == pool.stats.reshards >= 3
    assert pool.stats.reshard_retries == 1
    assert pool.stats.reshard_failures == 0
    assert pool.stats.failovers == 1 and pool.stats.rebalances == 1
    reg = trc.registry
    # per-reason routing counters: every remote admission routed by
    # load (no pool_route fault pinned)
    assert reg.get("serving_pool_routing_total",
                   labels={"reason": "load"}).value \
        == pool.stats.remote_prefills
    # the load gauge exists per prefill replica and ends at the last
    # pass's link-busy value (deterministic)
    for replica in ("prefill0", "prefill1"):
        assert reg.get("serving_pool_replica_load",
                       labels={"replica": replica}) is not None
    # per-replica labeled reshard counters on the routed source
    total_bytes = sum(
        reg.get("serving_reshard_src_bytes_total",
                labels={"replica": r}).value
        for r in ("prefill0", "prefill1")
        if reg.get("serving_reshard_src_bytes_total",
                   labels={"replica": r}) is not None)
    assert total_bytes > 0
    # all four replicas publish the health gauge; decode0 took the
    # two pinned probe hits
    for replica in ("prefill0", "prefill1", "decode0", "decode1"):
        g = reg.get("serving_replica_health",
                    labels={"replica": replica})
        assert g is not None and g.value <= HEALTH_STATES.index("healthy")
    # replay-exactness under the pinned schedule: byte-equal tick
    # stream, reshard spans and the rebalance instant included
    pool2, trc2 = go()
    assert trc.tick_stream() == trc2.tick_stream()
    assert pool2.stats.as_dict() == pool.stats.as_dict()

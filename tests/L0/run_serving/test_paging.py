"""Page pool + prefix-sharing contracts: host-side allocator invariants
(alloc/free/refcount, LRU eviction, out-of-pages behavior), stored-once
prefix sharing, and the copy-on-write acceptance contract — a slot
appending into a shared page must never perturb the other request's
logits (bit-identity, not tolerance)."""

import dataclasses
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    PagePool, PagedDecodeEngine, PoolExhausted, prefix_page_keys,
)
from apex_tpu.serving.cache import RESERVED_PAGES, SCRATCH_PAGE

S_MAX = 32


def _cfg():
    return dataclasses.replace(gpt_tiny(), use_rope=True,
                               hidden_dropout=0.0)


def _engine(params, cfg, num_pages, page_size=4, **kw):
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                             num_pages=num_pages, page_size=page_size,
                             cache_dtype=jnp.float32, buckets=(16, 32),
                             **kw)


# -- prefix keys ------------------------------------------------------------

def test_prefix_page_keys_chain():
    """Key i commits to every token of pages 0..i: a longer prompt's
    keys extend a shorter one's, and any token change invalidates all
    keys from its page onward (including a partial last page)."""
    a = prefix_page_keys([1, 2, 3, 4, 5, 6], 4)
    b = prefix_page_keys([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    assert len(a) == 2 and len(b) == 3
    assert b[0] == a[0]
    assert b[1] != a[1]  # partial page (5, 6) vs full (5, 6, 7, 8)
    c = prefix_page_keys([1, 2, 9, 4, 5, 6], 4)
    assert c[0] != a[0] and c[1] != a[1]
    with pytest.raises(ValueError, match="positive"):
        prefix_page_keys([1], 0)


def test_prefix_page_key_encoding_is_pinned():
    """The canonical byte layout under the chain hash — ``<II{n}i``
    little-endian (version, count, tokens) — pinned by exact hex. The
    chained digests are a CROSS-REPLICA wire format (prefix-cache
    dedup, transfer checksums in the disaggregated tier), so any
    drift here silently severs every cached prefix and quarantines
    every in-flight handoff: a layout change must bump
    ``PAGE_KEY_VERSION``, not mutate these vectors."""
    from apex_tpu.serving.paging import PAGE_KEY_VERSION, _encode_page

    assert PAGE_KEY_VERSION == 1
    assert _encode_page((1, 2, 3, 4)).hex() == \
        "010000000400000001000000020000000300000004000000"
    assert [k.hex() for k in prefix_page_keys([1, 2, 3, 4, 5, 6, 7], 4)] \
        == ["79e1a907696f5ad880df64ad64b10044647381ac2788c8f53e33ce"
            "66f9f9a025",
            "384380725a66cc2f73081861c743d7c658bc5bc5c3a40dbbed2e1e2"
            "27c2ff961"]
    # a partial page commits to its count: [0] under page_size 4 must
    # not alias [0, 0] or a zero-padded full page
    assert prefix_page_keys([0], 4)[0].hex() == \
        "7d450465ceb49083708a6970827f0e0b116ed285072a95b451e55f583f56da8d"
    assert prefix_page_keys(list(range(8)), 2)[-1].hex() == \
        "68885af65c19be66af637a6cf362f02b6dc9c2c6ab3423a08c7600a81ccd0e86"
    # int32 wire range is enforced, never truncated
    with pytest.raises(struct.error):
        _encode_page((2**31,))


def test_spill_header_encoding_is_pinned():
    """The host-tier spill payload header — ``<IIIIII`` little-endian
    (version, layers, heads, page_size, head_dim, dtype_tag) followed
    by the 32-byte chain key — pinned by exact hex. Spill records
    outlive engines (the registry is shared across replicas), so any
    drift silently quarantines every resident record at its next
    promotion: a layout change must bump ``PAGE_KEY_VERSION``."""
    from apex_tpu.serving.paging import (
        SPILL_DTYPE_TAGS, SPILL_HEADER_BYTES, decode_spill_header,
        encode_spill_header, spill_checksum,
    )

    assert SPILL_HEADER_BYTES == 56
    assert SPILL_DTYPE_TAGS == {"bfloat16": 1, "float32": 2,
                                "float16": 3, "int8": 4}
    key = bytes(range(32))
    header = encode_spill_header(key, 2, 2, 4, 8, 1)
    assert header.hex() == (
        "010000000200000002000000040000000800000001000000"
        "000102030405060708090a0b0c0d0e0f"
        "101112131415161718191a1b1c1d1e1f")
    assert decode_spill_header(header) == {
        "version": 1, "num_layers": 2, "num_heads": 2, "page_size": 4,
        "head_dim": 8, "dtype_tag": 1, "key": key}
    with pytest.raises(ValueError, match="32-byte"):
        encode_spill_header(b"short", 2, 2, 4, 8, 1)
    with pytest.raises(ValueError, match="56 bytes"):
        decode_spill_header(header[:-1])
    # the checksum binds header AND payload (scale planes included)
    k = np.arange(8, dtype=np.float32).reshape(1, 1, 1, 2, 4)
    v = k + 8
    d = spill_checksum(header, k, v)
    assert d == spill_checksum(header, k.copy(), v.copy())
    assert d != spill_checksum(header, k + 1, v)
    assert d != spill_checksum(header, k, v, k[..., 0, 0], v[..., 0, 0])


# -- PagePool ---------------------------------------------------------------

def test_pool_alloc_free_refcount():
    pool = PagePool(6, 4)
    assert pool.num_free == 6 - RESERVED_PAGES
    a, b = pool.alloc(), pool.alloc()
    assert a != b and a >= RESERVED_PAGES and b >= RESERVED_PAGES
    assert pool.refcount(a) == 1 and not pool.needs_copy(a)
    pool.retain(a)
    assert pool.refcount(a) == 2 and pool.needs_copy(a)
    pool.release(a)
    assert pool.refcount(a) == 1 and not pool.needs_copy(a)
    pool.release(a)
    assert pool.refcount(a) == 0 and pool.num_free == 3
    with pytest.raises(ValueError, match="free/reserved"):
        pool.release(a)  # double free
    with pytest.raises(ValueError, match="free/reserved"):
        pool.release(SCRATCH_PAGE)
    with pytest.raises(ValueError, match="free/reserved"):
        pool.retain(a)


def test_pool_free_order_is_validated_permutation():
    with pytest.raises(ValueError, match="permutation"):
        PagePool(6, 4, free_order=[3, 4, 5])  # misses 2
    with pytest.raises(ValueError, match="permutation"):
        PagePool(6, 4, free_order=[0, 1, 2, 3])  # includes reserved
    pool = PagePool(6, 4, free_order=[5, 3, 4, 2])
    assert pool.alloc() == 5 and pool.alloc() == 3


def test_pool_lru_eviction_and_exhaustion():
    pool = PagePool(RESERVED_PAGES + 3, 4)
    pages = [pool.alloc() for _ in range(3)]
    assert pool.alloc() is None  # dry, nothing cached to evict
    k1 = prefix_page_keys([1, 2, 3, 4], 4)
    k2 = prefix_page_keys([5, 6, 7, 8], 4)
    pool.register_prefix(k1, pages[:1])
    pool.register_prefix(k2, pages[1:2])
    for p in pages:
        pool.release(p)
    assert pool.num_free == 1 and pool.num_cached == 2
    # a hit refreshes recency: k1 becomes most-recent, so the first
    # eviction under pressure drops k2, not k1
    hit = pool.match_prefix(k1)
    assert hit == pages[:1]
    pool.release(hit[0])
    got = {pool.alloc(), pool.alloc()}  # free page + evict k2
    assert got == {pages[1], pages[2]}
    assert pool.match_prefix(k2) == []      # evicted
    assert pool.match_prefix(k1) != []      # survived (refreshed)
    pool.release(pool._prefix[k1[0]])
    assert pool.alloc() is not None  # evicts k1, the last entry
    assert pool.num_cached == 0 and pool.alloc() is None


# -- engine: stored-once sharing, COW, out-of-pages -------------------------

def test_prefix_shared_pages_stored_once():
    """Two requests with the same prompt hold the SAME physical pages:
    the second admission allocates nothing and its prefill logits are
    bit-identical (the rows are literally the same memory)."""
    cfg = _cfg()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    eng = _engine(params, cfg, num_pages=10)
    prompt = [5, 7, 11, 13, 17, 19, 23, 29]  # 2 full pages of 4
    l0 = eng.prefill(0, prompt)
    free_before = eng.pool.num_free
    l1 = eng.prefill(1, prompt)
    assert eng._slot_pages[0] == eng._slot_pages[1]
    assert eng.pool.num_free == free_before  # zero new allocations
    np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    for p in eng._slot_pages[0]:
        assert eng.pool.refcount(p) == 3  # 2 slots + registry


def test_cow_does_not_perturb_sharing_request():
    """The acceptance contract: two requests share a partial last
    prompt page; both then append (triggering copy-on-write). The
    logits of each must be BIT-IDENTICAL to a run where it decodes
    alone — COW never mutates the shared original, and the registry's
    cached copy survives at refcount 1."""
    cfg = _cfg()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    prompt = [5, 7, 11, 13, 17, 19]  # 1.5 pages of 4: partial page shared
    div_a, div_b = 31, 37            # divergent appended tokens

    def alone(slot, token):
        eng = _engine(params, cfg, num_pages=12)
        logits = eng.prefill(slot, prompt)
        assert eng.prepare_decode({slot: len(prompt)}) == []
        toks = [0, 0]
        toks[slot] = token
        active = jnp.asarray([i == slot for i in range(2)])
        step = eng.decode(jnp.asarray(toks, jnp.int32), active)
        return np.asarray(logits), np.asarray(step[slot])

    ref_pre_a, ref_a = alone(0, div_a)
    ref_pre_b, ref_b = alone(1, div_b)

    eng = _engine(params, cfg, num_pages=12)
    pre_a = eng.prefill(0, prompt)
    pre_b = eng.prefill(1, prompt)
    shared = eng._slot_pages[0][1]
    assert eng.pool.refcount(shared) == 3  # 2 slots + registry
    assert eng.prepare_decode({0: len(prompt), 1: len(prompt)}) == []
    # both slots COW'd the partial page to distinct private copies; the
    # registry keeps the pristine original
    assert eng._slot_pages[0][1] != shared
    assert eng._slot_pages[1][1] != shared
    assert eng._slot_pages[0][1] != eng._slot_pages[1][1]
    assert eng.pool.refcount(shared) == 1
    step = eng.decode(jnp.asarray([div_a, div_b], jnp.int32),
                      jnp.asarray([True, True]))
    np.testing.assert_array_equal(np.asarray(pre_a), ref_pre_a)
    np.testing.assert_array_equal(np.asarray(pre_b), ref_pre_b)
    np.testing.assert_array_equal(np.asarray(step[0]), ref_a)
    np.testing.assert_array_equal(np.asarray(step[1]), ref_b)
    # the cached prefix is still shareable after both divergences
    eng2_pages = eng.pool.match_prefix(
        prefix_page_keys(prompt, eng.page_size))
    assert len(eng2_pages) == 2 and eng2_pages[1] == shared


def test_prefill_raises_pool_exhausted_when_out_of_pages():
    """An admission the pool can't cover (even after LRU eviction)
    raises typed ``PoolExhausted`` — carrying need/free/cached — and
    leaks nothing: every transient reference is rolled back so the
    request can be retried after evictions."""
    cfg = _cfg()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    eng = _engine(params, cfg, num_pages=RESERVED_PAGES + 3,
                  prefix_sharing=False)
    assert eng.prefill(0, [5, 7, 11, 13, 17, 19, 23, 29]) is not None
    free_before = eng.pool.num_free
    with pytest.raises(PoolExhausted) as exc:
        eng.prefill(1, [2, 3, 4, 6, 8, 9, 10, 12])
    assert exc.value.need == 2
    assert exc.value.free == free_before
    assert exc.value.cached == 0
    assert eng.pool.num_free == free_before  # rollback, no leak
    eng.check_invariants()                   # books balance post-rollback
    # the retry is typed too — and still leak-free
    with pytest.raises(PoolExhausted):
        eng.prefill(1, [2, 3, 4, 6, 8, 9, 10, 12])
    assert eng.pool.num_free == free_before
    eng.free_slot(0)
    assert eng.pool.num_free == 3
    eng.check_invariants()


def test_page_demand_rejects_oversized_requests():
    cfg = _cfg()
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    eng = _engine(params, cfg, num_pages=RESERVED_PAGES + 3)
    eng.page_demand(12)  # 3 pages: fits
    with pytest.raises(ValueError, match="pages"):
        eng.page_demand(13)  # 4 pages > 3 usable

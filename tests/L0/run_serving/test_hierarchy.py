"""Hierarchical KV-cache: host-memory spill tier + prefix registry.

Covers the two-tier contract end to end:

- LRU-evicted sole-owned prefix pages SPILL to the shared
  :class:`PrefixRegistry`; registry hits at admission PROMOTE them
  back, and the promoted bytes are BITWISE equal to what was spilled
  (float pools and the int8 pool including its scale planes);
- pages a slot still attends (refcount > 1) never spill;
- committed streams are bit-identical to a spill-disabled engine —
  greedy and sampled, speculative on and off, chunked admission, and
  through the :class:`DisaggregatedRouter` pair sharing one registry;
- the ``host_spill`` / ``host_promote`` fault sites degrade gracefully
  (failed promote re-prefills) and multi-fault seeds replay
  bit-for-bit, with the registry audited every tick (``audit=True``);
- corrupt/stale registry records are quarantined (dropped, never
  installed) by the checksum + header verification.

``APEX_CHAOS_SPILL_SEED`` (comma-separated ints) overrides the seeds
the multi-fault leg sweeps — the CI chaos matrix fans these out.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    ContinuousBatchingScheduler, DisaggregatedRouter, FaultInjector,
    PagedDecodeEngine, PoolInvariantError, PrefixRegistry, Request,
    SpillRecord, Tracer, prefix_page_keys,
)

pytestmark = pytest.mark.chaos

EOS = -1
MAX_LEN = 32
_SPILL_SEEDS = tuple(
    int(s) for s in os.environ.get("APEX_CHAOS_SPILL_SEED",
                                   "0,1,2").split(","))

#: The hot prefix every hierarchy run re-admits (2 pages at
#: page_size 4), plus cold prompts that churn it out of HBM.
HOT = tuple(range(7, 15))
COLD = ((101, 102, 103, 104, 105, 106, 107, 108),
        (201, 202, 203, 204, 205, 206, 207, 208),
        (301, 302, 303, 304, 305, 306, 307, 308))


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, host_tier=None, injector=None, num_pages=10,
            **kw):
    cfg, params = model
    kw.setdefault("tracer", Tracer())
    kw.setdefault("cache_dtype", jnp.float32)
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), injector=injector,
                             host_tier=host_tier, **kw)


def _churn_reqs():
    """Admit HOT, churn it out through three cold prompts (the 8-page
    pool spills it), re-admit HOT — once greedy, once sampled."""
    return ([Request(prompt=HOT, max_new_tokens=4)]
            + [Request(prompt=p, max_new_tokens=4) for p in COLD]
            + [Request(prompt=HOT, max_new_tokens=4,
                       temperature=1.0, seed=3)])


def _drive(engine, reqs, **kw):
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS, audit=True,
                                        **kw)
    for r in reqs:
        sched.submit(r)
    return sched, sched.run()


@pytest.fixture(scope="module")
def golden_run(model):
    """ONE fault-free tier-less churn drive, shared by every test that
    only needs the golden streams (none of them reuse the engine)."""
    return _drive(_engine(model), _churn_reqs())


# -- spill / promote mechanics ----------------------------------------------

def test_spill_on_evict_then_promote_bitwise_equal(model):
    """Pool churn spills the hot prefix; re-admission promotes it and
    the promoted HBM pages carry the exact bytes that were spilled."""
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)
    keys = prefix_page_keys(list(HOT), eng.page_size)
    pages0 = list(eng._slot_pages[0])
    snap = [np.asarray(t) for t in eng._tier_extract(
        eng.cache, jnp.asarray(pages0, jnp.int32))]
    eng.free_slot(0)
    # drain the pool: the sweep must spill both registered hot pages
    held = []
    while True:
        p = eng.pool.alloc()
        if p is None:
            break
        held.append(p)
    assert eng.stats.host_spills == len(keys) == 2
    assert all(k in tier for k in keys)
    tier.check_invariants()
    for p in held:
        eng.pool.release(p)
    # promotion: the registry chain refills HBM with identical bytes
    promoted, ticks = eng._promote_chain(keys, 0)
    assert len(promoted) == 2 and ticks >= 1
    assert eng.stats.host_promotes == 2
    after = [np.asarray(t) for t in eng._tier_extract(
        eng.cache, jnp.asarray(promoted, jnp.int32))]
    for a, b in zip(snap, after):
        np.testing.assert_array_equal(a, b)
    for p in promoted:
        eng.pool.release(p)
    assert tier.hits == 2 and tier.hit_rate > 0


def test_int8_promote_roundtrips_pages_and_scales(model):
    """The int8 pool spills quantized tiles WITH their per-page scale
    planes; promotion restores both bitwise (a page that came back
    without its scales would dequantize wrong)."""
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier, cache_dtype=jnp.int8)
    eng.prefill(0, HOT)
    keys = prefix_page_keys(list(HOT), eng.page_size)
    pages0 = list(eng._slot_pages[0])
    snap = [np.asarray(t) for t in eng._tier_extract(
        eng.cache, jnp.asarray(pages0, jnp.int32))]
    assert len(snap) == 4  # k, v, k_scale, v_scale
    eng.free_slot(0)
    held = []
    while (p := eng.pool.alloc()) is not None:
        held.append(p)
    rec = tier.get(keys[0])
    assert rec is not None and rec.k_scale is not None
    assert rec.k.dtype == np.int8
    for p in held:
        eng.pool.release(p)
    promoted, _ = eng._promote_chain(keys, 0)
    assert len(promoted) == 2
    after = [np.asarray(t) for t in eng._tier_extract(
        eng.cache, jnp.asarray(promoted, jnp.int32))]
    for a, b in zip(snap, after):
        np.testing.assert_array_equal(a, b)
    for p in promoted:
        eng.pool.release(p)


def test_attended_pages_never_spill(model):
    """A page a slot still attends (refcount > 1) leaves the registry
    on the sweep WITHOUT spilling: only the registry's sole reference
    guarantees the rows are the pristine registered prefix."""
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)       # slot 0 holds the pages; registry too
    keys = prefix_page_keys(list(HOT), eng.page_size)
    assert all(eng.pool.refcount(p) == 2 for p in eng._slot_pages[0])
    held = []
    while (p := eng.pool.alloc()) is not None:
        held.append(p)
    assert eng.stats.host_spills == 0 and len(tier) == 0
    assert all(k not in tier for k in keys)
    # the slot still serves its prefix from HBM, untouched
    assert all(eng.pool.refcount(p) == 1 for p in eng._slot_pages[0])
    for p in held:
        eng.pool.release(p)
    eng.free_slot(0)
    eng.pool.check_invariants()


def test_registry_budget_lru_and_oversized_rejection(model):
    """Byte-budgeted LRU: admission evicts the coldest records to fit;
    a single record over the whole budget is rejected, not admitted."""
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)
    eng.free_slot(0)
    while eng.pool.alloc() is not None:
        pass
    rec = next(iter(tier._entries.values()))
    small = PrefixRegistry(rec.nbytes)          # exactly one record
    keys = list(tier._entries)
    assert small.put(keys[0], tier._entries[keys[0]])
    assert small.put(keys[1], tier._entries[keys[1]])
    assert len(small) == 1 and small.evictions == 1
    assert keys[0] not in small and keys[1] in small
    small.check_invariants()
    tiny = PrefixRegistry(rec.nbytes - 1)
    assert not tiny.put(keys[0], tier._entries[keys[0]])
    assert tiny.rejected == 1 and len(tiny) == 0
    # dedup: re-putting an existing key only refreshes recency
    assert not small.put(keys[1], tier._entries[keys[1]])


def test_registry_invariants_catch_corruption(model):
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)
    eng.free_slot(0)
    while eng.pool.alloc() is not None:
        pass
    tier.check_invariants()
    key = next(iter(tier._entries))
    rec = tier._entries[key]
    tier._entries[key] = rec._replace(
        k=np.ascontiguousarray(rec.k) + 1)      # payload no longer
    with pytest.raises(PoolInvariantError,                # checksums
                       match="fails its spill checksum"):
        tier.check_invariants()
    tier._entries[key] = rec
    tier._bytes += 1
    with pytest.raises(PoolInvariantError, match="drifted"):
        tier.check_invariants()
    tier._bytes -= 1
    with pytest.raises(ValueError, match="different chain key"):
        tier.put(b"\x00" * 32, rec)


def test_corrupt_record_quarantined_promote_degrades(model, golden_run):
    """A record whose payload rotted in host memory fails checksum
    verification at promote time: it is DROPPED (never installed) and
    the admission silently re-prefills — committed stream untouched."""
    _, golden = golden_run
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)
    eng.free_slot(0)
    while eng.pool.alloc() is not None:
        pass
    keys = prefix_page_keys(list(HOT), eng.page_size)
    rec = tier._entries[keys[0]]
    flipped = np.ascontiguousarray(rec.k).copy()
    flipped.flat[0] = -flipped.flat[0] if flipped.flat[0] else 1
    tier._entries[keys[0]] = SpillRecord(
        rec.header, flipped, rec.v, rec.k_scale, rec.v_scale,
        rec.digest)
    promoted, ticks = eng._promote_chain(keys, 0)
    assert promoted == [] and ticks == 0
    assert eng.stats.host_promote_failures == 1
    assert keys[0] not in tier        # quarantined
    # and a full scheduler run over the same shape stays golden
    tier2 = PrefixRegistry(1 << 20)
    eng2 = _engine(model, host_tier=tier2)
    _, outs = _drive(eng2, _churn_reqs())
    assert outs == golden


def test_stale_header_key_is_rejected(model):
    """A record registered under one chain key can never install under
    another — the transfer tier's wrong-prompt guarantee, extended."""
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    eng.prefill(0, HOT)
    eng.free_slot(0)
    while eng.pool.alloc() is not None:
        pass
    keys = prefix_page_keys(list(HOT), eng.page_size)
    other = prefix_page_keys([9, 9, 9, 9], eng.page_size)
    rec = tier._entries[keys[0]]
    # graft the foreign record under 'other' bypassing put()'s check
    tier._entries[other[0]] = rec
    tier._bytes += rec.nbytes
    promoted, _ = eng._promote_chain(other, 0)
    assert promoted == []
    assert eng.stats.host_promote_failures == 1
    assert other[0] not in tier


# -- stream bit-identity -----------------------------------------------------

@pytest.mark.parametrize("variant", ["plain", "spec", "chunked"])
def test_streams_bit_identical_to_spill_disabled(model, golden_run,
                                                 variant):
    """The hierarchy is invisible to committed streams: greedy AND
    sampled tokens match a spill-disabled engine bit for bit, with
    spec decode on, and under chunked admission — while the spill and
    promote paths demonstrably ran."""
    eng_kw = {"spec_k": 2} if variant == "spec" else {}
    sched_kw = {"chunk_tokens": 8} if variant == "chunked" else {}
    if variant == "plain":
        _, golden = golden_run
    else:
        _, golden = _drive(_engine(model, **eng_kw), _churn_reqs(),
                           **sched_kw)
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier, **eng_kw)
    _, outs = _drive(eng, _churn_reqs(), **sched_kw)
    assert outs == golden
    assert eng.stats.host_spills > 0
    assert eng.stats.host_promotes > 0
    assert eng.stats.host_promote_ticks >= 1
    assert tier.hit_rate > 0


def test_int8_streams_bit_identical(model):
    """The int8 pool keeps its monolithic prefill (the chunk core
    refuses quantized pools) — promotion is purely a capacity win and
    the streams must not move."""
    _, golden = _drive(_engine(model, cache_dtype=jnp.int8),
                       _churn_reqs())
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier, cache_dtype=jnp.int8)
    _, outs = _drive(eng, _churn_reqs())
    assert outs == golden
    assert eng.stats.host_promotes > 0


def test_promote_reprices_the_admission_clock(model, golden_run):
    """A host-tier hit admits at the SUFFIX depth plus promote ticks —
    the re-admitted hot prompt's TTFT beats the spill-disabled
    engine's re-prefill on the tick clock."""
    sched_b, golden = golden_run
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier)
    sched_a, outs = _drive(eng, _churn_reqs())
    assert outs == golden
    rid = len(_churn_reqs()) - 1              # the re-admitted HOT
    ttft_a = sched_a.outcomes[rid].ttft_ticks
    ttft_b = sched_b.outcomes[rid].ttft_ticks
    assert ttft_a < ttft_b, (ttft_a, ttft_b)


# -- fault sites -------------------------------------------------------------

def test_host_spill_fault_drops_the_spill_gracefully(model, golden_run):
    """A fired ``host_spill`` drops that page from both tiers; streams
    stay golden (the prefix just re-prefills later)."""
    _, golden = golden_run
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier,
                  injector=FaultInjector(schedule={"host_spill": (0,)}))
    _, outs = _drive(eng, _churn_reqs())
    assert outs == golden
    assert eng.stats.host_spill_failures == 1


def test_host_promote_fault_degrades_to_reprefill(model, golden_run):
    """A fired ``host_promote`` breaks the chain mid-promotion; the
    remainder re-prefills and the committed stream stays golden."""
    _, golden = golden_run
    tier = PrefixRegistry(1 << 20)
    eng = _engine(model, host_tier=tier,
                  injector=FaultInjector(
                      schedule={"host_promote": (0,)}))
    _, outs = _drive(eng, _churn_reqs())
    assert outs == golden
    assert eng.stats.host_promote_failures == 1


@pytest.mark.parametrize("seed", _SPILL_SEEDS)
def test_multi_fault_seeds_stay_golden_and_replay(model, golden_run,
                                                  seed):
    """Rate-driven spill AND promote faults together: every run stays
    bit-identical to golden (these sites never corrupt streams), and
    the same seed replays the same fault pattern and stats."""
    _, golden = golden_run

    def run():
        tier = PrefixRegistry(1 << 20)
        eng = _engine(model, host_tier=tier,
                      injector=FaultInjector(
                          seed=seed, rates={"host_spill": 0.5,
                                            "host_promote": 0.5}))
        _, outs = _drive(eng, _churn_reqs())
        return eng, tier, outs

    eng_a, tier_a, outs_a = run()
    eng_b, tier_b, outs_b = run()
    assert outs_a == golden and outs_b == golden
    assert outs_a == outs_b
    for f in ("host_spills", "host_spill_failures", "host_promotes",
              "host_promote_failures", "host_promote_ticks"):
        assert getattr(eng_a.stats, f) == getattr(eng_b.stats, f), f
    assert tier_a.stats() == tier_b.stats()
    assert eng_a.injector.counts == eng_b.injector.counts
    # CI post-mortem artifact: one Perfetto dump per sweep seed,
    # uploaded by the chaos workflow legs
    out_path = os.environ.get("APEX_CHAOS_TRACE_OUT")
    if out_path:
        root, ext = os.path.splitext(out_path)
        eng_a.tracer.dump_jsonl(
            f"{root}.spill_seed{seed}{ext or '.jsonl'}")


# -- the disaggregated pair --------------------------------------------------

def _disagg(model, tier, reqs):
    cfg, params = model
    inj, trc = FaultInjector(), Tracer()
    kw = dict(num_slots=2, max_len=MAX_LEN, num_pages=10, page_size=4,
              buckets=(16, 32), cache_dtype=jnp.float32, injector=inj,
              tracer=trc, host_tier=tier)
    pe = PagedDecodeEngine(params, cfg, **kw)
    de = PagedDecodeEngine(params, cfg, **kw)
    router = DisaggregatedRouter(pe, de, eos_id=EOS, audit=True)
    for r in reqs:
        router.submit(r)
    return pe, de, router, router.run()


def test_disagg_pair_shares_one_registry(model):
    """Both replicas spill into and promote from the SAME registry —
    one replica's prefill seeds everyone's cache — and the routed
    streams stay bit-identical to the tier-less pair."""
    _, _, _, golden = _disagg(model, None, _churn_reqs())
    tier = PrefixRegistry(1 << 20)
    pe, de, router, outs = _disagg(model, tier, _churn_reqs())
    assert outs == golden
    assert de.stats.host_promotes > 0       # active-side promotion
    assert pe.stats.host_spills + de.stats.host_spills > 0
    assert tier.hit_rate > 0


def test_disagg_rejects_mismatched_tiers(model):
    cfg, params = model
    inj, trc = FaultInjector(), Tracer()
    kw = dict(num_slots=2, max_len=MAX_LEN, num_pages=10, page_size=4,
              buckets=(16, 32), injector=inj, tracer=trc)
    pe = PagedDecodeEngine(params, cfg, host_tier=PrefixRegistry(1024),
                           **kw)
    de = PagedDecodeEngine(params, cfg, host_tier=None, **kw)
    with pytest.raises(ValueError, match="share ONE PrefixRegistry"):
        DisaggregatedRouter(pe, de, eos_id=EOS)


def test_int8_engine_requires_known_dtype_tag(model):
    cfg, params = model
    with pytest.raises(ValueError, match="no spill wire tag"):
        PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                          num_pages=10, page_size=4, buckets=(16, 32),
                          cache_dtype=jnp.int32,
                          host_tier=PrefixRegistry(1024))

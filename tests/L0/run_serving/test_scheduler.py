"""Continuous-batching scheduler: admit/evict lifecycle over a fixed
slot pool, and output invariance to slot placement and pool size —
including the paged engine (page placement, pool pressure, and
preemption-by-requeue must all be invisible in the outputs)."""

import dataclasses

import jax
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (ContinuousBatchingScheduler, DecodeEngine,
                              PagedDecodeEngine, Request)

EOS = 0
MAX_LEN = 32


def _cfg():
    return dataclasses.replace(gpt_tiny(), use_rope=True,
                               hidden_dropout=0.0)


def _params(cfg):
    return init_gpt(jax.random.PRNGKey(0), cfg)


def _run(params, cfg, requests, num_slots, top_k=0):
    engine = DecodeEngine(params, cfg, num_slots=num_slots,
                          max_len=MAX_LEN, top_k=top_k)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in requests:
        sched.submit(r)
    return sched.run()


def test_more_requests_than_slots():
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(2 + i, 3 + i, 5 + i), max_new_tokens=5)
            for i in range(5)]
    outs = _run(params, cfg, reqs, num_slots=2)
    assert len(outs) == 5
    for toks in outs:
        assert 1 <= len(toks) <= 5
        assert all(isinstance(t, int) for t in toks)
        if len(toks) < 5:  # early exit only ever means EOS
            assert toks[-1] == EOS


def test_greedy_output_independent_of_num_slots():
    """The same greedy request set must decode to the same tokens
    whether it runs 1-at-a-time or fully batched — slot packing is a
    throughput concern, never a numerics one."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=4),
            Request(prompt=(17, 19), max_new_tokens=4),
            Request(prompt=(23, 29, 31, 37), max_new_tokens=4)]
    a = _run(params, cfg, reqs, num_slots=1)
    b = _run(params, cfg, reqs, num_slots=3)
    assert a == b


def test_seeded_sampling_independent_of_slot_placement():
    """Per-request keys are derived from (seed, tokens generated so
    far), not from slot index or admission order — so a sampled request
    is reproducible regardless of what else shares the batch."""
    cfg = _cfg()
    params = _params(cfg)
    probe = Request(prompt=(5, 7, 11), max_new_tokens=6,
                    temperature=0.8, seed=42)
    alone = _run(params, cfg, [probe], num_slots=1)[0]
    filler = [Request(prompt=(2, 3), max_new_tokens=6,
                      temperature=0.9, seed=i) for i in range(3)]
    crowded = _run(params, cfg, [probe] + filler, num_slots=4)[0]
    assert alone == crowded


def test_max_new_tokens_respected():
    cfg = _cfg()
    params = _params(cfg)
    outs = _run(params, cfg, [Request(prompt=(3, 5), max_new_tokens=1),
                              Request(prompt=(3, 5), max_new_tokens=3)],
                num_slots=2)
    assert len(outs[0]) == 1
    assert len(outs[1]) <= 3


def test_submit_validates():
    cfg = _cfg()
    engine = DecodeEngine(_params(cfg), cfg, num_slots=1,
                          max_len=MAX_LEN)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    with pytest.raises(ValueError):
        sched.submit(Request(prompt=()))
    with pytest.raises(ValueError):
        sched.submit(Request(prompt=tuple(range(MAX_LEN + 1))))


def test_run_on_empty_queue():
    cfg = _cfg()
    engine = DecodeEngine(_params(cfg), cfg, num_slots=1,
                          max_len=MAX_LEN)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    assert sched.run() == []


# -- paged engine -----------------------------------------------------------

def _run_paged(params, cfg, requests, num_slots, num_pages, page_size=4,
               free_order=None):
    engine = PagedDecodeEngine(params, cfg, num_slots=num_slots,
                               max_len=MAX_LEN, num_pages=num_pages,
                               page_size=page_size, buckets=(16, 32),
                               free_order=free_order)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in requests:
        sched.submit(r)
    return sched.run(), engine


def _mixed_requests():
    return [Request(prompt=(7, 11, 13), max_new_tokens=5),
            Request(prompt=(17, 19), max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=(7, 11, 13, 29), max_new_tokens=4),
            Request(prompt=(7, 11, 13), max_new_tokens=5,
                    temperature=0.7, seed=9)]


def test_paged_outputs_match_dense():
    """The paged engine is a drop-in for the dense one: the same
    request mix (greedy + seeded sampling, shared prompt prefixes)
    through the same scheduler produces identical token streams."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _mixed_requests()
    engine = DecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                          buckets=(16, 32))
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in reqs:
        sched.submit(r)
    dense = sched.run()
    paged, _ = _run_paged(params, cfg, reqs, num_slots=2, num_pages=20)
    assert paged == dense


def test_paged_outputs_independent_of_page_placement():
    """Permuted free-list orders scatter the same requests across
    different physical pages — the outputs (including seeded sampling)
    must not change."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg = _cfg()
    params = _params(cfg)
    reqs = _mixed_requests()
    usable = list(range(RESERVED_PAGES, 20))
    a, _ = _run_paged(params, cfg, reqs, num_slots=2, num_pages=20)
    b, _ = _run_paged(params, cfg, reqs, num_slots=2, num_pages=20,
                      free_order=list(reversed(usable)))
    assert a == b


def test_paged_preemption_requeues_and_resumes():
    """A pool too small for the full batch preempts a slot mid-decode
    (pages released, request requeued WITH its progress); the resumed
    request must finish with exactly the tokens an uncontended run
    produces — preemption is a capacity event, never a numerics one."""
    cfg = _cfg()
    params = _params(cfg)
    # two greedy requests, each individually fine (4 pages needed, 5
    # usable) but over-committed together: both cross a page boundary
    # at pos 8 and only one new page remains
    reqs = [Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=8),
            Request(prompt=(23, 29, 31, 37, 41), max_new_tokens=8)]
    roomy, _ = _run_paged(params, cfg, reqs, num_slots=2, num_pages=20)

    engine = PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                               num_pages=7, page_size=4,
                               buckets=(16, 32))
    preempted = []
    orig = engine.prepare_decode

    def spy(positions, n_new=1):
        out = orig(positions, n_new=n_new)
        preempted.extend(out)
        return out

    engine.prepare_decode = spy
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in reqs:
        sched.submit(r)
    tight = sched.run()
    assert preempted  # the pool pressure actually bit
    assert tight == roomy


def test_paged_cow_exact_fit_pool_completes():
    """Regression (livelock): with prefix sharing on, prefill
    registers the prompt's partial last page (refcount 2), so the
    first decode append wants a COW page — transiently one MORE page
    than submit validated. With usable pages == the validated need the
    clone alloc fails; the old code preempted, and re-admission
    recreated the identical state, spinning run() forever. The failed
    alloc's LRU sweep already dropped the registry's reference, so the
    append is in-place legal and the run must finish with exactly the
    uncontended tokens."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg = _cfg()
    params = _params(cfg)
    # 5-token prompt + 3 new = 8 rows = exactly 2 pages of 4
    req = Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=3)
    roomy, _ = _run_paged(params, cfg, [req], num_slots=1, num_pages=20)

    engine = PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                               num_pages=2 + RESERVED_PAGES, page_size=4,
                               buckets=(16, 32))
    prefills = 0
    orig = engine.prefill

    def spy(slot, prompt):
        nonlocal prefills
        prefills += 1
        assert prefills < 10, "re-prefilling forever — COW livelock"
        return orig(slot, prompt)

    engine.prefill = spy
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    sched.submit(req)
    assert sched.run() == roomy


def test_preempted_slots_requeue_in_submission_order():
    """Several slots preempted in one tick must rejoin the queue front
    in submission order, not slot-index order (FIFO fairness)."""
    cfg = _cfg()
    params = _params(cfg)
    engine = PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                               num_pages=20, page_size=4,
                               buckets=(16, 32))
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    # request 0 finishes on its prefill logits, freeing slot 0 for
    # request 2 — leaving the LATER request in the LOWER slot
    sched.submit(Request(prompt=(3, 5), max_new_tokens=1))
    sched.submit(Request(prompt=(7, 11), max_new_tokens=8))
    sched._admit()
    sched.submit(Request(prompt=(13, 17), max_new_tokens=8))
    sched._admit()
    assert [s.request_id for s in sched._slots] == [2, 1]
    engine.prepare_decode = lambda positions, n_new=1: list(positions)
    sched._tick()
    assert [rid for rid, _, _ in sched._queue] == [1, 2]


def test_paged_prefill_rejects_oversized_prompt():
    """Engine-level guard: prefill driven directly (without the
    scheduler's submit check) must reject a prompt beyond max_len with
    a clear error, before any page references are taken."""
    cfg = _cfg()
    params = _params(cfg)
    engine = PagedDecodeEngine(params, cfg, num_slots=1, max_len=8,
                               num_pages=20, page_size=4, buckets=(4, 8))
    free_before = engine.pool.num_free
    with pytest.raises(ValueError, match="max_len"):
        engine.prefill(0, tuple(range(2, 11)))
    assert engine.pool.num_free == free_before  # nothing leaked


# -- speculative decoding ---------------------------------------------------
#
# THE contract: spec_k only changes how many ticks a stream takes,
# never which tokens it emits. Every test here compares committed
# token streams with == (exact integer equality) against the plain
# spec_k=0 run — tolerance would hide a real divergence in the accept
# rule or the verify step's rollback.

def _spec_requests():
    # repetitive prompts give the n-gram drafter traction (suffixes
    # recur, so real accept/reject mixes are exercised, not just the
    # all-rejected path); the sampled requests pin the
    # fold_in(seed, n_generated + j) key alignment
    return [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=8),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=8,
                    temperature=0.8, seed=3),
            Request(prompt=(7, 11, 7, 11), max_new_tokens=6,
                    temperature=0.7, seed=9),
            Request(prompt=(13, 17, 19), max_new_tokens=5)]


def _spec_stats(params, cfg, requests, num_slots, spec_k, paged):
    if paged:
        engine = PagedDecodeEngine(params, cfg, num_slots=num_slots,
                                   max_len=MAX_LEN, num_pages=24,
                                   page_size=4, buckets=(16, 32),
                                   spec_k=spec_k)
    else:
        engine = DecodeEngine(params, cfg, num_slots=num_slots,
                              max_len=MAX_LEN, buckets=(16, 32),
                              spec_k=spec_k)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS,
                                        audit=paged)
    for r in requests:
        sched.submit(r)
    return sched.run(), sched.stats


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
@pytest.mark.parametrize("spec_k", [1, 2, 3])
def test_spec_stream_bit_identical_to_plain(spec_k, paged):
    """Greedy + seeded-sampled requests through the draft→verify→accept
    loop: the committed streams equal the plain spec_k=0 streams
    token-for-token, at every draft depth, on both cache layouts."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _spec_requests()
    plain, _ = _spec_stats(params, cfg, reqs, 2, 0, paged)
    spec, stats = _spec_stats(params, cfg, reqs, 2, spec_k, paged)
    assert spec == plain
    assert stats.tokens_drafted > 0  # the drafter actually proposed
    assert stats.tokens_accepted >= 0


def test_spec_accepts_make_progress():
    """On a maximally predictable greedy stream the accept walk must
    actually commit drafted tokens (acceptance_rate > 0) — otherwise
    spec mode silently degenerates to plain decode plus overhead."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 7, 11, 7, 11, 7), max_new_tokens=10)]
    plain, _ = _spec_stats(params, cfg, reqs, 1, 0, True)
    spec, stats = _spec_stats(params, cfg, reqs, 1, 3, True)
    assert spec == plain
    assert stats.tokens_accepted > 0
    assert 0.0 < stats.acceptance_rate <= 1.0


def test_spec_stream_independent_of_slot_placement():
    """The sampled probe request decodes to the same stream alone and
    crowded, under spec — keys stay a pure function of
    (seed, n_generated), never of slot index or batch mix."""
    cfg = _cfg()
    params = _params(cfg)
    probe = Request(prompt=(5, 7, 5, 7, 5), max_new_tokens=6,
                    temperature=0.8, seed=42)
    alone, _ = _spec_stats(params, cfg, [probe], 1, 2, True)
    filler = [Request(prompt=(2, 3, 2, 3), max_new_tokens=6,
                      temperature=0.9, seed=i) for i in range(3)]
    crowded, _ = _spec_stats(params, cfg, [probe] + filler, 4, 2, True)
    assert alone[0] == crowded[0]


def test_spec_respects_max_new_tokens_and_eos():
    """A verify tick can sample EOS or hit max_new_tokens mid-grid —
    the walk must stop committing exactly where the plain stream
    stops (never over-commit from an accepted tail)."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 7, 11), max_new_tokens=1),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=2),
            Request(prompt=(13, 17, 13, 17), max_new_tokens=16)]
    plain, _ = _spec_stats(params, cfg, reqs, 3, 0, True)
    spec, _ = _spec_stats(params, cfg, reqs, 3, 3, True)
    assert spec == plain
    assert len(spec[0]) == 1 and len(spec[1]) <= 2


def test_spec_near_max_len_degrades_to_plain():
    """When any active slot is within spec_k+1 rows of max_len the tick
    runs plain (the dynamic_update_slice clamp hazard) — streams still
    finish and match the plain run exactly."""
    cfg = _cfg()
    params = _params(cfg)
    # 5 prompt + 8 new = 13 of max_len 16: the last ticks CANNOT fit a
    # k=3 verify window, so the guard must kick in
    def run(spec_k):
        engine = PagedDecodeEngine(params, cfg, num_slots=1, max_len=16,
                                   num_pages=24, page_size=4,
                                   buckets=(8, 16), spec_k=spec_k)
        sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
        sched.submit(Request(prompt=(7, 11, 7, 11, 7),
                             max_new_tokens=8))
        return sched.run()

    assert run(3) == run(0)


def test_paged_submit_validates_page_demand():
    cfg = _cfg()
    engine = PagedDecodeEngine(_params(cfg), cfg, num_slots=1,
                               max_len=MAX_LEN, num_pages=5, page_size=4,
                               buckets=(16, 32))
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    with pytest.raises(ValueError, match="pages"):
        # 3 usable pages = 12 rows; 5 prompt + 8 new = 13 can't fit
        sched.submit(Request(prompt=(2, 3, 5, 7, 11), max_new_tokens=8))
    sched.submit(Request(prompt=(2, 3, 5, 7, 11), max_new_tokens=7))
    outs = sched.run()
    assert len(outs) == 1 and 1 <= len(outs[0]) <= 7


# -- model-based & tree speculation -----------------------------------------
#
# Same contract as linear n-gram spec, new machinery: a TP-shardable
# draft GPT proposes the candidates (DraftModel), optionally as trees
# verified in one forward through the ancestor-matrix mask, with a
# per-stream adaptive depth controller. Every mode must keep committed
# streams integer-identical to plain spec_k=0 decode.

def _draft_for(params, cfg, num_slots):
    # the TARGET doubles as its own drafter: acceptance is high, so the
    # accept walk, the tree path commit, and the draft-cache resync all
    # run on real accept/reject mixes instead of the all-rejected path
    from apex_tpu.serving import DraftModel
    return DraftModel(params, cfg, num_slots=num_slots, max_len=MAX_LEN)


def _model_spec_run(params, cfg, requests, num_slots, spec_k, paged,
                    tree=False, adaptive=False, self_draft=True):
    if self_draft:
        dm = _draft_for(params, cfg, num_slots) if spec_k else None
    else:  # a genuinely different (randomly-initialised) draft net
        dm = (None if not spec_k else
              _draft_for(init_gpt(jax.random.PRNGKey(99), cfg), cfg,
                         num_slots))
    kw = dict(spec_k=spec_k, draft_model=dm, tree_spec=tree,
              adaptive_spec=adaptive)
    if not spec_k:
        kw = {}
    if paged:
        engine = PagedDecodeEngine(params, cfg, num_slots=num_slots,
                                   max_len=MAX_LEN, num_pages=24,
                                   page_size=4, buckets=(16, 32), **kw)
    else:
        engine = DecodeEngine(params, cfg, num_slots=num_slots,
                              max_len=MAX_LEN, buckets=(16, 32), **kw)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS, audit=paged)
    for r in requests:
        sched.submit(r)
    return sched.run(), sched.stats


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_model_draft_stream_bit_identical_to_plain(paged):
    """Model-drafted linear speculation (greedy + seeded sampled): the
    committed streams equal the plain run token-for-token, and the
    self-draft actually lands accepts (the resync path is exercised on
    both full and partial acceptance)."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _spec_requests()
    plain, _ = _model_spec_run(params, cfg, reqs, 2, 0, paged)
    spec, stats = _model_spec_run(params, cfg, reqs, 2, 3, paged)
    assert spec == plain
    assert stats.tokens_drafted > 0
    assert stats.tokens_accepted > 0  # self-draft must make progress


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_tree_spec_stream_bit_identical_to_plain(paged):
    """Tree speculation: multi-branch drafts verified in ONE forward
    via the ancestor mask, the accept walk following the committed
    root-to-leaf path. Streams stay integer-identical to plain decode
    on both layouts, and the tree path commits accepted tokens."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _spec_requests()
    plain, _ = _model_spec_run(params, cfg, reqs, 2, 0, paged)
    spec, stats = _model_spec_run(params, cfg, reqs, 2, 3, paged,
                                  tree=True)
    assert spec == plain
    assert stats.spec_ticks > 0
    assert stats.tokens_accepted > 0


def test_tree_spec_with_mismatched_draft_still_exact():
    """A randomly-initialised draft net proposes mostly-wrong trees —
    the rejected tails and the forced-chain re-sends must still leave
    the committed stream exactly equal to plain decode (the rollback /
    resync contract under worst-case rejection)."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _spec_requests()
    plain, _ = _model_spec_run(params, cfg, reqs, 2, 0, False)
    spec, _ = _model_spec_run(params, cfg, reqs, 2, 3, False,
                              tree=True, self_draft=False)
    assert spec == plain


def test_ngram_tree_spec_matches_plain():
    """tree_spec without a draft model: n-gram chains ride the tree
    verify path as single-branch trees. Still exact."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _spec_requests()
    engine = DecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                          buckets=(16, 32), spec_k=3, tree_spec=True)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in reqs:
        sched.submit(r)
    plain, _ = _model_spec_run(params, cfg, reqs, 2, 0, False)
    assert sched.run() == plain


def test_adaptive_controller_converges_to_plain():
    """On an adversarial stream (high-temperature sampling against a
    mismatched draft net) the per-stream EWMA controller must shrink
    spec_k to plain ticks: the run stays integer-identical to plain
    decode, most ticks are plain, and the tick count never exceeds the
    plain run's (each tick commits >= 1 token, so adaptive spec can
    only match or beat plain pace — the same-process A/B contract)."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(3, 1, 4, 1, 5), max_new_tokens=20,
                    temperature=5.0, seed=123),
            Request(prompt=(2, 7, 1, 8), max_new_tokens=20,
                    temperature=4.0, seed=77)]
    plain, pstats = _model_spec_run(params, cfg, reqs, 2, 0, False)
    out, stats = _model_spec_run(params, cfg, reqs, 2, 4, False,
                                 adaptive=True, self_draft=False)
    assert out == plain
    assert stats.plain_ticks > stats.spec_ticks  # converged toward plain
    assert (stats.plain_ticks + stats.spec_ticks
            <= pstats.plain_ticks)  # never slower than plain (in ticks)


def test_adaptive_controller_keeps_speculating_when_accepted():
    """The flip side: with the target as its own drafter, acceptance
    stays high and the controller must KEEP the depth up (mostly spec
    ticks), finishing in fewer ticks than plain decode."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=12),
            Request(prompt=(13, 17, 19), max_new_tokens=12)]
    plain, pstats = _model_spec_run(params, cfg, reqs, 2, 0, False)
    out, stats = _model_spec_run(params, cfg, reqs, 2, 3, False,
                                 adaptive=True)
    assert out == plain
    assert stats.spec_ticks > 0
    assert (stats.plain_ticks + stats.spec_ticks
            < pstats.plain_ticks)  # strictly fewer parameter reads


def test_spec_config_validation():
    """draft_model / tree_spec / adaptive_spec all require spec_k >= 1;
    the draft net must match the target's slot count and vocab; tree
    verify refuses the int8 page pool."""
    import jax.numpy as jnp
    cfg = _cfg()
    params = _params(cfg)
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                     tree_spec=True)
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                     adaptive_spec=True)
    with pytest.raises(ValueError, match="slots"):
        DecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                     spec_k=2, draft_model=_draft_for(params, cfg, 1))
    with pytest.raises(ValueError, match="int8"):
        PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                          num_pages=24, page_size=4, spec_k=2,
                          tree_spec=True, cache_dtype=jnp.int8)


# -- chunked prefill ---------------------------------------------------------
#
# Same invariance contract as speculation: ``chunk_tokens=`` only moves
# WHEN prompt work runs (between decode ticks, under the tick token
# budget), never which tokens any stream commits. Every comparison is
# exact integer equality against the synchronous (monolithic-admission)
# scheduler.


def _chunky_requests():
    """_mixed_requests stretched: prompts long enough that
    chunk_tokens in {4, 8} actually splits them, with a shared prefix
    pair and mixed greedy/sampled."""
    base = (7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47)
    return [Request(prompt=base, max_new_tokens=5),
            Request(prompt=base[:9], max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=base + (53, 59, 61), max_new_tokens=4),
            Request(prompt=(5, 3), max_new_tokens=5,
                    temperature=0.7, seed=9)]


def _run_chunked(params, cfg, requests, num_slots, chunk_tokens,
                 paged=False, num_pages=24, spec_k=0,
                 tick_token_budget=None):
    # fp32 cache on BOTH sides of every comparison: the identity
    # contract is "chunking moves when prompt work runs, never the
    # math" — at bf16 the cache itself rounds K/V, so a monolithic
    # forward (unrounded in-forward activations) and a chunked one
    # (re-read rounded cache) can legitimately differ in the last bit.
    import jax.numpy as jnp

    if paged:
        engine = PagedDecodeEngine(params, cfg, num_slots=num_slots,
                                   max_len=MAX_LEN, num_pages=num_pages,
                                   page_size=4, buckets=(16, 32),
                                   spec_k=spec_k,
                                   cache_dtype=jnp.float32)
    else:
        engine = DecodeEngine(params, cfg, num_slots=num_slots,
                              max_len=MAX_LEN,
                              cache_dtype=jnp.float32)
    sched = ContinuousBatchingScheduler(
        engine, eos_id=EOS, audit=paged, chunk_tokens=chunk_tokens,
        tick_token_budget=tick_token_budget)
    for r in requests:
        sched.submit(r)
    return sched.run(), sched


@pytest.mark.parametrize("chunk_tokens", [4, 8])
def test_chunked_streams_match_sync_dense(chunk_tokens):
    cfg = _cfg()
    params = _params(cfg)
    reqs = _chunky_requests()
    want, _ = _run_chunked(params, cfg, reqs, 2, None)  # sync golden
    got, sched = _run_chunked(params, cfg, reqs, 2, chunk_tokens)
    assert got == want
    # the prompts really were split, not admitted in one piece
    assert sched.stats.prefill_chunks > len(reqs)


@pytest.mark.parametrize("chunk_tokens", [4, 8])
def test_chunked_streams_match_sync_paged(chunk_tokens):
    cfg = _cfg()
    params = _params(cfg)
    reqs = _chunky_requests()
    want, _ = _run_chunked(params, cfg, reqs, 2, None, paged=True)
    got, sched = _run_chunked(params, cfg, reqs, 2, chunk_tokens,
                              paged=True)
    assert got == want
    assert sched.stats.prefill_chunks > len(reqs)


def test_chunked_streams_invariant_to_tick_token_budget():
    """The budget only throttles how many chunks share a tick — a huge
    budget (whole prompts per tick) and the tight default must commit
    the same tokens."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = _chunky_requests()
    tight, _ = _run_chunked(params, cfg, reqs, 2, 4, paged=True)
    wide, _ = _run_chunked(params, cfg, reqs, 2, 4, paged=True,
                           tick_token_budget=64)
    assert tight == wide


def test_chunked_spec_streams_match_plain_sync():
    """Chunked prefill composes with speculative decode: the chunked +
    speculating scheduler still matches the plain synchronous one
    token-for-token (spec == plain and chunked == sync, composed)."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 7, 11, 7, 11, 7, 11, 7, 11),
                    max_new_tokens=6),
            Request(prompt=(5, 3, 5, 3, 5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.8, seed=3)]
    want, _ = _run_chunked(params, cfg, reqs, 2, None, paged=True)
    got, sched = _run_chunked(params, cfg, reqs, 2, 4, paged=True,
                              spec_k=3)
    assert got == want
    assert sched.stats.prefill_chunks > len(reqs)
    assert sched.stats.tokens_drafted > 0  # speculation really ran


def test_chunked_final_logits_match_one_shot_paged():
    """Engine-level contract: the final chunk's last-token logits are
    BITWISE equal to a one-shot prefill of the same prompt — same
    jitted executable family, same padded math, no chunk-count drift."""
    import numpy as np

    import jax.numpy as jnp

    cfg = _cfg()
    params = _params(cfg)
    prompt = tuple(range(2, 2 + 13))    # 13 tokens -> 4 chunks of 4

    def engine():
        # fp32 cache for the same reason as _run_chunked: bitwise is
        # only promised where the cache itself doesn't round
        return PagedDecodeEngine(params, cfg, num_slots=1,
                                 max_len=MAX_LEN, num_pages=24,
                                 page_size=4, buckets=(16, 32),
                                 cache_dtype=jnp.float32)

    one_shot = np.asarray(engine().prefill(0, prompt))
    eng = engine()
    state = eng.begin_chunk_prefill(0, prompt)
    pos, ct = int(state.get("start", 0)), 4
    while True:
        chunk = prompt[pos:pos + ct]
        final = pos + ct >= len(prompt)
        logits = eng.chunk_prefill(0, chunk, pos, state, ct, final)
        if final:
            break
        pos += ct
    eng.finish_chunk_prefill(0, state)
    eng.check_invariants()
    assert np.array_equal(np.asarray(logits), one_shot)


def test_chunked_bounds_cotenant_itl_tail_on_the_tick_clock():
    """The point of the feature, on the deterministic work-charged
    clock: a long prompt admitted mid-run opens an inter-token gap in
    the co-tenant stream equal to its WHOLE prefill when monolithic,
    but bounded near chunk_tokens when chunked — with the committed
    streams themselves identical."""
    from apex_tpu.serving import Tracer

    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(5, 3), max_new_tokens=12),
            Request(prompt=(3, 5), max_new_tokens=4),
            Request(prompt=tuple(range(2, 26)), max_new_tokens=2)]

    def run(chunk_tokens):
        trc = Tracer()
        engine = PagedDecodeEngine(params, cfg, num_slots=2,
                                   max_len=MAX_LEN, num_pages=24,
                                   page_size=4, buckets=(16, 32),
                                   tracer=trc)
        # eos_id=-1: unreachable, so the co-tenant really decodes all
        # 12 tokens while the long prompt prefills
        sched = ContinuousBatchingScheduler(engine, eos_id=-1,
                                            chunk_tokens=chunk_tokens)
        for r in reqs:
            sched.submit(r)
        return sched.run(), trc.latency_summary()["itl_p99"]

    streams_c, tail_chunked = run(4)
    streams_m, tail_mono = run(None)
    assert streams_c == streams_m       # identity first, then latency
    assert tail_chunked < tail_mono     # the tail actually collapsed


def test_chunk_config_validation():
    """chunk_tokens must be >= 1, divide max_len, be page-aligned on a
    paged engine, and is refused over the int8 page pool; the tick
    token budget must be positive."""
    import jax.numpy as jnp

    cfg = _cfg()
    params = _params(cfg)
    dense = DecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN)
    paged = PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                              num_pages=8, page_size=4,
                              buckets=(16, 32))
    with pytest.raises(ValueError, match=">= 1"):
        ContinuousBatchingScheduler(dense, eos_id=EOS, chunk_tokens=0)
    with pytest.raises(ValueError, match="divide"):
        ContinuousBatchingScheduler(dense, eos_id=EOS, chunk_tokens=5)
    with pytest.raises(ValueError, match="page_size"):
        ContinuousBatchingScheduler(paged, eos_id=EOS, chunk_tokens=2)
    int8 = PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                             num_pages=8, page_size=4, buckets=(16, 32),
                             cache_dtype=jnp.int8)
    with pytest.raises(ValueError, match="int8"):
        ContinuousBatchingScheduler(int8, eos_id=EOS, chunk_tokens=4)
    with pytest.raises(ValueError, match="tick_token_budget"):
        ContinuousBatchingScheduler(dense, eos_id=EOS, chunk_tokens=4,
                                    tick_token_budget=0)

"""Continuous-batching scheduler: admit/evict lifecycle over a fixed
slot pool, and output invariance to slot placement and pool size."""

import dataclasses

import jax
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (ContinuousBatchingScheduler, DecodeEngine,
                              Request)

EOS = 0
MAX_LEN = 32


def _cfg():
    return dataclasses.replace(gpt_tiny(), use_rope=True,
                               hidden_dropout=0.0)


def _params(cfg):
    return init_gpt(jax.random.PRNGKey(0), cfg)


def _run(params, cfg, requests, num_slots, top_k=0):
    engine = DecodeEngine(params, cfg, num_slots=num_slots,
                          max_len=MAX_LEN, top_k=top_k)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    for r in requests:
        sched.submit(r)
    return sched.run()


def test_more_requests_than_slots():
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(2 + i, 3 + i, 5 + i), max_new_tokens=5)
            for i in range(5)]
    outs = _run(params, cfg, reqs, num_slots=2)
    assert len(outs) == 5
    for toks in outs:
        assert 1 <= len(toks) <= 5
        assert all(isinstance(t, int) for t in toks)
        if len(toks) < 5:  # early exit only ever means EOS
            assert toks[-1] == EOS


def test_greedy_output_independent_of_num_slots():
    """The same greedy request set must decode to the same tokens
    whether it runs 1-at-a-time or fully batched — slot packing is a
    throughput concern, never a numerics one."""
    cfg = _cfg()
    params = _params(cfg)
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=4),
            Request(prompt=(17, 19), max_new_tokens=4),
            Request(prompt=(23, 29, 31, 37), max_new_tokens=4)]
    a = _run(params, cfg, reqs, num_slots=1)
    b = _run(params, cfg, reqs, num_slots=3)
    assert a == b


def test_seeded_sampling_independent_of_slot_placement():
    """Per-request keys are derived from (seed, tokens generated so
    far), not from slot index or admission order — so a sampled request
    is reproducible regardless of what else shares the batch."""
    cfg = _cfg()
    params = _params(cfg)
    probe = Request(prompt=(5, 7, 11), max_new_tokens=6,
                    temperature=0.8, seed=42)
    alone = _run(params, cfg, [probe], num_slots=1)[0]
    filler = [Request(prompt=(2, 3), max_new_tokens=6,
                      temperature=0.9, seed=i) for i in range(3)]
    crowded = _run(params, cfg, [probe] + filler, num_slots=4)[0]
    assert alone == crowded


def test_max_new_tokens_respected():
    cfg = _cfg()
    params = _params(cfg)
    outs = _run(params, cfg, [Request(prompt=(3, 5), max_new_tokens=1),
                              Request(prompt=(3, 5), max_new_tokens=3)],
                num_slots=2)
    assert len(outs[0]) == 1
    assert len(outs[1]) <= 3


def test_submit_validates():
    cfg = _cfg()
    engine = DecodeEngine(_params(cfg), cfg, num_slots=1,
                          max_len=MAX_LEN)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    with pytest.raises(ValueError):
        sched.submit(Request(prompt=()))
    with pytest.raises(ValueError):
        sched.submit(Request(prompt=tuple(range(MAX_LEN + 1))))


def test_run_on_empty_queue():
    cfg = _cfg()
    engine = DecodeEngine(_params(cfg), cfg, num_slots=1,
                          max_len=MAX_LEN)
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS)
    assert sched.run() == []

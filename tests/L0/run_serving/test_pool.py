"""Chaos tier for the pool-scale serving tier (``serving.router``
``PoolRouter`` + ``serving.transfer`` ``PageReshard``): N prefill x M
decode replica pools with load-based routing, device-to-device page
resharding, and N-way failover.

The load-bearing contracts:

- FAULT-FREE IDENTITY — pool committed streams are integer-identical
  to the colocated scheduler's AND to the 1x1 ``DisaggregatedRouter``'s
  across every pool shape (1x1, 2x1, 2x2), with every admission's
  handoff riding the device-to-device reshard tier;
- every reshard fault degrades GRACEFULLY: retries inside the budget,
  quarantined corruption, host-staged re-ship on exhaustion
  (``ReshardFailed``), colocated service as the last rung — all
  invisible in the committed token streams;
- a ``pool_route`` fault degrades the ROUTING POLICY (fixed-order
  pick), never the stream;
- N-way failover walks the ladder decode sibling → borrowed prefill
  replica → last-replica-standing, and rebalances home when a decode
  replica recovers — committed streams stay bit-identical throughout
  (drains resume via the preemption path);
- the randomized multi-fault sweep replays bit-for-bit (outcomes,
  stats, injector counts, tick-clock event stream) under ``audit=True``.

``APEX_CHAOS_POOL_SEED`` (comma-separated ints) overrides the sweep's
seed set — the CI chaos matrix fans one seed per leg and uploads each
leg's Perfetto dump.
"""

import dataclasses
import os

import jax
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    ContinuousBatchingScheduler, DisaggregatedRouter, FaultInjector,
    PagedDecodeEngine, PageReshard, PoolRouter, PrefixRegistry, Request,
    ReshardFailed, Tracer, FINISH_REASONS,
)

pytestmark = pytest.mark.chaos

EOS = -1       # unreachable: healthy streams run to max_new_tokens
MAX_LEN = 32

#: The randomized sweep's seeds; the CI chaos matrix overrides this to
#: one seed per leg.
_POOL_SEEDS = tuple(
    int(s) for s in os.environ.get("APEX_CHAOS_POOL_SEED",
                                   "0,1,2").split(","))


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, injector=None, tracer=None, num_pages=20, **kw):
    cfg, params = model
    kw.setdefault("tracer", tracer if tracer is not None else Tracer())
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), injector=injector, **kw)


def _pool(model, n_prefill=2, n_decode=2, schedule=None, rates=None,
          seed=0, num_pages=20, spec_k=0, **kw):
    inj = FaultInjector(seed=seed, rates=rates, schedule=schedule)
    trc = Tracer()
    prefills = [_engine(model, inj, trc, num_pages=num_pages,
                        spec_k=spec_k) for _ in range(n_prefill)]
    decodes = [_engine(model, inj, trc, num_pages=num_pages,
                       spec_k=spec_k) for _ in range(n_decode)]
    return PoolRouter(prefills, decodes, EOS, audit=True, **kw)


_REQS = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=8),
         Request(prompt=(6, 7, 8), max_new_tokens=6, temperature=0.8,
                 seed=7),
         Request(prompt=(9, 10, 11, 12), max_new_tokens=4,
                 temperature=1.1, seed=5)]


def _drive(sched, reqs=_REQS):
    for r in reqs:
        sched.submit(r)
    return sched.run()


def _golden(model, reqs=_REQS, spec_k=0):
    eng = _engine(model, spec_k=spec_k)
    return _drive(ContinuousBatchingScheduler(eng, eos_id=EOS,
                                              audit=True), reqs)


def _assert_all_ok_golden(router, golden):
    assert sorted(router.outcomes) == list(range(len(golden)))
    for rid, out in router.outcomes.items():
        assert out.reason in FINISH_REASONS and out.ok
        assert list(out.tokens) == golden[rid], f"request {rid} diverged"


# -- fault-free identity across pool shapes ----------------------------------

@pytest.mark.parametrize("shape", [(1, 1), (2, 1), (2, 2)])
@pytest.mark.parametrize("spec_k", [0, 2])
def test_fault_free_pool_streams_match_colocated(model, shape, spec_k):
    """The headline contract at every pool shape: greedy AND sampled
    streams, speculation on and off, integer-identical to the
    colocated scheduler — with every admission served by a remote
    prefill replica over the device-to-device reshard tier (zero
    host-staged transfers)."""
    n_prefill, n_decode = shape
    golden = _golden(model, spec_k=spec_k)
    pool = _pool(model, n_prefill, n_decode, spec_k=spec_k)
    assert _drive(pool) == golden
    assert pool.stats.remote_prefills == len(_REQS)
    assert pool.stats.colocated_prefills == 0
    assert pool.stats.reshards == len(_REQS)
    assert pool.stats.transfers == 0
    assert pool.stats.failovers == 0
    assert all(h.state == "healthy" for h in pool.health.values())
    _assert_all_ok_golden(pool, golden)


def test_pool_matches_pair_router_streams(model):
    """Pool streams are bit-identical to the 1x1 DisaggregatedRouter's
    (not just to colocated): same committed tokens, same outcomes —
    the pool only moves WHERE work runs."""
    inj, trc = FaultInjector(), Tracer()
    pair = DisaggregatedRouter(_engine(model, inj, trc),
                               _engine(model, inj, trc), EOS,
                               audit=True)
    pair_streams = _drive(pair)
    pool = _pool(model, 2, 2)
    assert _drive(pool) == pair_streams
    assert {r: o.tokens for r, o in pool.outcomes.items()} \
        == {r: o.tokens for r, o in pair.outcomes.items()}


def test_host_staged_pool_matches_reshard_pool(model):
    """``use_reshard=False`` pins the pool to the host-staged channel
    — streams are identical either way (the tiers differ only in link
    and pricing, never in bytes)."""
    golden = _golden(model)
    host = _pool(model, 2, 2, use_reshard=False)
    assert _drive(host) == golden
    assert host.stats.transfers == len(_REQS)
    assert host.stats.reshards == 0


def test_cross_replica_prefix_dedup_pool_wide(model):
    """Requests sharing a full prompt page dedup across the POOL: the
    active decode replica registered the page at the first install,
    so the second handoff ships one page fewer regardless of which
    prefill replica served it."""
    reqs = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=6),
            Request(prompt=(1, 2, 3, 4, 9), max_new_tokens=6,
                    temperature=0.8, seed=7)]
    golden = _golden(model, reqs)
    pool = _pool(model, 2, 2)
    assert _drive(pool, reqs) == golden
    assert pool.stats.transfer_pages_deduped == 1
    assert pool.stats.remote_prefills == 2


# -- one pinned fault per new site ------------------------------------------

def test_reshard_send_fault_retries_to_golden(model):
    """One dropped d2d send: retried inside the same reshard budget,
    delivered on attempt 2, stream bit-identical."""
    golden = _golden(model)
    pool = _pool(model, schedule={"reshard_send": (0,)})
    assert _drive(pool) == golden
    assert pool.stats.reshard_retries == 1
    assert pool.stats.reshard_failures == 0
    assert pool.stats.remote_prefills == len(_REQS)
    _assert_all_ok_golden(pool, golden)


def test_reshard_recv_corruption_quarantines_to_golden(model):
    """One in-flight byte flip on the d2d link: the chain-key-bound
    checksum catches it, the payload is quarantined, the retry
    re-extracts clean tiles — golden equality proves no corrupt page
    was ever attended."""
    golden = _golden(model)
    pool = _pool(model, schedule={"reshard_recv": (0,)})
    assert _drive(pool) == golden
    assert pool.stats.reshard_corrupt == 1
    assert pool.stats.reshard_retries == 1
    assert pool.stats.reshard_failures == 0
    _assert_all_ok_golden(pool, golden)


def test_reshard_exhaustion_degrades_to_host_staged(model):
    """Every attempt of the first reshard dropped: ReshardFailed is
    raised, caught, and the SAME pages re-ship over the host-staged
    channel — the admission still lands remotely (never colocated for
    a link fault) and the stream is golden."""
    golden = _golden(model)
    pool = _pool(model, schedule={"reshard_send": (0, 1, 2)})
    assert _drive(pool) == golden
    assert pool.stats.reshard_failures == 1
    assert pool.stats.transfers >= 1        # the host-staged re-ship
    assert pool.stats.remote_prefills == len(_REQS)
    assert pool.stats.colocated_prefills == 0
    names = [e.name for e in pool.tracer.events]
    assert "failover" in names              # the tier-degrade instant
    _assert_all_ok_golden(pool, golden)


def test_reshard_exhaustion_is_typed(model):
    """Driving the channel directly: persistent d2d drops exhaust the
    budget with a TYPED ReshardFailed carrying attempts/pages/corrupt
    — and a clean channel still ships the same pages afterwards."""
    inj = FaultInjector(schedule={"reshard_send": (0, 1, 2)})
    src = _engine(model, inj)
    src.prefill(0, [1, 2, 3, 4, 5])
    reshard = PageReshard(injector=inj, tracer=src.tracer,
                          stats=src.stats, max_retries=2)
    with pytest.raises(ReshardFailed) as ei:
        reshard.ship(src, [1, 2, 3, 4, 5], src._slot_pages[0],
                     replica="prefill0")
    assert ei.value.attempts == 3 and ei.value.pages == 2
    assert ei.value.corrupt is False
    assert src.stats.reshard_failures == 1
    k_tile, v_tile, attempts = reshard.ship(
        src, [1, 2, 3, 4, 5], src._slot_pages[0], replica="prefill0")
    assert attempts == 1 and k_tile.shape[1] == 2
    assert src.stats.reshards == 1


def test_pool_route_fault_falls_back_fixed_order(model):
    """A pool_route fault degrades the load-based pick to the first
    routable replica in fixed order — a routing-policy fault moves
    placement, never a committed token."""
    golden = _golden(model)
    pool = _pool(model, schedule={"pool_route": (0,)})
    assert _drive(pool) == golden
    assert pool.stats.route_fallbacks == 1
    assert pool.stats.remote_prefills == len(_REQS)
    _assert_all_ok_golden(pool, golden)


# -- N-way failover ladder ---------------------------------------------------

def test_active_decode_down_fails_over_to_sibling(model):
    """The active decode replica dies mid-stream (probe order is
    prefill0, prefill1, decode0, decode1 per tick: indices 2 and 6
    are decode0's first two probes): the slots drain and move to the
    decode SIBLING (headroom pick), never a prefill borrow while a
    sibling is routable — streams integer-identical to golden."""
    golden = _golden(model)
    pool = _pool(model, schedule={"replica_health": (2, 6)})
    assert _drive(pool) == golden
    assert pool.stats.failovers == 1
    assert pool.stats.rebalances == 1
    assert pool.engine.active_name == "decode1"
    names = [e.name for e in pool.tracer.events]
    assert "rebalance" in names and "preempted" in names
    _assert_all_ok_golden(pool, golden)


def test_all_decode_down_borrows_prefill_then_rebalances_home(model):
    """Both decode replicas die (decode0 at probe indices 2/6, decode1
    at 3/7): the slots borrow a PREFILL replica (the ladder's last
    rung before last-standing), and once a decode replica climbs back
    up the ladder the router rebalances the slots home — streams stay
    golden through both moves."""
    golden = _golden(model)
    pool = _pool(model,
                 schedule={"replica_health": (2, 6, 3, 7)})
    assert _drive(pool) == golden
    assert pool.stats.failovers >= 1
    assert pool.stats.rebalances >= 2       # the borrow + the move home
    assert pool.engine.active_name in pool.engine.decode_names
    _assert_all_ok_golden(pool, golden)


def test_all_replicas_down_last_standing_keeps_serving(model):
    """Every ladder bottoms out at once (all four replicas fail every
    probe for the whole run): there is no routable failover target,
    so the incumbent keeps decoding — health gates ROUTING, not
    survival. The third request admits after the collapse and is
    served colocated. Streams golden, outcomes typed, no hang."""
    golden = _golden(model)
    pool = _pool(model,
                 schedule={"replica_health": tuple(range(0, 96))})
    assert _drive(pool) == golden
    assert pool.stats.failovers == 0
    assert pool.stats.colocated_prefills >= 1
    assert all(h.state == "down" for h in pool.health.values())
    _assert_all_ok_golden(pool, golden)


# -- construction contracts --------------------------------------------------

def test_pool_validates_replicas_pool_wide(model):
    cfg, params = model
    inj, trc = FaultInjector(), Tracer()

    def eng(**kw):
        return _engine(model, kw.pop("injector", inj),
                       kw.pop("tracer", trc), **kw)

    # mixed pool geometry: the odd replica out is caught PAIRWISE even
    # when the first prefill/decode pair agrees
    with pytest.raises(ValueError, match="agree on page_size"):
        odd = PagedDecodeEngine(params, cfg, num_slots=2,
                                max_len=MAX_LEN, num_pages=20,
                                page_size=8, buckets=(16, 32),
                                injector=inj, tracer=trc)
        PoolRouter([eng(), eng()], [eng(), odd], EOS)
    # mixed host tiers: the shared-PrefixRegistry-or-none rule is
    # pool-wide, not per-pair
    with pytest.raises(ValueError, match="ONE PrefixRegistry"):
        tier = PrefixRegistry(capacity_bytes=1 << 20)
        PoolRouter([eng(host_tier=tier), eng()],
                   [eng(host_tier=tier), eng(host_tier=tier)], EOS)
    # a repeated engine instance anywhere in the pool
    with pytest.raises(ValueError, match="two engine instances"):
        e = eng()
        PoolRouter([e, eng()], [eng(), e], EOS)
    with pytest.raises(ValueError, match="ONE FaultInjector"):
        PoolRouter([eng(injector=FaultInjector()), eng()],
                   [eng(), eng()], EOS)
    with pytest.raises(ValueError, match="ONE Tracer"):
        PoolRouter([eng(), eng()], [eng(), eng(tracer=Tracer())], EOS)
    with pytest.raises(ValueError, match="chunked prefill"):
        PoolRouter([eng()], [eng()], EOS, chunk_tokens=4)
    with pytest.raises(ValueError, match="at least one"):
        PoolRouter([], [eng()], EOS)
    with pytest.raises(ValueError, match="placement names unknown"):
        PoolRouter([eng(), eng()], [eng(), eng()], EOS,
                   placement={"prefill9": 1})


# -- randomized multi-fault sweep -------------------------------------------

@pytest.mark.parametrize("seed", _POOL_SEEDS)
def test_multi_fault_pool_chaos_replays_bit_for_bit(model, seed):
    """Every pool site armed at once (reshard drop/corrupt, routing
    faults, replica health, plus host-tier and decode cross-talk),
    audited every tick: every outcome typed, every ok stream exactly
    golden, every degraded stream a golden prefix — and the whole run
    replays bit-for-bit: outcomes, stats, injector counts, and the
    tick-clock event stream."""
    golden = _golden(model)
    rates = {"reshard_send": 0.25, "reshard_recv": 0.2,
             "pool_route": 0.15, "replica_health": 0.1,
             "page_send": 0.1, "decode_exec": 0.05}

    def chaos_run():
        pool = _pool(model, rates=rates, seed=seed)
        _drive(pool)
        return pool

    pool = chaos_run()
    assert sorted(pool.outcomes) == list(range(len(_REQS)))
    for rid, out in pool.outcomes.items():
        assert out.reason in FINISH_REASONS
        want = golden[rid]
        if out.ok:
            assert list(out.tokens) == want, f"request {rid} diverged"
        else:
            assert list(out.tokens) == want[:len(out.tokens)], \
                f"request {rid}: degraded stream not a golden prefix"
    replay = chaos_run()
    assert replay.outcomes == pool.outcomes
    assert replay.stats.as_dict() == pool.stats.as_dict()
    assert replay.engine.injector.counts == pool.engine.injector.counts
    assert replay.tracer.tick_stream() == pool.tracer.tick_stream()
    assert {h.state for h in replay.health.values()} \
        == {h.state for h in pool.health.values()}
    # CI post-mortem artifact: one Perfetto dump per sweep seed,
    # uploaded by the chaos workflow legs
    out_path = os.environ.get("APEX_CHAOS_TRACE_OUT")
    if out_path:
        root, ext = os.path.splitext(out_path)
        pool.tracer.dump_jsonl(
            f"{root}.pool_seed{seed}{ext or '.jsonl'}")

"""sample_tokens: greedy/temperature selection, top-k support
restriction, and determinism under explicit PRNG keys."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving import sample_tokens

V = 64


def _logits(n, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (n, V),
                             jnp.float32)


def _keys(n, seed=7):
    return jax.random.split(jax.random.PRNGKey(seed), n)


def test_greedy_is_argmax():
    logits = _logits(4)
    out = sample_tokens(logits, _keys(4), jnp.zeros((4,), jnp.float32))
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_greedy_ignores_keys():
    logits = _logits(3)
    temps = jnp.zeros((3,), jnp.float32)
    a = sample_tokens(logits, _keys(3, seed=1), temps)
    b = sample_tokens(logits, _keys(3, seed=2), temps)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sampling_deterministic_per_key():
    logits = _logits(4)
    temps = jnp.full((4,), 0.9, jnp.float32)
    a = sample_tokens(logits, _keys(4), temps)
    b = sample_tokens(logits, _keys(4), temps)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = sample_tokens(logits, _keys(4, seed=99), temps)
    assert (np.asarray(a) != np.asarray(c)).any()


def test_top_k_restricts_support():
    logits = _logits(8, seed=3)
    k = 5
    temps = jnp.full((8,), 1.3, jnp.float32)
    allowed = np.asarray(jnp.argsort(logits, -1)[:, -k:])
    for seed in range(4):
        out = np.asarray(sample_tokens(logits, _keys(8, seed=seed),
                                       temps, top_k=k))
        for i, tok in enumerate(out):
            assert tok in allowed[i]


def test_top_k_one_is_argmax():
    logits = _logits(4, seed=5)
    out = sample_tokens(logits, _keys(4), jnp.ones((4,), jnp.float32),
                        top_k=1)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_mixed_greedy_and_sampled_rows():
    logits = _logits(4, seed=6)
    temps = jnp.asarray([0.0, 1.0, 0.0, 1.0], jnp.float32)
    out = np.asarray(sample_tokens(logits, _keys(4), temps))
    greedy = np.asarray(jnp.argmax(logits, -1))
    assert out[0] == greedy[0] and out[2] == greedy[2]

"""sample_tokens: greedy/temperature selection, top-k / top-p support
restriction, determinism under explicit PRNG keys — and the
speculative grid/accept helpers that reuse the same sampler."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving import (sample_token_grid, sample_tokens,
                              speculative_accept)

V = 64


def _logits(n, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (n, V),
                             jnp.float32)


def _keys(n, seed=7):
    return jax.random.split(jax.random.PRNGKey(seed), n)


def test_greedy_is_argmax():
    logits = _logits(4)
    out = sample_tokens(logits, _keys(4), jnp.zeros((4,), jnp.float32))
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_greedy_ignores_keys():
    logits = _logits(3)
    temps = jnp.zeros((3,), jnp.float32)
    a = sample_tokens(logits, _keys(3, seed=1), temps)
    b = sample_tokens(logits, _keys(3, seed=2), temps)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sampling_deterministic_per_key():
    logits = _logits(4)
    temps = jnp.full((4,), 0.9, jnp.float32)
    a = sample_tokens(logits, _keys(4), temps)
    b = sample_tokens(logits, _keys(4), temps)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = sample_tokens(logits, _keys(4, seed=99), temps)
    assert (np.asarray(a) != np.asarray(c)).any()


def test_top_k_restricts_support():
    logits = _logits(8, seed=3)
    k = 5
    temps = jnp.full((8,), 1.3, jnp.float32)
    allowed = np.asarray(jnp.argsort(logits, -1)[:, -k:])
    for seed in range(4):
        out = np.asarray(sample_tokens(logits, _keys(8, seed=seed),
                                       temps, top_k=k))
        for i, tok in enumerate(out):
            assert tok in allowed[i]


def test_top_k_one_is_argmax():
    logits = _logits(4, seed=5)
    out = sample_tokens(logits, _keys(4), jnp.ones((4,), jnp.float32),
                        top_k=1)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_mixed_greedy_and_sampled_rows():
    logits = _logits(4, seed=6)
    temps = jnp.asarray([0.0, 1.0, 0.0, 1.0], jnp.float32)
    out = np.asarray(sample_tokens(logits, _keys(4), temps))
    greedy = np.asarray(jnp.argmax(logits, -1))
    assert out[0] == greedy[0] and out[2] == greedy[2]


# -- top-p (nucleus) --------------------------------------------------------

def _nucleus(logits, p):
    """Reference support: per row, the smallest set of top tokens whose
    softmax mass reaches p (the argmax always belongs)."""
    probs = np.asarray(jax.nn.softmax(logits, -1))
    order = np.argsort(-probs, axis=-1)
    allowed = []
    for r in range(probs.shape[0]):
        mass, keep = 0.0, []
        for tok in order[r]:
            keep.append(int(tok))
            mass += probs[r, tok]
            if mass >= p:
                break
        allowed.append(set(keep))
    return allowed


def test_top_p_restricts_support():
    logits = _logits(8, seed=3)
    p = 0.6
    temps = jnp.full((8,), 1.3, jnp.float32)
    allowed = _nucleus(logits, p)
    for seed in range(4):
        out = np.asarray(sample_tokens(logits, _keys(8, seed=seed),
                                       temps, top_p=p))
        for i, tok in enumerate(out):
            assert int(tok) in allowed[i]


def test_top_p_tiny_is_argmax():
    """A nucleus smaller than any single token's mass still keeps the
    argmax — the support can never be empty."""
    logits = _logits(4, seed=5)
    out = sample_tokens(logits, _keys(4), jnp.ones((4,), jnp.float32),
                        top_p=1e-6)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_top_p_off_values_are_full_vocab():
    """0 and 1 both mean "off": identical draws to the unrestricted
    sampler (bitwise — same keys, same program shape)."""
    logits = _logits(6, seed=8)
    temps = jnp.full((6,), 1.1, jnp.float32)
    base = np.asarray(sample_tokens(logits, _keys(6), temps))
    for p in (0.0, 1.0):
        np.testing.assert_array_equal(
            np.asarray(sample_tokens(logits, _keys(6), temps, top_p=p)),
            base)


def test_top_p_composes_with_top_k():
    """With both set, the support is the intersection (top-k applies
    first, nucleus prunes within it)."""
    logits = _logits(8, seed=9)
    k, p = 5, 0.7
    temps = jnp.full((8,), 1.3, jnp.float32)
    topk = np.asarray(jnp.argsort(logits, -1)[:, -k:])
    nuc = _nucleus(logits, p)
    for seed in range(4):
        out = np.asarray(sample_tokens(logits, _keys(8, seed=seed),
                                       temps, top_k=k, top_p=p))
        for i, tok in enumerate(out):
            assert int(tok) in topk[i] and int(tok) in nuc[i]


def test_top_p_does_not_disturb_greedy():
    logits = _logits(4, seed=2)
    out = sample_tokens(logits, _keys(4), jnp.zeros((4,), jnp.float32),
                        top_p=0.3)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


# -- speculative grid + accept ----------------------------------------------

def test_sample_token_grid_matches_per_position_sampler():
    """Grid position (b, j) must draw exactly what sample_tokens draws
    for row b with key[b, j] — the property the speculative
    bit-identity contract stands on."""
    b, k1 = 3, 4
    logits = jax.random.normal(jax.random.PRNGKey(4), (b, k1, V),
                               jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(11), b * k1).reshape(
        b, k1, 2)
    temps = jnp.asarray([0.0, 0.9, 1.2], jnp.float32)
    grid = np.asarray(sample_token_grid(logits, keys, temps, top_p=0.9))
    for j in range(k1):
        col = np.asarray(sample_tokens(logits[:, j], keys[:, j], temps,
                                       top_p=0.9))
        np.testing.assert_array_equal(grid[:, j], col)


def test_speculative_accept_counts_matching_prefix():
    toks = jnp.asarray([[5, 6, 7, 9],    # full match
                        [5, 6, 7, 9],    # mismatch at j=1
                        [5, 6, 7, 9],    # match but draft_len caps at 2
                        [5, 6, 7, 9]],   # empty draft
                       jnp.int32)
    drafts = jnp.asarray([[5, 6, 7],
                          [5, 0, 7],
                          [5, 6, 7],
                          [0, 0, 0]], jnp.int32)
    lens = jnp.asarray([3, 3, 2, 0], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(speculative_accept(toks, drafts, lens)),
        [3, 1, 2, 0])


def test_speculative_accept_pad_positions_never_match():
    """0-padded draft tails must not count as accepts even when the
    sampled token happens to be 0 (the pad value)."""
    toks = jnp.asarray([[0, 0]], jnp.int32)
    drafts = jnp.asarray([[0, 0]], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(speculative_accept(
            toks, drafts, jnp.asarray([1], jnp.int32))), [1])
    np.testing.assert_array_equal(
        np.asarray(speculative_accept(
            toks, drafts, jnp.asarray([0], jnp.int32))), [0])

"""Chaos tier: deterministic fault injection against the serving
engine's graceful-degradation layer.

Every test drives the REAL scheduler/engine through the named fault
sites (``serving.faults.SITES``) and checks the degradation contract:

- every submitted request ends in a typed ``RequestOutcome``;
- pool invariants hold after every tick (``audit=True``);
- a stream untouched by faults is BIT-IDENTICAL to the fault-free
  golden run, and a degraded request's tokens are a PREFIX of its
  golden stream (quarantine never commits a corrupt token);
- the same seed replays the same faults and the same outcomes.

``eos_id=-1`` throughout: no token can match it, so golden streams
always run to ``max_new_tokens`` and prefix assertions are exact.
"""

import dataclasses
import os

import jax
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    AdmissionRejected, ContinuousBatchingScheduler, DeadlineExceeded,
    DecodeEngine, FaultInjector, LivelockError, PagedDecodeEngine,
    PoolInvariantError, Request, RetryBudgetExhausted, FINISH_REASONS,
    Tracer,
)
from apex_tpu.serving.faults import SITES, fault_draw

pytestmark = pytest.mark.chaos

EOS = -1       # unreachable: every healthy stream runs to max_new_tokens
MAX_LEN = 32


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, injector=None, num_slots=2, num_pages=20, **kw):
    cfg, params = model
    # tracing is ON for the whole chaos tier: every bit-identity /
    # golden-equality contract below must hold with the observability
    # hooks live (they are host-side and must never perturb a stream)
    kw.setdefault("tracer", Tracer())
    return PagedDecodeEngine(params, cfg, num_slots=num_slots,
                             max_len=MAX_LEN, num_pages=num_pages,
                             page_size=4, buckets=(16, 32),
                             injector=injector, **kw)


def _drive(engine, reqs, **kw):
    sched = ContinuousBatchingScheduler(engine, eos_id=EOS, **kw)
    for r in reqs:
        sched.submit(r)
    return sched, sched.run()


def _golden(model, reqs, num_slots=2):
    _, outs = _drive(_engine(model, num_slots=num_slots), reqs)
    return outs


def _check_contract(sched, reqs, golden):
    """The degradation contract every chaos run must satisfy."""
    assert sorted(sched.outcomes) == list(range(len(reqs)))
    for rid, out in sched.outcomes.items():
        assert out.reason in FINISH_REASONS
        want = golden[rid]
        if out.ok:
            assert list(out.tokens) == want, f"request {rid} diverged"
        else:   # degraded: committed tokens are a golden prefix
            assert list(out.tokens) == want[:len(out.tokens)], \
                f"request {rid}: degraded stream is not a golden prefix"


# -- the injector itself -----------------------------------------------------

def test_fault_draw_is_pure():
    """Schedules are pure functions of (seed, site, index) — the
    replay guarantee rests on this, not on any RNG state."""
    assert fault_draw(3, "sample", 7) == fault_draw(3, "sample", 7)
    draws = {fault_draw(s, site, i) for s in (0, 1) for site in SITES
             for i in (0, 5)}
    assert len(draws) == 2 * len(SITES) * 2  # no collisions across keys
    u01s = [fault_draw(0, "pool_alloc", i)[0] for i in range(200)]
    assert all(0.0 <= u < 1.0 for u in u01s)
    # roughly uniform: a rate-0.5 site fires about half the time
    assert 60 < sum(u < 0.5 for u in u01s) < 140


def test_injector_inert_by_default_and_validates_sites():
    inert = FaultInjector()
    assert not inert.armed
    assert all(not inert.fire(s) for s in SITES for _ in range(50))
    assert inert.counts == {s: 0 for s in SITES}
    assert inert.calls("sample") == 50
    with pytest.raises(ValueError, match="unknown fault sites"):
        FaultInjector(rates={"warp_core": 1.0})
    with pytest.raises(ValueError, match="unknown fault sites"):
        FaultInjector(schedule={"holodeck": (0,)})
    with pytest.raises(KeyError):
        inert.fire("not_a_site")


def test_injector_schedule_replays_bit_for_bit():
    """Same seed, same visit order -> same fired pattern; pinned
    schedule entries fire regardless of rates."""
    a = FaultInjector(seed=11, rates={"decode_exec": 0.3})
    b = FaultInjector(seed=11, rates={"decode_exec": 0.3})
    pat_a = [a.draw("decode_exec") for _ in range(64)]
    assert pat_a == [b.draw("decode_exec") for _ in range(64)]
    assert any(f for f, _ in pat_a)
    c = FaultInjector(seed=12, rates={"decode_exec": 0.3})
    assert pat_a != [c.draw("decode_exec") for _ in range(64)]
    pinned = FaultInjector(schedule={"prefill_exec": (2,)})
    assert [pinned.fire("prefill_exec") for _ in range(4)] == [
        False, False, True, False]


# -- one site at a time, pinned schedules ------------------------------------

def test_pool_alloc_fault_recovers_to_golden(model):
    """A transient allocation refusal parks the admission (typed
    internally as PoolExhausted, no retry charged — capacity is not the
    request's fault) and the next tick succeeds bit-identically."""
    reqs = [Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=4)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"pool_alloc": (0,)}))
    sched, outs = _drive(eng, reqs, audit=True)
    assert outs == golden
    assert sched.stats.pool_exhausted == 1
    assert sched.stats.retries == 0
    assert sched.outcomes[0].ok


def test_cow_clone_fault_preempts_and_recovers(model):
    """A failed copy-on-write clone preempts the slot (pages released,
    request requeued with its progress); the resumed stream matches the
    fault-free run exactly."""
    reqs = [Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=4)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"cow_clone": (0,)}))
    sched, outs = _drive(eng, reqs, audit=True)
    assert outs == golden
    assert sched.stats.preemptions == 1
    assert sched.stats.cow_copies >= 1  # the retried clone succeeded
    assert sched.outcomes[0].ok


def test_prefill_exec_fault_retries_to_golden(model):
    """A transient prefill failure charges the retry budget and leaves
    nothing behind (audit on); the retried admission is bit-identical."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=4)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"prefill_exec": (0,)}))
    sched, outs = _drive(eng, reqs, audit=True)
    assert outs == golden
    assert sched.stats.retries == 1
    assert sched.outcomes[0].ok and sched.outcomes[0].retries == 1


def test_decode_nan_quarantine_keeps_cotenant_bit_identical(model):
    """A NaN decode row quarantines ONE slot. With a zero retry budget
    the victim terminates typed, its tokens a golden prefix — and the
    co-tenant stream must be bit-identical to the fault-free run (the
    corrupt row never touches other slots' logits or keys)."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=5),
            Request(prompt=(23, 29), max_new_tokens=5)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"decode_exec": (0,)}))
    sched, _ = _drive(eng, reqs, audit=True, max_retries=0)
    assert sched.stats.nan_events == 1
    bad = [rid for rid, o in sched.outcomes.items() if not o.ok]
    assert len(bad) == 1
    victim = sched.outcomes[bad[0]]
    assert victim.reason == "retry_budget"
    assert isinstance(victim.error, RetryBudgetExhausted)
    # first token (from prefill) committed, the corrupt one never was
    assert list(victim.tokens) == golden[bad[0]][:1]
    ok = (set(sched.outcomes) - set(bad)).pop()
    assert list(sched.outcomes[ok].tokens) == golden[ok]


def test_decode_nan_quarantine_retry_is_bit_identical(model):
    """Same fault, default retry budget: the victim resumes from its
    committed tokens and BOTH streams equal the golden run exactly."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=5),
            Request(prompt=(23, 29), max_new_tokens=5)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"decode_exec": (0,)}))
    sched, outs = _drive(eng, reqs, audit=True)
    assert outs == golden
    assert sched.stats.nan_events == 1
    assert all(o.ok for o in sched.outcomes.values())


def test_sample_fault_at_admission_recovers(model):
    """An out-of-vocabulary first token is caught by the admission
    range gate; the request retries and matches golden."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=4,
                    temperature=0.8, seed=5)]
    golden = _golden(model, reqs)
    eng = _engine(model, FaultInjector(schedule={"sample": (0,)}))
    sched, outs = _drive(eng, reqs, audit=True)
    assert outs == golden
    assert sched.stats.bad_samples == 1
    assert sched.outcomes[0].ok and sched.outcomes[0].retries == 1


def _spec_engine(model, injector=None, spec_k=3, num_pages=20):
    cfg, params = model
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), spec_k=spec_k,
                             injector=injector)


def test_draft_fault_degrades_to_plain_and_recovers(model):
    """A mid-stream draft fault degrades that slot to an empty draft
    (an all-empty tick runs plain decode) for the tick — drafting is
    best-effort, so NO retry budget is charged — and the recovered
    stream is bit-identical to both the fault-free speculative golden
    and the never-speculated plain run."""
    reqs = [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=6),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.8, seed=3)]

    def run(injector=None):
        return _drive(_spec_engine(model, injector), reqs, audit=True)

    _, golden = run()
    assert golden == _golden(model, reqs)  # spec == plain, fault-free
    sched, outs = run(FaultInjector(schedule={"draft_exec": (1, 4)}))
    assert outs == golden
    assert sched.stats.draft_faults == 2
    assert sched.stats.retries == 0
    assert all(o.ok for o in sched.outcomes.values())
    # degraded ticks still drafted nothing FOR THE VICTIM only: the
    # co-tenant kept speculating (drafted counters moved)
    assert sched.stats.tokens_drafted > 0


def _model_spec_engine(model, injector=None, spec_k=3, tree=False,
                       num_pages=20):
    from apex_tpu.serving import DraftModel

    cfg, params = model
    dm = DraftModel(params, cfg, num_slots=2, max_len=MAX_LEN)
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), spec_k=spec_k,
                             draft_model=dm, tree_spec=tree,
                             injector=injector)


def test_model_draft_fault_ladder_degrades_and_recovers(model):
    """The draft_exec LADDER on a model-drafting engine: one fired draw
    mid-stream degrades that tick from model drafts to n-gram drafts
    (one draft_fault, no retry charged); a consecutive fired pair kills
    the tick's drafting entirely (plain tick, two draft_faults). Both
    degradations recover bit-identical to the fault-free golden — the
    draft cache's resync-by-common-prefix absorbs the skipped ticks."""
    reqs = [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=6),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.8, seed=3)]

    def run(injector=None):
        return _drive(_model_spec_engine(model, injector), reqs,
                      audit=True)

    _, golden = run()
    assert golden == _golden(model, reqs)  # model spec == plain decode
    # rung 1: model draft -> n-gram draft for the tick
    sched, outs = run(FaultInjector(schedule={"draft_exec": (1,)}))
    assert outs == golden
    assert sched.stats.draft_faults == 1
    assert sched.stats.retries == 0
    # rung 2: n-gram fails too -> plain tick, still golden
    sched, outs = run(FaultInjector(schedule={"draft_exec": (1, 2)}))
    assert outs == golden
    assert sched.stats.draft_faults == 2
    assert sched.stats.retries == 0
    assert all(o.ok for o in sched.outcomes.values())


def test_tree_spec_fault_ladder_recovers(model):
    """Same ladder under TREE speculation: a degraded tick loses its
    draft trees (n-gram chains or a plain tick) but the committed
    streams stay bit-identical to the fault-free tree golden."""
    reqs = [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=6),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.8, seed=3)]

    def run(injector=None):
        return _drive(_model_spec_engine(model, injector, tree=True),
                      reqs, audit=True)

    _, golden = run()
    assert golden == _golden(model, reqs)
    sched, outs = run(FaultInjector(schedule={"draft_exec": (1, 2)}))
    assert outs == golden
    assert sched.stats.draft_faults == 2
    assert sched.stats.retries == 0


@pytest.mark.parametrize("seed", [0, 1])
def test_spec_multi_fault_chaos_is_typed_prefixed_and_replayable(
        model, seed):
    """Randomized faults at every site INCLUDING draft_exec against the
    speculative scheduler: typed outcomes, golden-prefix degradation,
    bit-for-bit replay — the spec tick must compose with quarantine,
    preemption and retry exactly like the plain one."""
    reqs = [Request(prompt=(7, 11, 7, 11), max_new_tokens=5),
            Request(prompt=(17, 19, 17, 19), max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=(7, 11, 13, 29), max_new_tokens=4),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.7, seed=9)]
    golden = _golden(model, reqs)
    rates = {"pool_alloc": 0.1, "cow_clone": 0.2, "prefill_exec": 0.15,
             "decode_exec": 0.1, "sample": 0.1, "draft_exec": 0.3}

    def chaos_run():
        eng = _spec_engine(model,
                           FaultInjector(seed=seed, rates=rates),
                           num_pages=14)
        sched, _ = _drive(eng, reqs, audit=True)
        return sched

    sched = chaos_run()
    _check_contract(sched, reqs, golden)
    replay = chaos_run()
    assert replay.outcomes == sched.outcomes
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts


# -- typed terminations ------------------------------------------------------

def test_retry_budget_exhausted_surfaces_typed(model):
    """A persistently failing request terminates with
    ``RetryBudgetExhausted`` carrying its id and retry count — it never
    wedges the scheduler."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=4)]
    eng = _engine(model,
                  FaultInjector(schedule={"prefill_exec": range(10)}))
    sched, outs = _drive(eng, reqs, audit=True, max_retries=2)
    assert outs == [[]]
    out = sched.outcomes[0]
    assert out.reason == "retry_budget" and not out.ok
    assert isinstance(out.error, RetryBudgetExhausted)
    assert out.error.request_id == 0
    assert out.retries == 3  # budget of 2 + the exhausting charge


def test_deadline_exceeded_queued_and_mid_decode(model):
    """Deadlines are scheduler ticks — deterministic. A request expiring
    while queued ends empty; one expiring mid-decode keeps its golden
    prefix."""
    probe = Request(prompt=(7, 11, 13), max_new_tokens=8)
    golden = _golden(model, [probe], num_slots=1)
    # starved in the queue behind a slot hog
    hog = Request(prompt=(23, 29), max_new_tokens=8)
    starved = dataclasses.replace(probe, deadline_ticks=2)
    sched, _ = _drive(_engine(model, num_slots=1), [hog, starved])
    out = sched.outcomes[1]
    assert out.reason == "deadline" and isinstance(out.error,
                                                   DeadlineExceeded)
    assert out.tokens == ()
    assert sched.stats.deadline_expired == 1
    # cut mid-decode: tokens committed so far are a golden prefix
    cut = dataclasses.replace(probe, deadline_ticks=3)
    sched2, _ = _drive(_engine(model, num_slots=1), [cut])
    out2 = sched2.outcomes[0]
    assert out2.reason == "deadline"
    assert 0 < len(out2.tokens) < len(golden[0])
    assert list(out2.tokens) == golden[0][:len(out2.tokens)]


def test_admission_backpressure(model):
    """A bounded queue sheds load typed instead of growing without
    bound; accepted requests are unaffected."""
    eng = _engine(model, num_slots=2)
    sched = ContinuousBatchingScheduler(eng, eos_id=EOS, max_queue=2)
    sched.submit(Request(prompt=(7, 11), max_new_tokens=2))
    sched.submit(Request(prompt=(13, 17), max_new_tokens=2))
    with pytest.raises(AdmissionRejected):
        sched.submit(Request(prompt=(19, 23), max_new_tokens=2))
    assert sched.stats.admission_rejections == 1
    outs = sched.run()
    assert len(outs) == 2 and all(len(t) == 2 for t in outs)
    # queue drained: there is room again
    sched.submit(Request(prompt=(19, 23), max_new_tokens=2))


def test_livelock_watchdog_raises_with_diagnostics(model):
    """Regression for the PR-8 COW livelock, generalized: force the
    unfixable variant (every page always claims to need a copy on an
    exact-fit pool) and the watchdog must raise a diagnostic
    ``LivelockError`` — stuck request set + pool snapshot — instead of
    spinning forever."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg, params = model
    eng = PagedDecodeEngine(params, cfg, num_slots=1, max_len=MAX_LEN,
                            num_pages=2 + RESERVED_PAGES, page_size=4,
                            buckets=(16, 32))
    eng.pool.needs_copy = lambda page: True   # re-create the bug, hard
    sched = ContinuousBatchingScheduler(eng, eos_id=EOS,
                                        watchdog_limit=8)
    sched.submit(Request(prompt=(7, 11, 13, 17, 19), max_new_tokens=3))
    with pytest.raises(LivelockError) as exc:
        sched.run()
    stuck = exc.value.stuck
    assert stuck["queued"] == [0] or stuck["slots"] == {0: 0}
    # the cycle ends each tick preempted: pages released back, nothing
    # leaked — the snapshot is the diagnostic that shows the pool was
    # NOT exhausted, i.e. a logic livelock rather than real pressure
    assert exc.value.pool["num_free"] == 2
    assert exc.value.pool["refcounts"] == {}
    assert exc.value.pool["slot_pages"] == [[]]


def test_invariant_audit_catches_corruption(model):
    """The audit actually detects broken books, host side and device
    side (a green chaos run is only meaningful if it can fail)."""
    eng = _engine(model, num_slots=1)
    eng.prefill(0, (7, 11, 13, 17, 19))
    eng.check_invariants()  # healthy baseline
    # host side: a slot claiming a reference the pool never granted
    eng._slot_pages[0].append(eng._slot_pages[0][0])
    with pytest.raises(PoolInvariantError, match="out of balance"):
        eng.check_invariants()
    eng._slot_pages[0].pop()
    eng.check_invariants()
    # device side: block table repointed behind the allocator's back
    eng.cache = eng.cache._replace(
        block_tables=eng.cache.block_tables.at[0, 0].set(9))
    with pytest.raises(PoolInvariantError, match="device row"):
        eng.check_invariants()


# -- randomized multi-fault chaos --------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_multi_fault_chaos_is_typed_prefixed_and_replayable(model, seed):
    """Randomized faults at every site at once, invariants audited
    after every tick. Every request must end typed; healthy outcomes
    equal the golden run bit-for-bit, degraded ones are golden
    prefixes; and replaying the same seed reproduces the run exactly."""
    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=5),
            Request(prompt=(17, 19), max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=(7, 11, 13, 29), max_new_tokens=4),
            Request(prompt=(23, 29, 31, 37, 41), max_new_tokens=6),
            Request(prompt=(7, 11, 13), max_new_tokens=5,
                    temperature=0.7, seed=9)]
    golden = _golden(model, reqs)
    rates = {"pool_alloc": 0.1, "cow_clone": 0.2, "prefill_exec": 0.15,
             "decode_exec": 0.1, "sample": 0.1}

    def chaos_run():
        eng = _engine(model, FaultInjector(seed=seed, rates=rates),
                      num_pages=12)
        sched, _ = _drive(eng, reqs, audit=True)
        return sched

    sched = chaos_run()
    _check_contract(sched, reqs, golden)
    replay = chaos_run()
    assert replay.outcomes == sched.outcomes
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts
    # the deterministic tick-clock trace stream replays byte-exactly
    assert replay.engine.tracer.tick_stream() \
        == sched.engine.tracer.tick_stream()


# -- chunked prefill under faults --------------------------------------------

_CHUNKY = (7, 11, 13, 17, 19, 23, 29, 31, 37, 41)   # 10 tokens, 3 chunks


def test_chunk_prefill_fault_mid_prompt_requeues_clean(model):
    """A fault on the SECOND chunk of a staged prefill frees the slot
    with zero leaked pages or refcounts (audit runs every tick), charges
    one retry, and the retried request — restarted from the prompt
    head — commits a stream bit-identical to the fault-free golden."""
    reqs = [Request(prompt=_CHUNKY, max_new_tokens=4)]
    golden = _golden(model, reqs)
    eng = _engine(model,
                  FaultInjector(schedule={"chunk_prefill_exec": (1,)}))
    sched, outs = _drive(eng, reqs, audit=True, chunk_tokens=4)
    assert outs == golden
    assert sched.stats.retries == 1
    assert sched.outcomes[0].ok and sched.outcomes[0].retries == 1
    # nothing left behind: no slot holds pages, books balance
    eng.check_invariants()
    assert all(not pages for pages in eng._slot_pages)


def test_chunk_prefill_fault_on_final_chunk_recovers(model):
    """Same contract when the FINAL chunk faults — the chunk whose
    logits feed the first token. The staged progress is discarded whole
    and the retry is still bit-identical."""
    reqs = [Request(prompt=_CHUNKY, max_new_tokens=4,
                    temperature=0.8, seed=5)]
    golden = _golden(model, reqs)
    eng = _engine(model,
                  FaultInjector(schedule={"chunk_prefill_exec": (2,)}))
    sched, outs = _drive(eng, reqs, audit=True, chunk_tokens=4)
    assert outs == golden
    assert sched.stats.retries == 1
    assert sched.outcomes[0].ok
    eng.check_invariants()
    assert all(not pages for pages in eng._slot_pages)


def test_chunk_prefill_fault_exhausts_retry_budget_typed(model):
    """A persistently faulting chunk terminates typed with an empty
    stream — staged chunks never commit tokens — and leaks nothing."""
    reqs = [Request(prompt=_CHUNKY, max_new_tokens=4)]
    eng = _engine(model,
                  FaultInjector(schedule={"chunk_prefill_exec":
                                          range(20)}))
    sched, outs = _drive(eng, reqs, audit=True, chunk_tokens=4,
                         max_retries=2)
    assert outs == [[]]
    out = sched.outcomes[0]
    assert out.reason == "retry_budget" and not out.ok
    assert isinstance(out.error, RetryBudgetExhausted)
    eng.check_invariants()
    assert all(not pages for pages in eng._slot_pages)


@pytest.mark.parametrize("seed", [0, 1])
def test_chunked_multi_fault_chaos_is_typed_prefixed_and_replayable(
        model, seed):
    """The randomized sweep with chunked prefill on and the
    chunk_prefill_exec site armed: typed outcomes, golden-prefix
    degradation against the SYNCHRONOUS golden, bit-for-bit replay."""
    reqs = [Request(prompt=_CHUNKY, max_new_tokens=5),
            Request(prompt=_CHUNKY[:7], max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=(5, 3), max_new_tokens=4),
            Request(prompt=_CHUNKY + (43, 47), max_new_tokens=4,
                    temperature=0.7, seed=9)]
    golden = _golden(model, reqs)
    rates = {"pool_alloc": 0.1, "cow_clone": 0.2,
             "chunk_prefill_exec": 0.2, "decode_exec": 0.1,
             "sample": 0.1}

    def chaos_run():
        eng = _engine(model, FaultInjector(seed=seed, rates=rates),
                      num_pages=14)
        sched, _ = _drive(eng, reqs, audit=True, chunk_tokens=4)
        return sched

    sched = chaos_run()
    _check_contract(sched, reqs, golden)
    assert sched.engine.injector.counts["chunk_prefill_exec"] > 0 \
        or sched.stats.prefill_chunks > 0
    replay = chaos_run()
    assert replay.outcomes == sched.outcomes
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts
    assert replay.engine.tracer.tick_stream() \
        == sched.engine.tracer.tick_stream()
    # CI post-mortem artifact: run_tests.sh chaos points this env var
    # at a tmp path and the workflow uploads the dumps
    out = os.environ.get("APEX_CHAOS_TRACE_OUT")
    if out:
        root, ext = os.path.splitext(out)
        sched.engine.tracer.dump_jsonl(
            f"{root}.seed{seed}{ext or '.jsonl'}")

@pytest.mark.slow
def test_multi_fault_chaos_on_int8_pool(model):
    """One seed of the randomized sweep on the QUANTIZED page pool
    (kv_dtype=int8): the degradation contract and bit-exact replay
    must hold with per-page scales riding the COW-clone, preemption
    and retry paths. Golden is the int8 engine's own fault-free run —
    the contract is about fault transparency, not quantization
    accuracy (that lives in test_quant.py / the L1 parity gate)."""
    import jax.numpy as jnp

    reqs = [Request(prompt=(7, 11, 13), max_new_tokens=5),
            Request(prompt=(17, 19), max_new_tokens=5,
                    temperature=0.8, seed=3),
            Request(prompt=(23, 29, 31, 37, 41), max_new_tokens=6),
            Request(prompt=(7, 11, 13), max_new_tokens=5,
                    temperature=0.7, seed=9)]
    _, golden = _drive(_engine(model, cache_dtype=jnp.int8), reqs)
    rates = {"pool_alloc": 0.1, "cow_clone": 0.2, "prefill_exec": 0.15,
             "decode_exec": 0.1, "sample": 0.1}

    def chaos_run():
        eng = _engine(model, FaultInjector(seed=1, rates=rates),
                      num_pages=12, cache_dtype=jnp.int8)
        sched, _ = _drive(eng, reqs, audit=True)
        return sched

    sched = chaos_run()
    _check_contract(sched, reqs, golden)
    replay = chaos_run()
    assert replay.outcomes == sched.outcomes
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts


def test_error_taxonomy_contract():
    """Every SITE_CONTRACTS degrade error is a real taxonomy class,
    every taxonomy class carries the payload contract, and the table
    covers SITES exactly — the static half of what apxlint APX802/
    APX803 verify, exercised live so a rename breaks a test before it
    breaks the lint."""
    from apex_tpu.serving import (
        InjectedFault, NonFiniteLogits, PromoteFailed, QuotaExhausted,
        ReplicaUnavailable, ServingError, SloViolation, SpillFailed,
        StreamFailed, health,
    )
    from apex_tpu.serving.faults import SITE_CONTRACTS

    assert set(SITE_CONTRACTS) == set(SITES)
    for site, (err_name, sweep) in SITE_CONTRACTS.items():
        if err_name is None:
            continue  # policy-only fault (routing fallback)
        cls = getattr(health, err_name, None) or (
            InjectedFault if err_name == "InjectedFault" else None)
        assert cls is not None, f"{site}: unknown error {err_name}"
        assert issubclass(cls, (ServingError, InjectedFault))
        if sweep is not None:
            assert sweep.startswith("APEX_CHAOS_")

    # payload contract: ServingError subclasses ship diagnostics a
    # flight recorder can attach to
    base = ServingError("boom")
    assert base.payload == {}
    nf = NonFiniteLogits("nan logits in slot 3")
    assert isinstance(nf, ServingError) and nf.payload == {}
    ru = ReplicaUnavailable("decode down", replica="decode_1")
    assert ru.replica == "decode_1" and ru.payload["replica"] == "decode_1"
    sf = SpillFailed("dropped", key="ab12")
    assert sf.key == "ab12" and sf.payload["key"] == "ab12"
    pf = PromoteFailed("stale header", key="cd34", pages=2)
    assert pf.pages == 2 and pf.payload == {"key": "cd34", "pages": 2}
    sfl = StreamFailed("emit dropped", request_id=3, delivered=5,
                       dropped=2)
    assert sfl.payload == {"request_id": 3, "delivered": 5, "dropped": 2}
    qx = QuotaExhausted("over quota", tenant="small", need=6, quota=4,
                        charged=0)
    assert qx.tenant == "small" and qx.payload["need"] == 6
    sv = SloViolation("ttft blown", tenant="chat", metric="ttft",
                      observed=9, bound=4)
    assert sv.metric == "ttft" and sv.payload["bound"] == 4

    # InjectedFault is the injector's typed carrier, not a ServingError:
    # the scheduler's retry ladder catches it by ITS type
    inj = InjectedFault("prefill_exec", 4)
    assert inj.site == "prefill_exec" and inj.index == 4
    assert not isinstance(inj, ServingError)

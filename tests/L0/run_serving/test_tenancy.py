"""Chaos tier for the multi-tenant streaming front-end
(``serving.tenancy`` + ``serving.streaming``): per-token streams,
weighted fair share over the tick token budget, per-tenant page
quotas, priority preemption and per-tenant SLO observability.

The load-bearing contracts:

- BIT-IDENTITY — tenancy reorders WHEN work happens, never WHAT
  commits: committed streams (and therefore delivered stream tokens)
  are integer-identical to the untenanted scheduler across plain,
  speculative, chunked-prefill and disaggregated-pool serving;
- a ``stream_emit`` fault degrades DELIVERY only: the batch drops,
  the stream closes with a typed ``StreamFailed``, and its delivered
  tokens stay a strict prefix of the committed stream — the request
  itself keeps decoding and finishes ok;
- quotas are typed and leak-free: a request that could never fit its
  tenant's quota raises ``QuotaExhausted`` at ``submit()``; transient
  pressure defers admission (``quota_deferrals``) and the reservation
  books drain to zero once the scheduler does;
- weighted shares converge to the declared ratios on the tick clock
  while every tenant stays backlogged (stride scheduling);
- ``SloViolation`` is a latency fact, not a failure: stamped into
  ``RequestOutcome.slo`` with ``ok`` untouched;
- the randomized multi-fault chaos sweep replays bit-for-bit
  (outcomes, stats, injector counts, tick-clock event stream, stream
  snapshots) and dumps tenant-labeled Perfetto artifacts.

``APEX_CHAOS_TENANT_SEED`` (comma-separated ints) overrides the
sweep's seed set — the CI chaos matrix fans one seed per leg and
uploads each leg's Perfetto dump.
"""

import dataclasses
import os

import jax
import pytest

from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.serving import (
    ContinuousBatchingScheduler, FaultInjector, PagedDecodeEngine,
    PoolRouter, QuotaExhausted, Request, SloViolation, StreamFailed,
    Tenant, TenancyPolicy, Tracer, FINISH_REASONS,
)

pytestmark = pytest.mark.chaos

EOS = -1       # unreachable: healthy streams run to max_new_tokens
MAX_LEN = 32

#: The randomized sweep's seeds; the CI chaos matrix overrides this to
#: one seed per leg.
_TENANT_SEEDS = tuple(
    int(s) for s in os.environ.get("APEX_CHAOS_TENANT_SEED",
                                   "0,1,2").split(","))


@pytest.fixture(scope="module")
def model():
    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    return cfg, init_gpt(jax.random.PRNGKey(0), cfg)


def _engine(model, injector=None, tracer=None, num_pages=24, **kw):
    cfg, params = model
    kw.setdefault("tracer", tracer if tracer is not None else Tracer())
    return PagedDecodeEngine(params, cfg, num_slots=2, max_len=MAX_LEN,
                             num_pages=num_pages, page_size=4,
                             buckets=(16, 32), injector=injector, **kw)


#: The standard two-class mix: a weighted, higher-rung interactive
#: tenant sharing the engine with a batch tenant (no quotas — the
#: quota tests build their own policies).
_TENANTS = (Tenant("interactive", weight=3.0, priority=1),
            Tenant("batch", weight=1.0))

_REQS = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=8,
                 tenant_id="interactive"),
         Request(prompt=(6, 7, 8), max_new_tokens=6, temperature=0.8,
                 seed=7, tenant_id="batch"),
         Request(prompt=(9, 10, 11, 12), max_new_tokens=4,
                 temperature=1.1, seed=5, tenant_id="interactive")]


def _drive(sched, reqs=_REQS):
    for r in reqs:
        sched.submit(r)
    return sched.run()


def _golden(model, reqs=_REQS, spec_k=0, chunk=None):
    """Untenanted, unstreamed committed streams — the identity
    baseline every tenanted run must reproduce integer-exactly."""
    eng = _engine(model, spec_k=spec_k)
    return _drive(ContinuousBatchingScheduler(eng, eos_id=EOS,
                                              audit=True,
                                              chunk_tokens=chunk), reqs)


def _tenanted(model, injector=None, tenants=_TENANTS, spec_k=0,
              chunk=None, num_pages=24, **skw):
    eng = _engine(model, injector, num_pages=num_pages, spec_k=spec_k)
    return ContinuousBatchingScheduler(
        eng, eos_id=EOS, audit=True, chunk_tokens=chunk,
        tenancy=TenancyPolicy(tenants), streams=True, **skw)


# -- bit-identity: tenanted streams == untenanted committed streams ---------

@pytest.mark.parametrize("spec_k,chunk", [(0, None), (2, None), (0, 8)])
def test_tenanted_streams_bit_identical_to_untenanted(model, spec_k,
                                                      chunk):
    """The headline contract: weighted fair share + priority rungs +
    per-token streaming change WHEN work runs, never WHAT commits —
    plain, speculative and chunked-prefill committed streams are
    integer-identical to the untenanted scheduler, and every
    TokenStream delivered the full committed stream."""
    golden = _golden(model, spec_k=spec_k, chunk=chunk)
    sched = _tenanted(model, spec_k=spec_k, chunk=chunk)
    assert _drive(sched) == golden
    for rid, out in sorted(sched.outcomes.items()):
        assert out.ok and out.reason in FINISH_REASONS
        assert out.tenant_id == _REQS[rid].tenant_id
        st = sched.streams.streams[rid]
        assert st.closed and not st.failed
        assert st.delivered == golden[rid]
    assert sched.tenancy.charged_total() == 0
    assert sched.stats.stream_tokens == sum(len(g) for g in golden)


def test_pool_tenanted_streams_bit_identical(model):
    """Same identity through the disaggregated pool tier: tenancy and
    streaming ride the PoolRouter's composite engine (shared tracer +
    injector across replicas) without perturbing a token."""
    golden = _golden(model)
    inj, trc = FaultInjector(), Tracer()
    prefills = [_engine(model, inj, trc) for _ in range(2)]
    decodes = [_engine(model, inj, trc)]
    pool = PoolRouter(prefills, decodes, EOS, audit=True,
                      tenancy=TenancyPolicy(_TENANTS), streams=True)
    assert _drive(pool) == golden
    for rid, out in sorted(pool.outcomes.items()):
        assert out.ok and out.tenant_id == _REQS[rid].tenant_id
        assert pool.streams.streams[rid].delivered == golden[rid]
    assert pool.tenancy.charged_total() == 0


# -- stream_emit chaos: strict-prefix delivery ------------------------------

@pytest.mark.parametrize("spec_k,chunk", [(0, None), (2, None), (0, 8)])
def test_stream_emit_chaos_delivers_strict_prefix(model, spec_k, chunk):
    """Arm the ``stream_emit`` site hard: dropped delivery batches
    close their stream with a typed ``StreamFailed`` whose delivered
    tokens are a STRICT prefix of the committed stream — and the
    committed streams themselves stay exactly golden (delivery is
    host-side fan-out, never part of the commit path). Replays
    bit-for-bit."""
    golden = _golden(model, spec_k=spec_k, chunk=chunk)

    def chaos_run():
        sched = _tenanted(
            model, FaultInjector(seed=3, rates={"stream_emit": 0.4}),
            spec_k=spec_k, chunk=chunk)
        _drive(sched)
        return sched

    sched = chaos_run()
    assert sched.stats.stream_failures > 0
    failed = 0
    for rid, out in sorted(sched.outcomes.items()):
        assert out.ok, "a delivery fault must never fail the request"
        assert list(out.tokens) == golden[rid]
        st = sched.streams.streams[rid]
        assert st.closed
        assert st.delivered == golden[rid][:len(st.delivered)]
        if st.failed:
            failed += 1
            assert isinstance(st.error, StreamFailed)
            assert st.error.payload["request_id"] == rid
            assert len(st.delivered) < len(golden[rid]), \
                "failed stream must be a STRICT prefix"
    assert failed == sched.stats.stream_failures
    replay = chaos_run()
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts
    assert replay.streams.snapshot() == sched.streams.snapshot()


def test_stream_emit_chaos_on_pool(model):
    """The same strict-prefix contract through the disaggregated pool:
    the StreamMux draws ``stream_emit`` on the pool's shared injector,
    so dropped deliveries replay bit-for-bit there too."""
    golden = _golden(model)

    def chaos_run():
        inj = FaultInjector(seed=5, rates={"stream_emit": 0.5})
        trc = Tracer()
        pool = PoolRouter([_engine(model, inj, trc) for _ in range(2)],
                          [_engine(model, inj, trc)], EOS, audit=True,
                          tenancy=TenancyPolicy(_TENANTS), streams=True)
        _drive(pool)
        return pool

    pool = chaos_run()
    assert pool.stats.stream_failures > 0
    for rid, out in sorted(pool.outcomes.items()):
        assert out.ok and list(out.tokens) == golden[rid]
        st = pool.streams.streams[rid]
        assert st.delivered == golden[rid][:len(st.delivered)]
    replay = chaos_run()
    assert replay.stats.as_dict() == pool.stats.as_dict()
    assert replay.streams.snapshot() == pool.streams.snapshot()


# -- quotas: typed at submit, deferred under pressure, leak-free ------------

def test_quota_exhausted_typed_and_leak_free(model):
    """A request that could NEVER fit its tenant's page quota raises
    typed ``QuotaExhausted`` at ``submit()`` with the full payload;
    requests that fit are admitted one at a time under transient
    pressure (``quota_deferrals`` while a slot sits free) — and the
    reservation books drain to exactly zero with the scheduler."""
    sched = _tenanted(model, tenants=(Tenant("small", page_quota=4),))
    pol = sched.tenancy
    with pytest.raises(QuotaExhausted) as exc:
        sched.submit(Request(prompt=tuple(range(1, 10)),
                             max_new_tokens=12, tenant_id="small"))
    assert exc.value.payload == {"tenant": "small", "need": 6,
                                 "quota": 4, "charged": 0}
    assert sched.stats.quota_exhausted == 1
    assert sched.outcomes == {}, "fail-fast must not allocate an id"

    # two fitting requests: worst cases 4 + 3 pages against quota 4 —
    # the second must WAIT for the first's credit though a slot is free
    sched.submit(Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=8,
                         tenant_id="small"))
    sched.submit(Request(prompt=(6, 7, 8), max_new_tokens=8,
                         tenant_id="small"))
    streams = sched.run()
    assert len(streams) == 2
    assert all(out.ok for out in sched.outcomes.values())
    assert sched.stats.quota_deferrals >= 1
    assert pol.charged_total() == 0
    assert pol.ledger.charged("small") == 0
    for rid, st in sorted(sched.streams.streams.items()):
        assert st.delivered == streams[rid]

    # unknown tenants are a config error, not a quota event
    with pytest.raises(ValueError):
        sched.submit(Request(prompt=(1, 2), max_new_tokens=2,
                             tenant_id="nobody"))


# -- weighted fair share: stride convergence on the tick clock --------------

def test_weighted_shares_converge_on_tick_clock(model):
    """Two saturating tenants at declared weights 3:1: while both stay
    backlogged, committed-token shares converge to the weight ratio
    (stride scheduling — each token advances its tenant's virtual time
    by 1/weight, admission picks the lowest vtime), and backlogged
    vtimes stay within one request's stride of each other."""
    sched = _tenanted(model, num_pages=48,
                      tenants=(Tenant("heavy", weight=3.0),
                               Tenant("light", weight=1.0)))
    pol = sched.tenancy
    for i in range(16):
        sched.submit(Request(prompt=(1 + i, 2, 3), max_new_tokens=6,
                             tenant_id="heavy"))
        sched.submit(Request(prompt=(4, 5 + i, 6), max_new_tokens=6,
                             tenant_id="light"))
    for _ in range(36):     # both tenants stay backlogged throughout
        sched.step()
    heavy, light = pol.tokens("heavy"), pol.tokens("light")
    assert light > 0, "a 3:1 share must not starve the light tenant"
    ratio = heavy / light
    assert 2.2 <= ratio <= 4.0, \
        f"share ratio {ratio:.2f} off the declared 3:1"
    # the stride invariant: backlogged vtimes track within one
    # request's stride (max_new_tokens / min weight)
    assert abs(pol.vtime("heavy") - pol.vtime("light")) <= 6.5
    sched.run()             # drain: everything still completes ok
    assert len(sched.outcomes) == 32
    assert all(out.ok for out in sched.outcomes.values())
    assert pol.charged_total() == 0


# -- priority preemption ----------------------------------------------------

def test_priority_preemption_requeues_resident_lower_rung(model):
    """A strictly-higher-rung waiting tenant preempts a resident
    lower-rung slot (requeue via the pool-pressure resume path): the
    ``tenant_preemptions`` counter ticks, the paid request jumps the
    line, and every committed stream — including the preempted one's —
    stays integer-identical to the untenanted golden."""
    reqs = [Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=12,
                    tenant_id="free"),
            Request(prompt=(6, 7, 8), max_new_tokens=12,
                    tenant_id="free"),
            Request(prompt=(9, 10, 11, 12), max_new_tokens=6,
                    tenant_id="paid")]
    golden = _golden(model, reqs)
    sched = _tenanted(model, tenants=(Tenant("free", priority=0),
                                      Tenant("paid", priority=2)))
    sched.submit(reqs[0])
    sched.submit(reqs[1])
    for _ in range(3):      # both slots resident on the free tenant
        sched.step()
    sched.submit(reqs[2])
    sched.run()
    assert sched.stats.tenant_preemptions >= 1
    for rid, out in sorted(sched.outcomes.items()):
        assert out.ok and list(out.tokens) == golden[rid]
        assert sched.streams.streams[rid].delivered == golden[rid]
    paid, = [o for o in sched.outcomes.values() if o.tenant_id == "paid"]
    free_ttfts = [o.ttft_ticks for o in sched.outcomes.values()
                  if o.tenant_id == "free"]
    assert paid.ttft_ticks <= min(free_ttfts) + 12, \
        "preemption must move the paid tenant ahead of a full drain"


# -- per-tenant SLOs --------------------------------------------------------

def test_slo_violations_typed_and_observable(model):
    """Tight TTFT/ITL bounds on an oversubscribed tenant: finished
    requests carry a typed ``SloViolation`` in ``RequestOutcome.slo``
    with ``ok`` untouched (an SLO miss is a latency fact, not a
    failure), the ``slo_violations`` counter matches, and the tracer's
    tenant-labeled latency summary is populated."""
    sched = _tenanted(model, tenants=(
        Tenant("strict", ttft_slo_ticks=1, itl_slo_ticks=1),))
    reqs = [Request(prompt=(1 + i, 2, 3, 4), max_new_tokens=6,
                    tenant_id="strict") for i in range(4)]
    _drive(sched, reqs)
    viols = [o for o in sched.outcomes.values() if o.slo is not None]
    assert viols, "oversubscribed 1-tick bounds must be broken"
    assert all(isinstance(o.slo, SloViolation) for o in viols)
    assert all(o.slo.metric in ("ttft", "itl") for o in viols)
    assert all(o.slo.observed > o.slo.bound for o in viols)
    assert all(o.ok for o in sched.outcomes.values())
    assert sched.stats.slo_violations == len(viols)
    summary = sched.tracer.tenant_latency_summary("strict")
    assert summary["ttft_p50"] >= 1 and summary["itl_p99"] >= 1


# -- randomized multi-fault sweep -------------------------------------------

@pytest.mark.parametrize("seed", _TENANT_SEEDS)
def test_multi_fault_tenant_chaos_replays_bit_for_bit(model, seed):
    """Every serving-path site armed at once (stream drops, pool
    pressure, prefill/decode/sample cross-talk) over the tenanted,
    streaming scheduler, audited every tick: every outcome typed,
    every ok stream exactly golden, every degraded stream a golden
    prefix, every delivery a strict prefix of its commit — and the
    whole run replays bit-for-bit: outcomes, stats, injector counts,
    stream snapshots and the tick-clock event stream."""
    golden = _golden(model)
    rates = {"stream_emit": 0.25, "pool_alloc": 0.1,
             "prefill_exec": 0.1, "decode_exec": 0.1, "sample": 0.1}

    def chaos_run():
        sched = _tenanted(model, FaultInjector(seed=seed, rates=rates),
                          num_pages=16)
        _drive(sched)
        return sched

    sched = chaos_run()
    assert sorted(sched.outcomes) == list(range(len(_REQS)))
    for rid, out in sorted(sched.outcomes.items()):
        assert out.reason in FINISH_REASONS
        want = golden[rid]
        if out.ok:
            assert list(out.tokens) == want, f"request {rid} diverged"
        else:
            assert list(out.tokens) == want[:len(out.tokens)], \
                f"request {rid}: degraded stream not a golden prefix"
        st = sched.streams.streams[rid]
        assert st.delivered == list(out.tokens)[:len(st.delivered)]
    assert sched.tenancy.charged_total() == 0
    replay = chaos_run()
    assert replay.outcomes == sched.outcomes
    assert replay.stats.as_dict() == sched.stats.as_dict()
    assert replay.engine.injector.counts == sched.engine.injector.counts
    assert replay.tracer.tick_stream() == sched.tracer.tick_stream()
    assert replay.streams.snapshot() == sched.streams.snapshot()
    # CI post-mortem artifact: one tenant-labeled Perfetto dump per
    # sweep seed, uploaded by the chaos workflow legs
    out_path = os.environ.get("APEX_CHAOS_TRACE_OUT")
    if out_path:
        root, ext = os.path.splitext(out_path)
        sched.tracer.dump_jsonl(
            f"{root}.tenant_seed{seed}{ext or '.jsonl'}")

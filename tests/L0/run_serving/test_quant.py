"""Quantized-tier accuracy gates + int8 KV edge cases.

The accuracy contract (documented in docs/source/quantization.rst):
teacher-forced decode under the int8 tiers stays within a fixed
max-|logit-error| envelope of the fp32 full-sequence forward —
``W8_MAX_ABS`` for any weight-quantized config, ``KV8_MAX_ABS`` for an
int8 cache under full-precision weights — on rope AND learned
positions, dense AND paged caches, single-chip AND tp2. Speculative
decoding under int8 weights keeps the stream contract exactly:
token-for-token identical to that config's plain decode.

The edge cases pin the int8 page-pool invariants: the all-zero page
(scale 0) dequantizes to exact zeros, unallocated pages stay pristine
under real traffic, copy-on-write clones a page bit-identically
INCLUDING its scale rows, and physical placement stays invisible.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import apply_gpt_unsharded, gpt_tiny, init_gpt
from apex_tpu.quant import kv_dequantize, kv_quantize, quantize_params
from apex_tpu.serving import (
    ContinuousBatchingScheduler, DecodeEngine, PagedDecodeEngine,
    Request, init_cache, make_decode_fn, make_prefill_fn,
)

# Compile-heavy (every test jits fresh prefill/decode programs per
# quant config): excluded from the driver's `-m 'not slow'` tier and
# run via `./run_tests.sh L0` (no marker filter) instead.
pytestmark = pytest.mark.slow

S_TOTAL, PROMPT, S_MAX = 16, 8, 32

# Max |logit error| vs the fp32 full forward on the gpt_tiny gate
# model. Measured: ~1.2e-2 for w8 and w8+kv8, ~4e-3 for kv8-only —
# the envelopes leave ~4x headroom without admitting a broken kernel
# (a sign flip or lost scale lands orders of magnitude outside).
W8_MAX_ABS = 0.05
KV8_MAX_ABS = 0.02


def _cfg(use_rope):
    return dataclasses.replace(gpt_tiny(), use_rope=use_rope,
                               hidden_dropout=0.0)


def _full_logits(params, cfg, seq):
    hidden = apply_gpt_unsharded(params, cfg, seq)
    table = params["embedding"]["word"]["embedding"]
    return jnp.dot(hidden, table.T).astype(jnp.float32)


def _teacher_forced(params, cfg, seq, quantized=False):
    prefill = make_prefill_fn(cfg, quantized=quantized)
    decode = make_decode_fn(cfg, quantized=quantized)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, logits = prefill(params, cache, seq[:, :PROMPT],
                            jnp.ones((PROMPT,), jnp.int32),
                            jnp.int32(0))
    rows = [logits[0]]
    for t in range(PROMPT, seq.shape[1]):
        tokens = jnp.asarray([int(seq[0, t]), 0], jnp.int32)
        cache, logits = decode(params, cache, tokens,
                               jnp.asarray([True, False]))
        rows.append(logits[0])
    return jnp.stack(rows)


def _paged_teacher_forced(params, cfg, seq, cache_dtype,
                          free_order=None):
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=cache_dtype, buckets=(8, 16, 32),
                            free_order=free_order)
    logits = eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    rows = [logits[0]]
    for t in range(PROMPT, seq.shape[1]):
        assert eng.prepare_decode({0: t}) == []
        logits = eng.decode(jnp.asarray([int(seq[0, t]), 0], jnp.int32),
                            jnp.asarray([True, False]))
        rows.append(logits[0])
    return jnp.stack(rows)


def _golden(params, cfg, seq):
    return np.asarray(_full_logits(params, cfg, seq)[0, PROMPT - 1:])


def _seq(cfg, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), (1, S_TOTAL), 0,
                              cfg.vocab_size)


# -- accuracy gates ---------------------------------------------------------

@pytest.mark.parametrize("use_rope,paged",
                         [(True, False), (False, True)],
                         ids=["rope-dense", "learned_pos-paged"])
def test_w8_teacher_forced_within_tolerance(use_rope, paged):
    """Weight-only int8 over a full-precision cache: every
    teacher-forced logit stays inside W8_MAX_ABS of the fp32 golden.
    The lower bound proves the int8 kernels were actually in the loop —
    a silent fall-through to the fp32 path would read as a pass.
    Two diagonal combos cover both position modes and both cache
    layouts; the remaining corners of the cross-product ride in the
    w8+kv8 gate below (rope-paged, learned_pos-paged) and the tp2
    gate (rope-dense + rope-paged)."""
    cfg = _cfg(use_rope)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg)
    want = _golden(params, cfg, seq)
    qp = quantize_params(params)
    if paged:
        got = _paged_teacher_forced(qp, cfg, seq, jnp.float32)
    else:
        got = _teacher_forced(qp, cfg, seq, quantized=True)
    err = np.abs(np.asarray(got) - want).max()
    assert err < W8_MAX_ABS, err
    assert err > 1e-4, "suspiciously exact: int8 path not exercised?"


@pytest.mark.parametrize("use_rope", [True, False],
                         ids=["rope", "learned_pos"])
def test_w8kv8_paged_within_tolerance(use_rope):
    """The full quantized tier — int8 weights AND int8 page pool —
    still inside the weight-tier envelope (the KV error rides well
    under the weight error; they don't compound past it)."""
    cfg = _cfg(use_rope)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg)
    want = _golden(params, cfg, seq)
    got = _paged_teacher_forced(quantize_params(params), cfg, seq,
                                jnp.int8)
    err = np.abs(np.asarray(got) - want).max()
    assert err < W8_MAX_ABS, err
    assert err > 1e-4


def test_kv8_only_within_tolerance():
    """int8 page pool under full-precision weights: the tighter
    KV8_MAX_ABS envelope — per-page-per-head scales keep the cache
    error well under the weight-quantization error."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg)
    want = _golden(params, cfg, seq)
    got = _paged_teacher_forced(params, cfg, seq, jnp.int8)
    err = np.abs(np.asarray(got) - want).max()
    assert err < KV8_MAX_ABS, err
    assert err > 1e-5


def test_tp2_w8_decode_matches_unsharded():
    """tp=2 quantized decode (dense + paged/kv8): logits match the
    single-chip quantized step to fp32 tolerance AND stay inside the
    accuracy envelope — sharding the int8 tree (row/column shards of
    the quantized kernels with their sibling scale shards) is a layout
    change, never an accuracy one."""
    from apex_tpu.models.gpt import GPTModel
    from apex_tpu.serving import make_tp_decode_fn, make_tp_paged_decode_fn
    from apex_tpu.transformer import parallel_state as ps

    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    qp = quantize_params(params)
    seq = _seq(cfg)
    want_row = _golden(params, cfg, seq)[1]  # logits after seq[PROMPT]
    ps.initialize_model_parallel(tensor_model_parallel_size_=2)
    model = GPTModel(cfg, tp_size=2)
    tokens = jnp.asarray([int(seq[0, PROMPT]), 0], jnp.int32)
    active = jnp.asarray([True, False])

    # dense: one quantized-prefilled cache through both decode paths
    prefill = make_prefill_fn(cfg, quantized=True)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, _ = prefill(qp, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    clone = jax.tree.map(jnp.copy, cache)
    _, ref = make_decode_fn(cfg, quantized=True)(qp, cache, tokens,
                                                 active)
    _, got = make_tp_decode_fn(model, quantized=True)(qp, clone, tokens,
                                                      active)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               rtol=1e-4, atol=1e-4)
    assert np.abs(np.asarray(got[0]) - want_row).max() < W8_MAX_ABS

    # paged + int8 pool: engine-built cache, same contract
    eng = PagedDecodeEngine(qp, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.int8, buckets=(8, 16, 32))
    eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    eng.prepare_decode({0: PROMPT})
    clone = jax.tree.map(jnp.copy, eng.cache)
    ref = eng.decode(tokens, active)
    _, got = make_tp_paged_decode_fn(model, quantized=True,
                                     kv_quantized=True)(qp, clone,
                                                        tokens, active)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               rtol=1e-4, atol=1e-4)
    assert np.abs(np.asarray(got[0]) - want_row).max() < W8_MAX_ABS


# -- speculative decoding under int8 weights --------------------------------

@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_spec_stream_w8_bit_identical_to_plain(paged):
    """The stream contract survives quantization unchanged: spec_k
    draft/verify under int8 weights commits token-for-token the plain
    (spec_k=0) quantized streams — greedy AND seeded sampling. Exact
    integer equality; the accept walk compares the SAME quantized
    logits on both sides, so tolerance would hide a real rollback
    bug. (The int8 CACHE gets the same contract separately —
    test_kv8_rejected_tails_do_not_perturb — via the insert-then-zero
    page requantization rule.)"""
    cfg = _cfg(True)
    qp = quantize_params(init_gpt(jax.random.PRNGKey(0), cfg))
    reqs = [Request(prompt=(7, 11, 7, 11, 7), max_new_tokens=6),
            Request(prompt=(5, 3, 5, 3), max_new_tokens=6,
                    temperature=0.8, seed=3),
            Request(prompt=(13, 17, 19), max_new_tokens=4)]

    def run(spec_k):
        if paged:
            eng = PagedDecodeEngine(qp, cfg, num_slots=2, max_len=S_MAX,
                                    num_pages=24, page_size=4,
                                    buckets=(16, 32), spec_k=spec_k)
        else:
            eng = DecodeEngine(qp, cfg, num_slots=2, max_len=S_MAX,
                               buckets=(16, 32), spec_k=spec_k)
        sched = ContinuousBatchingScheduler(eng, eos_id=0)
        for r in reqs:
            sched.submit(r)
        return sched.run(), sched.stats

    plain, _ = run(0)
    spec, stats = run(2)
    assert spec == plain
    assert stats.tokens_drafted > 0


def test_kv8_rejected_tails_do_not_perturb():
    """The int8-cache analogue of
    test_decode.py::test_verify_rejected_rows_not_observable, and the
    contract that makes kv8 speculation exact: two runs whose first
    verify step carried DIFFERENT garbage draft tails must produce
    bit-identical later verify AND plain-decode logits. The verify
    write pins the page scale for tail columns (rescale only at the
    window root) and zeroes rows strictly after each insert, so a
    rejected tail can never re-round committed history. The prompt is
    deliberately NOT page-aligned (6 tokens, page_size 4): the verify
    window straddles a half-full page, the case where a naive
    requantize would perturb committed rows."""
    k = 3
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    pl = 6  # mid-page: rows 4..5 of page 1 committed, tails land 6..9
    seq = _seq(cfg)

    def run(garbage):
        eng = PagedDecodeEngine(params, cfg, num_slots=1, max_len=S_MAX,
                                num_pages=14, page_size=4,
                                cache_dtype=jnp.int8, buckets=(8, 16),
                                spec_k=k)
        eng.prefill(0, [int(t) for t in np.asarray(seq[0, :pl])])
        eng.prepare_decode({0: pl}, n_new=k + 1)
        bad = jnp.concatenate(
            [seq[:, pl:pl + 1], jnp.full((1, k), garbage, jnp.int32)],
            axis=1)
        eng.verify(bad)
        eng.commit([1])  # only the pending token survives the walk
        eng.prepare_decode({0: pl + 1}, n_new=k + 1)
        l_verify = eng.verify(seq[:, pl + 1:pl + k + 2])
        eng.commit([1])
        eng.prepare_decode({0: pl + 2})
        l_plain = eng.decode(seq[:, pl + 2], jnp.asarray([True]))
        return np.asarray(l_verify), np.asarray(l_plain)

    va, pa = run(3)
    vb, pb = run(499)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(pa, pb)


# -- int8 KV edge cases -----------------------------------------------------

def test_kv_quantize_all_zero_page():
    """The scale-0 guard: an all-zero page quantizes to exact int8
    zeros with scale 0 and dequantizes to exact fp32 zeros — no NaN/inf
    from the 0/0 — even alongside a non-zero page in the same batch."""
    zero = jnp.zeros((2, 4, 8, 16))
    hot = jnp.concatenate([zero[:1], jnp.ones((1, 4, 8, 16))])
    q, scale = kv_quantize(zero)
    assert q.dtype == jnp.int8 and not np.asarray(q).any()
    assert not np.asarray(scale).any()
    back = np.asarray(kv_dequantize(q, scale))
    assert np.isfinite(back).all() and not back.any()
    q, scale = kv_quantize(hot)
    assert not np.asarray(q[0]).any() and np.asarray(q[1]).any()
    assert not np.asarray(scale[0]).any()
    np.testing.assert_allclose(np.asarray(kv_dequantize(q, scale)[1]),
                               1.0, rtol=1e-2)


def test_int8_unallocated_pages_stay_pristine():
    """Real prefill + decode traffic through an int8 pool must leave
    every page the allocator never handed out — NULL included — at
    exact zeros with zero scales. Inactive-slot writes are redirected
    to SCRATCH, never a free page (prefix sharing off, so no
    registry-cached pages muddy the live set)."""
    from apex_tpu.serving.cache import SCRATCH_PAGE

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg)
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.int8, buckets=(8, 16, 32),
                            prefix_sharing=False)
    eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    for t in range(PROMPT, PROMPT + 4):
        eng.prepare_decode({0: t})
        eng.decode(jnp.asarray([int(seq[0, t]), 0], jnp.int32),
                   jnp.asarray([True, False]))
    live = {SCRATCH_PAGE}
    for pages in eng._slot_pages:
        live.update(pages)
    cache = eng.cache
    for page in range(14):
        if page in live:
            continue
        for pool in (cache.k, cache.v):
            assert not np.asarray(pool[:, page]).any(), page
        for scale in (cache.k_scale, cache.v_scale):
            assert not np.asarray(scale[:, page]).any(), page
    # the live pages did take real int8 traffic
    assert any(np.asarray(cache.k[:, p]).any()
               for p in eng._slot_pages[0])


def test_int8_cow_clone_bit_identical():
    """Copy-on-write on a quantized pool clones the page's int8 tiles
    AND its k/v scale rows bitwise, touching nothing else."""
    from apex_tpu.serving.cache import init_paged_cache
    from apex_tpu.serving.decode import make_copy_page_fn

    cfg = _cfg(True)
    cache = init_paged_cache(cfg, 2, S_MAX, 8, 4, jnp.int8)
    rng = np.random.RandomState(0)

    def fill(leaf, lo, hi, dtype):
        return jnp.asarray(rng.randint(lo, hi, leaf.shape), dtype)

    cache = cache._replace(
        k=fill(cache.k, -127, 128, jnp.int8),
        v=fill(cache.v, -127, 128, jnp.int8),
        k_scale=jnp.asarray(rng.rand(*cache.k_scale.shape), jnp.float32),
        v_scale=jnp.asarray(rng.rand(*cache.v_scale.shape), jnp.float32))
    before = jax.tree.map(np.asarray, cache)
    src, dst = 3, 6
    after = jax.tree.map(
        np.asarray, make_copy_page_fn()(cache, jnp.int32(src),
                                        jnp.int32(dst)))
    for b, a in zip(before[:2] + before[4:], after[:2] + after[4:]):
        np.testing.assert_array_equal(a[:, dst], b[:, src])
        mask = np.arange(a.shape[1]) != dst
        np.testing.assert_array_equal(a[:, mask], b[:, mask])
    np.testing.assert_array_equal(after.lengths, before.lengths)
    np.testing.assert_array_equal(after.block_tables,
                                  before.block_tables)


def test_int8_cow_does_not_perturb_sharing_request():
    """The bf16 COW acceptance contract holds verbatim on an int8
    pool: two requests sharing a partial prompt page both append
    (copy-on-write), and each one's logits are BIT-IDENTICAL to its
    alone run — the clone carried the scales, the shared original was
    never re-quantized."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    prompt = [5, 7, 11, 13, 17, 19]  # 1.5 pages of 4: partial shared
    div = (31, 37)

    def engine(num_pages=12):
        return PagedDecodeEngine(params, cfg, num_slots=2,
                                 max_len=S_MAX, num_pages=num_pages,
                                 page_size=4, cache_dtype=jnp.int8,
                                 buckets=(16, 32))

    def alone(slot, token):
        eng = engine()
        eng.prefill(slot, prompt)
        assert eng.prepare_decode({slot: len(prompt)}) == []
        toks = [0, 0]
        toks[slot] = token
        active = jnp.asarray([i == slot for i in range(2)])
        return np.asarray(eng.decode(jnp.asarray(toks, jnp.int32),
                                     active)[slot])

    refs = [alone(0, div[0]), alone(1, div[1])]
    eng = engine()
    eng.prefill(0, prompt)
    eng.prefill(1, prompt)
    shared = eng._slot_pages[0][1]
    assert eng.prepare_decode({0: len(prompt), 1: len(prompt)}) == []
    assert eng._slot_pages[0][1] != shared  # both COW'd
    assert eng._slot_pages[1][1] != shared
    step = eng.decode(jnp.asarray(div, jnp.int32),
                      jnp.asarray([True, True]))
    np.testing.assert_array_equal(np.asarray(step[0]), refs[0])
    np.testing.assert_array_equal(np.asarray(step[1]), refs[1])


def test_int8_decode_bit_identical_across_page_placements():
    """Physical placement stays invisible on the quantized pool: the
    same request through permuted free-list orders produces
    BIT-IDENTICAL logits at every step — scales live with their pages,
    so re-placement can't re-quantize anything."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg)
    usable = list(range(RESERVED_PAGES, 14))
    rng = np.random.RandomState(3)
    orders = [None, list(rng.permutation(usable))]
    runs = [np.asarray(_paged_teacher_forced(params, cfg, seq, jnp.int8,
                                             free_order=order))
            for order in orders]
    for other in runs[1:]:
        np.testing.assert_array_equal(runs[0], other)


def test_dense_cache_rejects_int8():
    """The dense cache has no scale plumbing — int8 must be a loud
    constructor error, not a silently-garbage cache."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="int8"):
        DecodeEngine(params, cfg, num_slots=1, max_len=S_MAX,
                     cache_dtype=jnp.int8)

"""ngram_draft: the host-side prompt-lookup drafter (pure function of
the token history — exact-value tests, no device work), plus the
tree-speculation grid packer ``tree_arrays``, the grid accept walk
``tree_speculative_accept``, and the lockstep ``DraftModel``."""

from apex_tpu.serving import ngram_draft


def test_repeating_pattern_continues():
    # suffix [1, 2] last occurred at index 0; the continuation is
    # [3, 1, 2] — the draft that makes a period-3 loop free to decode
    assert ngram_draft([1, 2, 3, 1, 2], 3) == [3, 1, 2]


def test_longest_suffix_wins():
    # the trigram suffix [1, 2, 3] recurs (continuation 9) and so does
    # the bigram [2, 3] (a later occurrence continues with 5); longer
    # evidence must win over recency at a shorter length
    hist = [1, 2, 3, 9, 2, 3, 5, 1, 2, 3]
    assert ngram_draft(hist, 1) == [9]


def test_recency_breaks_ties_within_a_length():
    # [2, 3] occurs twice with different continuations; the MOST RECENT
    # earlier occurrence (-> 5) is the draft, not the first (-> 9)
    hist = [2, 3, 9, 2, 3, 5, 2, 3]
    assert ngram_draft(hist, 1, max_ngram=2) == [5]


def test_terminal_self_match_excluded():
    # every suffix of [1, 2, 3] occurs only once (at the end): a
    # drafter that matched the suffix against itself would return
    # garbage here instead of the honest empty draft
    assert ngram_draft([1, 2, 3], 3) == []


def test_no_recurrence_returns_empty():
    assert ngram_draft([1, 2, 3, 4, 5, 6], 4) == []


def test_short_and_empty_history():
    assert ngram_draft([], 3) == []
    assert ngram_draft([7], 3) == []  # nothing before the 1-gram suffix


def test_draft_truncated_at_history_end():
    # the match sits one token from the end: only one continuation
    # token exists, and the drafter must return the short draft rather
    # than pad or over-read
    assert ngram_draft([5, 9, 5], 4) == [9, 5]


def test_k_bounds():
    hist = [1, 2, 3, 1, 2]
    assert ngram_draft(hist, 0) == []
    assert ngram_draft(hist, -1) == []
    assert ngram_draft(hist, 2) == [3, 1]


def test_ngram_window_bounds():
    hist = [1, 2, 3, 1, 2]
    assert ngram_draft(hist, 3, max_ngram=0) == []
    assert ngram_draft(hist, 3, min_ngram=0) == []
    # min_ngram above any recurring length -> empty
    assert ngram_draft([9, 1, 2, 3, 1, 2, 3], 2, min_ngram=3,
                       max_ngram=3) == [1, 2]


# -- tree_arrays: the verify-grid packer -------------------------------------

def test_tree_arrays_packs_forced_chain_and_tree():
    import numpy as np

    from apex_tpu.serving import tree_arrays

    # slot 0: forced chain [9, 8] (f=2, root col 1), tree = root child A
    #         with children B (chain) — cols 2, 3
    # slot 1: forced [5] only (plain re-send, no tree)
    toks, depth, anc, valid, parents, start = tree_arrays(
        [[9, 8], [5]], [([4, 6], [-1, 0]), None], k1=4)
    assert toks.tolist() == [[9, 8, 4, 6], [5, 0, 0, 0]]
    assert depth.tolist() == [[0, 1, 2, 3], [0, 0, 0, 0]]
    assert valid.tolist() == [[False, False, True, True],
                              [False, False, False, False]]
    assert parents.tolist() == [[-1, 0, 1, 2], [-1, -1, -1, -1]]
    assert start.tolist() == [1, 0]
    # ancestor sets: col 3 sees the whole chain, pads see only self
    assert anc[0, :, 3].tolist() == [True, True, True, True]
    assert anc[0, :, 0].tolist() == [True, False, False, False]
    assert anc[1, :, 1].tolist() == [False, True, False, False]
    # branching: two children of the same root get disjoint subtrees
    t2, d2, a2, v2, p2, s2 = tree_arrays(
        [[7]], [([1, 2, 3], [-1, -1, 0])], k1=4)
    assert p2.tolist() == [[-1, 0, 0, 1]]
    assert d2.tolist() == [[0, 1, 1, 2]]
    assert not a2[0, 2, 3] and a2[0, 1, 3]  # C under A, not under B


def test_tree_arrays_validates():
    import pytest

    from apex_tpu.serving import tree_arrays

    with pytest.raises(ValueError, match="pending"):
        tree_arrays([[]], [None], k1=2)
    with pytest.raises(ValueError, match="exceeds grid"):
        tree_arrays([[1, 2]], [([3, 4, 5], [-1, 0, 1])], k1=4)
    with pytest.raises(ValueError, match="earlier node"):
        tree_arrays([[1]], [([3, 4], [-1, 5])], k1=4)


# -- tree_speculative_accept: the grid walk ----------------------------------

def test_tree_accept_walks_matching_branch():
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.serving import tree_arrays, tree_speculative_accept

    # grid: root=7 (col 0), children A=4 (col 1), B=5 (col 2), A's
    # child C=6 (col 3)
    toks, depth, anc, valid, parents, start = tree_arrays(
        [[7]], [([4, 5, 6], [-1, -1, 0])], k1=4)
    V = 16

    def grid(samples_by_col):
        g = np.zeros((1, 4), np.int32)
        for col, s in samples_by_col.items():
            g[0, col] = s
        return jnp.asarray(g)

    args = (jnp.asarray(toks), jnp.asarray(parents), jnp.asarray(valid),
            jnp.asarray(start))
    # root samples B (5) -> hop to col 2; col 2 samples something with
    # no matching child -> stop. Commits: root sample + B's sample.
    cnt, path = tree_speculative_accept(grid({0: 5, 2: 9}), *args)
    assert cnt.tolist() == [2]
    assert path[0, :2].tolist() == [0, 2]
    # root samples A (4) -> col 1; col 1 samples C (6) -> col 3; stop
    cnt, path = tree_speculative_accept(grid({0: 4, 1: 6, 3: 11}), *args)
    assert cnt.tolist() == [3]
    assert path[0, :3].tolist() == [0, 1, 3]
    # root samples neither child -> only the root's sample commits
    cnt, path = tree_speculative_accept(grid({0: 9}), *args)
    assert cnt.tolist() == [1]
    assert path[0, :1].tolist() == [0]


# -- DraftModel: lockstep greedy drafting ------------------------------------

def _draft_setup():
    import dataclasses

    import jax

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import DraftModel

    cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                              hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    return cfg, params, DraftModel(params, cfg, num_slots=2, max_len=32)


def _greedy_reference(params, cfg, history, k):
    """k greedy continuations of ``history`` via the model's own full
    forward — what DraftModel must reproduce through its incremental
    cache."""
    import jax.numpy as jnp

    from apex_tpu.models.gpt import apply_gpt_unsharded

    toks = list(history)
    out = []
    for _ in range(k):
        h = apply_gpt_unsharded(params, cfg,
                                jnp.asarray([toks], jnp.int32))
        table = params["embedding"]["word"]["embedding"]
        logits = jnp.dot(h[0, -1], table.T)
        nxt = int(jnp.argmax(logits))
        out.append(nxt)
        toks.append(nxt)
    return out


def test_draft_model_matches_greedy_reference():
    cfg, params, dm = _draft_setup()
    h0 = [5, 9, 3, 7]
    h1 = [11, 13, 2]
    chains = dm.draft([h0, h1], [3, 2])
    assert chains[0] == _greedy_reference(params, cfg, h0, 3)
    assert chains[1] == _greedy_reference(params, cfg, h1, 2)


def test_draft_model_resyncs_after_rejection():
    """After a partial accept the target's history DIVERGES from what
    the draft cache saw; the next draft call must roll back to the
    common prefix and still match the from-scratch greedy reference."""
    cfg, params, dm = _draft_setup()
    h = [5, 9, 3, 7]
    first = dm.draft([h, None], [3, 0])[0]
    # target accepted one draft token then resampled a different one
    h2 = h + [first[0], (first[1] + 1) % cfg.vocab_size]
    second = dm.draft([h2, None], [3, 0])[0]
    assert second == _greedy_reference(params, cfg, h2, 3)


def test_draft_model_free_slot_clears_state():
    cfg, params, dm = _draft_setup()
    a = dm.draft([[5, 9, 3], None], [2, 0])[0]
    dm.free_slot(0)
    # a different request in the recycled slot must not inherit rows
    b = dm.draft([[7, 11], None], [2, 0])[0]
    assert b == _greedy_reference(params, cfg, [7, 11], 2)
    dm.free_slot(0)
    assert dm.draft([[5, 9, 3], None], [2, 0])[0] == a


def test_draft_model_tree_adds_second_best_root():
    """draft_tree spends its k-node budget as a greedy chain of k - 1
    plus the second-best first token as an alternative root child —
    two DISTINCT children of the walk root."""
    cfg, params, dm = _draft_setup()
    toks, parents = dm.draft_tree([[5, 9, 3, 7], None], [3, 0])[0]
    assert len(toks) == 3
    assert toks[:2] == _greedy_reference(params, cfg, [5, 9, 3, 7], 2)
    assert parents == [-1, 0, -1]  # chain + the alternative root
    assert toks[2] != toks[0]  # genuinely second-best, not a duplicate

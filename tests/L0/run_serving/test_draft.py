"""ngram_draft: the host-side prompt-lookup drafter. Pure function of
the token history — these are exact-value tests, no device work."""

from apex_tpu.serving import ngram_draft


def test_repeating_pattern_continues():
    # suffix [1, 2] last occurred at index 0; the continuation is
    # [3, 1, 2] — the draft that makes a period-3 loop free to decode
    assert ngram_draft([1, 2, 3, 1, 2], 3) == [3, 1, 2]


def test_longest_suffix_wins():
    # the trigram suffix [1, 2, 3] recurs (continuation 9) and so does
    # the bigram [2, 3] (a later occurrence continues with 5); longer
    # evidence must win over recency at a shorter length
    hist = [1, 2, 3, 9, 2, 3, 5, 1, 2, 3]
    assert ngram_draft(hist, 1) == [9]


def test_recency_breaks_ties_within_a_length():
    # [2, 3] occurs twice with different continuations; the MOST RECENT
    # earlier occurrence (-> 5) is the draft, not the first (-> 9)
    hist = [2, 3, 9, 2, 3, 5, 2, 3]
    assert ngram_draft(hist, 1, max_ngram=2) == [5]


def test_terminal_self_match_excluded():
    # every suffix of [1, 2, 3] occurs only once (at the end): a
    # drafter that matched the suffix against itself would return
    # garbage here instead of the honest empty draft
    assert ngram_draft([1, 2, 3], 3) == []


def test_no_recurrence_returns_empty():
    assert ngram_draft([1, 2, 3, 4, 5, 6], 4) == []


def test_short_and_empty_history():
    assert ngram_draft([], 3) == []
    assert ngram_draft([7], 3) == []  # nothing before the 1-gram suffix


def test_draft_truncated_at_history_end():
    # the match sits one token from the end: only one continuation
    # token exists, and the drafter must return the short draft rather
    # than pad or over-read
    assert ngram_draft([5, 9, 5], 4) == [9, 5]


def test_k_bounds():
    hist = [1, 2, 3, 1, 2]
    assert ngram_draft(hist, 0) == []
    assert ngram_draft(hist, -1) == []
    assert ngram_draft(hist, 2) == [3, 1]


def test_ngram_window_bounds():
    hist = [1, 2, 3, 1, 2]
    assert ngram_draft(hist, 3, max_ngram=0) == []
    assert ngram_draft(hist, 3, min_ngram=0) == []
    # min_ngram above any recurring length -> empty
    assert ngram_draft([9, 1, 2, 3, 1, 2, 3], 2, min_ngram=3,
                       max_ngram=3) == [1, 2]

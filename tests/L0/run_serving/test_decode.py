"""Serving headline contract: KV-cached incremental decode must match
the full-sequence forward to fp32 tolerance at identical positions —
plus the supporting invariants (pad-independence of bucketed prefill,
cache-donation bit-identity, cache dtype behavior)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import apply_gpt_unsharded, gpt_tiny, init_gpt
from apex_tpu.serving import init_cache, make_decode_fn, make_prefill_fn
from apex_tpu.serving.cache import KVCache

S_TOTAL, PROMPT, S_MAX = 20, 8, 32


def _cfg(use_rope):
    return dataclasses.replace(gpt_tiny(), use_rope=use_rope,
                               hidden_dropout=0.0)


def _full_logits(params, cfg, seq):
    hidden = apply_gpt_unsharded(params, cfg, seq)
    table = params["embedding"]["word"]["embedding"]
    return jnp.dot(hidden, table.T).astype(jnp.float32)


def _teacher_forced(params, cfg, seq, cache_dtype=jnp.float32,
                    num_slots=2):
    """prefill(seq[:PROMPT]) then decode feeding the TRUE next tokens;
    returns logits rows aligned with positions PROMPT-1 .. S_TOTAL-1."""
    prefill = make_prefill_fn(cfg)
    decode = make_decode_fn(cfg)
    cache = init_cache(cfg, num_slots, S_MAX, cache_dtype)
    cache, logits = prefill(params, cache, seq[:, :PROMPT],
                            jnp.ones((PROMPT,), jnp.int32),
                            jnp.int32(0))
    rows = [logits[0]]
    pad_tokens = jnp.zeros((num_slots - 1,), jnp.int32)
    active = jnp.asarray([True] + [False] * (num_slots - 1))
    for t in range(PROMPT, seq.shape[1]):
        tokens = jnp.concatenate(
            [jnp.asarray([int(seq[0, t])], jnp.int32), pad_tokens])
        cache, logits = decode(params, cache, tokens, active)
        rows.append(logits[0])
    return jnp.stack(rows)


@pytest.mark.parametrize("use_rope", [True, False],
                         ids=["rope", "learned_pos"])
def test_decode_matches_full_forward(use_rope):
    cfg = _cfg(use_rope)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(1), (1, S_TOTAL), 0,
                             cfg.vocab_size)
    want = _full_logits(params, cfg, seq)[0, PROMPT - 1:]
    got = _teacher_forced(params, cfg, seq)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_prefill_pad_tail_never_attended():
    """Bucket padding regression: prefill of the same prompt padded with
    two different garbage tails must produce identical logits AND an
    identical cache — pad K/V can never leak into attention, now or
    through later in-place cache writes."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    prefill = make_prefill_fn(cfg)
    prompt = np.asarray([[5, 7, 11, 13, 17]], np.int32)  # ragged: 5
    bucket = 16
    mask = (np.arange(bucket) < prompt.shape[1]).astype(np.int32)

    def run(pad_value):
        ids = np.full((1, bucket), pad_value, np.int32)
        ids[:, : prompt.shape[1]] = prompt
        cache = init_cache(cfg, 1, S_MAX, jnp.float32)
        return prefill(params, cache, jnp.asarray(ids),
                       jnp.asarray(mask), jnp.int32(0))

    cache_a, logits_a = run(0)
    cache_b, logits_b = run(499)
    np.testing.assert_array_equal(np.asarray(logits_a),
                                  np.asarray(logits_b))
    for a, b in zip(cache_a, cache_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the decode continuation is identical too
    decode = make_decode_fn(cfg)
    _, la = decode(params, cache_a, jnp.asarray([3], jnp.int32),
                   jnp.asarray([True]))
    _, lb = decode(params, cache_b, jnp.asarray([3], jnp.int32),
                   jnp.asarray([True]))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_ragged_batch_parity():
    """Prompts of different lengths, each bucketed with a pad tail, all
    decoding concurrently in one cache — every slot must still match
    its own full-sequence forward."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    prefill = make_prefill_fn(cfg)
    decode = make_decode_fn(cfg)
    lens = [3, 8, 13]
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, cfg.vocab_size, size=(1, n + 4)).astype(
        np.int32) for n in lens]
    cache = init_cache(cfg, len(lens), S_MAX, jnp.float32)
    for i, (n, seq) in enumerate(zip(lens, seqs)):
        bucket = 16
        ids = np.zeros((1, bucket), np.int32)
        ids[:, :n] = seq[:, :n]
        mask = (np.arange(bucket) < n).astype(np.int32)
        cache, logits = prefill(params, cache, jnp.asarray(ids),
                                jnp.asarray(mask), jnp.int32(i))
        want = _full_logits(params, cfg, jnp.asarray(seq[:, :n]))
        np.testing.assert_allclose(np.asarray(logits[0]),
                                   np.asarray(want[0, -1]),
                                   rtol=1e-4, atol=1e-4)
    # four teacher-forced decode steps with ALL slots active at their
    # own (ragged) positions
    for t in range(4):
        tokens = jnp.asarray([int(s[0, n + t]) for n, s in
                              zip(lens, seqs)], jnp.int32)
        cache, logits = decode(params, cache, tokens,
                               jnp.ones((len(lens),), bool))
        for i, (n, seq) in enumerate(zip(lens, seqs)):
            want = _full_logits(params, cfg,
                                jnp.asarray(seq[:, : n + t + 1]))
            np.testing.assert_allclose(np.asarray(logits[i]),
                                       np.asarray(want[0, -1]),
                                       rtol=1e-4, atol=1e-4)


def test_cache_donation_bit_identity():
    """The donated jitted decode must produce bit-identical caches and
    logits to a fresh-cache run of the same steps — donation is a
    buffer-reuse optimization, never a numerics change."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    prefill = make_prefill_fn(cfg)
    decode = make_decode_fn(cfg)

    def run_steps():
        cache = init_cache(cfg, 1, S_MAX, jnp.bfloat16)
        cache, _ = prefill(params, cache,
                           jnp.asarray([[2, 3, 5, 7]], jnp.int32),
                           jnp.ones((4,), jnp.int32), jnp.int32(0))
        outs = []
        for tok in (11, 13, 17):
            cache, logits = decode(params, cache,
                                   jnp.asarray([tok], jnp.int32),
                                   jnp.asarray([True]))
            outs.append(np.asarray(logits))
        return cache, outs

    cache_a, outs_a = run_steps()
    cache_b, outs_b = run_steps()
    for a, b in zip(outs_a, outs_b):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(cache_a, cache_b):
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32))
    assert cache_a.k.dtype == jnp.bfloat16
    assert int(cache_a.lengths[0]) == 7  # 4 prompt + 3 decoded


def test_init_cache_validates():
    cfg = _cfg(False)
    with pytest.raises(ValueError, match="position table"):
        init_cache(cfg, 1, cfg.max_position_embeddings + 1)
    with pytest.raises(ValueError, match="positive"):
        init_cache(cfg, 0, 8)
    c = init_cache(cfg, 2, 16)
    assert isinstance(c, KVCache) and c.k.dtype == jnp.bfloat16
    assert c.k.shape == (cfg.num_layers, 2, cfg.num_heads, 16,
                         cfg.head_dim)


# -- paged cache ------------------------------------------------------------

def _paged_teacher_forced(params, cfg, seq, free_order=None):
    """Paged analogue of :func:`_teacher_forced`: prefill + decode via
    :class:`PagedDecodeEngine` (page_size 8, so the 8-token prompt ends
    exactly at a page boundary only for the default PROMPT — boundary
    allocation and in-page appends both get exercised)."""
    from apex_tpu.serving import PagedDecodeEngine

    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.float32, buckets=(8, 16, 32),
                            free_order=free_order)
    logits = eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    rows = [logits[0]]
    for t in range(PROMPT, seq.shape[1]):
        assert eng.prepare_decode({0: t}) == []
        logits = eng.decode(
            jnp.asarray([int(seq[0, t]), 0], jnp.int32),
            jnp.asarray([True, False]))
        rows.append(logits[0])
    return jnp.stack(rows)


@pytest.mark.parametrize("use_rope", [True, False],
                         ids=["rope", "learned_pos"])
def test_paged_decode_matches_full_forward(use_rope):
    """The serving headline contract holds through the page
    indirection: paged incremental decode == full-sequence forward to
    fp32 tolerance at identical positions."""
    cfg = _cfg(use_rope)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(1), (1, S_TOTAL), 0,
                             cfg.vocab_size)
    want = _full_logits(params, cfg, seq)[0, PROMPT - 1:]
    got = _paged_teacher_forced(params, cfg, seq)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_paged_decode_bit_identical_across_page_placements():
    """Physical page placement is an allocator detail: the same request
    decoded through permuted free-list orders must produce
    BIT-IDENTICAL logits at every step (masked scores are exactly
    zeroed in the softmax, so unmapped/garbage pages contribute exactly
    0.0 — tolerance would hide a real leak)."""
    from apex_tpu.serving.cache import RESERVED_PAGES

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(1), (1, S_TOTAL), 0,
                             cfg.vocab_size)
    usable = list(range(RESERVED_PAGES, 14))
    rng = np.random.RandomState(3)
    orders = [None, list(reversed(usable)),
              list(rng.permutation(usable))]
    runs = [np.asarray(_paged_teacher_forced(params, cfg, seq, order))
            for order in orders]
    for other in runs[1:]:
        np.testing.assert_array_equal(runs[0], other)


def test_paged_dense_logits_agree():
    """Paged and dense decode run the same math over the same rows —
    they must agree to tight fp32 tolerance at every step (not bitwise:
    the attention reductions are differently shaped programs)."""
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = jax.random.randint(jax.random.PRNGKey(1), (1, S_TOTAL), 0,
                             cfg.vocab_size)
    dense = np.asarray(_teacher_forced(params, cfg, seq))
    paged = np.asarray(_paged_teacher_forced(params, cfg, seq))
    np.testing.assert_allclose(paged, dense, rtol=1e-5, atol=1e-5)


# -- speculative verify -----------------------------------------------------

def _seq(cfg, n, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), (1, n), 0,
                              cfg.vocab_size)


@pytest.mark.parametrize("k", [1, 3])
def test_verify_matches_full_forward(k):
    """The k+1-position verify forward is exact: row j equals the full
    forward's logits after reading seq[: PROMPT + j + 1] — the verify
    step is a prefill-shaped continuation, not an approximation."""
    from apex_tpu.serving import make_verify_fn

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 1)
    prefill = make_prefill_fn(cfg)
    verify = make_verify_fn(cfg)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    # column 0 = the pending token, columns 1.. = drafts; slot 1 idle
    # (its rows 0..k take garbage writes the masks never admit)
    tokens = jnp.concatenate(
        [seq[:, PROMPT:], jnp.zeros((1, k + 1), jnp.int32)], axis=0)
    cache, logits = verify(params, cache, tokens)
    want = _full_logits(params, cfg, seq)[0, PROMPT:]
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # lengths are committed by the HOST after the accept walk, never by
    # the verify step itself
    assert int(cache.lengths[0]) == PROMPT


@pytest.mark.parametrize("k", [1, 3])
def test_paged_verify_matches_full_forward(k):
    """Same exactness through the page indirection (page_size 8 with
    PROMPT 8: the verify window starts ON a page boundary, so
    prepare_decode's n_new-row allocation is load-bearing)."""
    from apex_tpu.serving import PagedDecodeEngine

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 1)
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.float32,
                            buckets=(8, 16, 32), spec_k=k)
    eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    assert eng.prepare_decode({0: PROMPT}, n_new=k + 1) == []
    tokens = jnp.concatenate(
        [seq[:, PROMPT:], jnp.zeros((1, k + 1), jnp.int32)], axis=0)
    logits = eng.verify(tokens)
    want = _full_logits(params, cfg, seq)[0, PROMPT:]
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_verify_rejected_rows_not_observable(paged):
    """The rollback contract, bitwise: two runs whose first verify step
    carried DIFFERENT garbage draft tails (all rejected — only the
    pending token commits) must produce a bit-identical next verify
    step AND a bit-identical next plain-decode step. Rejected rows are
    written, but every later mask either re-writes them first (verify:
    the new window covers the stale range) or never admits them (plain:
    scores masked at fp32 -inf before softmax) — tolerance here would
    hide a real leak."""
    k = 3
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 2)

    def run(garbage):
        if paged:
            from apex_tpu.serving import PagedDecodeEngine
            eng = PagedDecodeEngine(params, cfg, num_slots=1,
                                    max_len=S_MAX, num_pages=14,
                                    page_size=8, cache_dtype=jnp.float32,
                                    buckets=(8, 16, 32), spec_k=k)
            eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
            eng.prepare_decode({0: PROMPT}, n_new=k + 1)
            bad = jnp.concatenate(
                [seq[:, PROMPT:PROMPT + 1],
                 jnp.full((1, k), garbage, jnp.int32)], axis=1)
            eng.verify(bad)
            eng.commit([1])  # accept only the pending token
            eng.prepare_decode({0: PROMPT + 1}, n_new=k + 1)
            l_verify = eng.verify(seq[:, PROMPT + 1:PROMPT + k + 2])
            eng.commit([1])
            eng.prepare_decode({0: PROMPT + 2})
            l_plain = eng.decode(seq[:, PROMPT + 2],
                                 jnp.asarray([True]))
            return np.asarray(l_verify), np.asarray(l_plain)
        from apex_tpu.serving import make_verify_fn
        prefill = make_prefill_fn(cfg)
        verify = make_verify_fn(cfg)
        decode = make_decode_fn(cfg)
        cache = init_cache(cfg, 1, S_MAX, jnp.float32)
        cache, _ = prefill(params, cache, seq[:, :PROMPT],
                           jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
        bad = jnp.concatenate(
            [seq[:, PROMPT:PROMPT + 1],
             jnp.full((1, k), garbage, jnp.int32)], axis=1)
        cache, _ = verify(params, cache, bad)
        cache = cache._replace(lengths=cache.lengths + 1)
        cache, l_verify = verify(params, cache,
                                 seq[:, PROMPT + 1:PROMPT + k + 2])
        cache = cache._replace(lengths=cache.lengths + 1)
        cache, l_plain = decode(params, cache, seq[:, PROMPT + 2],
                                jnp.asarray([True]))
        return np.asarray(l_verify), np.asarray(l_plain)

    va, pa = run(3)
    vb, pb = run(499)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(pa, pb)


def test_verify_agrees_with_plain_decode_steps():
    """Feeding the verify window one token at a time through plain
    decode must land on the same logits to tight fp32 tolerance (not
    bitwise: the two are differently shaped reductions — the stream
    bit-identity contract lives at the sampled-token level, see
    test_scheduler.py)."""
    from apex_tpu.serving import make_verify_fn

    k = 3
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 1)
    plain = np.asarray(_teacher_forced(params, cfg, seq))[1:]

    prefill = make_prefill_fn(cfg)
    verify = make_verify_fn(cfg)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    tokens = jnp.concatenate(
        [seq[:, PROMPT:], jnp.zeros((1, k + 1), jnp.int32)], axis=0)
    _, logits = verify(params, cache, tokens)
    np.testing.assert_allclose(np.asarray(logits[0]), plain,
                               rtol=1e-5, atol=1e-5)


def test_tp_verify_matches_unsharded():
    """tp=2 speculative verify (dense + paged): logits match the
    unsharded verify step to fp32 tolerance and the greedy accept walk
    commits the identical token prefix — the TP mesh composes with
    speculation unchanged."""
    from apex_tpu.models.gpt import GPTModel
    from apex_tpu.serving import (
        PagedDecodeEngine, make_tp_paged_verify_fn, make_tp_verify_fn,
        make_verify_fn,
    )
    from apex_tpu.transformer import parallel_state as ps

    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    k = 2
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 1)
    tokens = jnp.concatenate(
        [seq[:, PROMPT:], jnp.zeros((1, k + 1), jnp.int32)], axis=0)
    ps.initialize_model_parallel(tensor_model_parallel_size_=2)
    model = GPTModel(cfg, tp_size=2)

    # dense: one prefilled cache, cloned through both verify paths
    prefill = make_prefill_fn(cfg)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    clone = jax.tree.map(jnp.copy, cache)
    _, want = make_verify_fn(cfg)(params, cache, tokens)
    _, got = make_tp_verify_fn(model)(params, clone, tokens)
    np.testing.assert_allclose(np.asarray(got[0]),
                               np.asarray(want[0]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(jnp.argmax(got[0], -1)),
                                  np.asarray(jnp.argmax(want[0], -1)))

    # paged: engine-built cache (block tables + pool), same contract
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.float32,
                            buckets=(8, 16, 32), spec_k=k)
    eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    eng.prepare_decode({0: PROMPT}, n_new=k + 1)
    clone = jax.tree.map(jnp.copy, eng.cache)
    want = eng.verify(tokens)
    _, got = make_tp_paged_verify_fn(model)(params, clone, tokens)
    np.testing.assert_allclose(np.asarray(got[0]),
                               np.asarray(want[0]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(jnp.argmax(got[0], -1)),
                                  np.asarray(jnp.argmax(want[0], -1)))


# -- tree verify ------------------------------------------------------------
#
# One forward over a DRAFT TREE: grid node j writes its K/V at physical
# row pos + j but attends at logical position pos + depth[j], seeing
# committed history plus exactly its ancestor set (anc[:, j]). A linear
# chain is the k1-wide special case and must reproduce the existing
# verify step bit-for-bit; branch nodes must each match the full
# forward over their OWN root-to-leaf path.

def _chain_tree(k1):
    """depth = arange, anc[src, q] = src <= q: the linear chain
    (``anc[i, j]`` means column i visible to QUERY column j, so the
    chain is upper-triangular in (src, query) order)."""
    depth = jnp.arange(k1, dtype=jnp.int32)[None, :]
    anc = jnp.triu(jnp.ones((k1, k1), bool))[None]
    return depth, anc


def test_tree_verify_linear_chain_bit_identical_to_verify():
    """With a chain ancestor matrix the tree verify IS the linear
    verify — same program shape, same writes, bit-identical logits
    and cache. Tolerance would hide a mask bug."""
    from apex_tpu.serving import make_tree_verify_fn, make_verify_fn

    k = 3
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + k + 1)
    prefill = make_prefill_fn(cfg)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    clone = jax.tree.map(jnp.copy, cache)
    tokens = jnp.concatenate(
        [seq[:, PROMPT:], jnp.zeros((1, k + 1), jnp.int32)], axis=0)
    cache_a, want = make_verify_fn(cfg)(params, cache, tokens)
    depth, anc = _chain_tree(k + 1)
    depth = jnp.broadcast_to(depth, (2, k + 1))
    anc = jnp.broadcast_to(anc, (2, k + 1, k + 1))
    cache_b, got = make_tree_verify_fn(cfg)(params, clone, tokens,
                                            depth, anc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for a, b in zip(cache_a, cache_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tree_verify_branches_match_full_forward():
    """A two-branch tree: root R with children A and B, A with child C.
    Each node's logits row must equal the full forward over prompt +
    its OWN ancestor path — sibling branches never contaminate each
    other even though their K/V rows coexist in the window."""
    from apex_tpu.serving import make_tree_verify_fn, tree_arrays

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + 1)
    prefill = make_prefill_fn(cfg)
    cache = init_cache(cfg, 1, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    root = int(seq[0, PROMPT])
    a_tok, b_tok, c_tok = 101, 202, 303
    toks, depth, anc, valid, parents, start = tree_arrays(
        [[root]], [([a_tok, b_tok, c_tok], [-1, -1, 0])], k1=4)
    assert list(parents[0]) == [-1, 0, 0, 1]
    _, logits = make_tree_verify_fn(cfg)(
        params, cache, jnp.asarray(toks), jnp.asarray(depth),
        jnp.asarray(anc))
    logits = np.asarray(logits[0])
    # column j of the grid == last row of the full forward over the
    # prompt + j's root-to-node path
    paths = {0: [root], 1: [root, a_tok], 2: [root, b_tok],
             3: [root, a_tok, c_tok]}
    for col, path in paths.items():
        full = jnp.concatenate(
            [seq[:, :PROMPT], jnp.asarray([path], jnp.int32)], axis=1)
        want = np.asarray(_full_logits(params, cfg, full)[0, -1])
        np.testing.assert_allclose(logits[col], want,
                                   rtol=1e-4, atol=1e-4)
    # and the sibling branches really did diverge
    assert (np.argmax(logits[1]) != np.argmax(logits[2])
            or not np.allclose(logits[1], logits[2]))


def test_paged_tree_verify_matches_dense():
    """The tree mask composes with the page indirection: paged tree
    verify agrees with the dense tree verify to tight fp32 tolerance
    (differently shaped reductions — argmax must agree exactly)."""
    from apex_tpu.serving import (
        PagedDecodeEngine, make_tree_verify_fn,
    )

    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + 1)
    root = int(seq[0, PROMPT])
    from apex_tpu.serving import tree_arrays
    toks, depth, anc, _, _, _ = tree_arrays(
        [[root]], [([101, 202, 303], [-1, -1, 0])], k1=4)

    prefill = make_prefill_fn(cfg)
    cache = init_cache(cfg, 1, S_MAX, jnp.float32)
    cache, _ = prefill(params, cache, seq[:, :PROMPT],
                       jnp.ones((PROMPT,), jnp.int32), jnp.int32(0))
    _, want = make_tree_verify_fn(cfg)(
        params, cache, jnp.asarray(toks), jnp.asarray(depth),
        jnp.asarray(anc))

    eng = PagedDecodeEngine(params, cfg, num_slots=1, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.float32, buckets=(8, 16, 32),
                            spec_k=3, tree_spec=True)
    eng.prefill(0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    eng.prepare_decode({0: PROMPT}, n_new=4)
    got = eng.tree_verify(jnp.asarray(toks), jnp.asarray(depth),
                          jnp.asarray(anc))
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(got[0], -1)),
        np.asarray(jnp.argmax(want[0], -1)))


def test_tp_tree_verify_matches_unsharded():
    """tp=2 tree verify (dense + paged): the tree descriptors are
    replicated host decisions, heads shard over ``model`` — logits
    match the unsharded tree verify to fp32 tolerance with exact
    argmax agreement, mirroring test_tp_verify_matches_unsharded."""
    from apex_tpu.models.gpt import GPTModel
    from apex_tpu.serving import (
        PagedDecodeEngine, make_tp_paged_tree_verify_fn,
        make_tp_tree_verify_fn, make_tree_verify_fn, tree_arrays,
    )
    from apex_tpu.transformer import parallel_state as ps

    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = _cfg(True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    seq = _seq(cfg, PROMPT + 1)
    root = int(seq[0, PROMPT])
    toks, depth, anc, _, _, _ = tree_arrays(
        [[root], [root]], [([101, 202, 303], [-1, -1, 0]),
                           ([11, 22, 33], [-1, 0, 1])], k1=4)
    toks, depth, anc = (jnp.asarray(toks), jnp.asarray(depth),
                        jnp.asarray(anc))
    ps.initialize_model_parallel(tensor_model_parallel_size_=2)
    model = GPTModel(cfg, tp_size=2)

    prefill = make_prefill_fn(cfg)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    for slot in (0, 1):
        cache, _ = prefill(params, cache, seq[:, :PROMPT],
                           jnp.ones((PROMPT,), jnp.int32),
                           jnp.int32(slot))
    clone = jax.tree.map(jnp.copy, cache)
    _, want = make_tree_verify_fn(cfg)(params, cache, toks, depth, anc)
    _, got = make_tp_tree_verify_fn(model)(params, clone, toks, depth,
                                           anc)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(jnp.argmax(got, -1)),
                                  np.asarray(jnp.argmax(want, -1)))

    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=S_MAX,
                            num_pages=14, page_size=8,
                            cache_dtype=jnp.float32, buckets=(8, 16, 32),
                            spec_k=3, tree_spec=True)
    for slot in (0, 1):
        eng.prefill(slot, [int(t) for t in np.asarray(seq[0, :PROMPT])])
    eng.prepare_decode({0: PROMPT, 1: PROMPT}, n_new=4)
    clone = jax.tree.map(jnp.copy, eng.cache)
    want = eng.tree_verify(toks, depth, anc)
    _, got = make_tp_paged_tree_verify_fn(model)(params, clone, toks,
                                                 depth, anc)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(jnp.argmax(got, -1)),
                                  np.asarray(jnp.argmax(want, -1)))


def test_init_paged_cache_validates():
    from apex_tpu.serving import init_paged_cache
    from apex_tpu.serving.cache import (
        PagedKVCache, RESERVED_PAGES, SCRATCH_PAGE,
    )

    cfg = _cfg(False)
    with pytest.raises(ValueError, match="position table"):
        init_paged_cache(cfg, 1, cfg.max_position_embeddings + 1, 6, 16)
    with pytest.raises(ValueError, match="positive"):
        init_paged_cache(cfg, 0, 8, 6, 4)
    with pytest.raises(ValueError, match="reserved"):
        init_paged_cache(cfg, 1, 8, RESERVED_PAGES, 4)
    c = init_paged_cache(cfg, 2, 16, 6, 4)
    assert isinstance(c, PagedKVCache) and c.k.dtype == jnp.bfloat16
    assert c.k.shape == (cfg.num_layers, 6, cfg.num_heads, 4,
                         cfg.head_dim)
    assert c.block_tables.shape == (2, 4)  # ceil(16 / 4) per slot
    assert int(c.block_tables.min()) == SCRATCH_PAGE  # parked on scratch
    assert int(c.block_tables.max()) == SCRATCH_PAGE

"""Driver-contract tests for ``__graft_entry__``.

Round-1 postmortem (VERDICT.md "What's weak" #1): the driver imports the
module and calls ``dryrun_multichip(8)`` directly — it does NOT run the
``__main__`` block — and in r01 that path failed because the n-device CPU
world was only configured under ``__main__``. These tests exercise the
function exactly the way the driver does, in-process and in a fresh
interpreter with a pre-initialized too-small backend.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_dryrun_multichip_in_process():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)
    finally:
        sys.path.remove(REPO)


def test_entry_returns_jittable():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        import jax
        fn, example_args = __graft_entry__.entry()
        out = jax.jit(fn).lower(*example_args)  # compile-check only
        assert out is not None
    finally:
        sys.path.remove(REPO)


def test_dryrun_multichip_resets_small_world():
    """Simulate the exact r01 failure: JAX already initialized with ONE
    device when ``dryrun_multichip(8)`` is called. The function must tear
    down and rebuild an 8-device world itself."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "import __graft_entry__\n"
        # phases=1: only the world-reset contract is under test here;
        # the in-process test runs every phase
        "__graft_entry__.dryrun_multichip(8, phases=1)\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "OK" in proc.stdout, proc.stdout

"""Driver-contract smoke for bench.py's PARENT mode — the orchestration
layer (config ORDER, per-config subprocesses, budget handling, headline
re-emission) that otherwise only runs on the live TPU at round end.
BENCH_r04's rc=124 was an orchestration failure, not a kernel failure;
this pins the wiring on the CPU rig."""

import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                    "..", "..", ".."))


def test_parent_runs_headline_first_and_reemits_it_last():
    env = dict(os.environ,
               APEX_TPU_TEST_PLATFORM="cpu",   # JAX_PLATFORMS is latched
               BENCH_ONLY="headline,layer_norm",
               BENCH_BUDGET_S="300")
    # test timeout exceeds the parent's budget + caps so a hung child
    # surfaces as the parent's own cap/skip lines, not TimeoutExpired
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=450, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    metrics = [d.get("metric") for d in lines]
    # the headline config emits its per-(batch, state-mode) sweep lines
    # first, then the contract metric — so the first NON-sweep metric is
    # the headline; measured values present, no error lines
    main = [m for m in metrics if not m.startswith("headline_")]
    assert main[0] == "bert_tiny_cpu_smoke", metrics
    assert "fused_layer_norm_fwdbwd_h1024" in metrics, metrics
    assert not any("error" in d for d in lines), lines
    # both optimizer-state modes raced every round (the dead-end
    # evidence trail BASELINE.md r7 relies on), winner in the contract
    assert any(m.endswith("_fp32") for m in metrics), metrics
    assert any(m.endswith("_bf16m_castout") for m in metrics), metrics
    head = [d for d in lines if d["metric"] == "bert_tiny_cpu_smoke"]
    assert head[0]["state_mode"] in ("fp32", "bf16m_castout"), head
    # the contract metric is re-emitted LAST (parse-the-tail convention)
    assert metrics[-1] == "bert_tiny_cpu_smoke", metrics
    assert len(head) == 2
    assert lines[-1]["value"] > 0


def test_ab_mode_contract():
    """`bench.py ab <pair>` — the same-process A/B instrument's output
    contract (ratio + band + absolute medians), pinned on the cheapest
    pair so the driver-side ab_kernels config can be trusted blind."""
    env = dict(os.environ,
               APEX_TPU_TEST_PLATFORM="cpu",
               APEX_TPU_TEST_NUM_DEVICES="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "ab",
         "ln_h1024"],
        capture_output=True, text=True, timeout=450, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    assert [d["metric"] for d in lines] == ["ab_ln_h1024"], lines
    d = lines[0]
    assert not d.get("error"), d
    lo, hi = d["band"]
    assert lo <= d["value"] <= hi, d
    assert d["a_us"] > 0 and d["b_us"] > 0
    assert d["a_wins"] == (d["value"] < 1.0)
    # unknown pair names error-line instead of dying
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "ab", "nope"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert r2.returncode == 0
    assert "unknown ab pair" in r2.stdout

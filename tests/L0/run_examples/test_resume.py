"""Checkpoint/resume acceptance (ref: ``examples/imagenet/main_amp.py``
``--resume`` reproducing the loss curve after a restart)."""

import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                    "..", "..", ".."))
SCRIPT = os.path.join(REPO, "examples", "imagenet", "main_amp.py")

ARGS = ["-a", "resnet10", "--image-size", "32", "--num-classes", "10",
        "-b", "8", "--print-freq", "1", "--opt-level", "O2"]


def run(args, env_extra=None):
    # JAX_PLATFORMS in the env is LATCHED AWAY by sitecustomize on this
    # host (the subprocesses were silently running on the real TPU
    # through the relay — 157 s of suite wall); APEX_TPU_TEST_PLATFORM
    # goes through jax.config inside the example instead.
    env = dict(os.environ, APEX_TPU_TEST_PLATFORM="cpu")
    env.update(env_extra or {})
    r = subprocess.run([sys.executable, SCRIPT] + ARGS + args,
                       capture_output=True, text=True, timeout=1200,
                       env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return {int(m.group(1)): m.group(2) for m in re.finditer(
        r"step\s+(\d+)\s+loss (\d+\.\d+)", r.stdout)}


def test_kill_and_resume_reproduces_loss_curve(tmp_path):
    ck_a = str(tmp_path / "a.ckpt")
    ck_b = str(tmp_path / "b.ckpt")

    straight = run(["--steps", "6", "--checkpoint", ck_a])
    # "killed" run: stops after 3 steps, saved at step 2
    run(["--steps", "3", "--checkpoint", ck_b])
    resumed = run(["--steps", "6", "--checkpoint", ck_b,
                   "--resume", ck_b])

    assert set(resumed) == {3, 4, 5}  # continued where it left off
    for s in (3, 4, 5):
        # bitwise-printed parity: deterministic synthetic data + exactly
        # restored (params, bn stats, optimizer, scaler) state
        assert resumed[s] == straight[s], (s, resumed[s], straight[s])


def test_checkpoint_atomicity(tmp_path):
    from apex_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    path = str(tmp_path / "t.ckpt")
    tree = {"a": jnp.arange(5, dtype=jnp.bfloat16),
            "b": [jnp.float32(1.5), np.int32(7)]}
    save_checkpoint(path, tree)
    out = load_checkpoint(path)
    assert out["a"].dtype == jnp.bfloat16  # ml_dtypes round-trips
    np.testing.assert_array_equal(out["a"],
                                  np.arange(5, dtype=jnp.bfloat16))
    # overwrite must go through rename (no partial file even on reload)
    save_checkpoint(path, {"a": jnp.zeros((3,))})
    out = load_checkpoint(path)
    np.testing.assert_array_equal(out["a"], np.zeros((3,)))
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []

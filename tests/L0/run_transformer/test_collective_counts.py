"""Collective-count contracts, asserted on compiled HLO.

Numerics tests cannot catch an accidentally-inserted extra allreduce —
an extra collective is numerically invisible and only shows up as lost
step time on hardware. These tests compile representative TP / SP / CE /
PP programs on the CPU mesh and count the collective ops in the
optimized HLO against the Megatron comm contract (SURVEY §2a mappings —
"the hottest comm in the stack"):

- TP MLP block (Column gather_output=False -> gelu -> Row
  input_is_parallel): ONE activation allreduce forward (end of Row), ONE
  more in backward (transpose of copy_to at the Column input), plus a
  bias-sized replicated-cotangent psum. Ref: ``mappings.py ::
  _CopyToModelParallelRegion/_ReduceFrom...``.
- SP MLP block: all-gather on seq entering Column, reduce-scatter
  leaving Row — mirrored in backward; the Column wgrad reuses the saved
  gathered input (no third AG). No activation allreduce. Ref:
  ``mappings.py`` sequence-parallel regions.
- vocab-parallel CE: three semantic psums forward (max, sum-exp, target
  logit; XLA combines the two sums -> 2 ops), ZERO new in backward
  (shard-local softmax-minus-onehot). Ref: ``cross_entropy.py ::
  _VocabParallelCrossEntropy``.
- collective 1F1B: exactly TWO collective-permutes per tick (activations
  +1, cotangents -1) — the scan body appears once in HLO. Ref:
  ``p2p_communication.py :: _communicate``.
"""

import re

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer import tensor_parallel as tp

TP = 8
M = P(ps.TENSOR_AXIS)

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute")


_SINGLETON = re.compile(r"replica_groups=\{\{\d+\},")


def _counts(fn, *args):
    """Count communicating collective ops in optimized HLO. Excludes
    degenerate singleton-replica-group ops (XLA artifacts that move no
    bytes). NOTE: XLA's combiner may merge same-kind reductions into one
    op with multiple operands — counts are ops, i.e. launches, which is
    the structure that costs latency."""
    text = jax.jit(fn).lower(*args).compile().as_text()
    out = dict.fromkeys(_COLLECTIVES, 0)
    for line in text.splitlines():
        for c in _COLLECTIVES:
            if f" {c}(" in line or f" {c}-start(" in line:
                if not _SINGLETON.search(line):
                    out[c] += 1
    return out


def _mlp_block(sequence_parallel):
    col = tp.ColumnParallelLinear(
        16, 32, gather_output=False,
        sequence_parallel_enabled=sequence_parallel)
    row = tp.RowParallelLinear(
        32, 16, input_is_parallel=True,
        sequence_parallel_enabled=sequence_parallel)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))

    def block(cp, rp, x):
        return row.apply(rp, jax.nn.gelu(col.apply(cp, x)))

    return block, col, row, cp, rp


def test_tp_mlp_forward_one_allreduce():
    ps.initialize_model_parallel(tensor_model_parallel_size_=TP)
    block, col, row, cp, rp = _mlp_block(False)
    x = jnp.ones((4, 16))
    fwd = ps.shard_map(block,
                       in_specs=(col.partition_specs(),
                                 row.partition_specs(), P()),
                       out_specs=P())
    c = _counts(fwd, cp, rp, x)
    assert c["all-reduce"] == 1, c
    assert c["all-gather"] == 0 and c["reduce-scatter"] == 0, c


def test_tp_mlp_backward_adds_exactly_one_allreduce():
    ps.initialize_model_parallel(tensor_model_parallel_size_=TP)
    block, col, row, cp, rp = _mlp_block(False)
    x = jnp.ones((4, 16))

    def loss(cp, rp, x):
        y = ps.shard_map(block,
                         in_specs=(col.partition_specs(),
                                   row.partition_specs(), P()),
                         out_specs=P())(cp, rp, x)
        return jnp.sum(y ** 2)

    # grad program = fwd (1 AR) + bwd dx psum (1 AR, the copy_to
    # transpose) + the Row bias cotangent psum (bias-sized — shard_map's
    # transpose rule for a replicated input; Megatron computes that grad
    # rank-locally, but 16 floats of AR is noise next to the activation
    # AR, so the structure is pinned rather than fought). Older XLA
    # doesn't combine two of the same-kind sums -> 4 launches there.
    c = _counts(jax.grad(loss, argnums=(0, 1, 2)), cp, rp, x)
    assert c["all-reduce"] in (3, 4), c
    assert c["all-gather"] == 0 and c["reduce-scatter"] == 0, c


def test_sp_mlp_forward_ag_rs_no_allreduce():
    ps.initialize_model_parallel(tensor_model_parallel_size_=TP)
    block, col, row, cp, rp = _mlp_block(True)
    x = jnp.ones((16, 2, 16))
    fwd = ps.shard_map(block,
                       in_specs=(col.partition_specs(),
                                 row.partition_specs(), M),
                       out_specs=M)
    c = _counts(fwd, cp, rp, x)
    assert c["all-gather"] == 1 and c["reduce-scatter"] == 1, c
    assert c["all-reduce"] == 0, c


def test_sp_mlp_backward_mirrors_ag_rs():
    ps.initialize_model_parallel(tensor_model_parallel_size_=TP)
    block, col, row, cp, rp = _mlp_block(True)
    x = jnp.ones((16, 2, 16))

    def loss(cp, rp, x):
        y = ps.shard_map(block,
                         in_specs=(col.partition_specs(),
                                   row.partition_specs(), M),
                         out_specs=M)(cp, rp, x)
        return jnp.sum(y ** 2)

    c = _counts(jax.grad(loss, argnums=(0, 1, 2)), cp, rp, x)
    # fwd AG + RS, bwd RS-transpose=AG(cotangent) + AG-transpose=RS;
    # the Column wgrad reuses the SAVED gathered input (no third AG —
    # the memory-for-comm trade Megatron's sequence_parallel also
    # defaults to). The one AR is the bias-sized replicated-cotangent
    # psum (see the TP backward test).
    assert c["all-reduce"] == 1, c
    assert c["all-gather"] == 2 and c["reduce-scatter"] == 2, c


def test_vocab_parallel_ce_fwd_allreduces_zero_bwd():
    ps.initialize_model_parallel(tensor_model_parallel_size_=TP)
    V, B = 64, 4
    logits = jnp.ones((B, V), jnp.float32)
    target = jnp.zeros((B,), jnp.int32)

    def fwd(lg, tg):
        return ps.shard_map(
            tp.vocab_parallel_cross_entropy,
            in_specs=(P(None, ps.TENSOR_AXIS), P()),
            out_specs=P())(lg, tg)

    # three semantic psums (max, sum-exp, target logit); newer XLA's
    # combiner merges the two same-kind sums into one op -> 2 launches,
    # older XLA leaves all 3
    c = _counts(fwd, logits, target)
    assert c["all-reduce"] in (2, 3), c

    def loss(lg):
        return jnp.sum(fwd(lg, target))

    cg = _counts(jax.grad(loss), logits)
    # backward is shard-local: no NEW collectives beyond the forward's
    # (the larger grad program can give the combiner MORE merge
    # opportunities, so <= rather than ==)
    assert cg["all-reduce"] <= c["all-reduce"], (c, cg)
    assert cg["all-reduce"] in (2, 3), cg


def test_1f1b_two_collective_permutes_per_tick():
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "_pp_rig", os.path.join(os.path.dirname(__file__),
                                "test_pipeline_parallel.py"))
    rig = ilu.module_from_spec(spec)
    spec.loader.exec_module(rig)
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fb,
    )

    pp, n_mb = 4, 8
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])
    params = rig._init(jax.random.PRNGKey(0), pp)
    batch = rig._batch(jax.random.PRNGKey(1), 2 * n_mb)
    fn = ps.shard_map(
        lambda p, b: fb(rig.MODEL, p, b, num_microbatches=n_mb),
        in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS), "head": P()},
                  P()),
        out_specs=(P(), {"embed": P(), "stages": P(ps.PIPE_AXIS),
                         "head": P()}),
    )
    c = _counts(fn, params, batch)
    assert c["collective-permute"] == 2, c


def test_ring_attention_rotates_only():
    """Context-parallel ring attention: K/V rotate via exactly two
    collective-permutes (the scan body appears once in HLO) and NOTHING
    is ever gathered — no rank holds the full sequence. Backward adds
    only the mirrored rotation. Ref: SURVEY §2c ring-attention row
    (beyond-reference capability)."""
    ps.initialize_model_parallel(context_parallel_size_=TP)
    from apex_tpu.transformer.context_parallel import ring_attention

    b, h, s, d = 2, 2, 8, 8  # s is GLOBAL: one token per rank at cp=8
    # (only the collective structure is pinned here; multi-token ring
    # blocks are covered by the CP parity tests in run_models/test_gpt)
    q = jnp.ones((b, h, s, d), jnp.float32)
    spec = P(None, None, ps.CONTEXT_AXIS)

    fwd = ps.shard_map(
        lambda q: ring_attention(q, q, q, causal=True),
        in_specs=spec, out_specs=spec)
    c = _counts(fwd, q)
    assert c["collective-permute"] == 2, c
    assert c["all-gather"] == 0 and c["all-reduce"] == 0, c

    cg = _counts(jax.grad(lambda q: jnp.sum(fwd(q) ** 2)), q)
    assert cg["collective-permute"] == 4, cg
    assert cg["all-gather"] == 0 and cg["all-reduce"] == 0, cg

"""Fused softmax kernels vs jnp references (ref:
``tests/L0/run_transformer/test_fused_softmax.py``-style golden tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import (
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)

MASK_VAL = -10000.0


def ref_masked(x, mask, scale):
    z = x.astype(jnp.float32) * scale
    z = jnp.where(mask != 0, MASK_VAL, z)
    return jax.nn.softmax(z, axis=-1).astype(x.dtype)


def ref_causal(x, scale):
    z = x.astype(jnp.float32) * scale
    sq, sk = z.shape[-2:]
    causal = jnp.tril(jnp.ones((sq, sk), bool))
    z = jnp.where(causal, z, MASK_VAL)
    return jax.nn.softmax(z, axis=-1).astype(x.dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape", [(2, 4, 32, 32), (1, 2, 17, 40)])
def test_scaled_masked_softmax_fwd(dtype, shape):
    b, np_, sq, sk = shape
    x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype) * 2
    mask = jax.random.bernoulli(
        jax.random.PRNGKey(1), 0.3, (b, 1, sq, sk)).astype(jnp.int32)
    got = scaled_masked_softmax(x, mask, 0.5)
    want = ref_masked(x, jnp.broadcast_to(mask, shape), 0.5)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


def test_scaled_masked_softmax_grads():
    shape = (2, 2, 16, 24)
    x = jax.random.normal(jax.random.PRNGKey(0), shape) * 2
    mask = jax.random.bernoulli(
        jax.random.PRNGKey(1), 0.2, (2, 1, 16, 24)).astype(jnp.int32)
    dy = jax.random.normal(jax.random.PRNGKey(2), shape)

    g = jax.grad(lambda x: jnp.sum(scaled_masked_softmax(x, mask, 0.7) * dy))(x)
    r = jax.grad(lambda x: jnp.sum(
        ref_masked(x, jnp.broadcast_to(mask, shape), 0.7) * dy))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_scaled_upper_triang_softmax_fwd_bwd(dtype):
    shape = (4, 24, 24)
    x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype) * 2
    got = scaled_upper_triang_masked_softmax(x, 1.3)
    want = ref_causal(x, 1.3)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)
    # strictly causal: everything above the diagonal ~ 0
    assert float(jnp.max(jnp.triu(got.astype(jnp.float32), k=1))) < 1e-4

    if dtype == jnp.float32:
        dy = jax.random.normal(jax.random.PRNGKey(1), shape)
        g = jax.grad(lambda x: jnp.sum(
            scaled_upper_triang_masked_softmax(x, 1.3) * dy))(x)
        r = jax.grad(lambda x: jnp.sum(ref_causal(x, 1.3) * dy))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_causal_4d_input():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 16, 16))
    got = scaled_upper_triang_masked_softmax(x, 1.0)
    want = ref_causal(x, 1.0)
    assert got.shape == x.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_dispatcher_fused_vs_fallback():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 16, 16),
                          jnp.bfloat16)
    mask = jax.random.bernoulli(
        jax.random.PRNGKey(1), 0.2, (2, 1, 16, 16)).astype(jnp.int32)

    fused = FusedScaleMaskSoftmax(input_in_bf16=True, scale=0.5,
                                  scaled_masked_softmax_fusion=True)
    fallback = FusedScaleMaskSoftmax(input_in_bf16=True, scale=0.5,
                                     scaled_masked_softmax_fusion=False)
    np.testing.assert_allclose(
        np.asarray(fused(x, mask), np.float32),
        np.asarray(fallback(x, mask), np.float32), rtol=2e-2, atol=2e-2)

    causal_f = FusedScaleMaskSoftmax(input_in_bf16=True,
                                     attn_mask_type=AttnMaskType.causal)
    causal_n = FusedScaleMaskSoftmax(input_in_bf16=True,
                                     attn_mask_type=AttnMaskType.causal,
                                     scaled_masked_softmax_fusion=False)
    np.testing.assert_allclose(
        np.asarray(causal_f(x), np.float32),
        np.asarray(causal_n(x), np.float32), rtol=2e-2, atol=2e-2)


def test_dispatcher_validation():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(scale=2.0, softmax_in_fp32=False)

"""Pipeline schedule live-memory bound (SURVEY §2a: 1F1B exists to bound
live activations at O(pp) microbatches; ref ``deallocate_output_tensor``
discipline).

The collective 1F1B writes its backward into the tick with ``jax.vjp``
and keeps stage inputs in a depth-``2pp-1`` ring, so per-stage live
activation memory must be **O(pp x microbatch), independent of the
number of microbatches M**. The CPU backend reports no buffer-assignment
stats (``memory_analysis().temp_size_in_bytes`` is 0), so the bound is
asserted on the optimized HLO: the largest *floating-point* buffer in
the compiled module must not grow with M at fixed microbatch size.
(The integer token batch is the program input and legitimately scales
with M; activations are floating point, so restricting to fp dtypes
isolates them.)
"""

import re

import jax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
)

import importlib.util as _ilu
import os as _os

_spec = _ilu.spec_from_file_location(
    "_pp_rig", _os.path.join(_os.path.dirname(__file__),
                             "test_pipeline_parallel.py"))
_rig = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_rig)
MODEL, _batch, _init = _rig.MODEL, _rig._batch, _rig._init

_FP_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1}
_SHAPE_RE = re.compile(r"\b(f64|f32|bf16|f16)\[([0-9,]*)\]")


def _max_fp_buffer_bytes(hlo_text: str) -> int:
    best = 0
    for dtype, dims in _SHAPE_RE.findall(hlo_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n * _FP_BYTES[dtype])
    return best


def _compiled_hlo(pp: int, n_mb: int, mb_size: int = 2) -> str:
    params = _init(jax.random.PRNGKey(0), pp)
    batch = _batch(jax.random.PRNGKey(1), mb_size * n_mb)
    fn = jax.jit(ps.shard_map(
        lambda p, b: forward_backward_pipelining_without_interleaving(
            MODEL, p, b, num_microbatches=n_mb),
        in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS), "head": P()},
                  P()),
        out_specs=(P(), {"embed": P(), "stages": P(ps.PIPE_AXIS),
                         "head": P()}),
    ))
    return fn.lower(params, batch).compile().as_text()


@pytest.mark.parametrize("pp", [2, 4])
def test_live_activation_memory_flat_in_num_microbatches(pp):
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])
    small = _max_fp_buffer_bytes(_compiled_hlo(pp, n_mb=4))
    big = _max_fp_buffer_bytes(_compiled_hlo(pp, n_mb=16))
    # 4x the microbatches must not grow any activation buffer: the ring
    # (2pp-1 stage inputs) and the grad accumulators bound live memory.
    assert big <= small, (small, big)


def test_forward_only_memory_flat_in_num_microbatches():
    pp = 2
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])

    def hlo(n_mb):
        params = _init(jax.random.PRNGKey(0), pp)
        batch = _batch(jax.random.PRNGKey(1), 2 * n_mb)
        fn = jax.jit(ps.shard_map(
            lambda p, b: forward_backward_pipelining_without_interleaving(
                MODEL, p, b, num_microbatches=n_mb, forward_only=True)[0],
            in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS),
                       "head": P()}, P()),
            out_specs=P(),
        ))
        return fn.lower(params, batch).compile().as_text()

    assert _max_fp_buffer_bytes(hlo(16)) <= _max_fp_buffer_bytes(hlo(4))


def test_interleaved_memory_flat_in_num_microbatches():
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving,
    )

    pp, vpp = 2, 2
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        virtual_pipeline_model_parallel_size_=vpp,
        devices=jax.devices()[:pp])

    def hlo(n_mb):
        params = _init(jax.random.PRNGKey(0), pp * vpp)
        params = dict(params)
        params["stages"] = jax.tree.map(
            lambda a: a.reshape((vpp, pp) + a.shape[1:]), params["stages"])
        batch = _batch(jax.random.PRNGKey(1), 2 * n_mb)
        fn = jax.jit(ps.shard_map(
            lambda p, b: forward_backward_pipelining_with_interleaving(
                MODEL, p, b, num_microbatches=n_mb),
            in_specs=({"embed": P(), "stages": P(None, ps.PIPE_AXIS),
                       "head": P()}, P()),
            out_specs=(P(), {"embed": P(), "stages": P(None, ps.PIPE_AXIS),
                             "head": P()}),
        ))
        return fn.lower(params, batch).compile().as_text()

    assert _max_fp_buffer_bytes(hlo(16)) <= _max_fp_buffer_bytes(hlo(4))

"""Variable-seqlen bucketing (the static-shape answer to the
reference's ``variable_seq_lengths`` p2p handshake — SURVEY §2a
``p2p_communication.py :: _communicate``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.utils.seqlen import bucket_for, default_buckets, pad_to_bucket


def test_default_buckets_ladder():
    assert default_buckets(1000) == (128, 256, 512, 1024)
    assert default_buckets(128) == (128,)
    assert default_buckets(129) == (128, 256)


def test_bucket_for_and_overflow():
    bs = (128, 256, 512)
    assert bucket_for(1, bs) == 128
    assert bucket_for(256, bs) == 256
    assert bucket_for(257, bs) == 512
    with pytest.raises(ValueError, match="largest bucket"):
        bucket_for(513, bs)


def test_pad_to_bucket_pads_and_masks():
    batch = {"ids": jnp.ones((2, 200), jnp.int32),
             "labels": jnp.ones((2, 200), jnp.int32)}
    padded, mask = pad_to_bucket(batch, 200, buckets=(128, 256))
    assert padded["ids"].shape == (2, 256)
    assert int(mask.sum()) == 200 and mask.shape == (256,)
    np.testing.assert_array_equal(np.asarray(padded["ids"][:, 200:]), 0)


def test_one_compile_per_bucket():
    """Two ragged lengths in one bucket -> ONE compiled executable."""
    traces = []

    @jax.jit
    def step(ids, mask):
        traces.append(1)
        return (ids * mask[None]).sum()

    for ln in (130, 200, 256):
        padded, mask = pad_to_bucket({"ids": jnp.ones((2, ln), jnp.int32)},
                                     ln, buckets=(128, 256))
        step(padded["ids"], mask)
    assert len(traces) == 1  # all three land in the 256 bucket

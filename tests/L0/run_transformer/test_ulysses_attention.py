"""Ulysses (all-to-all) sequence-parallel attention parity on the 8-way
context mesh — the second long-context strategy next to ring attention:
two all-to-alls swap seq<->heads so each rank runs exact full-sequence
attention for h/cp heads. Must reproduce unsharded flash attention,
forward AND gradients, incl. causal and padding masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.context_parallel import ulysses_attention
from apex_tpu.transformer.functional import flash_attention

CP = 8
B, H, S, D = 2, 8, 64, 16  # H % CP == 0; S_local = 8 per rank

SEQ_SHARDED = P(None, None, ps.CONTEXT_AXIS, None)


def cp_mesh():
    return ps.initialize_model_parallel(context_parallel_size_=CP)


def data(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, H, S, D)),
            jax.random.normal(ks[1], (B, H, S, D)),
            jax.random.normal(ks[2], (B, H, S, D)))


def run_ulysses(q, k, v, mask=None, **kw):
    cp_mesh()
    if mask is None:
        return ps.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, **kw),
            in_specs=(SEQ_SHARDED,) * 3, out_specs=SEQ_SHARDED)(q, k, v)
    return ps.shard_map(
        lambda q, k, v, m: ulysses_attention(q, k, v, m, **kw),
        in_specs=(SEQ_SHARDED,) * 3 + (P(None, ps.CONTEXT_AXIS),),
        out_specs=SEQ_SHARDED)(q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_flash_attention(causal):
    q, k, v = data()
    got = run_ulysses(q, k, v, causal=causal)
    want = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_padding_mask():
    q, k, v = data(1)
    mask = (jax.random.uniform(jax.random.PRNGKey(9), (B, S)) > 0.2
            ).astype(jnp.int32)
    got = run_ulysses(q, k, v, mask)
    want = flash_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_grads_match():
    # jit'd: grad-of-shard_map traced eagerly cost ~23 s on the 1-core
    # host; forward-parity tests keep the eager path covered
    q, k, v = data(2)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        return inner

    got = jax.jit(jax.grad(
        loss(lambda q, k, v: run_ulysses(q, k, v, causal=True)),
        argnums=(0, 1, 2)))(q, k, v)
    want = jax.jit(jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True)), argnums=(0, 1, 2)))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_heads_divisibility_error():
    cp_mesh()
    q = jnp.ones((1, 4, 64, 4))  # 4 heads on cp=8 (s_local = 8)

    with pytest.raises(ValueError, match="heads % cp"):
        ps.shard_map(lambda q: ulysses_attention(q, q, q),
                     in_specs=SEQ_SHARDED, out_specs=SEQ_SHARDED)(q)


def test_comm_structure_two_all_to_alls():
    """Ulysses' contract: exactly TWO all-to-alls per call (q/k/v ride
    one stacked collective in, the output one back) — no ring rotation,
    no gathers of q/k/v (the tiny key-mask all-gather is the one
    exception when a mask is passed)."""
    import re

    cp_mesh()
    q, k, v = data(3)
    f = jax.jit(ps.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, causal=True),
        in_specs=(SEQ_SHARDED,) * 3, out_specs=SEQ_SHARDED))
    text = f.lower(q, k, v).compile().as_text()
    single = re.compile(r"replica_groups=\{\{\d+\},")

    def count(op):
        return len([ln for ln in text.splitlines()
                    if f" {op}(" in ln and not single.search(ln)])

    assert count("all-to-all") == 2  # stacked qkv in; out back
    assert count("collective-permute") == 0
    assert count("all-gather") == 0

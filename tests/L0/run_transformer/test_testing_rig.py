"""transformer/testing tier (ref: ``apex/transformer/testing`` —
arguments/global_vars + standalone model re-exports)."""

import pytest

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer import testing as T
from apex_tpu.transformer.testing import arguments, global_vars


def test_parse_args_defaults_and_flags():
    ns = arguments.parse_args(args=[
        "--tensor-model-parallel-size", "2",
        "--pipeline-model-parallel-size", "4",
        "--num-layers", "8", "--bf16",
        "--some-unknown-megatron-flag", "x"])  # tolerated
    assert ns.tensor_model_parallel_size == 2
    assert ns.pipeline_model_parallel_size == 4
    assert ns.num_layers == 8 and ns.bf16 and not ns.fp16


def test_global_vars_registry():
    global_vars.unset_args()
    with pytest.raises(RuntimeError, match="set_args"):
        global_vars.get_args()
    ns = arguments.parse_args(args=[])
    global_vars.set_args(ns)
    assert global_vars.get_args() is ns
    assert global_vars.args_are_set()
    global_vars.unset_args()


def test_initialize_from_args_builds_mesh():
    ns = arguments.parse_args(args=[
        "--tensor-model-parallel-size", "2",
        "--pipeline-model-parallel-size", "2"])
    mesh = arguments.initialize_from_args(ns)
    assert dict(mesh.shape)[ps.TENSOR_AXIS] == 2
    assert dict(mesh.shape)[ps.PIPE_AXIS] == 2


def test_standalone_reexports():
    # reference-shaped imports resolve to the first-class zoo
    assert T.init_bert is not None and T.init_gpt is not None
    assert T.GPTModel is not None and T.bert_tiny().num_layers == 2

"""parallel_state mesh registry tests.

Mirrors the intent of the reference's ``tests/L0/run_transformer``
initialization tests, but over the 8-virtual-CPU-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps


def test_initialize_shapes():
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    assert ps.model_parallel_is_initialized()
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_pipeline_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert ps.get_context_parallel_world_size() == 1
    assert mesh.shape["model"] == 2
    # host-side ranks are 0
    assert ps.get_tensor_model_parallel_rank() == 0
    assert ps.get_pipeline_model_parallel_last_rank() == 1


def test_indivisible_world_raises():
    with pytest.raises(ps.ParallelStateError):
        ps.initialize_model_parallel(tensor_model_parallel_size_=3)


def test_default_mesh_is_pure_dp():
    mesh = ps.get_mesh()
    assert mesh.shape["data"] == len(jax.devices())
    assert mesh.shape["model"] == 1


def test_tp_axis_is_innermost():
    """Adjacent device ids must be TP neighbors (ICI locality)."""
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size_=4)
    devs = mesh.devices  # shape (dp=2, pp=1, cp=1, tp=4)
    ids = np.array([[d.id for d in row] for row in devs[:, 0, 0, :]])
    assert list(ids[0]) == [0, 1, 2, 3]
    assert list(ids[1]) == [4, 5, 6, 7]


def test_ranks_inside_shard_map():
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )

    def f(x):
        tp_r = ps.get_tensor_model_parallel_rank()
        pp_r = ps.get_pipeline_model_parallel_rank()
        dp_r = ps.get_data_parallel_rank()
        return x + tp_r * 100 + pp_r * 10 + dp_r

    out = ps.shard_map(
        f,
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P("data", None),
    )(jnp.zeros((2, 4)))
    # rows belong to dp ranks 0,1; within a row all tp/pp combos... rows are
    # sharded over data only, so each dp shard sees its own dp rank; the
    # tp/pp contributions are whatever that device's coordinates are — just
    # check the function traces and runs.
    assert out.shape == (2, 4)


def test_virtual_pipeline_bookkeeping():
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        virtual_pipeline_model_parallel_size_=2,
    )
    assert ps.get_virtual_pipeline_model_parallel_world_size() == 2
    ps.set_virtual_pipeline_model_parallel_rank(1)
    assert ps.get_virtual_pipeline_model_parallel_rank() == 1
    assert not ps.is_pipeline_first_stage()
    ps.set_virtual_pipeline_model_parallel_rank(0)
    assert ps.is_pipeline_first_stage()

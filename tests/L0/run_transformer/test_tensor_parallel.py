"""Tensor-parallel layer/mapping/cross-entropy tests on an 8-way TP mesh
(ref: ``tests/L0/run_transformer`` — golden comparison against the
unsharded computation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer import tensor_parallel as tp

TP = 8


def tp_mesh():
    return ps.initialize_model_parallel(tensor_model_parallel_size_=TP)


def smap(f, in_specs, out_specs):
    return ps.shard_map(f, in_specs=in_specs, out_specs=out_specs)


M = P(ps.TENSOR_AXIS)


def test_column_parallel_linear_matches_dense():
    mesh = tp_mesh()
    layer = tp.ColumnParallelLinear(32, 64, gather_output=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))

    want = x @ params["kernel"] + params["bias"]
    got = smap(layer.apply,
               in_specs=(layer.partition_specs(), P()),
               out_specs=P())(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_column_row_composition_matches_mlp():
    """Column(gather=False) -> gelu -> Row(input_is_parallel) == dense MLP
    with ONE allreduce — the Megatron block structure."""
    mesh = tp_mesh()
    col = tp.ColumnParallelLinear(32, 64, gather_output=False)
    row = tp.RowParallelLinear(64, 32, input_is_parallel=True)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32))

    want = jax.nn.gelu(x @ cp["kernel"] + cp["bias"]) @ rp["kernel"] \
        + rp["bias"]

    def block(cp, rp, x):
        return row.apply(rp, jax.nn.gelu(col.apply(cp, x)))

    got = smap(block,
               in_specs=(col.partition_specs(), row.partition_specs(), P()),
               out_specs=P())(cp, rp, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_tp_block_grads_match_dense():
    mesh = tp_mesh()
    col = tp.ColumnParallelLinear(16, 32, gather_output=False)
    row = tp.RowParallelLinear(32, 16, input_is_parallel=True)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16))

    def dense_loss(cp, rp, x):
        h = jax.nn.gelu(x @ cp["kernel"] + cp["bias"])
        return jnp.sum((h @ rp["kernel"] + rp["bias"]) ** 2)

    want = jax.grad(dense_loss, argnums=(0, 1))(cp, rp, x)

    def tp_loss_and_grads(cp, rp, x):
        def loss(cp, rp):
            return jnp.sum(row.apply(rp, jax.nn.gelu(col.apply(cp, x))) ** 2)
        return jax.grad(loss, argnums=(0, 1))(cp, rp)

    gcp, grp = smap(
        tp_loss_and_grads,
        in_specs=(col.partition_specs(), row.partition_specs(), P()),
        out_specs=(col.partition_specs(), row.partition_specs()))(cp, rp, x)

    np.testing.assert_allclose(np.asarray(gcp["kernel"]),
                               np.asarray(want[0]["kernel"]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(grp["kernel"]),
                               np.asarray(want[1]["kernel"]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gcp["bias"]),
                               np.asarray(want[0]["bias"]),
                               rtol=1e-4, atol=1e-4)


def test_sequence_parallel_mlp_matches_dense():
    """SP variant: activations sharded on seq (axis 0) outside the block;
    Column gathers, Row reduce-scatters. Layout (s, b, h)."""
    mesh = tp_mesh()
    col = tp.ColumnParallelLinear(16, 32, gather_output=False,
                                  sequence_parallel_enabled=True)
    row = tp.RowParallelLinear(32, 16, input_is_parallel=True,
                               sequence_parallel_enabled=True)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 2, 16))  # (s, b, h)

    want = jax.nn.gelu(x @ cp["kernel"] + cp["bias"]) @ rp["kernel"] \
        + rp["bias"]

    def block(cp, rp, x):
        return row.apply(rp, jax.nn.gelu(col.apply(cp, x)))

    got = smap(block,
               in_specs=(col.partition_specs(), row.partition_specs(), M),
               out_specs=M)(cp, rp, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_vocab_parallel_embedding_matches_dense():
    mesh = tp_mesh()
    emb = tp.VocabParallelEmbedding(64, 16)
    params = emb.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 10), 0, 64)

    want = jnp.take(params["embedding"], ids, axis=0)
    got = smap(emb.apply,
               in_specs=(emb.partition_specs(), P()),
               out_specs=P())(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_vocab_parallel_cross_entropy_matches_dense():
    mesh = tp_mesh()
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 10, 64)) * 3
    target = jax.random.randint(jax.random.PRNGKey(1), (4, 10), 0, 64)

    logp = jax.nn.log_softmax(logits, axis=-1)
    want = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]

    got = smap(tp.vocab_parallel_cross_entropy,
               in_specs=(P(None, None, ps.TENSOR_AXIS), P()),
               out_specs=P())(logits, target)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_vocab_parallel_cross_entropy_grads():
    mesh = tp_mesh()
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    target = jax.random.randint(jax.random.PRNGKey(1), (4,), 0, 64)

    def dense(logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, target[:, None], axis=-1))

    want = jax.grad(dense)(logits)

    def tp_grad(logits):
        return jax.grad(
            lambda l: jnp.mean(tp.vocab_parallel_cross_entropy(l, target))
        )(logits)

    got = smap(tp_grad,
               in_specs=P(None, ps.TENSOR_AXIS),
               out_specs=P(None, ps.TENSOR_AXIS))(logits)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_sequence_parallel_mappings_roundtrip():
    mesh = tp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))

    def rt(x):
        local = tp.scatter_to_sequence_parallel_region(x)
        return tp.gather_from_sequence_parallel_region(local, False)

    got = smap(rt, in_specs=P(), out_specs=P())(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))


def test_broadcast_data():
    mesh = tp_mesh()

    def f(batch):
        rank = jax.lax.axis_index(ps.TENSOR_AXIS)
        # non-0 ranks see garbage; broadcast must fix it
        data = {"ids": jnp.where(rank == 0, batch, -batch)}
        return tp.broadcast_data(["ids"], data)["ids"]

    batch = jnp.arange(8.0).reshape(2, 4)
    got = smap(f, in_specs=P(), out_specs=P())(batch)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(batch))


def test_rng_tracker_and_keys():
    mesh = tp_mesh()
    tracker = tp.get_rng_tracker()
    tracker.reset()
    tracker.add("model-parallel-rng", 123)

    def f(key):
        k = tp.model_parallel_rng_key(key)
        return jax.random.uniform(k, (1, 4))

    key = jax.random.PRNGKey(0)
    out = smap(f, in_specs=P(), out_specs=M)(key)
    # 8 ranks produced 8 DIFFERENT rows
    rows = np.asarray(out)
    assert len({tuple(r) for r in rows}) == 8

    states = tracker.get_states()
    k1 = tracker.fork()
    tracker.set_states(states)
    k2 = tracker.fork()
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_vocab_utility_and_split():
    f, t = tp.VocabUtility.vocab_range_from_global_vocab_size(64, 3, 8)
    assert (f, t) == (24, 32)
    parts = tp.split_tensor_along_last_dim(jnp.ones((2, 32)), 8)
    assert len(parts) == 8 and parts[0].shape == (2, 4)


def test_divisibility_errors():
    tp_mesh()
    with pytest.raises(ValueError):
        tp.ColumnParallelLinear(32, 65)  # 65 % 8 != 0
    with pytest.raises(ValueError):
        tp.RowParallelLinear(65, 32)
    with pytest.raises(ValueError):
        tp.VocabParallelEmbedding(65, 16)


def test_grad_accumulation_fusion_precision():
    """The fused wgrad path (ref ``fused_weight_gradient_mlp_cuda``) must
    beat plain AD on M-microbatch accumulation: plain AD rounds each
    microbatch's wgrad to bf16 before the fp32 accumulator sees it."""
    M, B, IN, OUT = 16, 32, 64, 48
    kx, kd = jax.random.split(jax.random.PRNGKey(0))
    xs = jax.random.normal(kx, (M, B, IN), jnp.bfloat16)
    dys = jax.random.normal(kd, (M, B, OUT), jnp.bfloat16)
    kernel = jax.random.normal(jax.random.PRNGKey(1), (IN, OUT),
                               jnp.float32)

    def wgrad(layer_fn, x, dy):
        return jax.grad(
            lambda k: jnp.sum(layer_fn(x, k).astype(jnp.float32)
                              * dy.astype(jnp.float32)))(kernel)

    def accumulate(layer_fn):
        acc = jnp.zeros((IN, OUT), jnp.float32)
        for i in range(M):
            acc = acc + wgrad(layer_fn, xs[i], dys[i])
        return acc

    plain = accumulate(lambda x, k: jnp.dot(x, k.astype(x.dtype)))
    fused = accumulate(tp.linear_with_grad_accumulation)
    # exact: same bf16 GEMM inputs, fp32 GEMM accumulation throughout
    exact = jnp.einsum("mbi,mbo->io", xs.astype(jnp.float32),
                       dys.astype(jnp.float32))

    err_plain = float(jnp.abs(plain - exact).max())
    err_fused = float(jnp.abs(fused - exact).max())
    assert fused.dtype == jnp.float32
    # plain AD's per-microbatch bf16 round-trip must show up as real loss
    assert err_fused < 0.5 * err_plain, (err_fused, err_plain)


def test_column_row_fusion_matches_dense():
    """gradient_accumulation_fusion=True must not change TP block grads
    (fp32 end to end here, so fused == plain == dense)."""
    mesh = tp_mesh()
    col = tp.ColumnParallelLinear(16, 32, gather_output=False,
                                  gradient_accumulation_fusion=True)
    row = tp.RowParallelLinear(32, 16, input_is_parallel=True,
                               gradient_accumulation_fusion=True)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16))

    def dense_loss(cp, rp, x):
        h = jax.nn.gelu(x @ cp["kernel"] + cp["bias"])
        return jnp.sum((h @ rp["kernel"] + rp["bias"]) ** 2)

    want = jax.grad(dense_loss, argnums=(0, 1))(cp, rp, x)

    def tp_grads(cp, rp, x):
        def loss(cp, rp):
            return jnp.sum(row.apply(rp, jax.nn.gelu(col.apply(cp, x)))
                           ** 2)
        return jax.grad(loss, argnums=(0, 1))(cp, rp)

    gcp, grp = smap(
        tp_grads,
        in_specs=(col.partition_specs(), row.partition_specs(), P()),
        out_specs=(col.partition_specs(), row.partition_specs()))(cp, rp, x)
    np.testing.assert_allclose(np.asarray(gcp["kernel"]),
                               np.asarray(want[0]["kernel"]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(grp["kernel"]),
                               np.asarray(want[1]["kernel"]),
                               rtol=1e-4, atol=1e-4)

"""Halo-exchange parity (ref: ``apex/contrib/test/peer_memory`` — the
halo moved between neighbors must equal slices of the gathered array)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.peer_memory import (
    PeerHaloExchanger1d,
    halo_exchange_1d,
)
from apex_tpu.transformer import parallel_state as ps

N = 8
B, H_LOC, W, C = 2, 4, 5, 3  # H sharded: global H = 32


def cp_mesh():
    return ps.initialize_model_parallel(context_parallel_size_=N)


def global_reference(x_global, halo, periodic):
    """Per-rank expected output built from the unsharded array."""
    outs = []
    for r in range(N):
        lo, hi = r * H_LOC, (r + 1) * H_LOC
        if periodic:
            prev = jnp.take(x_global, np.arange(lo - halo, lo), axis=1,
                            mode="wrap")
            nxt = jnp.take(x_global, np.arange(hi, hi + halo) %
                           x_global.shape[1], axis=1)
        else:
            prev = (x_global[:, lo - halo:lo] if r > 0 else
                    jnp.zeros((B, halo, W, C)))
            nxt = (x_global[:, hi:hi + halo] if r < N - 1 else
                   jnp.zeros((B, halo, W, C)))
        outs.append(jnp.concatenate([prev, x_global[:, lo:hi], nxt], 1))
    return jnp.concatenate(outs, axis=1)


@pytest.mark.parametrize("periodic", [False, True])
@pytest.mark.parametrize("halo", [1, 2])
def test_halo_matches_gathered_slices(halo, periodic):
    mesh = cp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (B, N * H_LOC, W, C))
    got = ps.shard_map(
        lambda x: halo_exchange_1d(x, halo, axis=1, periodic=periodic),
        in_specs=P(None, ps.CONTEXT_AXIS),
        out_specs=P(None, ps.CONTEXT_AXIS))(x)
    want = global_reference(x, halo, periodic)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gradients_accumulate_back():
    """Backward of the exchange returns each row's cotangent to its OWNER
    (halo rows consumed by a neighbor contribute back) — sum of grads
    equals grad of the gathered computation."""
    mesh = cp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(1), (B, N * H_LOC, W, C))

    def local_loss(x):
        # differentiate the LOCAL sum: under check_vma=False AD of a
        # per-rank output computes the grad of the sum over ranks; a
        # psum here would transpose to another psum and scale grads by N
        # (the same note as the pipeline schedules' loss masking)
        y = halo_exchange_1d(x, 1, axis=1)
        return jnp.sum(y ** 2, dtype=jnp.float32)

    g = ps.shard_map(jax.grad(local_loss),
                     in_specs=P(None, ps.CONTEXT_AXIS),
                     out_specs=P(None, ps.CONTEXT_AXIS))(x)
    # every interior row appears once as body and once as a neighbor's
    # halo => grad 2x for halo rows, 2x body: reference = grad of
    # sum(y²) over the rank-wise outputs of the gathered construction
    want = jax.grad(lambda x: jnp.sum(
        global_reference(x, 1, False) ** 2, dtype=jnp.float32))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_module_wrapper_and_validation():
    mesh = cp_mesh()
    x = jnp.ones((B, N * H_LOC, W, C))
    ex = PeerHaloExchanger1d(halo=2)
    got = ps.shard_map(ex, in_specs=P(None, ps.CONTEXT_AXIS),
                       out_specs=P(None, ps.CONTEXT_AXIS))(x)
    assert got.shape == (B, N * (H_LOC + 4), W, C)
    with pytest.raises(ValueError, match="halo"):
        ps.shard_map(lambda x: halo_exchange_1d(x, 0),
                     in_specs=P(None, ps.CONTEXT_AXIS),
                     out_specs=P(None, ps.CONTEXT_AXIS))(x)

"""Module-level MHA golden tests (ref:
``apex/contrib/test/multihead_attn/test_self_multihead_attn.py`` /
``test_encdec_multihead_attn.py`` — fast impl vs a straight-line
softmax-attention reference)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

S, SK, B, H, NH = 16, 24, 2, 64, 8
HD = H // NH


def _ref_attention(q, k, v, scale, mask=None, causal=False):
    """(b, nh, s, hd) straight-line softmax attention."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] != 0, s, -1e30)
    if causal:
        tri = jnp.tril(jnp.ones((q.shape[2], k.shape[2]), bool))
        s = jnp.where(tri[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v)


def _ref_self(params, x, mha, mask=None, causal=False):
    qkv = x @ params["qkv"]["kernel"]
    s, b, _ = qkv.shape
    qkv = qkv.reshape(s, b, NH, 3, HD)
    q, k, v = (qkv[:, :, :, j].transpose(1, 2, 0, 3) for j in range(3))
    ctx = _ref_attention(q, k, v, mha.scaling, mask, causal)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, H)
    return ctx @ params["out"]["kernel"]


@pytest.mark.parametrize("causal", [False, True])
def test_self_attn_matches_reference(causal):
    mha = SelfMultiheadAttn(H, NH)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))
    got = mha.apply(params, x, attn_mask_causal=causal, is_training=False)
    want = _ref_self(params, x, mha, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_key_padding_mask():
    mha = SelfMultiheadAttn(H, NH)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))
    mask = jnp.ones((B, S), jnp.int32).at[:, S // 2:].set(0)
    got = mha.apply(params, x, key_padding_mask=mask, is_training=False)
    want = _ref_self(params, x, mha, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_norm_add_variant():
    """include_norm_add: LN at input, residual add at output — output
    must equal plain-MHA(LN(x)) + x."""
    mha = SelfMultiheadAttn(H, NH, include_norm_add=True)
    plain = SelfMultiheadAttn(H, NH)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))

    xn = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
        x.var(-1, keepdims=True) + 1e-5)
    want = plain.apply({"qkv": params["qkv"], "out": params["out"]},
                       xn, is_training=False) + x
    got = mha.apply(params, x, is_training=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_encdec_matches_reference():
    mha = EncdecMultiheadAttn(H, NH)
    params = mha.init(jax.random.PRNGKey(0))
    q = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))
    enc = jax.random.normal(jax.random.PRNGKey(2), (SK, B, H))
    got = mha.apply(params, q, enc, is_training=False)

    qh = (q @ params["q"]["kernel"]).reshape(S, B, NH, HD).transpose(
        1, 2, 0, 3)
    kv = (enc @ params["kv"]["kernel"]).reshape(SK, B, NH, 2, HD)
    k, v = (kv[:, :, :, j].transpose(1, 2, 0, 3) for j in range(2))
    ctx = _ref_attention(qh, k, v, mha.scaling)
    want = ctx.transpose(2, 0, 1, 3).reshape(S, B, H) \
        @ params["out"]["kernel"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_dropout_deterministic_and_active():
    mha = SelfMultiheadAttn(H, NH, dropout=0.3)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))
    a = mha.apply(params, x, dropout_rng=jax.random.PRNGKey(5))
    b = mha.apply(params, x, dropout_rng=jax.random.PRNGKey(5))
    c = mha.apply(params, x, dropout_rng=jax.random.PRNGKey(6))
    d = mha.apply(params, x, is_training=False,
                  dropout_rng=jax.random.PRNGKey(5))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(jnp.max(jnp.abs(a - c))) > 0
    assert float(jnp.max(jnp.abs(a - d))) > 0  # eval disables dropout


def test_gradients_flow():
    mha = SelfMultiheadAttn(H, NH, bias=True, include_norm_add=True)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))

    g = jax.grad(lambda p: jnp.sum(
        mha.apply(p, x, attn_mask_causal=True, is_training=False) ** 2))(
        params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert bool(jnp.all(jnp.isfinite(leaf)))
        assert float(jnp.max(jnp.abs(leaf))) > 0

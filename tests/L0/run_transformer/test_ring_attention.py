"""Ring-attention (context parallel) parity on an 8-way context mesh:
the sequence-sharded ring must reproduce full flash/softmax attention
bit-closely, forward AND gradients, incl. causal and padding masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.context_parallel import ring_attention
from apex_tpu.transformer.functional import flash_attention

CP = 8
B, H, S, D = 2, 4, 64, 16  # S_local = 8 per rank


def cp_mesh():
    return ps.initialize_model_parallel(context_parallel_size_=CP)


def data(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, H, S, D))
    k = jax.random.normal(ks[1], (B, H, S, D))
    v = jax.random.normal(ks[2], (B, H, S, D))
    return q, k, v


SEQ_SHARDED = P(None, None, ps.CONTEXT_AXIS, None)


def run_ring(q, k, v, mask=None, **kw):
    mesh = cp_mesh()
    if mask is None:
        f = lambda q, k, v: ring_attention(q, k, v, **kw)  # noqa: E731
        return ps.shard_map(
            f, in_specs=(SEQ_SHARDED,) * 3, out_specs=SEQ_SHARDED)(q, k, v)
    f = lambda q, k, v, m: ring_attention(q, k, v, m, **kw)  # noqa: E731
    return ps.shard_map(
        f, in_specs=(SEQ_SHARDED,) * 3 + (P(None, ps.CONTEXT_AXIS),),
        out_specs=SEQ_SHARDED)(q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_flash_attention(causal):
    q, k, v = data()
    got = run_ring(q, k, v, causal=causal)
    want = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_padding_mask():
    q, k, v = data(1)
    mask = jnp.ones((B, S), jnp.int32).at[:, S // 3:].set(0)
    got = run_ring(q, k, v, mask)
    want = flash_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_fully_masked_rows_return_zero():
    """Causal + padding can fully mask early rows on later ranks' qs?
    Simplest total check: all-zero mask ⇒ all-zero output (the flash
    convention), no NaNs from the ring merge."""
    q, k, v = data(2)
    got = run_ring(q, k, v, jnp.zeros((B, S), jnp.int32))
    np.testing.assert_array_equal(np.asarray(got), 0.0)


@pytest.mark.parametrize("checkpoint_blocks", [False, True])
def test_gradients_match_full_attention(checkpoint_blocks):
    q, k, v = data(3)
    mesh = cp_mesh()

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v, causal=True,
                             checkpoint_blocks=checkpoint_blocks)
        return jnp.sum(out ** 2, dtype=jnp.float32)

    # sum over seq-sharded outputs: sum local partials then psum
    def local(q, k, v):
        val, grads = jax.value_and_grad(ring_loss, argnums=(0, 1, 2))(
            q, k, v)
        return jax.lax.psum(val, ps.CONTEXT_AXIS), grads

    got_loss, got_grads = jax.jit(ps.shard_map(
        local, in_specs=(SEQ_SHARDED,) * 3,
        out_specs=(P(), (SEQ_SHARDED,) * 3)))(q, k, v)

    want_loss, want_grads = jax.value_and_grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True) ** 2, dtype=jnp.float32),
        argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    for g, w in zip(got_grads, want_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=3e-5, atol=3e-6)


def test_cp1_degenerates_to_flash():
    ps.initialize_model_parallel(context_parallel_size_=1)
    q, k, v = data(4)
    got = ps.shard_map(
        lambda q, k, v: ring_attention(q, k, v),
        in_specs=(P(),) * 3, out_specs=P())(q, k, v)
    want = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)

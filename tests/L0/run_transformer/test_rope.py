"""Golden tests for fused RoPE (ref: ``apex/transformer/functional/fused_rope``,
tested upstream in ``tests/L0/run_transformer/test_fused_rope.py`` against a
non-fused torch RotaryEmbedding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.functional import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_bhsd,
    fused_apply_rotary_pos_emb_bshd,
    fused_apply_rotary_pos_emb_cached,
    rope_cos_sin,
    rope_frequencies,
)


def _reference_rope(t, freqs):
    """Straight-line jnp reference (the upstream non-fused path)."""
    d_rot = freqs.shape[-1]
    rot, rest = t[..., :d_rot], t[..., d_rot:]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    x1, x2 = jnp.split(rot, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = rot * cos + rotated * sin
    return jnp.concatenate([out, rest], axis=-1).astype(t.dtype)


S, B, H, D = 16, 2, 4, 32


@pytest.mark.parametrize("d_rot", [D, D // 2])
def test_forward_matches_reference(d_rot):
    t = jax.random.normal(jax.random.PRNGKey(0), (S, B, H, D))
    freqs = rope_frequencies(d_rot, S)
    np.testing.assert_allclose(fused_apply_rotary_pos_emb(t, freqs),
                               _reference_rope(t, freqs), rtol=1e-6)


def test_cached_matches_uncached():
    t = jax.random.normal(jax.random.PRNGKey(1), (S, B, H, D))
    freqs = rope_frequencies(D, S)
    cos, sin = rope_cos_sin(D, S)
    np.testing.assert_array_equal(
        fused_apply_rotary_pos_emb(t, freqs),
        fused_apply_rotary_pos_emb_cached(t, cos, sin))


@pytest.mark.parametrize("d_rot", [D, D // 2])
def test_gradient_matches_autodiff(d_rot):
    """The custom_vjp backward (rotation transpose) must equal autodiff of
    the straight-line reference."""
    t = jax.random.normal(jax.random.PRNGKey(2), (S, B, H, D))
    freqs = rope_frequencies(d_rot, S)
    g_fused = jax.grad(
        lambda t: jnp.sum(jnp.sin(fused_apply_rotary_pos_emb(t, freqs))))(t)
    g_ref = jax.grad(
        lambda t: jnp.sum(jnp.sin(_reference_rope(t, freqs))))(t)
    np.testing.assert_allclose(g_fused, g_ref, rtol=1e-5, atol=1e-6)


def test_freqs_gradient_matches_autodiff():
    """Learned rotary tables: grads w.r.t. freqs must be the true gradient,
    not silent zeros (the reference kernel returns no freq grad at all)."""
    t = jax.random.normal(jax.random.PRNGKey(6), (S, B, H, D))
    freqs = rope_frequencies(D, S)
    g_fused = jax.grad(
        lambda f: jnp.sum(jnp.sin(fused_apply_rotary_pos_emb(t, f))))(freqs)
    g_ref = jax.grad(
        lambda f: jnp.sum(jnp.sin(_reference_rope(t, f))))(freqs)
    assert float(jnp.max(jnp.abs(g_fused))) > 0
    np.testing.assert_allclose(g_fused, g_ref, rtol=1e-5, atol=1e-6)


def test_bfloat16_rotation_computed_in_fp32():
    """bf16 inputs: internal math must be fp32 (reference-kernel parity) —
    the bf16 result must round-trip from the fp32 reference."""
    t32 = jax.random.normal(jax.random.PRNGKey(7), (S, B, H, D))
    freqs = rope_frequencies(D, S)
    want = _reference_rope(t32, freqs)
    got = fused_apply_rotary_pos_emb(t32.astype(jnp.bfloat16), freqs)
    # one bf16 rounding of the input + one of the output — no accumulation
    np.testing.assert_allclose(got.astype(jnp.float32), want,
                               rtol=2e-2, atol=2e-2)


def test_layout_wrappers_agree():
    t_sbhd = jax.random.normal(jax.random.PRNGKey(3), (S, B, H, D))
    freqs = rope_frequencies(D, S)
    want = fused_apply_rotary_pos_emb(t_sbhd, freqs)
    got_bshd = fused_apply_rotary_pos_emb_bshd(
        t_sbhd.transpose(1, 0, 2, 3), freqs).transpose(1, 0, 2, 3)
    got_bhsd = fused_apply_rotary_pos_emb_bhsd(
        t_sbhd.transpose(1, 2, 0, 3), freqs).transpose(2, 0, 1, 3)
    np.testing.assert_allclose(got_bshd, want, rtol=1e-6)
    np.testing.assert_allclose(got_bhsd, want, rtol=1e-6)


def test_position_zero_is_identity():
    """θ(p=0) = 0 ⇒ row 0 passes through unchanged."""
    t = jax.random.normal(jax.random.PRNGKey(4), (S, B, H, D))
    out = fused_apply_rotary_pos_emb(t, rope_frequencies(D, S))
    np.testing.assert_allclose(out[0], t[0], rtol=1e-6)


def test_norm_preserved():
    """Rotations are isometries: per-(position, head) L2 norm is kept."""
    t = jax.random.normal(jax.random.PRNGKey(5), (S, B, H, D))
    out = fused_apply_rotary_pos_emb(t, rope_frequencies(D, S))
    np.testing.assert_allclose(jnp.linalg.norm(out, axis=-1),
                               jnp.linalg.norm(t, axis=-1), rtol=1e-5)

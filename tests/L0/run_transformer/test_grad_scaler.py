"""Megatron-style GradScaler: found_inf is OR-ed across model-parallel
axes — and only across axes the enclosing mapped region actually binds
(ref: ``apex/transformer/amp/grad_scaler.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.amp import GradScaler


def test_unscale_ors_found_inf_across_tensor_axis():
    ps.initialize_model_parallel(tensor_model_parallel_size_=8)
    scaler = GradScaler()
    state = scaler.init_state()

    def f(g):
        _, found_inf = scaler.unscale({"g": g}, state)
        return found_inf.astype(jnp.int32).reshape(1)

    # rank 3 overflows; every rank must see found_inf
    g = jnp.ones((8, 4), jnp.float32).at[3, 0].set(jnp.inf)
    out = ps.shard_map(f, mesh=ps.get_mesh(),
                       in_specs=(P(ps.TENSOR_AXIS),),
                       out_specs=P(ps.TENSOR_AXIS))(g)
    assert np.asarray(out).tolist() == [1] * 8


def test_unscale_works_on_tensor_only_shard_map():
    """A mapped region binding ONLY the tensor axis must not error on the
    unbound pipe axis (round-1 advisor finding)."""
    import numpy as onp
    from jax.sharding import Mesh

    scaler = GradScaler()
    state = scaler.init_state()
    mesh = Mesh(onp.array(jax.devices()[:2]), (ps.TENSOR_AXIS,))

    def f(g):
        grads, found_inf = scaler.unscale({"g": g}, state)
        return grads["g"], found_inf.astype(jnp.int32).reshape(1)

    g = jnp.ones((2, 4), jnp.float32)
    out, found = ps.shard_map(
        f, mesh=mesh, in_specs=(P(ps.TENSOR_AXIS),),
        out_specs=(P(ps.TENSOR_AXIS), P(ps.TENSOR_AXIS)))(g)
    assert np.asarray(found).tolist() == [0, 0]
    assert out.shape == (2, 4)

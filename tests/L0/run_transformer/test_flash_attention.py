"""Flash-attention kernel golden tests vs pure-jnp attention.

SURVEY.md §4 pattern: Pallas kernel compared against the stock jnp
implementation within dtype-scaled tolerances, fwd + grads, across
mask types and dtypes.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.functional import flash_attention


@pytest.fixture(params=[True, False], ids=["kernel", "xla"])
def fa(request):
    """Exercise BOTH dispatch paths: the Pallas kernel and the XLA
    short-seq path (`use_kernel` forced each way)."""
    return functools.partial(flash_attention, use_kernel=request.param)


def _reference(q, k, v, mask=None, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    neg = jnp.float32(-1e30)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] != 0, s, neg)
    if causal:
        sq, sk = s.shape[-2:]
        causal_m = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(causal_m, s, neg)
    # fully-masked rows: flash returns 0, mimic that
    p = jax.nn.softmax(s, axis=-1)
    any_valid = (s > neg / 2).any(-1, keepdims=True)
    p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype)


def _qkv(key, b, h, s, d, dtype):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, h, s, d), dtype)  # noqa: E731
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


TOL = {jnp.float32: dict(atol=2e-5, rtol=2e-5),
       jnp.bfloat16: dict(atol=2e-2, rtol=2e-2)}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(dtype, causal, fa):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 3, 80, 24, dtype)
    out = fa(q, k, v, causal=causal)
    ref = _reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **TOL[dtype])


def test_forward_padding_mask(fa):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 2, 40, 16, jnp.float32)
    mask = (jax.random.uniform(jax.random.PRNGKey(2), (2, 40)) > 0.3)
    mask = mask.at[:, 0].set(True).astype(jnp.int32)
    out = fa(q, k, v, mask)
    ref = _reference(q, k, v, mask)
    np.testing.assert_allclose(out, ref, **TOL[jnp.float32])


def test_fully_masked_rows_return_zero(fa):
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 1, 8, 8, jnp.float32)
    mask = jnp.zeros((1, 8), jnp.int32)
    out = fa(q, k, v, mask)
    np.testing.assert_allclose(out, jnp.zeros_like(out), atol=0)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(dtype, causal, fa):
    q, k, v = _qkv(jax.random.PRNGKey(4), 2, 2, 48, 16, dtype)
    mask = None
    if not causal:
        mask = (jax.random.uniform(jax.random.PRNGKey(5), (2, 48)) > 0.2)
        mask = mask.at[:, 0].set(True).astype(jnp.int32)

    def loss_flash(q, k, v):
        return (fa(q, k, v, mask, causal=causal)
                .astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, mask, causal=causal)
                .astype(jnp.float32) ** 2).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    tol = dict(atol=1e-3, rtol=1e-3) if dtype == jnp.float32 else \
        dict(atol=0.1, rtol=0.1)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol)


def test_cross_attention_seq_lengths(fa):
    """sq != sk (encoder-decoder shape, ref encdec_multihead_attn)."""
    key = jax.random.PRNGKey(6)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (2, 2, 24, 16))
    k = jax.random.normal(ks[1], (2, 2, 56, 16))
    v = jax.random.normal(ks[2], (2, 2, 56, 16))
    out = fa(q, k, v)
    ref = _reference(q, k, v)
    np.testing.assert_allclose(out, ref, **TOL[jnp.float32])


def test_dropout_statistics_and_determinism(fa):
    q, k, v = _qkv(jax.random.PRNGKey(7), 1, 2, 64, 16, jnp.float32)
    rng = jax.random.PRNGKey(8)
    f = functools.partial(flash_attention, dropout_rate=0.5, dropout_rng=rng)
    o1, o2 = f(q, k, v), f(q, k, v)
    # same rng => identical output (saved-mask semantics)
    np.testing.assert_array_equal(o1, o2)
    # different rng => different output
    o3 = fa(q, k, v, dropout_rate=0.5,
                         dropout_rng=jax.random.PRNGKey(9))
    assert not np.allclose(o1, o3)
    # dropout is unbiased-ish: mean magnitude comparable to no-dropout
    o0 = fa(q, k, v)
    ratio = float(jnp.abs(o1).mean() / jnp.abs(o0).mean())
    assert 0.5 < ratio < 2.0, ratio


def test_dropout_backward_uses_same_mask(fa):
    """grad must see the same keep mask as the forward: finite-difference
    check along a random direction."""
    q, k, v = _qkv(jax.random.PRNGKey(10), 1, 1, 32, 8, jnp.float32)
    rng = jax.random.PRNGKey(11)

    def loss(q):
        return (fa(q, k, v, dropout_rate=0.3, dropout_rng=rng)
                ** 2).sum()

    g = jax.grad(loss)(q)
    direction = jax.random.normal(jax.random.PRNGKey(12), q.shape)
    eps = 1e-3
    fd = (loss(q + eps * direction) - loss(q - eps * direction)) / (2 * eps)
    analytic = jnp.vdot(g, direction)
    np.testing.assert_allclose(fd, analytic, rtol=2e-2, atol=2e-2)


def test_softmax_scale_override(fa):
    q, k, v = _qkv(jax.random.PRNGKey(13), 1, 2, 32, 16, jnp.float32)
    out = fa(q, k, v, softmax_scale=0.05)
    ref = _reference(q, k, v, scale=0.05)
    np.testing.assert_allclose(out, ref, **TOL[jnp.float32])


def test_dispatch_paths_agree_with_dropout():
    """Kernel and XLA paths must produce the SAME dropped output for the
    same rng (shared _hash_keep mask) — dispatch never changes training
    randomness."""
    q, k, v = _qkv(jax.random.PRNGKey(14), 1, 2, 64, 16, jnp.float32)
    rng = jax.random.PRNGKey(15)
    a = flash_attention(q, k, v, dropout_rate=0.4, dropout_rng=rng,
                        use_kernel=True)
    b = flash_attention(q, k, v, dropout_rate=0.4, dropout_rng=rng,
                        use_kernel=False)
    np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


def test_auto_dispatch_threshold():
    """Below the crossover the XLA path runs (no pallas_call in the jaxpr);
    above it the kernel runs."""
    q, k, v = _qkv(jax.random.PRNGKey(16), 1, 1, 64, 8, jnp.float32)
    jaxpr = str(jax.make_jaxpr(lambda q, k, v: flash_attention(q, k, v))(
        q, k, v))
    assert "pallas_call" not in jaxpr
    q2, k2, v2 = _qkv(jax.random.PRNGKey(17), 1, 1, 512, 8, jnp.float32)
    jaxpr2 = str(jax.make_jaxpr(lambda q, k, v: flash_attention(q, k, v))(
        q2, k2, v2))
    assert "pallas_call" in jaxpr2


# -- VPU-diet variants (exp2 online softmax, bf16 p-tiles) ------------------

def _fam():
    """The flash_attention MODULE (the package __init__ rebinds the name
    to the function; importlib addresses the module, where the variant
    toggles and ``kernel_variant`` live)."""
    import importlib
    return importlib.import_module(
        "apex_tpu.transformer.functional.flash_attention")


def _fwdbwd(q, k, v, rate=0.0, rng=None, **kw):
    def loss(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, use_kernel=True, dropout_rate=rate,
            dropout_rng=rng, **kw).astype(jnp.float32) ** 2)
    l, grads = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
    return (l, *grads)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_variants_agree(dtype):
    """The shipped kernels (exp2 + bf16 p-tiles) vs the legacy toggles:
    pure arithmetic re-expression, so fwd AND all grads must agree to
    the golden tolerances. Variants are baked at TRACE time, so each
    side jits inside its context."""
    fam = _fam()
    q, k, v = _qkv(jax.random.PRNGKey(20), 1, 2, 512, 64, dtype)
    new = jax.jit(_fwdbwd)(q, k, v)
    with fam.kernel_variant(exp2=False, p_bf16=False):
        old = jax.jit(_fwdbwd)(q, k, v)
    for a, b in zip(new, old):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   **{kk: 3 * t for kk, t in
                                      TOL[dtype].items()})


def test_small_d_block_cap_variant_matches():
    """``small_d_max_block`` only retiles the grid — the math is
    identical, so a 256 cap must reproduce the default to fp32
    tolerance, dropout included (the counter-hash mask is addressed by
    GLOBAL (q,k) position, so retiling must not move any mask bit)."""
    fam = _fam()
    q, k, v = _qkv(jax.random.PRNGKey(21), 1, 2, 512, 64, jnp.float32)
    rng = jax.random.PRNGKey(22)
    base = jax.jit(lambda q, k, v: _fwdbwd(q, k, v, 0.3, rng))(q, k, v)
    with fam.kernel_variant(small_d_max_block=256):
        capped = jax.jit(lambda q, k, v: _fwdbwd(q, k, v, 0.3, rng))(
            q, k, v)
    for a, b in zip(base, capped):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=2e-4, rtol=2e-4)


def test_dropout_mask_invariant_across_variants():
    """The keep mask is a pure function of (rng, global position) — the
    exp2/bf16 toggles must not move a single mask bit. Recover each
    variant's mask from a rate-r run against its own no-dropout output
    (dropped entries of p are exact zeros, so out_drop == 0 exactly
    where whole rows drop is too coarse — compare elementwise scaling
    instead on V = identity-ish basis): with v = identity basis columns,
    out[q, i] directly exposes p[q, i]'s keep bit."""
    fam = _fam()
    s, d = 256, 64
    q = jax.random.normal(jax.random.PRNGKey(23), (1, 1, s, d),
                          jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(24), (1, 1, s, d),
                          jnp.float32)
    # v = one-hot rows: out[:, :, i, j] = sum_k p[i, k] * v[k, j] with
    # v[k, j] = (k % d == j) exposes p column-sums per residue class;
    # enough to catch any mask shift while keeping d < s workable
    v = (jnp.arange(s)[:, None] % d == jnp.arange(d)[None, :]).astype(
        jnp.float32)[None, None]
    rng = jax.random.PRNGKey(25)

    def dropped(toggles):
        if toggles:
            with fam.kernel_variant(**toggles):
                return jax.jit(lambda q, k, v: flash_attention(
                    q, k, v, causal=True, use_kernel=True,
                    dropout_rate=0.3, dropout_rng=rng))(q, k, v)
        return jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, use_kernel=True,
            dropout_rate=0.3, dropout_rng=rng))(q, k, v)

    base = dropped(None)
    for toggles in ({"exp2": False}, {"p_bf16": False},
                    {"exp2": False, "p_bf16": False},
                    {"small_d_max_block": 128}):
        other = dropped(toggles)
        np.testing.assert_allclose(np.asarray(base), np.asarray(other),
                                   atol=5e-5, rtol=5e-5,
                                   err_msg=f"mask moved under {toggles}")

"""Pipeline-parallel schedule parity tests (8-device CPU mesh).

Golden-model pattern (SURVEY.md §4): the pipelined schedules must
reproduce the loss and gradients of the plain sequential model to fp32
tolerance — the same check the reference's ``run_megatron_gpt_pipeline``
tests do across real GPUs, here on the virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import (
    PipelineModel,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    microbatches,
)

VOCAB, SEQ, HIDDEN, FF = 64, 8, 16, 32


def _embed_fn(p, mb):
    x = p["word"][mb["ids"]]
    return x + p["pos"][None, : x.shape[1]]


def _stage_fn(p, x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    h = (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["ln_w"] + p["ln_b"]
    h = jax.nn.gelu(h @ p["fc1"] + p["b1"]) @ p["fc2"] + p["b2"]
    return x + h


def _loss_fn(p, x, mb):
    logits = x @ p["proj"] + p["bias"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, mb["labels"][..., None], -1)[..., 0]
    return -ll.mean()


MODEL = PipelineModel(_embed_fn, _stage_fn, _loss_fn)


def _init(key, n_stages):
    ks = jax.random.split(key, 4)
    nrm = lambda k, s: jax.random.normal(k, s, jnp.float32) * 0.05  # noqa
    embed = {"word": nrm(ks[0], (VOCAB, HIDDEN)),
             "pos": nrm(ks[1], (SEQ, HIDDEN))}
    sk = jax.random.split(ks[2], 2 * n_stages)
    stages = {
        "ln_w": jnp.ones((n_stages, HIDDEN)),
        "ln_b": jnp.zeros((n_stages, HIDDEN)),
        "fc1": jnp.stack([nrm(sk[2 * i], (HIDDEN, FF))
                          for i in range(n_stages)]),
        "b1": jnp.zeros((n_stages, FF)),
        "fc2": jnp.stack([nrm(sk[2 * i + 1], (FF, HIDDEN))
                          for i in range(n_stages)]),
        "b2": jnp.zeros((n_stages, HIDDEN)),
    }
    head = {"proj": nrm(ks[3], (HIDDEN, VOCAB)), "bias": jnp.zeros((VOCAB,))}
    return {"embed": embed, "stages": stages, "head": head}


def _batch(key, batch_size):
    k1, k2 = jax.random.split(key)
    return {
        "ids": jax.random.randint(k1, (batch_size, SEQ), 0, VOCAB),
        "labels": jax.random.randint(k2, (batch_size, SEQ), 0, VOCAB),
    }


def _reference(params, batch, num_microbatches):
    """Plain sequential grad-accumulated loss — the golden model."""
    return forward_backward_no_pipelining(
        MODEL, params, batch, num_microbatches=num_microbatches,
        checkpoint_stages=False)


def _tree_close(a, b, atol):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=atol, rtol=1e-4)


@pytest.mark.parametrize("pp,n_mb", [(2, 4), (4, 8), (2, 2)])
def test_1f1b_matches_no_pipelining(pp, n_mb):
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])
    params = _init(jax.random.PRNGKey(0), pp)
    batch = _batch(jax.random.PRNGKey(1), 2 * n_mb)
    ref_loss, ref_grads = _reference(params, batch, n_mb)

    pipelined = ps.shard_map(
        lambda p, b: forward_backward_pipelining_without_interleaving(
            MODEL, p, b, num_microbatches=n_mb),
        in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS), "head": P()},
                  P()),
        out_specs=(P(), {"embed": P(), "stages": P(ps.PIPE_AXIS),
                         "head": P()}),
    )
    loss, grads = jax.jit(pipelined)(params, batch)
    np.testing.assert_allclose(loss, ref_loss, atol=1e-5, rtol=1e-5)
    _tree_close(grads, ref_grads, atol=1e-5)


@pytest.mark.parametrize("pp,vpp,n_mb", [(2, 2, 4), (2, 3, 4), (4, 2, 4)])
def test_interleaved_matches_no_pipelining(pp, vpp, n_mb):
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        virtual_pipeline_model_parallel_size_=vpp,
        devices=jax.devices()[:pp])
    n_stages = pp * vpp
    params = _init(jax.random.PRNGKey(2), n_stages)
    batch = _batch(jax.random.PRNGKey(3), 2 * n_mb)
    ref_loss, ref_grads = _reference(params, batch, n_mb)

    # chunk c -> slot [c // pp, c % pp]: a row-major reshape
    iparams = dict(params)
    iparams["stages"] = jax.tree.map(
        lambda a: a.reshape((vpp, pp) + a.shape[1:]), params["stages"])

    pipelined = ps.shard_map(
        lambda p, b: forward_backward_pipelining_with_interleaving(
            MODEL, p, b, num_microbatches=n_mb),
        in_specs=({"embed": P(), "stages": P(None, ps.PIPE_AXIS),
                   "head": P()}, P()),
        out_specs=(P(), {"embed": P(), "stages": P(None, ps.PIPE_AXIS),
                         "head": P()}),
    )
    loss, grads = jax.jit(pipelined)(iparams, batch)
    np.testing.assert_allclose(loss, ref_loss, atol=1e-5, rtol=1e-5)
    grads = dict(grads)
    grads["stages"] = jax.tree.map(
        lambda a: a.reshape((vpp * pp,) + a.shape[2:]), grads["stages"])
    _tree_close(grads, ref_grads, atol=1e-5)


def test_forward_only():
    pp, n_mb = 2, 4
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])
    params = _init(jax.random.PRNGKey(4), pp)
    batch = _batch(jax.random.PRNGKey(5), 2 * n_mb)
    ref_loss, _ = _reference(params, batch, n_mb)

    fwd = ps.shard_map(
        lambda p, b: forward_backward_pipelining_without_interleaving(
            MODEL, p, b, num_microbatches=n_mb, forward_only=True)[0],
        in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS), "head": P()},
                  P()),
        out_specs=P(),
    )
    loss = jax.jit(fwd)(params, batch)
    np.testing.assert_allclose(loss, ref_loss, atol=1e-5, rtol=1e-5)


def test_microbatch_count_from_calculator():
    """num_microbatches defaults to the global calculator (ref:
    ``get_num_microbatches``)."""
    pp = 2
    ps.initialize_model_parallel(pipeline_model_parallel_size_=pp,
                                 devices=jax.devices()[:pp])
    microbatches.setup_microbatch_calculator(
        rank=0, rampup_batch_size=None, global_batch_size=8,
        micro_batch_size=2, data_parallel_size=1)
    try:
        assert microbatches.get_num_microbatches() == 4
        params = _init(jax.random.PRNGKey(6), pp)
        batch = _batch(jax.random.PRNGKey(7), 8)
        ref_loss, _ = _reference(params, batch, 4)
        pipelined = ps.shard_map(
            lambda p, b: forward_backward_pipelining_without_interleaving(
                MODEL, p, b)[0],
            in_specs=({"embed": P(), "stages": P(ps.PIPE_AXIS),
                       "head": P()}, P()),
            out_specs=P(),
        )
        loss = jax.jit(pipelined)(params, batch)
        np.testing.assert_allclose(loss, ref_loss, atol=1e-5, rtol=1e-5)
    finally:
        microbatches.destroy_num_microbatches_calculator()


def test_dispatcher():
    ps.initialize_model_parallel(devices=jax.devices()[:1])
    assert get_forward_backward_func() is forward_backward_no_pipelining
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(pipeline_model_parallel_size_=2,
                                 devices=jax.devices()[:2])
    assert (get_forward_backward_func()
            is forward_backward_pipelining_without_interleaving)
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        pipeline_model_parallel_size_=2,
        virtual_pipeline_model_parallel_size_=2,
        devices=jax.devices()[:2])
    assert (get_forward_backward_func()
            is forward_backward_pipelining_with_interleaving)


def test_no_pipelining_forward_only_matches_grad_path():
    ps.initialize_model_parallel(devices=jax.devices()[:1])
    params = _init(jax.random.PRNGKey(8), 3)
    batch = _batch(jax.random.PRNGKey(9), 4)
    l1, g = forward_backward_no_pipelining(MODEL, params, batch,
                                           num_microbatches=2)
    l2, none = forward_backward_no_pipelining(
        MODEL, params, batch, num_microbatches=2, forward_only=True)
    assert none is None
    np.testing.assert_allclose(l1, l2, atol=1e-6)
    assert g is not None and jax.tree.leaves(g)


def test_lone_send_recv_fail_fast():
    # Under SPMD a send and its matching recv are ONE ppermute; the lone
    # reference names must refuse to run rather than double-shift
    import pytest

    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    for fn in (p2p.send_forward, p2p.recv_forward,
               p2p.send_backward, p2p.recv_backward):
        with pytest.raises(RuntimeError, match="single collective"):
            fn(jnp.ones(4))


def test_fp32_grad_accumulation_beats_bf16():
    """The gradient_accumulation_fusion analogue (ref:
    fused_weight_gradient_mlp_cuda): bf16 microbatch grads summed in an
    fp32 main-grad accumulator keep low bits a bf16 accumulator drops.
    Grad w.r.t. head = mb value; [256, 1, 1, ...] makes bf16 addition
    round every +1 away (bf16 ulp at 256 is 2)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        PipelineModel, forward_backward_no_pipelining,
    )

    model = PipelineModel(
        embed_fn=lambda e, mb: mb.astype(jnp.bfloat16),
        stage_fn=lambda sp, h: h + 0.0 * sp["w"].astype(h.dtype),
        loss_fn=lambda head, x, mb: jnp.sum(
            head["w"] * x).astype(jnp.float32),
    )
    params = {"embed": {}, "stages": {"w": jnp.ones((1, 1), jnp.bfloat16)},
              "head": {"w": jnp.ones((1,), jnp.bfloat16)}}
    batch = jnp.concatenate([jnp.array([256.0], jnp.float32),
                             jnp.ones((7,), jnp.float32)])

    _, g32 = jax.jit(lambda p: forward_backward_no_pipelining(
        model, p, batch, num_microbatches=8, checkpoint_stages=False))(
        params)
    _, gb16 = jax.jit(lambda p: forward_backward_no_pipelining(
        model, p, batch, num_microbatches=8, checkpoint_stages=False,
        fp32_grad_accum=False))(params)
    assert g32["head"]["w"].dtype == jnp.float32
    assert gb16["head"]["w"].dtype == jnp.bfloat16
    # exact mean: (256 + 7) / 8 = 32.875; bf16 accumulation loses the +1s
    np.testing.assert_allclose(float(g32["head"]["w"][0]), 32.875)
    assert float(gb16["head"]["w"][0]) == 32.0

"""DDP / SyncBatchNorm / LARC tests on the 8-virtual-device CPU mesh —
the reference needs >= 2 GPUs for these (``tests/distributed/``); here the
mesh rig makes them L0 unit tests."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.models import layers as L
from apex_tpu.parallel import (
    LARC, DistributedDataParallel, SyncBatchNorm, convert_syncbn_model,
)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.transformer import parallel_state as ps


def dp_mesh():
    return ps.initialize_model_parallel()  # pure data-parallel over 8


def shard_map(f, mesh, in_specs, out_specs):
    return ps.shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs)


def test_ddp_allreduce_matches_full_batch_grads():
    mesh = dp_mesh()
    ddp = DistributedDataParallel()
    k = jax.random.PRNGKey(0)
    w = jax.random.normal(k, (16, 4))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = jax.random.normal(jax.random.PRNGKey(2), (32, 4))

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    full_grad = jax.grad(loss)(w, x, y)

    def per_shard(w, x, y):
        w = ddp.local_replica({"w": w})["w"]  # torch-style per-rank replica
        g = jax.grad(loss)(w, x, y)           # local-shard mean grad
        return ddp.allreduce_grads({"w": g})["w"]

    ddp_grad = shard_map(
        per_shard, mesh,
        in_specs=(P(), P(ps.DATA_AXIS), P(ps.DATA_AXIS)),
        out_specs=P())(w, x, y)
    # mean-of-shard-means == full-batch mean when shards are equal size
    np.testing.assert_allclose(np.asarray(ddp_grad), np.asarray(full_grad),
                               rtol=1e-5, atol=1e-6)


def test_ddp_allreduce_always_fp32_keeps_dtype():
    mesh = dp_mesh()
    ddp = DistributedDataParallel(allreduce_always_fp32=True)

    def f(g):
        return ddp.allreduce_grads({"g": g})["g"]

    g = jnp.full((8, 4), 0.25, jnp.bfloat16)
    out = shard_map(f, mesh, in_specs=P(ps.DATA_AXIS), out_specs=P())(g)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 0.25)


def test_ddp_no_average_sums():
    mesh = dp_mesh()
    ddp = DistributedDataParallel(gradient_average=False)

    def f(g):
        return ddp.allreduce_grads({"g": g})["g"]

    g = jnp.ones((8, 4))
    out = shard_map(f, mesh, in_specs=P(ps.DATA_AXIS), out_specs=P())(g)
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_ddp_broadcast_params():
    mesh = dp_mesh()
    ddp = DistributedDataParallel()

    def f(seed):
        # every rank fabricates different params; broadcast must equalize
        rank = jax.lax.axis_index(ps.DATA_AXIS)
        p = {"w": jnp.full((4, 4), rank + 1.0)}
        p = ddp.broadcast_params(p)
        return p["w"][None]

    seeds = jnp.arange(8)
    out = shard_map(f, mesh, in_specs=P(ps.DATA_AXIS),
                    out_specs=P(ps.DATA_AXIS))(seeds)
    np.testing.assert_allclose(np.asarray(out), 1.0)  # rank 0's value


def test_sync_batchnorm_matches_global_bn():
    """SyncBN over 8 shards == plain BN over the gathered batch (the
    reference's two_gpu_unit_test assertion)."""
    mesh = dp_mesh()
    bn = SyncBatchNorm(6)
    params, state = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 5, 5, 6)) * 3 + 1

    y_ref, st_ref = L.batchnorm(params, state, x, train=True)

    def f(params, state, x):
        y, st = bn.apply(params, state, x, train=True)
        return y, st

    y_sync, st_sync = shard_map(
        f, mesh,
        in_specs=(P(), P(), P(ps.DATA_AXIS)),
        out_specs=(P(ps.DATA_AXIS), P()))(params, state, x)
    np.testing.assert_allclose(np.asarray(y_sync), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_sync["mean"]),
                               np.asarray(st_ref["mean"]), rtol=1e-5,
                               atol=1e-6)
    # biased-vs-unbiased var differs slightly between global (n) and
    # per-shard (n/8) corrections; allow that tolerance
    np.testing.assert_allclose(np.asarray(st_sync["var"]),
                               np.asarray(st_ref["var"]), rtol=2e-2)


def test_sync_batchnorm_no_affine():
    """affine=False: pure normalization — zero mean, unit var, no
    scale/bias params (reference supports this; round-2 verdict gap)."""
    mesh = dp_mesh()
    bn = SyncBatchNorm(6, affine=False)
    params, state = bn.init()
    assert params is None
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 6)) * 3 + 1

    y, _ = shard_map(
        lambda s, x: bn.apply(None, s, x, train=True), mesh,
        in_specs=(P(), P(ps.DATA_AXIS)), out_specs=(P(ps.DATA_AXIS), P()))(
        state, x)
    y = np.asarray(y)
    np.testing.assert_allclose(y.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.var(0), 1.0, rtol=1e-3)


def test_sync_batchnorm_no_running_stats_uses_batch_stats_in_eval():
    """track_running_stats=False: batch statistics in eval too (torch
    semantics), synchronized across ranks."""
    mesh = dp_mesh()
    bn = SyncBatchNorm(4, track_running_stats=False)
    params, state = bn.init()
    assert state is None
    x = jax.random.normal(jax.random.PRNGKey(4), (16, 4)) * 2 + 3

    y, new_state = shard_map(
        lambda p, x: bn.apply(p, None, x, train=False), mesh,
        in_specs=(P(), P(ps.DATA_AXIS)), out_specs=(P(ps.DATA_AXIS), P()))(
        params, x)
    assert new_state is None
    y = np.asarray(y)
    # eval with batch stats: output normalized over the GLOBAL batch
    np.testing.assert_allclose(y.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.var(0), 1.0, rtol=1e-3)


def test_sync_batchnorm_channel_first():
    """channel_last=False (NCHW): matches the channel-last path on the
    transposed input."""
    mesh = dp_mesh()
    bn_cl = SyncBatchNorm(6)
    bn_cf = SyncBatchNorm(6, channel_last=False)
    params, state = bn_cl.init()
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 5, 5, 6)) * 3 + 1
    x_cf = jnp.moveaxis(x, -1, 1)  # NCHW

    run = lambda bn, x: shard_map(  # noqa: E731
        lambda p, s, x: bn.apply(p, s, x, train=True), mesh,
        in_specs=(P(), P(), P(ps.DATA_AXIS)),
        out_specs=(P(ps.DATA_AXIS), P()))(params, state, x)
    y_cl, st_cl = run(bn_cl, x)
    y_cf, st_cf = run(bn_cf, x_cf)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(y_cf, 1, -1)),
                               np.asarray(y_cl), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(st_cf["mean"]),
                               np.asarray(st_cl["mean"]), rtol=1e-6)


def test_convert_syncbn_model_binds_axis():
    from apex_tpu.models import apply_resnet
    sync_apply = convert_syncbn_model(apply_resnet)
    assert isinstance(sync_apply, functools.partial)
    assert sync_apply.keywords["axis_name"] == ps.DATA_AXIS


def test_larc_clip_formula():
    p = {"w": jnp.full((4,), 2.0)}
    g = {"w": jnp.full((4,), 0.1)}
    base = FusedSGD(lr=0.1, momentum=0.0)
    larc = LARC(base, trust_coefficient=0.02, clip=True)
    state = larc.init(p)
    new_p, _ = larc.step(g, p, state)

    p_norm = 4.0
    g_norm = 0.2
    adaptive = 0.02 * p_norm / (g_norm + 1e-8)  # 0.4
    ratio = min(adaptive / 0.1, 1.0)            # clipped to 1
    want = 2.0 - 0.1 * 0.1 * ratio
    np.testing.assert_allclose(np.asarray(new_p["w"]), want, rtol=1e-5)

    # unclipped mode: effective layer lr = base_lr * adaptive (reference
    # multiplies the grad by adaptive_lr, inner step applies base lr)
    larc2 = LARC(FusedSGD(lr=0.1, momentum=0.0), trust_coefficient=0.02,
                 clip=False)
    new_p2, _ = larc2.step(g, p, larc2.init(p))
    want2 = 2.0 - 0.1 * adaptive * 0.1
    np.testing.assert_allclose(np.asarray(new_p2["w"]), want2, rtol=1e-4)

    # zero-grad leaves are untouched even with weight decay (reference
    # guards the wd fold behind nonzero norms)
    larc3 = LARC(FusedSGD(lr=0.1, momentum=0.0, weight_decay=0.0),
                 trust_coefficient=0.02, clip=True)
    zg = {"w": jnp.zeros((4,))}
    new_p3, _ = larc3.step(zg, p, larc3.init(p), weight_decay=0.5)
    np.testing.assert_allclose(np.asarray(new_p3["w"]), 2.0)


def test_ddp_bert_tiny_train_step():
    """BASELINE config #4 in miniature: BERT over DP-8 via shard_map —
    loss decreases and replicas stay bitwise identical."""
    from apex_tpu.models import apply_bert, bert_tiny, init_bert, mlm_loss
    from apex_tpu.optimizers import FusedAdam

    mesh = dp_mesh()
    cfg = bert_tiny()
    ddp = DistributedDataParallel()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    ids = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0,
                             cfg.vocab_size)
    mask = jnp.ones((16, 32), jnp.int32)

    def loss_fn(p, ids, mask):
        return mlm_loss(apply_bert(p, cfg, ids, mask)["mlm_logits"],
                        ids, mask)

    def per_shard(params, state, ids, mask):
        replica = ddp.local_replica(params)
        loss, grads = jax.value_and_grad(loss_fn)(replica, ids, mask)
        grads = ddp.allreduce_grads(grads)
        params, state = opt.step(grads, params, state)
        return params, state, jax.lax.pmean(loss, ps.DATA_AXIS)

    step = jax.jit(shard_map(
        per_shard, mesh,
        in_specs=(P(), P(), P(ps.DATA_AXIS), P(ps.DATA_AXIS)),
        out_specs=(P(), P(), P())))

    losses = []
    for _ in range(4):
        params, state, loss = step(params, state, ids, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_broadcast_params_exact_for_int_leaves():
    # masked-psum broadcast must not round-trip through fp32: an int32
    # value above 2^24 would silently lose low bits there
    mesh = dp_mesh()
    ddp = DistributedDataParallel()
    big = (1 << 24) + 1

    def f(rank_seed):
        tree = {
            "w": jnp.float32(1.5) + rank_seed,     # differs per rank
            "step": jnp.int32(big) + rank_seed.astype(jnp.int32),
            "flag": rank_seed < 1,                  # bool: True ONLY on rank 0
        }
        return ddp.broadcast_params(tree)

    seeds = jnp.arange(8, dtype=jnp.float32)
    out = shard_map(f, mesh, in_specs=(P(ps.DATA_AXIS),),
                    out_specs=P(ps.DATA_AXIS))(seeds)
    # every rank must now hold rank 0's exact values
    assert np.asarray(out["step"]).tolist() == [big] * 8
    np.testing.assert_array_equal(np.asarray(out["w"]), np.full(8, 1.5))
    assert np.asarray(out["flag"]).tolist() == [True] * 8

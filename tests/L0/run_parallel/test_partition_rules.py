"""Partition-rule engine tests (``apex_tpu.partition``): regex -> spec
matching semantics, the default GPT/BERT tables against the
hand-maintained references, optimizer/serving spec derivation from the
same table, the dp x tp x pp x cp mesh factory, and shard/gather
placement roundtrips on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.partition import (
    bert_rules,
    gpt_rules,
    kv_cache_rules,
    make_mesh,
    make_shard_and_gather_fns,
    match_partition_rules,
    optimizer_state_specs,
    rule_match_table,
    spec_axis_names,
    tree_paths,
)
from apex_tpu.transformer import parallel_state as ps


def _sds(shape, dtype="float32"):
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _flat(tree):
    return jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# matching semantics
# ---------------------------------------------------------------------------

def test_first_match_wins_and_search_is_unanchored():
    rules = (("w$", P("model", None)), ("a/w", P(None, "model")))
    tree = {"a": {"w": _sds((4, 4))}, "m": {"a": {"w": _sds((4, 4))}}}
    specs = match_partition_rules(rules, tree)
    # both leaves end in 'w': rule 0 wins everywhere, and the m/-prefixed
    # copy matches identically (the optimizer-family contract)
    assert specs["a"]["w"] == P("model", None)
    assert specs["m"]["a"]["w"] == P("model", None)


def test_scalar_leaves_replicate_without_rules():
    specs = match_partition_rules((), {"step": _sds(())})
    assert specs["step"] == P()


def test_unmatched_leaf_raises_with_path_and_shape():
    with pytest.raises(ValueError, match=r"a/w.*\(4, 8\)"):
        match_partition_rules((("nope", P()),), {"a": {"w": _sds((4, 8))}})


def test_tree_paths_and_match_table():
    tree = {"a": {"w": _sds((4,))}, "b": _sds((4,))}
    assert tree_paths(tree) == ["a/w", "b"]
    table = rule_match_table((("w", P(None)), ("zz", P())), tree)
    assert [(name, hits) for name, _, hits in table] == \
        [("a/w", [0]), ("b", [])]


def test_spec_axis_names_flattens_tuple_entries():
    assert spec_axis_names(P(("model", "data"), None)) == ["model", "data"]
    assert spec_axis_names(P(None, "model")) == ["model"]
    assert spec_axis_names(P()) == []


# ---------------------------------------------------------------------------
# default tables == hand-maintained references
# ---------------------------------------------------------------------------

def test_gpt_rules_reproduce_hand_specs():
    from apex_tpu.models.gpt import gpt_partition_specs, gpt_tiny, init_gpt

    cfg = gpt_tiny()
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    assert _flat(match_partition_rules(gpt_rules(), params)) == \
        _flat(gpt_partition_specs(cfg))


def test_bert_rules_reproduce_hand_specs():
    from apex_tpu.models.bert import (
        bert_partition_specs, bert_tiny, init_bert,
    )

    params = jax.eval_shape(
        lambda k: init_bert(k, bert_tiny()), jax.random.PRNGKey(0))
    assert _flat(match_partition_rules(bert_rules(), params)) == \
        _flat(bert_partition_specs(params))


def test_optimizer_state_specs_track_param_specs():
    from apex_tpu.models.gpt import gpt_tiny, init_gpt

    params = jax.eval_shape(
        lambda k: init_gpt(k, gpt_tiny()), jax.random.PRNGKey(0))
    base = _flat(match_partition_rules(gpt_rules(), params))
    fams = optimizer_state_specs(gpt_rules(), params)
    assert set(fams) == {"m", "v", "master"}
    for fam in fams:
        assert _flat(fams[fam]) == base


def test_cache_partition_specs_derive_from_rules():
    from apex_tpu.serving.cache import cache_partition_specs

    specs = cache_partition_specs()
    assert specs.k == P(None, None, ps.TENSOR_AXIS, None, None)
    assert specs.v == specs.k
    assert specs.lengths == P()
    # a custom table flows through
    flipped = ((r"(^|/)(k|v)$", P(None, None, None, ps.TENSOR_AXIS, None)),
               (r"(^|/)lengths$", P()))
    assert cache_partition_specs(flipped).k == \
        P(None, None, None, ps.TENSOR_AXIS, None)


def test_fused_adam_state_partition_specs():
    from apex_tpu.optimizers.fused_adam import FusedAdam

    param_specs = {"w": P("model", None), "b": P(None)}
    st = FusedAdam().state_partition_specs(param_specs)
    assert st.step == P()
    assert st.m == param_specs and st.v == param_specs
    with pytest.raises(ValueError, match="flat"):
        FusedAdam(use_flat_kernel=True).state_partition_specs(param_specs)


def test_distributed_adam_partition_spec_tensor_axis():
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )

    opt = DistributedFusedAdam(dp_size=2)
    assert opt.partition_spec().master == P(ps.DATA_AXIS, None)
    joint = opt.partition_spec(tensor_axis=ps.TENSOR_AXIS)
    assert joint.master == P((ps.TENSOR_AXIS, ps.DATA_AXIS), None)
    assert joint.m == joint.master and joint.v == joint.master
    assert joint.step == P()


# ---------------------------------------------------------------------------
# mesh factory
# ---------------------------------------------------------------------------

def test_make_mesh_installs_requested_degrees():
    mesh = make_mesh(dp=2, tp=2, pp=2, cp=1)
    assert dict(mesh.shape) == {"data": 2, "pipe": 2, "context": 1,
                                "model": 2}
    assert ps.get_mesh() is mesh
    assert ps.get_tensor_model_parallel_world_size() == 2


def test_make_mesh_rejects_oversubscription_and_bad_degrees():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(dp=4, tp=4)
    with pytest.raises(ValueError, match="positive"):
        make_mesh(dp=0)
    with pytest.raises(ValueError, match="exactly"):
        make_mesh(dp=2, tp=2, devices=jax.devices()[:2])


def test_initialize_model_parallel_validates_dp():
    with pytest.raises(ps.ParallelStateError, match="gives dp = 4"):
        ps.initialize_model_parallel(tensor_model_parallel_size_=2,
                                     data_parallel_size_=3)


# ---------------------------------------------------------------------------
# shard / gather fns
# ---------------------------------------------------------------------------

def test_shard_and_gather_roundtrip():
    mesh = make_mesh(dp=2, tp=2)
    tree = {"w": jnp.arange(32.0).reshape(4, 8),
            "b": jnp.arange(8.0)}
    specs = {"w": P(ps.TENSOR_AXIS, None), "b": P()}
    shard_fns, gather_fns = make_shard_and_gather_fns(specs, mesh)
    sharded = jax.tree_util.tree_map(lambda f, x: f(x), shard_fns, tree)
    assert sharded["w"].sharding.spec == P(ps.TENSOR_AXIS, None)
    back = jax.tree_util.tree_map(lambda f, x: f(x), gather_fns, sharded)
    np.testing.assert_array_equal(np.asarray(back["w"]), tree["w"])
    np.testing.assert_array_equal(np.asarray(back["b"]), tree["b"])

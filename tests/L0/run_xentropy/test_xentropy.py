"""Fused softmax-cross-entropy golden tests (ref pattern:
``apex/contrib/test/xentropy`` compares against ``F.cross_entropy``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.xentropy import (
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)


def _ref_loss(logits, labels, smoothing=0.0):
    x = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(x, axis=-1)
    n, v = x.shape
    nll = -jnp.take_along_axis(logp, jnp.maximum(labels, 0)[:, None],
                               1)[:, 0]
    smooth = -logp.mean(-1)
    loss = (1 - smoothing) * nll + smoothing * smooth
    return jnp.where(labels < 0, 0.0, loss)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_forward_matches_reference(dtype, smoothing):
    n, v = 64, 1000  # odd vocab exercises the padding/masking path
    logits = jax.random.normal(jax.random.PRNGKey(0), (n, v), dtype) * 3
    labels = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, v)
    out = softmax_cross_entropy_loss(logits, labels, smoothing)
    ref = _ref_loss(logits, labels, smoothing)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(out, ref, atol=tol, rtol=tol)


def test_ignored_labels_zero_loss_and_grad():
    n, v = 32, 257
    logits = jax.random.normal(jax.random.PRNGKey(2), (n, v))
    labels = jax.random.randint(jax.random.PRNGKey(3), (n,), 0, v)
    labels = labels.at[::4].set(-1)

    def total(x):
        return softmax_cross_entropy_loss(x, labels).sum()

    loss = softmax_cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(loss[::4], 0.0, atol=0)
    g = jax.grad(total)(logits)
    np.testing.assert_allclose(g[::4], 0.0, atol=0)
    assert float(jnp.abs(g[1]).sum()) > 0


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_reference(smoothing):
    n, v = 48, 500
    logits = jax.random.normal(jax.random.PRNGKey(4), (n, v)) * 2
    labels = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, v)
    w = jax.random.normal(jax.random.PRNGKey(6), (n,))

    g = jax.grad(lambda x: (softmax_cross_entropy_loss(x, labels,
                                                       smoothing) * w).sum()
                 )(logits)
    gr = jax.grad(lambda x: (_ref_loss(x, labels, smoothing) * w).sum()
                  )(logits)
    np.testing.assert_allclose(g, gr, atol=1e-5, rtol=1e-4)


def test_padding_idx_api():
    n, v = 16, 128
    logits = jax.random.normal(jax.random.PRNGKey(7), (n, v))
    labels = jnp.zeros((n,), jnp.int32)
    out = SoftmaxCrossEntropyLoss.apply(logits, labels, padding_idx=0)
    np.testing.assert_allclose(out, 0.0, atol=0)


def test_large_vocab_multi_tile():
    """Vocab spanning several lane tiles (BERT's 30522)."""
    n, v = 16, 30522
    logits = jax.random.normal(jax.random.PRNGKey(8), (n, v),
                               jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(9), (n,), 0, v)
    out = softmax_cross_entropy_loss(logits, labels)
    ref = _ref_loss(logits, labels)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)

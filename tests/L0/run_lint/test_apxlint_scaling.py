"""Scaling-tier (APX9xx) tests.

Same three layers as the other traced tiers:

- known-bad / known-clean *sweep entry* pairs per code: every checker
  must fire on a builder that seeds exactly its scale-variance bug and
  stay silent on the minimally-different clean twin;
- seeded-bug meta-tests: a hardcoded rank count survives the anchor
  shape and fires APX901 the moment the grid sweeps past it; a ZeRO
  state spec flipped to replicated (program seeded, contract held)
  fires APX903 at every swept shape;
- the repo registry itself must be populated, cover >= 6 mesh shapes,
  and lint clean — including the byte-exact per-mesh rows pinned in
  budgets.json.
"""

import os
import sys

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from apex_tpu.lint.scaling import (  # noqa: E402
    FULL_GRID, MeshShape, ScalingEntry, parse_tag, run_entries,
)
from apex_tpu.lint.scaling import registry as sreg  # noqa: E402
from apex_tpu.lint.traced.registry import _sds  # noqa: E402

MOD = "apex_tpu.lint"  # attribution target for synthetic entries

CP_GRID = (MeshShape(dp=1, tp=1, cp=2), MeshShape(dp=1, tp=1, cp=4))
DP_GRID = (MeshShape(dp=2), MeshShape(dp=4), MeshShape(dp=8))

#: a well-formed empty manifest — tests that exercise the per-mesh row
#: gate build their rows on top of this instead of reading the repo's
#: committed budgets.json
_EMPTY_MANIFEST = {"version": 1, "tolerance": 0.1, "entries": {}}


def _codes(entries, manifest=None):
    return [f.code for f in run_entries(entries, manifest=manifest)]


def _findings(entries, manifest=None):
    return run_entries(entries, manifest=manifest)


def _manifest_for(entry):
    """Stage the entry and pin its per-mesh rows, the way
    --write-budgets would."""
    from apex_tpu.lint.traced import budgets

    reports = [s.report for s in sreg.stage_entry(entry)]
    return budgets.build_manifest(reports, previous=_EMPTY_MANIFEST)


# ---------------------------------------------------------------------------
# grid
# ---------------------------------------------------------------------------

def test_mesh_shape_tags_round_trip():
    for shape in FULL_GRID:
        assert parse_tag(shape.tag) == shape
    assert MeshShape(dp=4, tp=2).tag == "dp4xtp2"
    assert MeshShape(dp=1, tp=1, cp=2).tag == "dp1xtp1xcp2"
    with pytest.raises(ValueError):
        parse_tag("dp4tp2")


def test_grid_covers_acceptance_floor():
    # the tier's contract: >= 6 distinct shapes, all on the 8-device
    # CPU world, sweeping dp, tp, and cp
    assert len(set(FULL_GRID)) >= 6
    assert all(s.devices <= 8 for s in FULL_GRID)
    assert {s.dp for s in FULL_GRID} >= {2, 4, 8}
    assert {s.tp for s in FULL_GRID} >= {1, 2}
    assert any(s.cp > 1 for s in FULL_GRID)


# ---------------------------------------------------------------------------
# APX901 — schedule isomorphism across shapes
# ---------------------------------------------------------------------------

def _ring_parts(shape, perm_of=None):
    """A context-ring halo step; ``perm_of`` overrides how the ppermute
    permutation is derived from the ring size (the seam APX901 guards)."""
    from apex_tpu.transformer import parallel_state as ps

    n = shape.cp
    perm = (perm_of or (lambda k: [(i, (i + 1) % k) for i in range(k)]))(n)

    def body(x):
        h = lax.ppermute(x, ps.CONTEXT_AXIS, perm=perm)
        return x + h

    fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                      out_specs=P(ps.CONTEXT_AXIS))
    return fn, (_sds((8, 4), "float32"),), None


def _ring_entry(name, build):
    return ScalingEntry(name, MOD, build=build, grid=CP_GRID,
                        checks=("schedule",))


def test_apx901_clean_ring_sweeps_clean():
    clean = _ring_entry("ring", lambda s: _ring_parts(s))
    assert _codes([clean]) == []


def test_apx901_reverse_ring_is_isomorphic():
    # shift(-1) at cp2 coincides with shift(+1); sweeping to cp4 must
    # not flag a consistently reversed ring
    rev = _ring_entry("rev", lambda s: _ring_parts(
        s, perm_of=lambda k: [(i, (i - 1) % k) for i in range(k)]))
    assert _codes([rev]) == []


def test_apx901_hardcoded_perm_fires_on_sweep():
    # [(0,1),(1,0)] is a legal 2-ring; at cp4 it is an explicit pair
    # list, not a rotation — the classic hardcoded mesh size
    bad = _ring_entry("hard", lambda s: _ring_parts(
        s, perm_of=lambda k: [(0, 1), (1, 0)]))
    findings = _findings([bad])
    assert any(f.code == "APX901" and "not scale-invariant"
               in f.message for f in findings), \
        [f.render() for f in findings]


def test_apx901_mesh_sized_structure_fires():
    # an extra collective that only exists at one swept size
    from apex_tpu.transformer import parallel_state as ps

    def build(shape):
        def body(x):
            y = lax.psum(x, ps.CONTEXT_AXIS)
            if shape.cp == 4:  # builder branches on the mesh size
                y = y + lax.pmax(x, ps.CONTEXT_AXIS)
            return y

        fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                          out_specs=P())
        return fn, (_sds((8, 4), "float32"),), None

    findings = _findings([_ring_entry("sized", build)])
    assert any(f.code == "APX901" and "not scale-invariant"
               in f.message for f in findings)


def test_apx901_perm_normalization_units():
    from apex_tpu.lint.scaling import isomorphism as iso

    assert iso._classify_perm(((0, 1), (1, 2), (2, 3), (3, 0)), 4) \
        == ("shift", 1, 4)
    assert iso._classify_perm(((0, 1), (1, 0)), 2) == ("shift", 1, 2)
    assert iso._classify_perm(((0, 1), (1, 0)), 4)[0] == "perm"
    assert iso._shift_equal(("shift", 1, 2), ("shift", 3, 4))
    assert not iso._shift_equal(("shift", 1, 4), ("shift", 3, 4))
    assert iso._shift_equal(("shift", 7, 8), ("shift", 3, 4))  # both -1


# ---------------------------------------------------------------------------
# APX902 — volume scaling law + per-mesh pinned rows
# ---------------------------------------------------------------------------

def _psum_parts(shape, rows=8):
    from apex_tpu.transformer import parallel_state as ps

    def body(x):
        return lax.psum(x, ps.CONTEXT_AXIS)

    fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                      out_specs=P())
    return fn, (_sds((rows * shape.cp, 4), "float32"),), None


def _vol_entry(name, build, model=None):
    return ScalingEntry(name, MOD, build=build, grid=CP_GRID,
                        checks=("volume",), volume_model=model)


def test_apx902_linear_law_fits_clean():
    # fixed local operand -> priced psum bytes linear in cp, matching
    # the declared one-term model; rows pinned from a fresh stage
    e = _vol_entry("lin", lambda s: _psum_parts(s),
                   model=lambda: {"psum": (("cp", lambda s: float(s.cp)),)})
    assert _codes([e], manifest=_manifest_for(e)) == []


def test_apx902_super_linear_misses_declared_law():
    # operand grows with cp -> priced bytes quadratic vs the declared
    # linear model
    e = _vol_entry("quad", lambda s: _psum_parts(s, rows=8 * s.cp),
                   model=lambda: {"psum": (("cp", lambda s: float(s.cp)),)})
    findings = _findings([e], manifest=_manifest_for(e))
    assert any(f.code == "APX902" and "does not follow the declared law"
               in f.message for f in findings), \
        [f.render() for f in findings]


def test_apx902_unmodeled_drift_guard():
    # same quadratic growth with NO declared model: the generic
    # super-linear guard along the cp axis must fire
    e = _vol_entry("drift", lambda s: _psum_parts(s, rows=8 * s.cp))
    findings = _findings([e], manifest=_manifest_for(e))
    assert any(f.code == "APX902" and "super-linearly" in f.message
               for f in findings)


def test_apx902_missing_and_drifted_rows():
    e = _vol_entry("rows", lambda s: _psum_parts(s),
                   model=lambda: {"psum": (("cp", lambda s: float(s.cp)),)})
    findings = _findings([e], manifest=_EMPTY_MANIFEST)
    missing = [f for f in findings if "no per-mesh budget row"
               in f.message]
    assert len(missing) == len(CP_GRID), \
        [f.render() for f in findings]

    pinned = _manifest_for(e)
    name = "rows@dp1xtp1xcp2"
    pinned["entries"][name]["collective_bytes"] += 1
    findings = _findings([e], manifest=pinned)
    assert any(f.code == "APX902" and "!= pinned" in f.message
               for f in findings)


def test_apx902_stale_row_and_missing_manifest():
    from apex_tpu.lint.scaling import volume

    stale = {"version": 1, "tolerance": 0.1, "entries": {
        "rows@dp64xtp1": {"hbm_bytes": 1, "hbm_ceiling": 1,
                          "collective_bytes": 1, "peak_live_bytes": 1,
                          "peak_live_cap": 1},
        "a_base_row": {"hbm_bytes": 1, "hbm_ceiling": 1,
                       "collective_bytes": 1, "peak_live_bytes": 1,
                       "peak_live_cap": 1}}}
    findings = volume.check_manifest_rows(
        {"rows": {"dp1xtp1xcp2"}}, stale)
    assert len(findings) == 1  # the @-row, never the base row
    assert "rows@dp64xtp1" in findings[0].message

    findings = volume.check_manifest_rows({"rows": {"t"}}, None)
    assert len(findings) == 1 and "does not exist" in findings[0].message


def test_apx902_fit_recovers_exact_coefficients():
    from apex_tpu.lint.scaling.volume import fit

    shapes = DP_GRID
    basis = (("dp", lambda s: float(s.dp)), ("1", lambda s: 1.0))
    measured = [100.0 * s.dp + 7.0 for s in shapes]
    coeffs, preds = fit(basis, shapes, measured)
    assert coeffs[0] == pytest.approx(100.0)
    assert coeffs[1] == pytest.approx(7.0)
    assert preds == pytest.approx(measured)


# ---------------------------------------------------------------------------
# APX903 — per-device memory monotonicity + taint re-run
# ---------------------------------------------------------------------------

def _dp_parts(shape, local_rows=None):
    from apex_tpu.transformer import parallel_state as ps

    def body(x):
        if local_rows is not None:
            # per-device scratch whose size tracks the mesh — the bug
            x = x + jnp.zeros((local_rows(shape), 4), jnp.float32).sum()
        return lax.psum(x, ps.DATA_AXIS)

    fn = ps.shard_map(body, in_specs=(P(ps.DATA_AXIS),),
                      out_specs=P())
    return fn, (_sds((8 * shape.dp, 4), "float32"),), None


def _mem_entry(name, build, state_bytes=None):
    return ScalingEntry(name, MOD, build=build, grid=DP_GRID,
                        checks=("memory",), state_bytes=state_bytes)


def test_apx903_shrinking_state_and_peak_clean():
    e = _mem_entry("ok", lambda s: _dp_parts(s),
                   state_bytes=lambda s: 4096 // s.dp)
    assert _codes([e]) == []


def test_apx903_growing_state_bytes_fires():
    e = _mem_entry("grow", lambda s: _dp_parts(s),
                   state_bytes=lambda s: 1024 * s.dp)
    findings = _findings([e])
    assert any(f.code == "APX903" and "optimizer-state bytes"
               in f.message for f in findings), \
        [f.render() for f in findings]


def test_apx903_growing_peak_live_fires():
    e = _mem_entry("peak", lambda s: _dp_parts(
        s, local_rows=lambda shape: 64 * shape.dp))
    findings = _findings([e])
    assert any(f.code == "APX903" and "peak-live" in f.message
               for f in findings)


# ---------------------------------------------------------------------------
# APX904 — rule-table scale safety
# ---------------------------------------------------------------------------

def _table_entry(name, heads, extra_rules=()):
    from apex_tpu.transformer import parallel_state as ps

    rules = ((r"(^|/)heads$", P(None, ps.TENSOR_AXIS)),
             (r"(^|/)bias$", P())) + tuple(extra_rules)
    trees = {"params": {"heads": _sds((4, heads, 16), "float32"),
                        "bias": _sds((16,), "float32")}}
    return ScalingEntry(name, MOD, checks=("tables",),
                        rules=lambda: rules, trees=lambda: trees,
                        grid=FULL_GRID)


def test_apx904_indivisible_head_axis_fires():
    # heads=2 divides tp<=2 but not the swept tp=4 — the exact bug
    # class the sweep exists to catch before an 8-chip pod does
    findings = _findings([_table_entry("h2", heads=2)])
    assert any(f.code == "APX904" and "does not divide" in f.message
               and "dp2xtp4" in f.message for f in findings), \
        [f.render() for f in findings]


def test_apx904_divisible_head_axis_clean():
    assert _codes([_table_entry("h8", heads=8)]) == []


def test_apx904_dead_rule_recoded_from_apx701():
    findings = _findings([_table_entry(
        "dead", heads=8,
        extra_rules=((r"(^|/)nonexistent$", P()),))])
    assert any(f.code == "APX904" and "dead rule" in f.message
               for f in findings)


def test_draft_gpt_medium_heads_divide_swept_tp():
    # regression for the real APX904 finding this tier surfaced: the
    # medium drafter shipped num_heads=2, indivisible at swept tp=4 —
    # its KV-cache head axis must divide every tp the grid sweeps
    from apex_tpu.models.gpt import draft_gpt_medium

    cfg = draft_gpt_medium()
    for tp in {s.tp for s in FULL_GRID}:
        assert cfg.num_heads % tp == 0, (cfg.num_heads, tp)
    # and the registered table entry is clean end-to-end
    entries = [e for e in sreg.repo_entries()
               if e.name == "gpt_draft_medium_rules_scale"]
    assert len(entries) == 1
    assert _codes(entries) == []


# ---------------------------------------------------------------------------
# seeded-bug meta-tests
# ---------------------------------------------------------------------------

def test_seeded_hardcoded_rank_count_fires_apx901():
    """A schedule gated on ``axis_index < 2``: uniform (and clean) on
    the 2-ring anchor shape, divergent the moment the sweep reaches
    cp=4 — the APX511 re-issue fires under the shape tag."""
    from apex_tpu.transformer import parallel_state as ps

    def build(shape):
        def body(x):
            i = lax.axis_index(ps.CONTEXT_AXIS)
            return lax.cond(
                i < 2,  # hardcoded rank count
                lambda v: lax.psum(v, ps.CONTEXT_AXIS),
                lambda v: v * 2.0, x)

        fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                          out_specs=P(ps.CONTEXT_AXIS))
        return fn, (_sds((8, 4), "float32"),), None

    findings = _findings([_ring_entry("ranks", build)])
    tagged = [f for f in findings if f.code == "APX901"
              and "[dp1xtp1xcp4]" in f.message]
    assert tagged, [f.render() for f in findings]
    # the anchor shape alone would have passed
    anchor = ScalingEntry("anchor", MOD, build=build,
                          grid=(MeshShape(dp=1, tp=1, cp=2),),
                          checks=("schedule",))
    assert _codes([anchor]) == []


def test_seeded_zero_spec_flip_fires_apx903():
    """A ZeRO-style step whose optimizer state the program wires
    replicated (every rank keeps the full buffer and dynamic-updates
    its slice) while the declared contract still says row-sharded —
    the APX703 re-run fires APX903 at every swept shape."""
    from apex_tpu.transformer import parallel_state as ps

    def parts(shape, flipped):
        dp = shape.dp
        rows = 64 * dp  # global state rows

        def step_sharded(m, g):
            gs = lax.psum_scatter(g, ps.DATA_AXIS,
                                  scatter_dimension=0, tiled=True)
            return m + gs

        def step_replicated(m, g):
            gs = lax.psum_scatter(g, ps.DATA_AXIS,
                                  scatter_dimension=0, tiled=True)
            i = lax.axis_index(ps.DATA_AXIS)
            off = i * gs.shape[0]
            mine = lax.dynamic_slice_in_dim(m, off, gs.shape[0], 0)
            return lax.dynamic_update_slice_in_dim(
                m, mine + gs, off, 0)

        contract = (P(ps.DATA_AXIS), P(ps.DATA_AXIS))  # the rule table
        wired = (P(), P(ps.DATA_AXIS)) if flipped else contract
        fn = ps.shard_map(
            step_replicated if flipped else step_sharded,
            in_specs=wired, out_specs=wired[0])
        # state is 1/dp of the grads either way; only its wiring flips
        args = (_sds((rows // dp, 4), "float32"),
                _sds((rows, 4), "float32"))
        return fn, args, contract

    bad = _mem_entry("flip", lambda s: parts(s, flipped=True))
    findings = _findings([bad])
    tagged = [f for f in findings if f.code == "APX903"
              and "does not shard what the table says" in f.message]
    assert len(tagged) == len(DP_GRID), \
        [f.render() for f in findings]
    assert all(f"[{s.tag}]" in f.message
               for s, f in zip(DP_GRID, tagged))

    clean = _mem_entry("noflip", lambda s: parts(s, flipped=False))
    assert _codes([clean]) == []


def test_stage_failure_is_apx100_not_silent():
    def broken(shape):
        raise RuntimeError("boom")

    findings = _findings([ScalingEntry(
        "broken", MOD, build=broken, grid=CP_GRID,
        checks=("schedule",))])
    assert [f.code for f in findings] == ["APX100"] * len(CP_GRID)
    assert "boom" in findings[0].message


# ---------------------------------------------------------------------------
# registry + CLI integration
# ---------------------------------------------------------------------------

def test_scaling_registry_populated_and_clean():
    entries = sreg.repo_entries()
    assert len(entries) >= 4, [e.name for e in entries]
    # both sweep archetypes present: a dp x tp program and a cp ring
    swept = [e for e in entries if e.build is not None]
    assert any(any(s.tp > 1 for s in e.grid) for e in swept)
    assert any(any(s.cp > 1 for s in e.grid) for e in swept)
    findings = sreg.check_repo()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_budgets_json_pins_per_mesh_rows():
    from apex_tpu.lint.traced import budgets

    manifest = budgets.load_manifest()
    assert manifest is not None
    rows = {n for n in manifest["entries"] if "@" in n}
    # every swept shape of every program entry has its pinned row
    for e in sreg.repo_entries():
        if e.build is None:
            continue
        base = e.budget_name or e.name
        for s in e.grid:
            assert f"{base}@{s.tag}" in rows, (base, s.tag)


def test_cost_tier_ignores_per_mesh_rows():
    # base cost reports alone must not flag the @-rows as stale
    from apex_tpu.lint.traced import budgets

    manifest = {"version": 1, "tolerance": 0.1, "entries": {
        "zzz@dp2xtp1": {"hbm_bytes": 1, "hbm_ceiling": 1,
                        "collective_bytes": 1, "peak_live_bytes": 1,
                        "peak_live_cap": 1}}}
    assert budgets.check([], manifest) == []


def test_cli_codes_apx9_glob_enables_tier(monkeypatch, capsys):
    from apex_tpu.lint import scaling
    from apex_tpu.lint.__main__ import main

    # a fast known-bad registry: the glob must reach it end-to-end
    monkeypatch.setattr(scaling, "repo_entries",
                        lambda: [_table_entry("h2", heads=2)])
    assert main(["--no-trace", "--codes", "APX9*"]) == 1
    out = capsys.readouterr().out
    assert "APX904" in out and "does not divide" in out
    # without the glob the same registry is never consulted
    assert main(["--no-trace"]) == 0


def test_cli_scaling_flag(monkeypatch):
    from apex_tpu.lint import scaling
    from apex_tpu.lint.__main__ import main

    monkeypatch.setattr(scaling, "repo_entries",
                        lambda: [_table_entry("h8", heads=8)])
    assert main(["--no-trace", "--scaling"]) == 0
    monkeypatch.setattr(scaling, "repo_entries",
                        lambda: [_table_entry("h2", heads=2)])
    assert main(["--no-trace", "--scaling"]) == 1

"""Trace-tier (APX5xx) tests.

Three layers, per the tier's contract:

- known-bad / known-clean *entry* pairs: every verifier must fire on a
  builder that seeds exactly its invariant violation and stay silent on
  the minimally-different clean twin;
- seeded-bug meta-tests: a scratch copy of a real repo module gets one
  invariant textually broken (``fp32_grad_accum`` default flipped, the
  adam ``input_output_aliases`` dict emptied), is imported under a
  throwaway name, traced, and the verifier must fire — while the
  unmodified module stays silent under the identical harness;
- the repo registry itself must be populated (>= 15 entries) and clean.
"""

import importlib.util
import os
import sys

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu.lint.traced.registry import (  # noqa: E402
    TraceEntry, _sds, run_entries,
)

MOD = "apex_tpu.lint"  # attribution target for synthetic entries


def _codes(entries):
    return [f.code for f in run_entries(entries)]


def _msgs(entries):
    return [f.message for f in run_entries(entries)]


# ---------------------------------------------------------------------------
# APX501 — sub-fp32 accumulators
# ---------------------------------------------------------------------------

def _b501_bad():
    fn = lambda x: jnp.cumsum(x, axis=-1)  # bf16 prefix accumulator
    return fn, (_sds((4, 2048), "bfloat16"),)


def _b501_clean():
    # jnp.sum upcasts bf16 to an fp32 accumulator on its own — the
    # clean twin of the same reduction
    fn = lambda x: jnp.sum(x, axis=-1)
    return fn, (_sds((4, 2048), "bfloat16"),)


def test_apx501_bad_and_clean():
    assert _codes([TraceEntry("bad", MOD, _b501_bad)]) == ["APX501"]
    assert _codes([TraceEntry("clean", MOD, _b501_clean)]) == []


def test_apx501_short_reductions_exempt():
    # a 64-long bf16 bias-wgrad-style fold is below the accumulation-
    # length threshold and must not fire
    def build():
        fn = lambda x: jnp.sum(x, axis=0, dtype=jnp.bfloat16)
        return fn, (_sds((64, 128), "bfloat16"),)

    assert _codes([TraceEntry("short", MOD, build)]) == []


def test_apx501_residual_carry_not_flagged():
    # x_{i+1} = x_i + f(x_i) is a residual, not an accumulator
    def build():
        def f(x, ws):
            def body(c, w):
                return c + jnp.tanh(c * w), None
            return jax.lax.scan(body, x, ws)[0]
        return f, (_sds((8, 16), "bfloat16"), _sds((4,), "bfloat16"))

    assert _codes([TraceEntry("residual", MOD, build)]) == []


def test_apx501_bf16_scan_accumulator_flagged():
    # acc_{i+1} = acc_i + g(xs_i) in bf16 is the bug
    def build():
        def f(xs):
            def body(acc, x):
                return acc + x * 2.0, None
            return jax.lax.scan(body, jnp.zeros((16,), jnp.bfloat16),
                                xs)[0]
        return f, (_sds((8, 16), "bfloat16"),)

    assert _codes([TraceEntry("accum", MOD, build)]) == ["APX501"]


# ---------------------------------------------------------------------------
# APX502 — unscale / overflow-guard placement
# ---------------------------------------------------------------------------

def _amp_entry(build):
    return TraceEntry("amp", "apex_tpu.amp.frontend", build,
                      checks=("amp",))


def _b502_noguard():
    def step(scale, p, x):
        g = jax.grad(lambda q: jnp.sum((q * x) ** 2) * scale)(p)
        g = g / scale
        return (p - 0.1 * g,), None  # no finite-flag select

    return step, (_sds((), "float32"), _sds((8,), "float32"),
                  _sds((8,), "float32"))


def _b502_nounscale():
    def step(scale, p, x):
        g = jax.grad(lambda q: jnp.sum((q * x) ** 2) * scale)(p)
        fin = jnp.isfinite(g).all()
        return (jnp.where(fin, p - 0.1 * g, p),), None  # scaled grads

    return step, (_sds((), "float32"), _sds((8,), "float32"),
                  _sds((8,), "float32"))


def _b502_clean():
    def step(scale, p, x):
        g = jax.grad(lambda q: jnp.sum((q * x) ** 2) * scale)(p)
        g = g / scale
        fin = jnp.isfinite(g).all()
        return (jnp.where(fin, p - 0.1 * g, p),), None

    return step, (_sds((), "float32"), _sds((8,), "float32"),
                  _sds((8,), "float32"))


def test_apx502_bad_and_clean():
    msgs = _msgs([_amp_entry(_b502_noguard)])
    assert len(msgs) == 1 and "overflow check" in msgs[0]
    msgs = _msgs([_amp_entry(_b502_nounscale)])
    assert len(msgs) == 1 and "missing unscale" in msgs[0]
    assert _codes([_amp_entry(_b502_clean)]) == []


# ---------------------------------------------------------------------------
# APX503 — materialization blowup
# ---------------------------------------------------------------------------

def _b503_bad():
    def f(q, k):
        s = jnp.einsum("sd,td->st", q.astype(jnp.float32),
                       k.astype(jnp.float32))  # (2048, 2048) fp32
        return jax.nn.softmax(s, axis=-1).sum()

    return f, (_sds((2048, 32), "bfloat16"), _sds((2048, 32), "bfloat16"))


def _b503_clean():
    # the chunked twin: 64-row score tiles stay under the floor
    def f(q, k):
        kf = k.astype(jnp.float32)

        def chunk(acc, qc):
            s = qc.astype(jnp.float32) @ kf.T  # (64, 2048) = 512 KiB
            return acc + jax.nn.softmax(s, axis=-1).sum(), None

        qs = q.reshape(32, 64, 32)
        return jax.lax.scan(chunk, jnp.float32(0.0), qs)[0]

    return f, (_sds((2048, 32), "bfloat16"), _sds((2048, 32), "bfloat16"))


def test_apx503_bad_and_clean():
    bad = TraceEntry("bad", MOD, _b503_bad, checks=("memory",))
    clean = TraceEntry("clean", MOD, _b503_clean, checks=("memory",))
    assert _codes([bad]) == ["APX503"]
    assert _codes([clean]) == []


# ---------------------------------------------------------------------------
# APX511 — communication-schedule simulation
# ---------------------------------------------------------------------------

def _mesh_cp2():
    from apex_tpu.transformer import parallel_state as ps

    ps.initialize_model_parallel(context_parallel_size_=2,
                                 devices=jax.devices()[:2])


def _sched_entry(name, build):
    return TraceEntry(name, "apex_tpu.transformer.parallel_state", build,
                      checks=("schedule",), mesh=_mesh_cp2, min_devices=2)


def _b511_divergent():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    def body(x):
        i = jax.lax.axis_index(ps.CONTEXT_AXIS)
        return jax.lax.cond(
            i == 0,
            lambda v: jax.lax.psum(v, ps.CONTEXT_AXIS),
            lambda v: v * 2.0, x)

    fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                      out_specs=P(ps.CONTEXT_AXIS))
    return fn, (_sds((8, 4), "float32"),)


def _b511_clean():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    def body(x):
        # rank-dependent *math* with a rank-independent schedule
        i = jax.lax.axis_index(ps.CONTEXT_AXIS)
        y = jnp.where(i == 0, x * 2.0, x)
        return jax.lax.psum(y, ps.CONTEXT_AXIS)

    fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                      out_specs=P())
    return fn, (_sds((8, 4), "float32"),)


def _b511_bad_perm():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    def body(x):
        # duplicated destination: both ranks send into rank 1
        return jax.lax.ppermute(x, ps.CONTEXT_AXIS,
                                perm=((0, 1), (1, 1)))

    fn = ps.shard_map(body, in_specs=(P(ps.CONTEXT_AXIS),),
                      out_specs=P(ps.CONTEXT_AXIS))
    return fn, (_sds((8, 4), "float32"),)


def _skip_if_few_devices(n=2):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def test_apx511_divergent_schedule():
    _skip_if_few_devices()
    msgs = _msgs([_sched_entry("bad", _b511_divergent)])
    assert len(msgs) == 1 and "diverges" in msgs[0], msgs


def test_apx511_clean_schedule():
    _skip_if_few_devices()
    assert _codes([_sched_entry("clean", _b511_clean)]) == []


def test_apx511_malformed_ppermute():
    _skip_if_few_devices()
    findings = run_entries([_sched_entry("perm", _b511_bad_perm)])
    assert any(f.code == "APX511" and "duplicated" in f.message
               for f in findings), [f.render() for f in findings]


# ---------------------------------------------------------------------------
# APX512 — verified aliasing
# ---------------------------------------------------------------------------

def _alias_entry(name, build, min_pairs):
    return TraceEntry(name, "apex_tpu.multi_tensor_apply.kernels", build,
                      checks=("aliases",), min_alias_pairs=min_pairs)


def _b512_severed():
    from apex_tpu.multi_tensor_apply import kernels as K

    def f(g, p, m, v):
        return K.flat_adam(g, p * 1.0, m, v, lr=1e-3, beta1=0.9,
                           beta2=0.99, eps=1e-8, step=1,
                           weight_decay=0.0, interpret=True)

    buf = _sds((8192, 128), "float32")
    return f, (buf, buf, buf, buf)


def _b512_clean():
    from apex_tpu.multi_tensor_apply import kernels as K

    def f(g, p, m, v):
        return K.flat_adam(g, p, m, v, lr=1e-3, beta1=0.9, beta2=0.99,
                           eps=1e-8, step=1, weight_decay=0.0,
                           interpret=True)

    buf = _sds((8192, 128), "float32")
    return f, (buf, buf, buf, buf)


def _b512_no_pairs():
    fn = lambda x: x * 2.0  # no pallas_call at all
    return fn, (_sds((8,), "float32"),)


def test_apx512_severed_and_clean():
    msgs = _msgs([_alias_entry("bad", _b512_severed, 3)])
    assert any("produced by 'mul'" in m for m in msgs), msgs
    assert _codes([_alias_entry("clean", _b512_clean, 3)]) == []


def test_apx512_dropped_pairs():
    msgs = _msgs([_alias_entry("none", _b512_no_pairs, 1)])
    assert len(msgs) == 1 and "dropped" in msgs[0]


def _b512_donation_clean():
    # a donated buffer with a same-aval output: the donation lands and
    # counts toward min_alias_pairs
    step = jax.jit(lambda c, x: (c + x, jnp.sum(x)), donate_argnums=0)
    fn = lambda c, x: step(c, x)
    return fn, (_sds((64, 32), "float32"), _sds((64, 32), "float32"))


def _b512_donation_orphaned():
    # the donated operand has no shape/dtype-matching output — XLA
    # silently discards the donation
    step = jax.jit(lambda c, x: jnp.sum(c + x), donate_argnums=0)
    fn = lambda c, x: step(c, x)
    return fn, (_sds((64, 32), "float32"), _sds((64, 32), "float32"))


def test_apx512_donation_counts_toward_pairs():
    assert _codes([_alias_entry("don", _b512_donation_clean, 1)]) == []


def test_apx512_orphaned_donation_fires():
    msgs = _msgs([_alias_entry("orphan", _b512_donation_orphaned, 0)])
    assert len(msgs) == 1 and "discards the donation" in msgs[0], msgs


# ---------------------------------------------------------------------------
# seeded-bug meta-tests over scratch copies of real modules
# ---------------------------------------------------------------------------

def _scratch_import(src_path, transform, tmp_path, name):
    txt = open(src_path, encoding="utf-8").read()
    seeded = transform(txt)
    assert seeded != txt, "seed transform did not apply"
    p = os.path.join(str(tmp_path), name + ".py")
    with open(p, "w", encoding="utf-8") as fh:
        fh.write(seeded)
    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception:
        sys.modules.pop(name, None)
        raise
    return mod


def _bf16_params(tree):
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16)
        if jnp.issubdtype(s.dtype, jnp.floating) else s, tree)


def test_seeded_fp32_grad_accum_flip_fires_apx501(tmp_path):
    from apex_tpu.lint.traced import precision
    from apex_tpu.lint.traced.registry import _pp_args, _pp_model
    from apex_tpu.transformer.pipeline_parallel import schedules

    seeded = _scratch_import(
        schedules.__file__,
        lambda t: t.replace("fp32_grad_accum: bool = True",
                            "fp32_grad_accum: bool = False"),
        tmp_path, "schedules_seeded_apx501")

    model = _pp_model()
    params, mb = _pp_args(3, 4)
    params = _bf16_params(params)

    def trace(mod):
        fn = lambda p, b: mod.forward_backward_no_pipelining(
            model, p, b, num_microbatches=2)
        return jax.make_jaxpr(fn)(params, mb)

    bad = precision.check_reductions(trace(seeded), "x.py", "seeded")
    assert bad and all(f.code == "APX501" for f in bad)
    assert "fp32_grad_accum" in bad[0].message
    # identical harness, unmodified module: silent
    assert precision.check_reductions(trace(schedules), "x.py",
                                      "clean") == []


def test_seeded_alias_drop_fires_apx512(tmp_path):
    from apex_tpu.lint.traced import aliases
    from apex_tpu.multi_tensor_apply import kernels

    seeded = _scratch_import(
        kernels.__file__,
        lambda t: t.replace("input_output_aliases={2: 0, 3: 1, 4: 2},",
                            "input_output_aliases={},"),
        tmp_path, "kernels_seeded_apx512")

    buf = _sds((8192, 128), "float32")

    def trace(mod):
        fn = lambda g, p, m, v: mod.flat_adam(
            g, p, m, v, lr=1e-3, beta1=0.9, beta2=0.99, eps=1e-8,
            step=1, weight_decay=0.0, interpret=True)
        return jax.make_jaxpr(fn)(buf, buf, buf, buf)

    bad = aliases.check(trace(seeded), "x.py", "seeded",
                        min_alias_pairs=3)
    assert [f.code for f in bad] == ["APX512"]
    assert "dropped" in bad[0].message
    assert aliases.check(trace(kernels), "x.py", "clean",
                         min_alias_pairs=3) == []


# ---------------------------------------------------------------------------
# registry + engine integration
# ---------------------------------------------------------------------------

def test_trace_registry_populated_and_clean():
    from apex_tpu.lint import traced

    entries = traced.repo_entries()
    assert len(entries) >= 15, len(entries)
    findings = traced.check_repo()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_trace_failure_is_apx100_not_silent():
    def broken():
        raise RuntimeError("boom")

    findings = run_entries([TraceEntry("broken", MOD, broken)])
    assert [f.code for f in findings] == ["APX100"]
    assert "broken" in findings[0].message


def test_trace_findings_pass_suppression_machinery(tmp_path):
    # engine attribution: a trace finding lands on the module file and
    # a file-level disable-file comment suppresses it
    from apex_tpu.lint import Finding
    from apex_tpu.lint.engine import _apply_suppressions

    mod = tmp_path / "fake_mod.py"
    mod.write_text("# apxlint: disable-file=APX501\nx = 1\n")
    kept = _apply_suppressions(
        [Finding("APX501", str(mod), 1, "seeded"),
         Finding("APX503", str(mod), 1, "kept")],
        {})
    assert [f.code for f in kept] == ["APX503"]

"""Cost-tier (APX6xx) tests.

Four layers, per the tier's contract:

- interpreter unit tests: exact read/write/flop/peak accounting on
  tiny synthetic programs, donation crediting (a donated cache counts
  once plus its in-place update delta), and the collective-volume fold
  over APX511 footprints;
- known-bad / known-clean pairs per code: a manifest is built from a
  clean report and each of APX601-604 must fire on a minimally-
  regressed variant while the clean twin stays silent;
- manifest plumbing: round-trip through ``--write-budgets``'s writer,
  schema validation, and hand-tightened ceilings surviving regen;
- the repo itself: every registered entry must cost-analyze, the
  committed budgets.json must gate them clean, and the medium decode
  entry must agree with BASELINE.md r8's hand roofline within 10%.
"""

import dataclasses
import importlib.util
import os
import sys

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu.lint.traced import budgets, cost  # noqa: E402
from apex_tpu.lint.traced.registry import _sds  # noqa: E402


def _report(fn, args, entry="syn", path="mod.py"):
    return cost.compute(jax.make_jaxpr(fn)(*args), path, entry)


def _codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------------------
# interpreter units
# ---------------------------------------------------------------------------

def test_read_write_flops_exact():
    rep = _report(lambda x, y: x @ y,
                  (_sds((128, 64), "float32"), _sds((64, 32), "float32")))
    assert rep.read_bytes == (128 * 64 + 64 * 32) * 4
    assert rep.write_bytes == 128 * 32 * 4
    assert rep.delta_write_bytes == 0
    assert rep.flops == 2 * 128 * 32 * 64
    # everything lives at once: both operands plus the product
    assert rep.peak_live_bytes == rep.read_bytes + rep.write_bytes
    assert rep.collective_bytes == 0 and rep.per_collective == {}


def test_operands_charged_once():
    # x feeds two consumers — the roofline charges its bytes ONCE
    rep = _report(lambda x: (x * 2.0, x + 1.0),
                  (_sds((256, 128), "float32"),))
    assert rep.read_bytes == 256 * 128 * 4
    assert rep.write_bytes == 2 * 256 * 128 * 4


def test_donated_cache_counts_once():
    def step(cache, x):
        cache = jax.lax.dynamic_update_slice(cache, x, (0, 0))
        return cache, jnp.sum(x)

    args = (_sds((1024, 1024), "float32"), _sds((1, 1024), "float32"))
    donated = _report(jax.jit(step, donate_argnums=0), args)
    plain = _report(jax.jit(step), args)

    cache_b, row_b = 1024 * 1024 * 4, 1024 * 4
    # both read the full cache + the update row
    assert donated.read_bytes == plain.read_bytes == cache_b + row_b
    # donation: the cache output is absorbed, only the dus row is
    # written in place (plus the 4-byte scalar)
    assert donated.write_bytes == 4
    assert donated.delta_write_bytes == row_b
    # no donation: the updated cache is a full second buffer
    assert plain.write_bytes == cache_b + 4
    assert plain.delta_write_bytes == 0
    assert plain.hbm_total_bytes > donated.hbm_total_bytes
    # ...and peak-live sees the second buffer too
    assert plain.peak_live_bytes >= donated.peak_live_bytes + cache_b


def test_scan_multiplies_flops():
    def fn(w, xs):
        def body(c, x):
            return c, x @ w
        return jax.lax.scan(body, 0.0, xs)[1]

    rep = _report(fn, (_sds((16, 16), "float32"),
                       _sds((8, 4, 16), "float32")))
    assert rep.flops == 8 * (2 * 4 * 16 * 16)


def test_fold_footprint_pricing():
    coll = {}
    fp = [
        ("coll", "psum", ("tp",), None, 512),
        ("scan", 3, [
            ("coll", "ppermute", ("pp",), ([(0, 1), (1, 0)],), 128),
        ]),
        ("while",
         [("coll", "all_gather", ("tp",), None, 64)],
         [("coll", "psum", ("tp",), None, 32)]),
    ]
    cost._fold_footprint(fp, 2, {"tp": 4, "pp": 8}, coll)
    assert coll == {
        "psum": 2 * 512 * 4 + 2 * 32 * 4,      # bytes x axis size
        "ppermute": 2 * 3 * 128 * 2,           # bytes x hop count x scan
        "all_gather": 2 * 64 * 4,
    }


# ---------------------------------------------------------------------------
# APX601-604 — known-bad / known-clean against a built manifest
# ---------------------------------------------------------------------------

def _clean_and_manifest():
    rep = _report(lambda x: x * 2.0, (_sds((512, 128), "float32"),))
    return rep, budgets.build_manifest([rep])


def test_budget_clean_twin_silent():
    rep, manifest = _clean_and_manifest()
    assert budgets.check([rep], manifest) == []


def test_apx601_apx602_traffic_regression():
    rep, manifest = _clean_and_manifest()
    # same entry name, twice the traffic: over the 1.25x ceiling AND
    # outside the 10% drift band
    fat = _report(lambda x: (x * 2.0, x + 1.0),
                  (_sds((512, 128), "float32"),))
    findings = budgets.check([fat], manifest)
    # doubling the output also doubles what's live, so the peak cap
    # trips alongside the traffic ceiling and the drift band
    assert _codes(findings) == ["APX601", "APX602", "APX604"]
    assert "ceiling" in findings[0].message
    # a within-band wiggle (< 10%, < ceiling) stays silent on both
    small = dataclasses.replace(
        rep, write_bytes=rep.write_bytes + rep.hbm_total_bytes // 20)
    assert budgets.check([small], manifest) == []


def test_apx603_collective_mismatch_is_exact():
    rep, manifest = _clean_and_manifest()
    moved = dataclasses.replace(rep, per_collective={"psum": 64})
    findings = budgets.check([moved], manifest)
    assert _codes(findings) == ["APX603"]
    assert "psum" not in manifest["entries"]  # volume-only contract


def test_apx604_peak_live_over_cap():
    rep, manifest = _clean_and_manifest()
    cap = manifest["entries"][rep.entry]["peak_live_cap"]
    hot = dataclasses.replace(rep, peak_live_bytes=cap + 1)
    assert _codes(budgets.check([hot], manifest)) == ["APX604"]


def test_apx602_missing_entry_and_stale_manifest():
    rep, manifest = _clean_and_manifest()
    new = dataclasses.replace(rep, entry="unbudgeted")
    findings = budgets.check([new, rep], manifest)
    assert _codes(findings) == ["APX602"]
    assert "unbudgeted" in findings[0].message

    stale = budgets.check([], manifest)
    assert _codes(stale) == ["APX602"]
    assert "no longer registered" in stale[0].message
    assert stale[0].path.endswith("budgets.json")


def test_apx602_missing_or_malformed_manifest():
    rep, _ = _clean_and_manifest()
    missing = budgets.check([rep], None)
    assert _codes(missing) == ["APX602"]
    assert "--write-budgets" in missing[0].message

    bad = budgets.check([rep], {"version": 2, "entries": 3})
    assert _codes(bad) == ["APX602"]
    assert "schema" in bad[0].message


# ---------------------------------------------------------------------------
# manifest plumbing
# ---------------------------------------------------------------------------

def test_manifest_roundtrip_and_ceiling_preservation(tmp_path):
    rep, _ = _clean_and_manifest()
    path = os.path.join(str(tmp_path), "budgets.json")
    manifest = budgets.write_manifest([rep], path=path)
    assert budgets.validate(manifest) == []
    loaded = budgets.load_manifest(path)
    assert loaded == manifest
    assert budgets.check([rep], loaded, path=path) == []
    row = loaded["entries"][rep.entry]
    assert row["hbm_bytes"] == rep.hbm_total_bytes
    assert row["hbm_ceiling"] == int(rep.hbm_total_bytes * 1.25)

    # a reviewer tightens the ceiling by hand: regeneration keeps it
    loaded["entries"][rep.entry]["hbm_ceiling"] = 7
    import json
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(loaded, fh)
    regen = budgets.write_manifest([rep], path=path)
    assert regen["entries"][rep.entry]["hbm_ceiling"] == 7


def test_committed_manifest_is_valid():
    manifest = budgets.load_manifest()
    assert manifest is not None, "budgets.json must be committed"
    assert budgets.validate(manifest) == []


# ---------------------------------------------------------------------------
# the repo registry under the committed budgets
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_reports():
    from apex_tpu.lint.traced import (
        ensure_cpu_devices, repo_entries, run_entries,
    )
    ensure_cpu_devices()
    reports = []
    findings = run_entries(repo_entries(), run_checks=False,
                           cost_out=reports)
    assert findings == [], "\n".join(f.render() for f in findings)
    return reports


def test_repo_costs_clean_under_committed_budgets(repo_reports):
    assert len(repo_reports) >= 23
    findings = budgets.check(repo_reports, budgets.load_manifest())
    assert findings == [], "\n".join(f.render() for f in findings)


def test_medium_decode_matches_r8_hand_roofline(repo_reports):
    """BASELINE.md r8 derives the decode ceiling by hand: every param
    byte plus the parked K/V history per step. The interpreter must
    land within 10% of that independent derivation."""
    rep = {r.entry: r for r in repo_reports}["gpt_decode_step_medium"]

    from apex_tpu.models.gpt import GPTConfig, init_gpt
    cfg = GPTConfig(use_rope=True)
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
    param_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params))
    kv_bytes = (32 * cfg.num_layers * cfg.num_heads * 512
                * (cfg.hidden_size // cfg.num_heads) * 2 * 2)
    hand = param_bytes + kv_bytes
    assert abs(rep.hbm_total_bytes - hand) / hand < 0.10


# ---------------------------------------------------------------------------
# seeded-bug meta-test: drop the decode cache donation
# ---------------------------------------------------------------------------

def _scratch_import(src_path, transform, tmp_path, name):
    txt = open(src_path, encoding="utf-8").read()
    seeded = transform(txt)
    assert seeded != txt, "seed transform did not apply"
    p = os.path.join(str(tmp_path), name + ".py")
    with open(p, "w", encoding="utf-8") as fh:
        fh.write(seeded)
    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception:
        sys.modules.pop(name, None)
        raise
    return mod


def test_seeded_donation_removal_fires_apx601(tmp_path):
    """Strip ``donate_argnums=1`` from the decode jit: the KV cache now
    writes a full second buffer every step, which must blow through a
    manifest seeded from the donating version."""
    from apex_tpu.lint.traced.registry import _serving_args, _serving_cfg
    from apex_tpu.serving import decode

    seeded = _scratch_import(
        decode.__file__,
        lambda t: t.replace(
            "jax.jit(decode, donate_argnums=1)", "jax.jit(decode)"),
        tmp_path, "decode_seeded_apx601")

    # deep enough that the cache dominates the step's traffic (the
    # registry's 2x32 shape is param-bound and wouldn't clear the
    # 1.25x ceiling even doubled)
    cfg = _serving_cfg()
    params, cache = _serving_args(cfg, num_slots=8, max_len=256)
    args = (params, cache, _sds((8,), "int32"), _sds((8,), "bool"))

    def rep_of(mod):
        closed = jax.make_jaxpr(mod.make_decode_fn(cfg))(*args)
        return cost.compute(closed, "decode.py", "decode_step")

    clean, bad = rep_of(decode), rep_of(seeded)
    assert bad.hbm_total_bytes > clean.hbm_total_bytes
    # the un-donated cache is charged as a full extra write
    cache_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(cache))
    assert bad.write_bytes - clean.write_bytes >= cache_bytes // 2

    manifest = budgets.build_manifest([clean])
    assert budgets.check([clean], manifest) == []
    codes = _codes(budgets.check([bad], manifest))
    assert "APX601" in codes, codes

    sys.modules.pop("decode_seeded_apx601", None)

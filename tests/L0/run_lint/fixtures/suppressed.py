# apxlint: fixture
# The same violation as apx401_bad, silenced both ways the engine
# supports: an inline trailing comment and a standalone comment line
# directly above the flagged statement. Must lint clean.
import time

import jax


@jax.jit
def stamped(x):
    t = time.time()  # apxlint: disable=APX401
    # apxlint: disable=APX401
    u = time.time()
    return x * t * u

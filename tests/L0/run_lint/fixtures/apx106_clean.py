# apxlint: fixture
# Known-clean twin of apx106_bad.py: fp32 scale scratch and store, an
# fp32 preferred_element_type on the dequant dot, and an astype(int8)
# preceded by jnp.round in the same function. Must raise nothing.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _w8_body(x_ref, wq_ref, scale_ref, out_ref, new_scale_out,
             scale_scratch):
    w = wq_ref[...].astype(jnp.float32) * scale_ref[...]
    out_ref[...] = jnp.dot(x_ref[...], w,
                           preferred_element_type=jnp.float32)
    new_scale_out[...] = scale_ref[...]


def dequant_matmul(x, wq, scale):
    spec = pl.BlockSpec((128, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _w8_body,
        grid=(4,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct(x.shape, jnp.float32),
                   jax.ShapeDtypeStruct((128,), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((128,), jnp.float32)],
    )(x, wq, scale)


def quantize_rtn(t):
    scale = jnp.abs(t).max() / 127.0
    return jnp.clip(jnp.round(t / scale), -127, 127).astype(jnp.int8), scale

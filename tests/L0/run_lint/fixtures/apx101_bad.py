# apxlint: fixture
# Known-bad: _k writes m_out from m_ref (same stem) but the call only
# aliases operand 1 (x) — the missing {2: 1} entry must raise APX101.
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _k(sc_ref, x_ref, m_ref, x_out, m_out):
    x_out[:] = x_ref[:] * sc_ref[0, 0]
    m_out[:] = m_ref[:] + x_ref[:]


def step(sc, x, m):
    spec = pl.BlockSpec((256, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _k,
        grid=(4,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(x.shape, x.dtype),
                   jax.ShapeDtypeStruct(m.shape, m.dtype)],
        input_output_aliases={1: 0},
    )(sc, x, m)

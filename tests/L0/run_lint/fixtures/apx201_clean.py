# apxlint: fixture
# Known-clean: rank-dependent branches issue the SAME collective
# sequence (only the payload differs), and a config-static branch may
# diverge freely — neither raises APX201.
import jax
import jax.numpy as jnp
from jax import lax


def rank_dependent_payload(x):
    if lax.axis_index("data") == 0:
        y = lax.psum(x * 2.0, "data")
    else:
        y = lax.psum(jnp.zeros_like(x), "data")
    return y


def config_dependent_reduce(x, use_mean):
    if use_mean:
        return lax.pmean(x, "data")
    return x

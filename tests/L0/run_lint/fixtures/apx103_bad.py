# apxlint: fixture
# Known-bad: online-softmax statistics dropped to bf16 three ways —
# a bf16 m scratch tile, a bf16 lse output, and a store into l_ref that
# rounds through astype(bfloat16). Each must raise APX103.
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd(q_ref, k_ref, o_ref, lse_ref, m_ref, l_ref):
    m_ref[:] = jnp.maximum(m_ref[:], q_ref[:].max())
    l_ref[:] = (l_ref[:] + q_ref[:].sum()).astype(jnp.bfloat16)
    o_ref[:] = q_ref[:]
    lse_ref[:] = m_ref[:] + jnp.log(l_ref[:])


def attend(q, k):
    spec = pl.BlockSpec((128, 64), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _fwd,
        grid=(4,),
        in_specs=[spec, spec],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((q.shape[0], 128), jnp.bfloat16)),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16),
                        pltpu.VMEM((128, 128), jnp.float32)],
    )(q, k)

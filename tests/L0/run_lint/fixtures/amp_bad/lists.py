# apxlint: fixture
# Known-bad policy module: 'matmul' lives in two lists (APX301),
# 'softmax' is listed but neither wired nor declared UNWIRED (APX303),
# and 'linear' is declared UNWIRED while user.py intercepts it (APX304).
FP16_FUNCS = frozenset({"matmul", "linear"})

FP32_FUNCS = frozenset({"matmul", "softmax"})

CASTS = frozenset({"add"})

UNWIRED = frozenset({"add", "linear"})

# apxlint: fixture
# Known-bad wiring: 'bmm' is intercepted but listed nowhere (APX302);
# the 'linear' call makes amp_bad/lists.py's UNWIRED entry stale.
from apex_tpu.amp.autocast import cast_args


def matmul(a, b):
    a, b = cast_args("matmul", a, b)
    return a @ b


def linear(x, w):
    x, w = cast_args("linear", x, w)
    return x @ w


def bmm(a, b):
    a, b = cast_args("bmm", a, b)
    return a @ b

# apxlint: fixture
# Known-clean: the same serving host state consulted from plain host
# code (between ticks, not reachable from any traced root) is exactly
# how the scheduler uses it — no findings.
import jax

from apex_tpu.serving import ServingStats
from apex_tpu.serving.faults import FaultInjector

STATS = ServingStats()
INJECTOR = FaultInjector(rates={"decode_exec": 0.01})


def host_tick_report():
    return STATS.as_dict(), INJECTOR.counts


@jax.jit
def decode_body(logits):
    return logits * 2.0

# apxlint: fixture
# Known-clean wiring for amp_clean/lists.py.
from apex_tpu.amp.autocast import cast_args


def matmul(a, b):
    a, b = cast_args("matmul", a, b)
    return a @ b

# apxlint: fixture
# Known-clean policy module: disjoint lists, every op either wired in
# user.py or declared UNWIRED.
FP16_FUNCS = frozenset({"matmul"})

FP32_FUNCS = frozenset({"softmax"})

CASTS = frozenset({"add"})

UNWIRED = frozenset({"softmax", "add"})

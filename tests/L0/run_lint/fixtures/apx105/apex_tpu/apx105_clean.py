# apxlint: fixture
# Known-clean twin: mentions pallas_call in a docstring, a string, and
# a bare attribute reference, but never *calls* it — no kernel family
# here, so APX105 must stay silent even though the file sits under an
# apex_tpu/ path component.
"""Helper that merely documents how pl.pallas_call kernels register."""
from jax.experimental import pallas as pl

KERNEL_ENTRY = pl.pallas_call  # referenced, not called
NOTE = "wrap with pallas_call(kernel, ...) then add vmem + trace rows"

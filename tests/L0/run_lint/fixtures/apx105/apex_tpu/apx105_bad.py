# apxlint: fixture
# Known-bad: a real pallas_call kernel family living under an apex_tpu/
# path component that no VMEM Config and no TraceEntry names — APX105
# must fire exactly once, on the pallas_call line.
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _double_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0


def double(x):
    spec = pl.BlockSpec(x.shape, lambda: (0, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _double_kernel, in_specs=[spec], out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)

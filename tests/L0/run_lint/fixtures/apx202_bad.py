# apxlint: fixture
# Known-bad: "tensor" is not a mesh axis declared by parallel_state
# (the real axes are data/pipe/context/model) nor by any local Mesh.
# Must raise APX202.
from jax import lax


def reduce_over_typo_axis(x):
    return lax.psum(x, "tensor")

# apxlint: fixture
# apxlint: disable-file=APX401, APX402
# The apx401/apx402 violations below, silenced file-wide with a single
# header comment — the suppression shape the trace tier needs, since
# APX5xx findings land on the traced module at line 1 rather than on
# the offending statement. Must lint clean.
import time

import jax

_CALLS = 0


@jax.custom_vjp
def f(x):
    return x * time.time()


def _fwd(x):
    global _CALLS
    _CALLS += 1
    return f(x), x


def _bwd(res, g):
    return (2.0 * g,)


f.defvjp(_fwd, _bwd)

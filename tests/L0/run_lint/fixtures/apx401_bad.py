# apxlint: fixture
# Known-bad: a jit-traced body reads host state — time.time() and an
# np.random draw are frozen into the compiled program at trace time.
# Both reads must raise APX401 (the helper is reachable from the root).
import time

import jax
import numpy as np


def _noise(x):
    return x + np.random.rand()


@jax.jit
def stamped_step(x):
    t = time.time()
    return _noise(x) * t

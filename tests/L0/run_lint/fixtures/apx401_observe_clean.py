# apxlint: fixture
# Known-clean: the same observability state consulted from plain host
# code — the scheduler's hook-site pattern (`if trc.enabled:` between
# ticks, never reachable from a traced root) — raises nothing.
import jax

from apex_tpu.serving import MetricsRegistry
from apex_tpu.serving.observe import Tracer

REGISTRY = MetricsRegistry()
TRACER = Tracer()


def host_tick_report():
    if TRACER.enabled:
        TRACER.instant("tick")
    return REGISTRY.as_dict()


@jax.jit
def decode_body(logits):
    return logits * 2.0

# apxlint: fixture
# Known-bad: the serving observability layer (serving.observe) is
# registered host state — tracer flags, metric registries, and
# flight-recorder rings mutate between scheduler ticks, so consulting
# any of them inside a jitted decode body freezes one stale value into
# the compiled program. Both reads must raise APX401.
import jax

from apex_tpu.serving import MetricsRegistry
from apex_tpu.serving.observe import Tracer

REGISTRY = MetricsRegistry()
TRACER = Tracer()


@jax.jit
def decode_body(logits):
    if TRACER.enabled:
        logits = logits * 0.0
    scale = REGISTRY.counter("serving_retries_total").value
    return logits * (1.0 + scale)

# apxlint: fixture
# Known-clean: "data"/"model" are parallel_state axes; "rows" is
# declared by a local Mesh in this module.
import jax
from jax import lax
from jax.sharding import Mesh


def reduce_over_known_axes(x):
    x = lax.psum(x, "data")
    return lax.pmean(x, "model")


def local_mesh(devices, x):
    with Mesh(devices, ("rows",)):
        return lax.psum(x, "rows")

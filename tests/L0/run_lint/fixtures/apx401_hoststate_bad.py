# apxlint: fixture
# Known-bad: apex_tpu's OWN registered host state — the serving fault
# injector and ServingStats counters mutate between scheduler ticks, so
# consulting either inside a jitted decode body freezes one stale value
# into the compiled program. Both reads must raise APX401.
import jax

from apex_tpu.serving import ServingStats
from apex_tpu.serving.faults import FaultInjector

STATS = ServingStats()
INJECTOR = FaultInjector(rates={"decode_exec": 0.01})


@jax.jit
def decode_body(logits):
    if INJECTOR.fire("decode_exec"):
        logits = logits * 0.0
    return logits * (1.0 + STATS.nan_events)

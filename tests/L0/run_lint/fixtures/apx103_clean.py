# apxlint: fixture
# Known-clean twin of apx103_bad: stats stay fp32 end to end; the bf16
# cast on the probability tile (not a stats ref) is allowed.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd(q_ref, k_ref, o_ref, lse_ref, m_ref, l_ref):
    m_ref[:] = jnp.maximum(m_ref[:], q_ref[:].max())
    l_ref[:] = l_ref[:] + q_ref[:].sum()
    p = jnp.exp(q_ref[:]).astype(jnp.bfloat16)
    o_ref[:] = p.astype(q_ref.dtype)
    lse_ref[:] = m_ref[:] + jnp.log(l_ref[:])


def attend(q, k):
    spec = pl.BlockSpec((128, 64), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _fwd,
        grid=(4,),
        in_specs=[spec, spec],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((q.shape[0], 128), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.float32),
                        pltpu.VMEM((128, 128), jnp.float32)],
    )(q, k)

# apxlint: fixture
from health import ServingError


def test_base():
    assert issubclass(ServingError, RuntimeError)

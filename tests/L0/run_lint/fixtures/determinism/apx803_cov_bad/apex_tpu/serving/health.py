# apxlint: fixture
"""Known-bad APX803 coverage twin: GhostError has no test reference."""


class ServingError(RuntimeError):
    pass


class GhostError(ServingError):
    pass

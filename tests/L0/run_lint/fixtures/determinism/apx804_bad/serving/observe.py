# apxlint: fixture
"""Declared vocabulary for the APX804 bad twin."""
PHASES = ("exec", "commit")
LIFECYCLE = ("submitted", "finished")

# apxlint: fixture
"""Known-bad APX804: spans/instants/metrics drifting from the
declared vocabulary."""


class Chan:
    span = "teleport"                       # not in PHASES

    def run(self, trc, reg, name):
        trc.begin("warmup")                 # span missing from PHASES
        trc.end("warmup")                   # ditto at the close
        trc.instant("midpoint")             # instant missing from LIFECYCLE
        trc.begin(name)                     # dynamic emit-site name
        reg.counter("serving_ok_total", help="fixture")
        return reg.get("serving_missing_total")   # never-created metric

# apxlint: fixture
"""Known-clean APX803 coverage twin: every taxonomy class tested."""


class ServingError(RuntimeError):
    pass


class GhostError(ServingError):
    pass

# apxlint: fixture
from health import GhostError, ServingError


def test_taxonomy():
    assert issubclass(GhostError, ServingError)

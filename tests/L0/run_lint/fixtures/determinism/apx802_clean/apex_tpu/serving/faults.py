# apxlint: fixture
"""Known-clean APX802 twin: two sites, five artifacts each, all in
lockstep."""
SITES = ("alpha_exec", "beta_send")

SITE_CONTRACTS = {
    "alpha_exec": (None, None),               # policy-only fault
    "beta_send": ("BetaFailed", "APEX_CHAOS_BETA_SEED"),
}


class BetaFailed(RuntimeError):
    pass


class Hooks:
    def run(self):
        self.injector.draw("alpha_exec")
        self.injector.fire("beta_send")

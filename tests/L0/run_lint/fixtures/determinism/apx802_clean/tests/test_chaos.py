# apxlint: fixture
"""chaos fixture suite: every site replayed, the sweep env read."""
import os

SEED = int(os.environ.get("APEX_CHAOS_BETA_SEED", "0"))


def test_sites(injector):
    assert injector.draw("alpha_exec")
    assert injector.fire("beta_send")

# apxlint: fixture
"""Known-bad APX802: the site table drifts from its five artifacts in
every direction the checker covers."""
SITES = ("alpha_exec", "beta_send", "gamma_probe")

SITE_CONTRACTS = {
    "alpha_exec": ("AlphaError", None),       # AlphaError: undefined
    "beta_send": ("BetaFailed", "APEX_CHAOS_BETA_SEED"),
    "stale_site": (None, None),               # not in SITES
}
# gamma_probe: missing from SITE_CONTRACTS, never consulted, never
# referenced by a chaos test


class BetaFailed(RuntimeError):
    pass


class Hooks:
    def run(self):
        self.injector.draw("alpha_exec")
        self.injector.fire("beta_send")

# apxlint: fixture
"""chaos fixture suite: references alpha_exec only — beta_send and
gamma_probe have no chaos coverage, and nothing reads the sweep env."""


def test_alpha(injector):
    assert injector.draw("alpha_exec")

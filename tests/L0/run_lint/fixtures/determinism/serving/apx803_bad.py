# apxlint: fixture
"""Known-bad APX803: an untyped raise on the tick path falls through
every degrade ladder."""


class Sched:
    def run(self):
        if not self._slots:
            raise RuntimeError("no slots configured")

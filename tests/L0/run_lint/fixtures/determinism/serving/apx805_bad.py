# apxlint: fixture
"""Known-bad APX805: raw PRNGKey consumption, key reuse, and a split
tree on the tick path."""
import jax


class Engine:
    def step(self, seed, logits):
        key = jax.random.PRNGKey(seed)           # raw key, never folded
        a = jax.random.categorical(key, logits)  # first consumer
        b = jax.random.categorical(key, logits)  # reuse: correlated draw
        k1, k2 = jax.random.split(key)           # split tree
        return a, b, k1, k2

# apxlint: fixture
"""Known-bad APX801: every flavor of nondeterministic ordering on the
tick path — set iteration/materialization, set-in-text, wall clock,
unseeded random, hash()."""
import random
import time


class Sched:
    def run(self, n):
        pending = set(range(n))
        order = []
        for rid in pending:                     # set iteration
            order.append(rid)
        ready = [r for r in pending]            # comprehension source
        first = list(pending)                   # order-materializing call
        started = time.time()                   # wall clock on tick path
        jitter = random.random()                # unseeded stdlib RNG
        bucket = hash(order[0])                 # process-dependent value
        raise ValueError(f"stuck requests {pending}")   # set in text

# apxlint: fixture
"""Known-clean APX803 twin: taxonomy subclass for the degrade path,
allowlisted ValueError for constructor-time validation, re-raise."""
from apex_tpu.serving.health import ServingError


class SlotsExhausted(ServingError):
    pass


class Sched:
    def run(self):
        if not self._slots:
            raise SlotsExhausted("no slots configured")
        if self._chunk_tokens < 1:
            raise ValueError("chunk_tokens must be >= 1")
        try:
            self._tick()
        except SlotsExhausted as err:
            raise err

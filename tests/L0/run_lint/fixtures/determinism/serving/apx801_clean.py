# apxlint: fixture
"""Known-clean APX801 twin: same shapes, deterministic order — sorted
materialization, order-free set consumers, no host entropy."""


class Sched:
    def run(self, n, tick):
        pending = set(range(n))
        order = []
        for rid in sorted(pending):             # sorted: committed order
            order.append(rid)
        if n in pending:                        # membership: order-free
            depth = len(pending)                # size: order-free
        busy = pending & {0, 1}                 # set algebra: stays a set
        raise ValueError(f"stuck requests {sorted(pending)}")

# apxlint: fixture
"""APX8xx suppression: same violations as the bad fixtures, silenced
line-by-line through the shared engine machinery."""
import jax


class Sched:
    def run(self, n, seed, logits):
        pending = set(range(n))
        for rid in pending:  # apxlint: disable=APX801
            self._visit(rid)
        # apxlint: disable=APX805
        key = jax.random.PRNGKey(seed)
        if not pending:
            # apxlint: disable=APX803
            raise RuntimeError("no slots configured")
        return jax.random.categorical(key, logits)

# apxlint: fixture
"""Known-clean APX805 twin: per-slot keys derived as
fold_in(PRNGKey(request seed), position counter), batched by stack."""
import jax
import jax.numpy as jnp


class Engine:
    def step(self, seeds, counter, logits):
        keys = jnp.stack(
            [jax.random.fold_in(jax.random.PRNGKey(s), counter)
             for s in seeds])
        return jax.random.categorical(keys, logits)

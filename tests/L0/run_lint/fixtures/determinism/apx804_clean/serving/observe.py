# apxlint: fixture
"""Declared vocabulary for the APX804 clean twin."""
PHASES = ("exec", "commit", "teleport")
LIFECYCLE = ("submitted", "midpoint", "finished")

# apxlint: fixture
"""Known-clean APX804 twin: every emit site resolves against the
declared tuples; the read-back matches a creation site."""


class Chan:
    span = "teleport"

    def run(self, trc, reg):
        trc.begin("exec")
        trc.end("exec")
        trc.begin(self.span)                # declared span attribute
        trc.end(self.span)
        trc.instant("midpoint")
        reg.counter("serving_ok_total", help="fixture")
        return reg.get("serving_ok_total")

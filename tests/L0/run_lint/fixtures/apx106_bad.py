# apxlint: fixture
# Known-bad: the int8 quantization contract broken four ways — a bf16
# scale scratch tile, a store into scale_out that rounds through
# astype(bfloat16), a dequant-fused dot with no fp32
# preferred_element_type, and a truncating astype(int8) with no
# rounding call in scope. Each must raise APX106.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _w8_body(x_ref, wq_ref, scale_ref, out_ref, new_scale_out,
             scale_scratch):
    w = wq_ref[...].astype(jnp.float32) * scale_ref[...]
    out_ref[...] = jnp.dot(x_ref[...], w)  # no preferred_element_type
    new_scale_out[...] = scale_ref[...].astype(jnp.bfloat16)


def dequant_matmul(x, wq, scale):
    spec = pl.BlockSpec((128, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _w8_body,
        grid=(4,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct(x.shape, jnp.float32),
                   jax.ShapeDtypeStruct((128,), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((128,), jnp.bfloat16)],
    )(x, wq, scale)


def quantize_truncating(t):
    scale = jnp.abs(t).max() / 127.0
    return (t / scale).astype(jnp.int8), scale  # truncates toward zero

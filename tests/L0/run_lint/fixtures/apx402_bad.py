# apxlint: fixture
# Known-bad: a custom_vjp forward rule mutates a module global — the
# mutation happens once at trace time, not per step. Must raise APX402.
import jax

_CALLS = 0


@jax.custom_vjp
def f(x):
    return x * 2.0


def _fwd(x):
    global _CALLS
    _CALLS += 1
    return f(x), x


def _bwd(res, g):
    return (2.0 * g,)


f.defvjp(_fwd, _bwd)

# apxlint: fixture
# Known-bad: the psum only happens on shard 0 — every other shard skips
# its side of the collective and the mesh deadlocks. Must raise APX201.
import jax
from jax import lax


def rank_divergent_reduce(x):
    if lax.axis_index("data") == 0:
        x = lax.psum(x, "data")
    return x


def rank_reordered_collectives(x, y):
    rank = lax.axis_index("data")
    if rank == 0:
        x = lax.psum(x, "data")
        y = lax.ppermute(y, "data", [(0, 1)])
    else:
        y = lax.ppermute(y, "data", [(0, 1)])
        x = lax.psum(x, "data")
    return x, y

# apxlint: fixture
# Known-clean: host-state reads in plain host code (not reachable from
# any traced root) are fine, and `from jax import random` must not be
# mistaken for the stdlib random module.
import time

import jax
from jax import random


def host_timer():
    return time.time()


@jax.jit
def step(key, x):
    return x + random.normal(key, x.shape)

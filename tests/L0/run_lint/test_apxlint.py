"""apxlint fixture tests: every error code must fire on its known-bad
fixture and stay silent on the known-clean twin, suppression comments
must work, and — the meta-test — the repo itself must lint clean."""

import os

import pytest

from apex_tpu.lint import CODES
from apex_tpu.lint.engine import lint_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _codes(*names, **kw):
    paths = [os.path.join(FIXTURES, n) for n in names]
    findings, n = lint_paths(paths, trace=False, **kw)
    assert n == len(paths) or kw.get("include_fixtures"), \
        f"fixture file(s) not linted: {paths}"
    return [f.code for f in findings]


def test_codes_registry_complete():
    assert set(CODES) == {
        "APX100", "APX101", "APX102", "APX103", "APX105", "APX106",
        "APX201", "APX202",
        "APX301", "APX302", "APX303", "APX304",
        "APX401", "APX402",
        "APX501", "APX502", "APX503",
        "APX511", "APX512",
        "APX601", "APX602", "APX603", "APX604",
        "APX701", "APX702", "APX703", "APX704",
        "APX801", "APX802", "APX803", "APX804", "APX805",
        "APX901", "APX902", "APX903", "APX904",
    }
    assert all(CODES[c] for c in CODES)  # every code documented


def test_apx101_missing_alias():
    assert _codes("apx101_bad.py") == ["APX101"]
    assert _codes("apx101_clean.py") == []


def test_apx103_stats_precision():
    codes = _codes("apx103_bad.py")
    # bf16 m scratch, bf16 lse output, downcast store into l_ref
    assert codes.count("APX103") == 3, codes
    assert _codes("apx103_clean.py") == []


def test_apx106_quant_contracts():
    codes = _codes("apx106_bad.py")
    # bf16 scale scratch, downcast store into scale_out, dot without
    # preferred_element_type, truncating astype(int8)
    assert codes.count("APX106") == 4, codes
    assert _codes("apx106_clean.py") == []


def test_apx201_collective_divergence():
    codes = _codes("apx201_bad.py")
    assert codes.count("APX201") == 2, codes
    assert _codes("apx201_clean.py") == []


def test_apx202_unknown_axis():
    assert _codes("apx202_bad.py") == ["APX202"]
    assert _codes("apx202_clean.py") == []


def test_apx401_host_state_read():
    codes = _codes("apx401_bad.py")
    assert codes.count("APX401") == 2, codes  # time.time + np.random
    assert _codes("apx401_clean.py") == []


def test_apx401_serving_host_state():
    # apex_tpu's own registered host state: a FaultInjector consult and
    # a ServingStats counter read inside a jitted decode body
    codes = _codes("apx401_hoststate_bad.py")
    assert codes.count("APX401") == 2, codes
    assert _codes("apx401_hoststate_clean.py") == []


def test_apx401_observe_host_state():
    # the observability layer is host state too: a Tracer flag check
    # and a MetricsRegistry counter read inside a jitted decode body
    codes = _codes("apx401_observe_bad.py")
    assert codes.count("APX401") == 2, codes
    assert _codes("apx401_observe_clean.py") == []


def test_apx402_global_write():
    assert _codes("apx402_bad.py") == ["APX402"]


def test_apx105_unregistered_kernel_family():
    bad = os.path.join("apx105", "apex_tpu", "apx105_bad.py")
    clean = os.path.join("apx105", "apex_tpu", "apx105_clean.py")
    codes = _codes(bad)
    assert codes == ["APX105"], codes
    assert _codes(clean) == []


def test_apx105_registration_resolved_by_path_suffix():
    import ast as ast_mod

    from apex_tpu.lint import meta

    p = os.path.join(FIXTURES, "apx105", "apex_tpu", "apx105_bad.py")
    with open(p) as f:
        trees = {p: ast_mod.parse(f.read())}
    dotted = "apx105.apex_tpu.apx105_bad"
    # named by both registries: covered
    assert meta.check_files(trees, vmem_modules=[dotted],
                            trace_modules=[dotted]) == []
    # named by only one: the finding spells out which half is missing
    only_vmem = meta.check_files(trees, vmem_modules=[dotted],
                                 trace_modules=[])
    assert [f.code for f in only_vmem] == ["APX105"]
    assert "TraceEntry" in only_vmem[0].message
    assert "APX102" not in only_vmem[0].message


def test_suppression_comments():
    assert _codes("suppressed.py") == []


def test_file_level_suppression():
    # same violations as apx401_bad/apx402_bad, silenced by one
    # `# apxlint: disable-file=...` header comment
    assert _codes("suppressed_file.py") == []


def test_amp_list_coherence():
    findings, _ = lint_paths([os.path.join(FIXTURES, "amp_bad")],
                             trace=False, include_fixtures=True)
    codes = sorted(f.code for f in findings)
    assert codes == ["APX301", "APX302", "APX303", "APX304"], codes
    by_code = {f.code: f for f in findings}
    assert "matmul" in by_code["APX301"].message
    assert "bmm" in by_code["APX302"].message
    assert by_code["APX302"].path.endswith("user.py")
    assert "softmax" in by_code["APX303"].message
    assert "linear" in by_code["APX304"].message

    clean, _ = lint_paths([os.path.join(FIXTURES, "amp_clean")],
                          trace=False, include_fixtures=True)
    assert clean == []


def test_fixture_files_skipped_in_directory_walks():
    findings, n = lint_paths([FIXTURES], trace=False)
    assert n == 0 and findings == []


def test_apx102_vmem_budget():
    jax = pytest.importorskip("jax")
    from apex_tpu.lint import vmem

    def build():
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2.0

        def fn(x):
            spec = pl.BlockSpec((4096, 1024), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)
            return pl.pallas_call(
                kernel, grid=(2,), in_specs=[spec], out_specs=spec,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)

        return fn, (jax.ShapeDtypeStruct((8192, 1024), "float32"),)

    # 4096x1024 fp32 block = 16 MiB; doubled in+out = 64 MiB >> budget.
    findings = vmem.run_configs(
        [vmem.Config("oversized", "apex_tpu.lint.vmem", build)])
    assert [f.code for f in findings] == ["APX102"]
    assert "oversized" in findings[0].message

    # An untraceable config is APX100, not a silent pass.
    def broken():
        raise RuntimeError("boom")

    findings = vmem.run_configs(
        [vmem.Config("broken", "apex_tpu.lint.vmem", broken)])
    assert [f.code for f in findings] == ["APX100"]


def test_repo_lints_clean():
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    findings, n_files = lint_paths(
        [os.path.join(repo, "apex_tpu"), os.path.join(repo, "tests")],
        trace=True)
    assert n_files > 100
    assert findings == [], "\n".join(f.render() for f in findings)

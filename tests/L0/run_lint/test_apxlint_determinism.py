"""APX8xx determinism-tier tests: every code fires on its known-bad
fixture and stays silent on the known-clean twin, suppression works
through the shared engine, the repo itself lints clean with the tier
enabled — and, the load-bearing part, the seeded-bug meta-tests: take
a scratch copy of the REAL scheduler/router/CI matrix, re-introduce
the exact bug class the tier was built for, and assert the checker
catches it (so every code is proven live against production code, not
just against fixtures shaped for it)."""

import os
import shutil
import subprocess
import sys

import pytest

from apex_tpu.lint.engine import lint_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "determinism")
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _codes(*names, **kw):
    paths = [os.path.join(FIXTURES, n) for n in names]
    findings, n = lint_paths(paths, trace=False, determinism=True, **kw)
    assert n == len(paths) or kw.get("include_fixtures"), \
        f"fixture file(s) not linted: {paths}"
    return [f.code for f in findings]


def _dir_codes(name):
    findings, _ = lint_paths([os.path.join(FIXTURES, name)],
                             trace=False, determinism=True,
                             include_fixtures=True)
    return findings


# ---------------------------------------------------------------------------
# fixture pairs
# ---------------------------------------------------------------------------

def test_apx801_ordering():
    codes = _codes(os.path.join("serving", "apx801_bad.py"))
    # set iteration, comprehension, list(), wall clock, random, hash,
    # set-in-f-string
    assert codes.count("APX801") == 7, codes
    assert _codes(os.path.join("serving", "apx801_clean.py")) == []


def test_apx805_rng_discipline():
    codes = _codes(os.path.join("serving", "apx805_bad.py"))
    # raw PRNGKey, key reuse, split tree
    assert codes.count("APX805") == 3, codes
    assert _codes(os.path.join("serving", "apx805_clean.py")) == []


def test_apx803_raise_closure():
    assert _codes(os.path.join("serving", "apx803_bad.py")) \
        == ["APX803"]
    assert _codes(os.path.join("serving", "apx803_clean.py")) == []


def test_apx803_taxonomy_test_coverage():
    findings = _dir_codes("apx803_cov_bad")
    assert [f.code for f in findings] == ["APX803"]
    assert "GhostError" in findings[0].message
    assert _dir_codes("apx803_cov_clean") == []


def test_apx804_observe_coherence():
    findings = _dir_codes("apx804_bad")
    codes = [f.code for f in findings]
    # span attr, begin+end undeclared, instant undeclared, dynamic
    # name, never-created read-back
    assert codes.count("APX804") == 6, \
        "\n".join(f.render() for f in findings)
    assert _dir_codes("apx804_clean") == []


def test_apx802_fault_contracts():
    findings = _dir_codes("apx802_bad")
    rendered = "\n".join(f.render() for f in findings)
    codes = [f.code for f in findings]
    # gamma missing from table, stale_site, AlphaError unknown,
    # beta chaos-ref missing, beta sweep absent from ci + unread,
    # gamma unconsulted + chaos-ref missing, stale CI env
    assert codes.count("APX802") == 9, rendered
    for needle in ("gamma_probe", "stale_site", "AlphaError",
                   "APEX_CHAOS_BETA_SEED", "APEX_CHAOS_STALE_SEED"):
        assert needle in rendered, f"missing {needle}:\n{rendered}"
    assert _dir_codes("apx802_clean") == []


def test_suppression_through_shared_engine():
    assert _codes(os.path.join("serving", "suppressed_det.py")) == []


def test_fixtures_skipped_without_flag():
    # tick-path rules only apply inside a `serving` directory; the
    # fixture marker keeps the whole tree out of directory walks
    findings, n = lint_paths([FIXTURES], trace=False, determinism=True)
    assert n == 0 and findings == []


def test_repo_lints_determinism_clean():
    findings, n_files = lint_paths(
        [os.path.join(REPO, "apex_tpu"), os.path.join(REPO, "tests")],
        trace=False, determinism=True)
    assert n_files > 100
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# seeded-bug meta-tests: re-introduce the real bug class in a scratch
# copy of the production code and prove the checker catches it
# ---------------------------------------------------------------------------

SERVING = os.path.join(REPO, "apex_tpu", "serving")


def _scratch_serving(tmp_path):
    dst = tmp_path / "apex_tpu" / "serving"
    shutil.copytree(SERVING, dst,
                    ignore=shutil.ignore_patterns("__pycache__"))
    return dst


def _apx8(paths, code):
    findings, _ = lint_paths([str(p) for p in paths], trace=False,
                             determinism=True, select=(code,))
    return findings


def _mutate(path, old, new):
    src = path.read_text()
    assert src.count(old) == 1, f"mutation anchor drifted: {old!r}"
    path.write_text(src.replace(old, new))


def test_seeded_unsorted_requeue_caught(tmp_path):
    """Un-sort the chunked-prefill progress loop in a scratch copy of
    the REAL scheduler — the PR-8-review bug class — and APX801 must
    fire at that line."""
    dst = _scratch_serving(tmp_path)
    assert _apx8([dst], "APX801") == []  # scratch baseline is clean
    _mutate(dst / "scheduler.py",
            "for rid in sorted(progressed):",
            "for rid in progressed:")
    findings = _apx8([dst], "APX801")
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].path.endswith("scheduler.py")
    assert "_prefill_phase" in findings[0].message


def test_seeded_unordered_routing_key_caught(tmp_path):
    """Replace the router's deterministic load-key pick with an
    arbitrary set materialization in a scratch copy — routing order
    becomes hash-dependent — and APX801 must fire."""
    dst = _scratch_serving(tmp_path)
    _mutate(dst / "router.py",
            "return self._note_route(min(cands, key=self._load_key))",
            "return self._note_route(list(set(cands))[0])")
    findings = _apx8([dst], "APX801")
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].path.endswith("router.py")
    assert "_route_prefill" in findings[0].message


def test_seeded_dropped_ci_matrix_leg_caught(tmp_path):
    """Drop APEX_CHAOS_POOL_SEED from a scratch copy of the CI chaos
    matrix — the reshard/pool sites silently lose their sweep — and
    APX802 must name every orphaned site."""
    _scratch_serving(tmp_path)
    tests_dst = tmp_path / "tests"
    shutil.copytree(os.path.join(REPO, "tests", "L0", "run_serving"),
                    tests_dst / "run_serving",
                    ignore=shutil.ignore_patterns("__pycache__"))
    ci_dst = tmp_path / ".github" / "workflows"
    ci_dst.mkdir(parents=True)
    shutil.copy(os.path.join(REPO, ".github", "workflows", "ci.yml"),
                ci_dst / "ci.yml")

    scope = [tmp_path / "apex_tpu" / "serving"]
    assert _apx8(scope, "APX802") == []  # scratch baseline is clean

    ci = ci_dst / "ci.yml"
    src = ci.read_text()
    lines = [l for l in src.splitlines()
             if "APEX_CHAOS_POOL_SEED" not in l]
    assert len(lines) < len(src.splitlines())
    ci.write_text("\n".join(lines))

    findings = _apx8(scope, "APX802")
    rendered = "\n".join(f.render() for f in findings)
    for site in ("reshard_send", "reshard_recv", "pool_route"):
        assert site in rendered, rendered
    assert "APEX_CHAOS_POOL_SEED" in rendered


# ---------------------------------------------------------------------------
# CLI surface: --codes APX8* enables the tier end-to-end
# ---------------------------------------------------------------------------

def test_cli_codes_apx8_glob_enables_tier():
    from apex_tpu.lint.__main__ import main

    bad = os.path.join(FIXTURES, "serving", "apx801_bad.py")
    # the glob both enables --determinism and narrows the report
    assert main(["--no-trace", "--codes", "APX8*",
                 "--include-fixtures", bad]) == 1
    # without the tier the same file goes clean (no APX8xx run at all)
    assert main(["--no-trace", "--include-fixtures", bad]) == 0


def test_cli_determinism_flag(capsys):
    from apex_tpu.lint.__main__ import main

    bad = os.path.join(FIXTURES, "serving", "apx805_bad.py")
    assert main(["--no-trace", "--determinism",
                 "--include-fixtures", bad]) == 1
    assert "APX805" in capsys.readouterr().out
    clean = os.path.join(FIXTURES, "serving", "apx805_clean.py")
    assert main(["--no-trace", "--determinism",
                 "--include-fixtures", clean]) == 0


def test_cli_codes_unknown_apx8_pattern(capsys):
    from apex_tpu.lint.__main__ import main

    assert main(["--no-trace", "--codes", "APX87*"]) == 2
    assert "matches no known code" in capsys.readouterr().err


@pytest.mark.slow
def test_cli_module_invocation_budget():
    """`python -m apex_tpu.lint --determinism` over the repo: clean,
    and inside the 15s acceptance budget (cold interpreter included)."""
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint", "--determinism",
         "--no-trace", "apex_tpu", "tests"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 15.0, f"lint took {elapsed:.1f}s"

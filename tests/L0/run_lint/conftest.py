"""Keep pytest out of the lint fixtures: the determinism-tier fixture
mini-repos contain files named ``test_*.py`` (the APX802/APX803
cross-artifact checks read test *text*, so the fixtures ship fake test
files), which are lint inputs, not collectible tests."""

collect_ignore = ["fixtures"]

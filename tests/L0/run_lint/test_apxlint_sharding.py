"""Sharding-tier (APX7xx) tests.

Same three layers as the trace tier:

- known-bad / known-clean *entry* pairs: every APX701-704 verifier must
  fire on a rule table or builder that seeds exactly its contract
  violation and stay silent on the minimally-different clean twin;
- a seeded-bug meta-test: a scratch copy of ``apex_tpu.partition.tables``
  gets one rule's tensor axis textually flipped, is imported under a
  throwaway name, and APX702 must fire — while the unmodified table
  stays silent under the identical harness;
- the repo registry itself must be populated and clean (including the
  dp2 x tp2 ZeRO step gated against the committed budgets.json).

Plus the satellites that live in this tier: the ``--codes`` /
``--prune`` CLI surface and the budgets.json prune semantics.
"""

import dataclasses
import importlib.util
import os
import re
import sys

import pytest

jax = pytest.importorskip("jax")
from jax.sharding import PartitionSpec as P  # noqa: E402

from apex_tpu.lint.sharded.registry import (  # noqa: E402
    ShardedEntry, check_repo, repo_entries, run_entries,
)
from apex_tpu.lint.traced.registry import _mesh, _sds  # noqa: E402
from apex_tpu.transformer import parallel_state as ps  # noqa: E402

MOD = "apex_tpu.lint"  # attribution target for synthetic entries


def _codes(entries, manifest=None):
    return [f.code for f in run_entries(entries, manifest=manifest)]


def _msgs(entries, manifest=None):
    return [f.message for f in run_entries(entries, manifest=manifest)]


def _rule_entry(name, rules, trees, **kw):
    return ShardedEntry(name, MOD, rules=lambda: rules,
                        trees=lambda: trees, **kw)


def _build_entry(name, build, *, tp=2, n_devices=4, **kw):
    return ShardedEntry(name, MOD, rules=lambda: (), build=build,
                        mesh=_mesh(tp=tp, n_devices=n_devices),
                        min_devices=n_devices, **kw)


def _skip_if_few_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


# ---------------------------------------------------------------------------
# APX701 — rule-table coverage and spec sanity
# ---------------------------------------------------------------------------

def test_apx701_uncovered_leaf():
    trees = {"params": {"a": _sds((4,), "float32"),
                        "b": _sds((4,), "float32")}}
    msgs = _msgs([_rule_entry("t", (("a$", P(None)),), trees)])
    assert len(msgs) == 1 and "no rule matches" in msgs[0], msgs
    assert "'b'" in msgs[0]


def test_apx701_overlapping_rules():
    trees = {"params": {"a": _sds((4,), "float32"),
                        "b": _sds((4,), "float32")}}
    rules = (("a", P(None)), ("a|b", P(None)))
    msgs = _msgs([_rule_entry("t", rules, trees)])
    assert len(msgs) == 1 and "first-match-wins" in msgs[0], msgs


def test_apx701_dead_rule():
    trees = {"params": {"a": _sds((4,), "float32")}}
    rules = (("a", P(None)), ("zz", P(None)))
    msgs = _msgs([_rule_entry("t", rules, trees)])
    assert len(msgs) == 1 and "dead rule" in msgs[0], msgs


def test_apx701_spec_outranks_array():
    trees = {"params": {"a": _sds((4,), "float32")}}
    msgs = _msgs([_rule_entry("t", (("a", P("model", None)),), trees)])
    assert len(msgs) == 1 and "rank" in msgs[0], msgs


def test_apx701_axis_sanity_is_tree_independent():
    rules = (("a", P("tensor")),          # no such mesh axis
             ("b", P("model", "model")),  # same axis twice in one spec
             ("c(", P(None)))             # unparseable pattern
    msgs = _msgs([_rule_entry("t", rules, {})])
    assert len(msgs) == 3, msgs
    assert "do not exist" in msgs[0]
    assert "repeats" in msgs[1]
    assert "not a valid regex" in msgs[2]


def test_apx701_clean_table():
    trees = {"params": {"a": _sds((4,), "float32"), "b": _sds((), "float32")}}
    rules = (("a$", P("model")), ("b$", P()))
    assert _codes([_rule_entry("t", rules, trees)]) == []


# ---------------------------------------------------------------------------
# APX702 — cross-tree consistency
# ---------------------------------------------------------------------------

def test_apx702_root_anchored_rule_breaks_optimizer_families():
    # "^w$" matches the param path but not "m/w" / "v/w"; the fallthrough
    # rule replicates — exactly the drift the family re-match exists for
    rules = (("^w$", P("model")), ("/w$", P(None)))
    trees = {"params": {"w": _sds((4,), "float32")},
             "aux": {"box": {"w": _sds((4,), "float32")}}}
    findings = run_entries([_rule_entry("t", rules, trees,
                                        optimizer_families=("m", "v"))])
    assert [f.code for f in findings] == ["APX702", "APX702"], \
        [f.render() for f in findings]
    assert "optimizer family 'm'" in findings[0].message
    assert "shard differently" in findings[0].message


def test_apx702_unanchored_table_keeps_families_consistent():
    rules = (("w$", P("model")),)
    trees = {"params": {"w": _sds((4,), "float32")}}
    assert _codes([_rule_entry("t", rules, trees,
                               optimizer_families=("m", "v", "master"))]) == []


def _kv_trees():
    return {"params": {"qkv": {"kernel": _sds((4, 8), "float32")}},
            "kv_cache": {"k": _sds((2, 2, 2), "bfloat16"),
                         "v": _sds((2, 2, 2), "bfloat16"),
                         "lengths": _sds((2,), "int32")}}


def _kv_rules(cache_spec):
    return (("qkv/kernel$", P(None, "model")),
            (r"(^|/)(k|v)$", cache_spec),
            ("lengths$", P()))


def test_apx702_kv_head_axis_must_match_qkv():
    bad = _rule_entry("t", _kv_rules(P(None, None, None)), _kv_trees(),
                      kv_cache_tree="kv_cache")
    msgs = _msgs([bad])
    assert len(msgs) == 1 and "head axes" in msgs[0], msgs
    clean = _rule_entry("t", _kv_rules(P(None, "model", None)), _kv_trees(),
                        kv_cache_tree="kv_cache")
    assert _codes([clean]) == []


def test_apx702_kv_k_and_v_must_shard_alike():
    rules = (("qkv/kernel$", P(None, "model")),
             (r"(^|/)k$", P(None, "model", None)),
             (r"(^|/)v$", P(None, None, "model")),
             ("lengths$", P()))
    msgs = _msgs([_rule_entry("t", rules, _kv_trees(),
                              kv_cache_tree="kv_cache")])
    assert len(msgs) == 1 and "!= v spec" in msgs[0], msgs


def test_apx702_reference_spec_mismatch():
    trees = {"params": {"w": _sds((4, 4), "float32")}}
    rules = (("w$", P("model", None)),)
    bad = _rule_entry("t", rules, trees,
                      reference_specs=lambda: {"params":
                                               {"w": P(None, "model")}})
    msgs = _msgs([bad])
    assert len(msgs) == 1 and "hand-maintained reference" in msgs[0], msgs
    clean = _rule_entry("t", rules, trees,
                        reference_specs=lambda: {"params":
                                                 {"w": P("model", None)}})
    assert _codes([clean]) == []


# ---------------------------------------------------------------------------
# APX703 — rule-derived specs must survive into the staged program
# ---------------------------------------------------------------------------

def _b703_stale_in_specs():
    def body(x):
        return x * 2.0

    # wired with a stale hand-written spec; the table derives tensor-
    # sharded for this operand
    fn = ps.shard_map(body, in_specs=(P(ps.DATA_AXIS, None),),
                      out_specs=P(ps.DATA_AXIS, None))
    return fn, (_sds((8, 8), "float32"),), (P(ps.TENSOR_AXIS, None),)


def _b703_aligned():
    def body(x):
        return x * 2.0

    specs = (P(ps.DATA_AXIS, None),)
    fn = ps.shard_map(body, in_specs=specs, out_specs=specs[0])
    return fn, (_sds((8, 8), "float32"),), specs


def _b703_never_mapped():
    fn = lambda x: x * 2.0
    return fn, (_sds((8,), "float32"),), (P(ps.DATA_AXIS),)


def _b703_replicated_w():
    def body(x, w):
        return x @ w.T  # the transpose must keep the taint on the dot

    specs = (P(ps.DATA_AXIS, None), P())
    fn = ps.shard_map(body, in_specs=specs,
                      out_specs=P(ps.DATA_AXIS, None))
    return fn, (_sds((8, 32), "float32"), _sds((32, 32), "float32")), specs


def _b703_sharded_w():
    def body(x, w):
        return x @ w

    specs = (P(ps.DATA_AXIS, None), P(None, ps.TENSOR_AXIS))
    fn = ps.shard_map(body, in_specs=specs,
                      out_specs=P(ps.DATA_AXIS, ps.TENSOR_AXIS))
    return fn, (_sds((8, 32), "float32"), _sds((32, 32), "float32")), specs


def test_apx703_in_names_disagree_with_table():
    _skip_if_few_devices(4)
    findings = run_entries([_build_entry("stale", _b703_stale_in_specs)])
    assert [f.code for f in findings] == ["APX703"], \
        [f.render() for f in findings]
    assert "does not shard what the table says" in findings[0].message
    assert _codes([_build_entry("ok", _b703_aligned)]) == []


def test_apx703_in_specs_never_applied():
    _skip_if_few_devices(4)
    msgs = _msgs([_build_entry("unmapped", _b703_never_mapped)])
    assert len(msgs) == 1 and "never applied" in msgs[0], msgs


def test_apx703_silently_replicated_matmul_operand():
    _skip_if_few_devices(4)
    # (32, 32) fp32 = 4 KiB; the floor is lowered so the fixture stays tiny
    findings = run_entries([_build_entry("repl", _b703_replicated_w,
                                         replication_floor=1024)])
    assert [f.code for f in findings] == ["APX703"], \
        [f.render() for f in findings]
    assert "fully replicated" in findings[0].message
    assert "dot_general" in findings[0].message
    assert _codes([_build_entry("shard", _b703_sharded_w,
                                replication_floor=1024)]) == []


# ---------------------------------------------------------------------------
# APX704 — per-rank schedule + budgets.json-gated collective volume
# ---------------------------------------------------------------------------

def _b704_divergent():
    def body(x):
        i = jax.lax.axis_index(ps.DATA_AXIS)
        return jax.lax.cond(
            i == 0,
            lambda v: jax.lax.psum(v, ps.DATA_AXIS),
            lambda v: v * 2.0, x)

    specs = (P(ps.DATA_AXIS),)
    fn = ps.shard_map(body, in_specs=specs, out_specs=P(ps.DATA_AXIS))
    return fn, (_sds((8, 4), "float32"),), specs


def _b704_uniform():
    def body(x):
        return jax.lax.psum(x, ps.DATA_AXIS)

    specs = (P(ps.DATA_AXIS),)
    fn = ps.shard_map(body, in_specs=specs, out_specs=P())
    return fn, (_sds((8, 4), "float32"),), specs


def test_apx704_divergent_generated_schedule():
    _skip_if_few_devices(2)
    findings = run_entries([_build_entry("div", _b704_divergent,
                                         tp=1, n_devices=2)])
    assert [f.code for f in findings] == ["APX704"], \
        [f.render() for f in findings]
    assert "rule-generated schedule" in findings[0].message
    assert _codes([_build_entry("uni", _b704_uniform,
                                tp=1, n_devices=2)]) == []


def test_apx704_budget_row_gates_collective_volume():
    _skip_if_few_devices(2)
    e = _build_entry("vol", _b704_uniform, tp=1, n_devices=2,
                     budget_name="synthetic_vol")
    # no committed record: the entry demands one
    msgs = _msgs([e], manifest={"version": 1, "entries": {}})
    assert len(msgs) == 1 and "no budgets.json record" in msgs[0], msgs
    # a record with the wrong volume fires ...
    manifest = {"version": 1,
                "entries": {"synthetic_vol": {"collective_bytes": 1}}}
    findings = run_entries([e], manifest=manifest)
    assert [f.code for f in findings] == ["APX704"], \
        [f.render() for f in findings]
    m = re.search(r"staged collective volume (\d+) B", findings[0].message)
    assert m and int(m.group(1)) > 0
    # ... and pinning the measured volume goes clean
    manifest["entries"]["synthetic_vol"]["collective_bytes"] = int(m.group(1))
    assert _codes([e], manifest=manifest) == []


# ---------------------------------------------------------------------------
# seeded-bug meta-test over a scratch copy of the real table
# ---------------------------------------------------------------------------

def _scratch_import(src_path, transform, tmp_path, name):
    txt = open(src_path, encoding="utf-8").read()
    seeded = transform(txt)
    assert seeded != txt, "seed transform did not apply"
    p = os.path.join(str(tmp_path), name + ".py")
    with open(p, "w", encoding="utf-8") as fh:
        fh.write(seeded)
    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception:
        sys.modules.pop(name, None)
        raise
    return mod


def test_seeded_qkv_axis_flip_fires_apx702(tmp_path):
    from apex_tpu.partition import tables

    seeded = _scratch_import(
        tables.__file__,
        lambda t: t.replace('("layers/qkv/kernel", P(None, None, t)),',
                            '("layers/qkv/kernel", P(None, t, None)),'),
        tmp_path, "tables_seeded_apx702")

    base = next(e for e in repo_entries() if e.name == "gpt_tiny_rules")
    bad = dataclasses.replace(base, name="gpt_seeded",
                              rules=seeded.gpt_rules)
    findings = run_entries([bad])
    # the flip drifts from the hand reference AND orphans the KV cache's
    # head axis — both are APX702, nothing else fires
    assert findings and {f.code for f in findings} == {"APX702"}, \
        [f.render() for f in findings]
    # identical harness, unmodified table: silent
    assert _codes([base]) == []


# ---------------------------------------------------------------------------
# registry + engine integration
# ---------------------------------------------------------------------------

def test_sharded_registry_populated_and_clean():
    names = {e.name for e in repo_entries()}
    assert {"gpt_tiny_rules", "bert_tiny_rules",
            "gpt_tiny_dp2xtp2_zero"} <= names, names
    findings = check_repo()
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# budgets.json prune semantics (--write-budgets --prune)
# ---------------------------------------------------------------------------

class _Rep:
    def __init__(self, entry):
        self.entry = entry
        self.hbm_total_bytes = 10
        self.collective_bytes = 5
        self.peak_live_bytes = 3


def test_budgets_prune_drops_only_stale_rows():
    from apex_tpu.lint.traced import budgets

    stale_row = {"hbm_bytes": 9, "hbm_ceiling": 9, "collective_bytes": 9,
                 "peak_live_bytes": 9, "peak_live_cap": 9}
    prev = {"version": 1, "tolerance": 0.1,
            "entries": {"kept": {"hbm_bytes": 1, "hbm_ceiling": 100,
                                 "collective_bytes": 1,
                                 "peak_live_bytes": 1, "peak_live_cap": 100},
                        "stale": dict(stale_row)}}
    reports = [_Rep("kept")]
    carried = budgets.build_manifest(reports, previous=prev)
    assert carried["entries"]["stale"] == stale_row  # verbatim by default
    pruned = budgets.build_manifest(reports, previous=prev, prune=True)
    assert set(pruned["entries"]) == {"kept"}
    assert budgets.pruned_names(reports, prev) == ["stale"]
    assert budgets.pruned_names(reports, None) == []


# ---------------------------------------------------------------------------
# CLI surface: --codes and --prune
# ---------------------------------------------------------------------------

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def test_cli_codes_selects_matched_codes_only():
    from apex_tpu.lint.__main__ import main

    bad = os.path.join(FIXTURES, "apx101_bad.py")
    # the fixture's own code is reported ...
    assert main(["--no-trace", "--codes", "APX101", bad]) == 1
    # ... but a file whose findings are all outside the subset goes clean
    other = os.path.join(FIXTURES, "apx401_bad.py")
    assert main(["--no-trace", "--codes", "APX101", other]) == 0


def test_cli_codes_rejects_unknown_pattern(capsys):
    from apex_tpu.lint.__main__ import main

    assert main(["--no-trace", "--codes", "APX97*"]) == 2
    assert "matches no known code" in capsys.readouterr().err


def test_cli_prune_requires_write_budgets(capsys):
    from apex_tpu.lint.__main__ import main

    assert main(["--prune"]) == 2
    assert "--write-budgets" in capsys.readouterr().err

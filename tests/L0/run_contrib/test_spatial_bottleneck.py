"""Spatial-parallel bottleneck parity (ref:
``apex/contrib/bottleneck`` tests — sharded block vs the unsharded
reference on the same weights). The halo's zero-fill at the outer
boundary must reproduce SAME padding exactly, so parity is to float
tolerance, not approximate."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.bottleneck import (
    init_spatial_bottleneck,
    spatial_bottleneck,
    spatial_parallel_bottleneck,
)
from apex_tpu.transformer import parallel_state as ps

N = 8
B, H, W, C, MID = 2, 16, 5, 8, 4  # H sharded: 2 rows per rank >= halo 1


def _setup():
    ps.initialize_model_parallel(context_parallel_size_=N)
    key = jax.random.PRNGKey(0)
    params = init_spatial_bottleneck(jax.random.fold_in(key, 1), C, MID)
    x = jax.random.normal(jax.random.fold_in(key, 2), (B, H, W, C))
    return params, x


def test_forward_matches_unsharded():
    params, x = _setup()
    got = ps.shard_map(
        lambda p, x: spatial_parallel_bottleneck(p, x),
        in_specs=(P(), P(None, ps.CONTEXT_AXIS)),
        out_specs=P(None, ps.CONTEXT_AXIS))(params, x)
    want = spatial_bottleneck(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_unsharded():
    params, x = _setup()

    def sharded_loss(p, x):
        y = spatial_parallel_bottleneck(p, x)
        return jnp.sum(y ** 2, dtype=jnp.float32)

    g_x = ps.shard_map(
        jax.grad(sharded_loss, argnums=1),
        in_specs=(P(), P(None, ps.CONTEXT_AXIS)),
        out_specs=P(None, ps.CONTEXT_AXIS))(params, x)
    want_x = jax.grad(
        lambda x: jnp.sum(spatial_bottleneck(params, x) ** 2,
                          dtype=jnp.float32))(x)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(want_x),
                               rtol=1e-4, atol=1e-4)

"""GroupBN + ASP tests (ref: ``apex/contrib/test/{groupbn,sparsity}``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.sparsity import (
    ASP,
    apply_masks,
    compute_sparse_masks,
    m4n2_1d_mask,
)
from apex_tpu.models import layers as L
from apex_tpu.optimizers import FusedSGD
from apex_tpu.transformer import parallel_state as ps

N = 8


def dp_mesh():
    return ps.initialize_model_parallel()


# -- groupbn ---------------------------------------------------------------

def test_bn_group_equals_subgroup_stats():
    """bn_group=4: ranks 0-3 normalize with THEIR joint stats, 4-7 with
    theirs — equal to plain BN over each gathered half-batch."""
    mesh = dp_mesh()
    bn = BatchNorm2d_NHWC(6, bn_group=4)
    params, state = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 5, 5, 6)) * 2 + 1

    # NB: only y comes back — under bn_group=4 the two rank-groups hold
    # DIFFERENT running stats, so a replicated P() out_spec for the state
    # would silently pick one group's copy
    y = ps.shard_map(
        lambda p, s, x: bn.apply(p, s, x, train=True)[0],
        in_specs=(P(), P(), P(ps.DATA_AXIS)),
        out_specs=P(ps.DATA_AXIS))(params, state, x)

    bnp, bns = L.init_batchnorm(6)
    y_ref = jnp.concatenate([
        L.batchnorm(bnp, bns, x[:8], train=True, eps=1e-5)[0],
        L.batchnorm(bnp, bns, x[8:], train=True, eps=1e-5)[0]])
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-5)


def test_bn_group_zero_syncs_whole_axis():
    mesh = dp_mesh()
    bn = BatchNorm2d_NHWC(4, bn_group=0)
    params, state = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 4)) * 3 - 1
    y = ps.shard_map(
        lambda p, s, x: bn.apply(p, s, x, train=True)[0],
        in_specs=(P(), P(), P(ps.DATA_AXIS)),
        out_specs=P(ps.DATA_AXIS))(params, state, x)
    y = np.asarray(y)
    np.testing.assert_allclose(y.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.var(0), 1.0, rtol=1e-3)


def test_fused_add_relu_epilogue():
    bn = BatchNorm2d_NHWC(4, fuse_relu=True)  # bn_group=1: local, no mesh
    params, state = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(2), (32, 4))
    z = jax.random.normal(jax.random.PRNGKey(3), (32, 4))
    y, _ = bn.apply(params, state, x, z, train=True)
    yn, _ = BatchNorm2d_NHWC(4).apply(params, state, x, train=True)
    want = np.maximum(np.asarray(yn) + np.asarray(z), 0.0)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-6)
    assert (np.asarray(y) >= 0).all()


def test_bn_group_divisibility_error():
    mesh = dp_mesh()
    bn = BatchNorm2d_NHWC(4, bn_group=3)
    params, state = bn.init()
    x = jnp.ones((16, 4))
    with pytest.raises(ValueError, match="divide"):
        ps.shard_map(lambda p, s, x: bn.apply(p, s, x, train=True)[0],
                     in_specs=(P(), P(), P(ps.DATA_AXIS)),
                     out_specs=P(ps.DATA_AXIS))(params, state, x)


# -- ASP -------------------------------------------------------------------

def test_m4n2_mask_pattern():
    # explicit axis=-1: the torch-layout orientation
    w = jnp.asarray([[0.1, -3.0, 2.0, 0.05] * 4,
                     [4.0, 3.0, -2.0, 1.0] * 4], jnp.float32)
    m = np.asarray(m4n2_1d_mask(w, axis=-1))
    assert m.sum() == w.size // 2                   # exactly 50%
    assert m.reshape(2, 4, 4).sum(-1).min() == 2    # 2 per group of 4
    # keeps the two largest magnitudes of [0.1, -3, 2, 0.05]
    np.testing.assert_array_equal(m[0, :4], [False, True, True, False])


def test_m4n2_default_axis_is_contraction_dim():
    """This package's kernels are (in, out): the 2:4 groups must run
    DOWN the input dim (axis 0) so the pattern survives transposition to
    torch's (out, in) sparse-tensor-core layout."""
    w = jnp.asarray([[0.1], [-3.0], [2.0], [0.05],
                     [4.0], [3.0], [-2.0], [1.0]], jnp.float32)
    m = np.asarray(m4n2_1d_mask(w))                 # default axis=0
    np.testing.assert_array_equal(
        m[:, 0], [False, True, True, False, True, True, False, False])
    # groups of 4 along axis 0, 2 kept per group
    assert m.reshape(2, 4).sum(-1).tolist() == [2, 2]


def test_mask_tree_predicate():
    params = {"w": jnp.ones((16, 64)), "b": jnp.ones((64,)),
              "tiny": jnp.ones((2, 4))}
    masks = compute_sparse_masks(params)
    assert np.asarray(masks["w"]).sum() == 16 * 32   # pruned
    assert masks["b"] is True                        # 1-D: sentinel
    assert masks["tiny"] is True                     # too small: sentinel


def test_default_predicate_skips_embeddings():
    """The reference whitelist never sparsifies embedding tables — the
    default predicate must skip embedding-like leaves by path name even
    when their shape qualifies."""
    params = {"embed": {"word": {"embedding": jnp.ones((128, 64))}},
              "decoder": {"w": jnp.ones((128, 64))}}
    masks = compute_sparse_masks(params)
    assert masks["embed"]["word"]["embedding"] is True   # skipped
    assert np.asarray(masks["decoder"]["w"]).sum() == 128 * 32


def test_wrapped_optimizer_keeps_sparsity():
    asp = ASP()
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 64))}
    masks = asp.compute_sparse_masks(params)
    params = apply_masks(params, masks)
    opt = FusedSGD(lr=0.1, momentum=0.9)
    state = opt.init(params)
    step = asp.wrap_optimizer(opt, masks)
    for i in range(3):
        grads = {"w": jax.random.normal(jax.random.PRNGKey(i), (16, 64))}
        params, state = step(grads, params, state)
    w = np.asarray(params["w"])
    assert (w[~np.asarray(masks["w"])] == 0).all()   # pruned slots stay 0
    assert (w != 0).sum() == w.size // 2

"""L1 cross-product tier on the imagenet/ResNet path (SURVEY §4 — the
reference's ``tests/L1/cross_product/run.sh`` sweeps opt-level x
keep_batchnorm_fp32 x loss-scale over the imagenet example and compares
loss curves). BatchNorm is the point: ``keep_batchnorm_fp32`` only bites
on a model that HAS batch norm, which the BERT/GPT L1 sweeps don't.

Golden curve = the package's own O0 (fp32) run on identical data; every
swept combination must track it step by step and converge on its own.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import apply_resnet, cross_entropy_loss, init_resnet
from apex_tpu.optimizers import FusedSGD

STEPS = 8
DEPTH = 10
CLASSES = 10
BATCH, IMG = 8, 32


def resnet_curve(opt_level, kbn=None, loss_scale="dynamic", seed=0):
    """Loss curve of the imagenet example's train step (amp cast ->
    value_and_grad -> FusedSGD with found_inf gating -> bn-stats skip)."""
    h = amp.initialize(opt_level=opt_level, keep_batchnorm_fp32=kbn,
                       loss_scale=loss_scale, verbosity=0)
    params, bn_stats = init_resnet(jax.random.PRNGKey(seed), DEPTH, CLASSES)
    # lr low enough that the toy model does NOT memorize the data in one
    # step — the curve must stay O(1) for a per-step relative comparison
    # to mean anything
    opt = FusedSGD(lr=0.01, momentum=0.9, weight_decay=1e-4)
    opt_state = opt.init(params)
    scaler_state = h.init_state()

    @jax.jit
    def step(master, bn_stats, opt_state, scaler_state, images, labels):
        p = h.cast_model(master)
        images = h.cast_input(images)

        def loss_fn(p):
            logits, new_stats = apply_resnet(p, bn_stats, images, DEPTH,
                                             train=True)
            return cross_entropy_loss(logits, labels), new_stats

        (loss, new_stats), grads, found_inf, scaler_state = \
            h.value_and_grad(loss_fn, has_aux=True)(p, scaler_state)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        new_stats = amp.apply_if_finite(new_stats, bn_stats, found_inf)
        return master, new_stats, opt_state, scaler_state, loss

    losses = []
    # one FIXED batch (overfit) so the convergence check is unambiguous;
    # lr is low enough that memorization takes the whole curve instead
    # of collapsing to ~1e-2 in one step (where relative comparison is
    # meaningless)
    k = jax.random.PRNGKey(7_000)
    images = jax.random.normal(k, (BATCH, IMG, IMG, 3), jnp.float32)
    labels = jax.random.randint(k, (BATCH,), 0, CLASSES)
    for i in range(STEPS):
        params, bn_stats, opt_state, scaler_state, loss = step(
            params, bn_stats, opt_state, scaler_state, images, labels)
        losses.append(float(loss))
    return np.array(losses)


@pytest.fixture(scope="module")
def golden_curve():
    return resnet_curve("O0", loss_scale=1.0)


def test_golden_resnet_converges(golden_curve):
    assert np.all(np.isfinite(golden_curve))
    assert golden_curve[-1] < golden_curve[0] - 0.1, golden_curve


# The reference's run.sh crosses every axis; the informative subset is
# each opt level with both keep_batchnorm settings and both loss-scale
# modes represented (kbn is meaningless at O0/O1, where the model is
# not cast — SURVEY §4).
@pytest.mark.parametrize("opt_level,kbn,loss_scale", [
    ("O1", None, "dynamic"),
    ("O2", True, "dynamic"),
    ("O2", False, 128.0),
    ("O3", True, 128.0),
    ("O3", False, "dynamic"),
])
def test_resnet_amp_curve_tracks_fp32(golden_curve, opt_level, kbn,
                                      loss_scale):
    curve = resnet_curve(opt_level, kbn=kbn, loss_scale=loss_scale)
    assert np.all(np.isfinite(curve))
    # BatchNorm feeds bf16 rounding back through its running statistics,
    # so cast-model curves wander more than the LN-only BERT/GPT sweeps
    # (measured ~7% worst-step at O2) — tolerances reflect that; O3
    # without fp32 batchnorm is the loosest recipe the reference ships
    rtol = 0.15 if opt_level == "O3" else 0.10
    # atol floors the comparison once the toy model has memorized the
    # batch (loss ~1e-2..1e-3, where bf16 step noise swamps rtol)
    np.testing.assert_allclose(curve, golden_curve, rtol=rtol, atol=0.02)
    assert curve[-1] < curve[0] - 0.1
    if opt_level != "O1":  # O1 touches only opted-in ops on this model
        assert np.any(curve != golden_curve)

"""L1 convergence tier: multi-step loss-curve parity (SURVEY §4/§7 —
the reference's L1 ``cross_product`` suite trains fp16 vs fp32 pairs and
compares loss curves per step; the north star's "loss parity" clause).

The reference publishes no numbers (BASELINE.md), so the golden curve is
the package's own fp32 (O0) run: every amp level must track it within
mixed-precision tolerance step by step, and training must actually
converge (final < initial)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import (
    apply_bert, bert_tiny, gpt_loss_unsharded, gpt_tiny, init_bert,
    init_gpt, mlm_loss,
)
from apex_tpu.optimizers import FusedAdam

STEPS = 20


def bert_curve(opt_level, loss_scale="dynamic", seed=0,
               m_dtype=jnp.float32, emit_compute=False):
    """Loss curve of a full amp train loop on deterministic data.

    ``m_dtype``/``emit_compute`` exercise the reduced-precision optimizer
    state modes: bf16 first moment, and the fused bf16 cast-out consumed
    by ``cast_model(precast=...)`` instead of the per-step master cast."""
    cfg = bert_tiny()
    h = amp.initialize(opt_level=opt_level, loss_scale=loss_scale,
                       verbosity=0)
    params = init_bert(jax.random.PRNGKey(seed), cfg)
    opt = FusedAdam(lr=5e-4, weight_decay=0.01, m_dtype=m_dtype,
                    emit_compute_params=emit_compute)
    opt_state = opt.init(params)
    scaler_state = h.init_state()

    def batch(i):
        k = jax.random.PRNGKey(10_000 + i)
        ids = jax.random.randint(k, (4, 32), 0, cfg.vocab_size)
        return ids, jnp.ones_like(ids)

    @jax.jit
    def step(master, opt_state, scaler_state, compute, ids, mask):
        p = h.cast_model(master, precast=compute)

        def loss_fn(p):
            out = apply_bert(p, cfg, ids, mask)
            return mlm_loss(out["mlm_logits"], ids, mask)

        with h.autocast():
            loss, grads, found_inf, scaler_state = h.value_and_grad(
                loss_fn)(p, scaler_state)
        if emit_compute:
            master, opt_state, compute = opt.step(
                grads, master, opt_state, found_inf=found_inf,
                compute_params=p)
        else:
            master, opt_state = opt.step(grads, master, opt_state,
                                         found_inf=found_inf)
            compute = None
        return master, opt_state, scaler_state, compute, loss

    compute = h.cast_model(params) if emit_compute else None
    losses = []
    for i in range(STEPS):
        ids, mask = batch(i)
        params, opt_state, scaler_state, compute, loss = step(
            params, opt_state, scaler_state, compute, ids, mask)
        losses.append(float(loss))
    return np.array(losses)


@pytest.fixture(scope="module")
def golden_curve():
    return bert_curve("O0", loss_scale=1.0)


def test_golden_run_converges(golden_curve):
    assert np.all(np.isfinite(golden_curve))
    assert golden_curve[-1] < golden_curve[0] - 0.1, golden_curve


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_amp_curve_tracks_fp32(golden_curve, opt_level):
    """Per-step parity: |amp - fp32| relative error bounded along the
    WHOLE curve (bf16 matmul noise compounds; 5% absorbs it at toy
    scale), and the amp run converges on its own."""
    curve = bert_curve(opt_level)
    assert np.all(np.isfinite(curve))
    np.testing.assert_allclose(curve, golden_curve, rtol=0.05)
    assert curve[-1] < curve[0] - 0.1
    # the curves must NOT be identical — proof reduced precision ran
    assert np.any(curve != golden_curve)


def test_state_dtype_bf16_m_curve_tracks_fp32(golden_curve):
    """L1 gate for the reduced-precision optimizer state: O2 with bf16
    Adam first moments must track the fp32 golden curve within the same
    mixed-precision tolerance as plain O2."""
    curve = bert_curve("O2", m_dtype=jnp.bfloat16)
    assert np.all(np.isfinite(curve))
    np.testing.assert_allclose(curve, golden_curve, rtol=0.05)
    assert curve[-1] < curve[0] - 0.1


def test_state_dtype_castout_curve_tracks_fp32(golden_curve):
    """Full HBM-saving recipe: bf16 m AND the fused bf16 cast-out feeding
    ``cast_model(precast=...)`` — the train loop never re-casts master."""
    curve = bert_curve("O2", m_dtype=jnp.bfloat16, emit_compute=True)
    assert np.all(np.isfinite(curve))
    np.testing.assert_allclose(curve, golden_curve, rtol=0.05)
    assert curve[-1] < curve[0] - 0.1


def test_gpt_converges():
    # overfit ONE fixed batch — the unambiguous convergence smoke
    losses = gpt_curve(None, lr=1e-3, weight_decay=0.0,
                       batch_key=20_000)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.5, losses


def gpt_curve(compute_dtype, seed=0, lr=5e-4, weight_decay=0.01,
              batch_key=30_000):
    """GPT loss curve (fixed-batch overfit) — the decoder-side analogue
    of the BERT amp-level curves; also backs the convergence smoke."""
    cfg = gpt_tiny()
    params = init_gpt(jax.random.PRNGKey(seed), cfg)
    opt = FusedAdam(lr=lr, weight_decay=weight_decay)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(
            lambda p: gpt_loss_unsharded(p, cfg, ids, ids,
                                         compute_dtype=compute_dtype))(
            params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    # one FIXED batch (overfit) so the learning assertion is unambiguous
    ids = jax.random.randint(jax.random.PRNGKey(batch_key), (4, 32),
                             0, cfg.vocab_size)
    losses = []
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, ids)
        losses.append(float(loss))
    return np.array(losses)


def test_gpt_bf16_curve_tracks_fp32():
    """bf16 compute over fp32 master weights (the O2-shaped GPT recipe
    used by the TP bench) must track the fp32 curve — the L1 guarantee
    for the decoder stack, incl. the fused xentropy loss path."""
    fp32 = gpt_curve(None)
    bf16 = gpt_curve(jnp.bfloat16)
    assert np.all(np.isfinite(bf16))
    np.testing.assert_allclose(bf16, fp32, rtol=0.05)
    assert bf16[-1] < bf16[0] - 0.1       # actually learning
    assert np.any(bf16 != fp32)           # reduced precision really ran

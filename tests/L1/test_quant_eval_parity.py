"""L1 quantized-inference gate: teacher-forced eval-loss parity.

The L0 quant tests bound raw logit error on a random-init model; this
tier asks the question that matters for serving: after the model has
actually LEARNED something (the fixed-batch overfit of the convergence
smoke), does int8 inference reproduce the full-precision model's
per-position eval loss? The curve here is the teacher-forced NLL at
every decode position, run through the real serving paths (dense and
paged, weight-only int8 and int8 KV pool), compared to the fp32 run of
the same trained weights.

Tolerance: 2% relative per position (documented in
docs/source/quantization.rst; measured ~0.3% on this gate model — the
envelope leaves ~7x headroom while a lost scale or sign flip lands
orders of magnitude outside)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import gpt_loss_unsharded
from apex_tpu.models.gpt import gpt_tiny, init_gpt
from apex_tpu.optimizers import FusedAdam
from apex_tpu.quant import quantize_params
from apex_tpu.serving import (
    PagedDecodeEngine, init_cache, make_decode_fn, make_prefill_fn,
)

# Trains the fixture model in-process: excluded from the driver's
# `-m 'not slow'` tier; the PR gate runs this file by explicit path
# (`./run_tests.sh gate`, no marker filter), as does `L1`.
pytestmark = pytest.mark.slow

TRAIN_STEPS = 20
S_TOTAL, PROMPT, S_MAX = 20, 8, 32
QUANT_EVAL_RTOL = 0.02


@pytest.fixture(scope="module")
def trained():
    """(cfg, trained fp32 params, eval sequence): the gpt_tiny
    fixed-batch overfit — same recipe as the convergence smoke, so the
    eval NLL is well below the uniform floor and quantization error is
    stressed by real (post-training) weight ranges."""
    cfg = dataclasses.replace(gpt_tiny(), hidden_dropout=0.0,
                              use_rope=True)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-3, weight_decay=0.0)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(
            lambda p: gpt_loss_unsharded(p, cfg, ids, ids))(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    ids = jax.random.randint(jax.random.PRNGKey(20_000), (4, 32), 0,
                             cfg.vocab_size)
    for _ in range(TRAIN_STEPS):
        params, opt_state, _ = step(params, opt_state, ids)
    return cfg, params, ids[:1, :S_TOTAL]


def _teacher_forced_rows(cfg, params, seq, *, paged, cache_dtype,
                         quantized):
    if paged:
        eng = PagedDecodeEngine(params, cfg, num_slots=2,
                                max_len=S_MAX, num_pages=14,
                                page_size=8, cache_dtype=cache_dtype,
                                buckets=(8, 16, 32))
        logits = eng.prefill(
            0, [int(t) for t in np.asarray(seq[0, :PROMPT])])
        rows = [logits[0]]
        for t in range(PROMPT, S_TOTAL):
            assert eng.prepare_decode({0: t}) == []
            logits = eng.decode(
                jnp.asarray([int(seq[0, t]), 0], jnp.int32),
                jnp.asarray([True, False]))
            rows.append(logits[0])
        return jnp.stack(rows)
    prefill = make_prefill_fn(cfg, quantized=quantized)
    decode = make_decode_fn(cfg, quantized=quantized)
    cache = init_cache(cfg, 2, S_MAX, jnp.float32)
    cache, logits = prefill(params, cache, seq[:, :PROMPT],
                            jnp.ones((PROMPT,), jnp.int32),
                            jnp.int32(0))
    rows = [logits[0]]
    for t in range(PROMPT, S_TOTAL):
        cache, logits = decode(params, cache,
                               jnp.asarray([int(seq[0, t]), 0],
                                           jnp.int32),
                               jnp.asarray([True, False]))
        rows.append(logits[0])
    return jnp.stack(rows)


def _nll_curve(cfg, params, seq, **kw):
    """Per-position teacher-forced NLL: row at position t scores the
    true token seq[t+1] (the last row has no target)."""
    rows = _teacher_forced_rows(cfg, params, seq, **kw)[:-1]
    tgt = np.asarray(seq[0, PROMPT:])
    lse = jax.nn.logsumexp(rows, axis=-1)
    return np.asarray(lse - rows[np.arange(len(tgt)), tgt])


@pytest.fixture(scope="module")
def golden_nll(trained):
    cfg, params, seq = trained
    curve = _nll_curve(cfg, params, seq, paged=False, cache_dtype=None,
                       quantized=False)
    # the overfit actually bit: mean eval NLL is clearly under the
    # uniform floor, so the parity assertions compare real predictions
    assert np.all(np.isfinite(curve))
    assert curve.mean() < np.log(cfg.vocab_size) - 0.5, curve
    return curve


@pytest.mark.parametrize("variant", ["w8_dense", "w8_paged",
                                     "w8_kv8", "kv8_only"])
def test_quant_eval_curve_tracks_fp32(trained, golden_nll, variant):
    cfg, params, seq = trained
    qp = quantize_params(params)
    curve = {
        "w8_dense": lambda: _nll_curve(cfg, qp, seq, paged=False,
                                       cache_dtype=None,
                                       quantized=True),
        "w8_paged": lambda: _nll_curve(cfg, qp, seq, paged=True,
                                       cache_dtype=jnp.float32,
                                       quantized=True),
        "w8_kv8": lambda: _nll_curve(cfg, qp, seq, paged=True,
                                     cache_dtype=jnp.int8,
                                     quantized=True),
        "kv8_only": lambda: _nll_curve(cfg, params, seq, paged=True,
                                       cache_dtype=jnp.int8,
                                       quantized=False),
    }[variant]()
    assert np.all(np.isfinite(curve))
    np.testing.assert_allclose(curve, golden_nll,
                               rtol=QUANT_EVAL_RTOL)
    # the curves must NOT be identical — proof the int8 path ran
    assert np.any(curve != golden_nll)

"""Test rig: run everything on an 8-virtual-device CPU mesh.

The reference tests multi-GPU paths only with real GPUs
(``skipIf(torch.cuda.device_count() < N)``, SURVEY.md §4). On TPU/JAX we can
do better: XLA's CPU backend exposes N virtual devices, so every DP/TP/PP/SP
code path is exercised in CI with no accelerator. Pallas kernels run in
interpreter mode off-TPU (see ``apex_tpu.utils.platform``).

The session environment pins ``JAX_PLATFORMS`` to the TPU tunnel (axon) and
``sitecustomize`` imports jax at interpreter startup, so env vars are
already latched — we must go through ``jax.config`` instead (backends are
not initialized until the first ``jax.devices()`` call).
"""

import os

import jax
import pytest

_platform = os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)
if _platform == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: the option doesn't exist — the XLA flag read at
        # backend init does the same job (works as long as no device has
        # been touched yet, which conftest import order guarantees)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")

# Persistent compilation cache: the suite's wall time is dominated by XLA
# compiles on this host's single CPU core, and most test programs are
# identical run to run — cache them so iterating on one module doesn't
# recompile the world. Exported to the environment too, so the
# subprocess-driving tests (examples, graft entry) inherit it.
_cache_dir = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


# ``quick`` tier (`./run_tests.sh quick` == `-m quick`): everything
# OUTSIDE the compile- and subprocess-heavy modules below and the L1
# convergence sweeps — the contributor/driver inner loop. The full
# `-m 'not slow'` tier remains the gate; quick only ADDS a marker, it
# never hides a test from the default run.
_HEAVY_MODULES = {
    "test_bench_parent.py",     # bench.py subprocesses
    "test_resume.py",           # kill-and-resume subprocess
    "test_graft_entry.py",      # in-process dryrun (all mesh shapes)
    "test_gpt.py",              # tp8/pp/cp shard_map compiles
    "test_models.py",           # resnet18/50 builds
    "test_determinism.py",      # profiler + bitwise train steps
    "test_pipeline_memory.py",  # compiled-memory analysis
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        p = item.path
        if p.name not in _HEAVY_MODULES and "L1" not in p.parts:
            item.add_marker(pytest.mark.quick)


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    """Each test starts with no global mesh installed."""
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    yield
    parallel_state.destroy_model_parallel()

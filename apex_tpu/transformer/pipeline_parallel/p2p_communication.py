"""Stage-to-stage activation/grad exchange over the ``pipe`` mesh axis.

Reference: ``apex/transformer/pipeline_parallel/p2p_communication.py ::
_communicate`` — NCCL ``batch_isend_irecv`` between adjacent pipeline
ranks, with a shape/dtype handshake for ``variable_seq_lengths``.

TPU-native redesign: under single-controller SPMD there are no point-to-
point sockets — the exchange is ONE ``lax.ppermute`` (XLA collective-
permute, which rides a direct ICI hop between mesh-adjacent chips).  A
"send" on stage *i* and the matching "recv" on stage *i+1* are the same
collective, so the reference's eight send/recv entry points collapse into
ring shifts:

- forward direction (activations):   shift **+1** along ``pipe``
- backward direction (gradients):    shift **-1** along ``pipe``

The shape handshake disappears entirely: XLA requires static shapes, so
both sides always agree by construction (``variable_seq_lengths`` is
handled at a higher level by bucketing/padding batches, the standard TPU
approach).

All functions must be called INSIDE ``parallel_state.shard_map`` (or any
mapped region binding the ``pipe`` axis).  They are linear, so JAX's
built-in transpose gives the correct dual (a reversed ppermute) under
``jax.grad`` — no custom_vjp needed.

The wraparound link (last stage -> first stage) is included in the ring;
schedules mask the wrapped value where the reference would simply not
post a recv.  On hardware the extra hop is off the critical path (it
overlaps with the first stage's injection compute).
"""

from typing import Any

import jax
from jax import lax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size


def _ring(n: int, step: int):
    return [(i, (i + step) % n) for i in range(n)]


def _shift(x: Any, step: int) -> Any:
    """ppermute every leaf of ``x`` by ``step`` stages along ``pipe``."""
    n = axis_size(ps.PIPE_AXIS)
    perm = _ring(n, step)
    return jax.tree.map(lambda a: lax.ppermute(a, ps.PIPE_AXIS, perm), x)


# -- reference-shaped API ----------------------------------------------------
# Each reference send/recv PAIR is one collective here; the lone send_* and
# recv_* names are kept as documented aliases of the combined op so schedule
# code written against the reference API ports mechanically.

def send_forward_recv_forward(output_tensor: Any) -> Any:
    """Send activations to the next stage; return what the previous stage
    sent us (ref: ``send_forward`` + ``recv_forward`` fused)."""
    return _shift(output_tensor, +1)


def send_backward_recv_backward(input_tensor_grad: Any) -> Any:
    """Send grads to the previous stage; return the next stage's grads
    (ref: ``send_backward`` + ``recv_backward`` fused)."""
    return _shift(input_tensor_grad, -1)


def send_forward_recv_backward(output_tensor: Any,
                               input_tensor_grad: Any) -> Any:
    """1F1B steady-state exchange: activations go +1 while grads go -1
    (ref: ``send_forward_recv_backward``). Returns (recv_fwd, recv_bwd)."""
    return _shift(output_tensor, +1), _shift(input_tensor_grad, -1)


def send_backward_recv_forward(input_tensor_grad: Any,
                               output_tensor: Any) -> Any:
    """Mirror of :func:`send_forward_recv_backward`; returns
    (recv_bwd, recv_fwd)."""
    return _shift(input_tensor_grad, -1), _shift(output_tensor, +1)


# Lone send/recv: under SPMD a "send" and its matching "recv" are ONE
# collective, so code ported from the reference that calls send_forward(x)
# and then recv_forward(...) — two ops in the NCCL world — would ppermute
# TWICE here and double-shift activations. Rather than silently alias,
# the lone names fail fast with the correct replacement.

def _one_collective(name: str, repl: str):
    def guard(*_a, **_k):
        raise RuntimeError(
            f"p2p_communication.{name}: under SPMD the send and its "
            f"matching recv are a single collective — call {repl}(x) "
            f"EXACTLY ONCE per exchange (it both sends and returns the "
            f"received value). Calling lone send_*/recv_* pairs as in "
            f"the reference would ppermute twice and double-shift.")
    guard.__name__ = name
    guard.__doc__ = (f"Removed alias; use :func:`{repl}` once per "
                     f"exchange (see module docstring).")
    return guard


send_forward = _one_collective("send_forward", "send_forward_recv_forward")
recv_forward = _one_collective("recv_forward", "send_forward_recv_forward")
send_backward = _one_collective("send_backward",
                                "send_backward_recv_backward")
recv_backward = _one_collective("recv_backward",
                                "send_backward_recv_backward")

"""Pipeline parallelism (ref: ``apex/transformer/pipeline_parallel``).

- :mod:`microbatches` — microbatch calculator (+ batch-size rampup)
- :mod:`p2p_communication` — stage-to-stage exchange via ppermute
- :mod:`schedules` — no-pipelining / 1F1B / interleaved collective
  schedules behind :func:`get_forward_backward_func`
"""

from apex_tpu.transformer.pipeline_parallel import (  # noqa: F401
    microbatches,
    p2p_communication,
    schedules,
)
from apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    get_current_global_batch_size,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    PipelineModel,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    split_batch_into_microbatches,
)

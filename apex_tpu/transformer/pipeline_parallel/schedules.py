"""Pipeline-parallel schedules over the ``pipe`` mesh axis.

Reference: ``apex/transformer/pipeline_parallel/schedules/__init__.py ::
get_forward_backward_func`` + ``fwd_bwd_no_pipelining.py``,
``fwd_bwd_pipelining_without_interleaving.py`` (1F1B),
``fwd_bwd_pipelining_with_interleaving.py`` (virtual/interleaved 1F1B).

TPU-native redesign — the *collective pipeline*.  The reference drives
each stage from host Python, posting NCCL p2p ops between ranks and
invoking torch autograd per microbatch.  Under XLA's single-controller
SPMD model the whole schedule is instead ONE jitted program:

- stage parameters are **stacked on a leading axis and sharded over the
  ``pipe`` mesh axis** (each device holds its stage's slice);
- the microbatch loop is a ``lax.scan`` over "ticks"; at every tick each
  device runs ONE forward microbatch (activations rotate +1 via
  ``lax.ppermute``) AND one backward microbatch (cotangents rotate -1)
  — true 1F1B steady state in a single uniform tick;
- the backward IS hand-written, with ``jax.vjp`` inside the tick: stage
  inputs are kept in a depth-``2*pp-1`` circular buffer and the
  backward recomputes the stage forward from the saved input (the
  activation-recompute discipline the reference pairs with 1F1B), so
  the scan itself is never differentiated and **live activation memory
  is O(pp × microbatch), independent of the number of microbatches** —
  the ``deallocate_output_tensor`` property, asserted on compiled HLO by
  ``tests/L0/run_transformer/test_pipeline_memory.py``;
- grad/loss accumulators ride the scan carry in fp32.

Bubble accounting: the plain schedule runs ``M + 2(pp-1)`` ticks for
``M`` microbatches — the same fill/steady/drain span as 1F1B (fill
``pp-1``, drain ``pp-1``).  The interleaved schedule uses ``vpp`` lanes
per device (virtual chunks round-robin over stages, chunk ``c`` on
device ``c % pp``) and runs ``M + 2(pp*vpp - 1)`` ticks; each tick
computes all resident lanes, so in steady state utilization matches the
reference (ticks are the same stage-size — see the module docstring of
``p2p_communication`` for why SPMD prefers uniform ticks).  Grads and
losses are bit-for-bit the same math as the reference's schedules.

On fill/drain "garbage" compute: during the bubble every stage runs its
tick body on masked data where the reference's ranks sit idle.  This is
deliberate — each stage is its own chip, so the garbage tick costs ZERO
wall-clock (the pipeline advances at one tick per step either way; the
bubble's cost is the tick COUNT, identical to the reference's 1F1B
bubble), and it keeps the scan body branch-free.  Gating the stage
behind per-device ``lax.cond`` would save only energy, at the price of
divergent control flow around the TP collectives inside ``stage_fn``.

Model contract (the functional analogue of the reference's
``forward_step_func(batch, model)`` protocol):

    model = PipelineModel(embed_fn, stage_fn, loss_fn)
    params = {"embed": ..., "stages": <leaves stacked on a leading
              stage axis>, "head": ...}

- ``embed_fn(embed_params, microbatch) -> hidden`` — first-stage input.
- ``stage_fn(one_stage_params, hidden) -> hidden`` — homogeneous body.
- ``loss_fn(head_params, hidden, microbatch) -> scalar`` — last stage.

For the pipelined schedules, call INSIDE ``parallel_state.shard_map``
with in_specs ``P(PIPE_AXIS)`` on the leading axis of ``stages`` leaves
(shape ``(pp, ...)``; interleaved: ``(vpp, pp, ...)`` with spec
``P(None, PIPE_AXIS)``) and replicated embed/head/batch.  Returned
grads match the (local) structure of ``params``; embed/head grads are
psum'd over ``pipe`` so every stage holds the full value — the analogue
of the reference's embedding-group allreduce.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size
from apex_tpu.transformer.pipeline_parallel import microbatches as mb_calc
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_backward_recv_backward,
    send_forward_recv_forward,
)

Pytree = Any


@dataclasses.dataclass(frozen=True)
class PipelineModel:
    embed_fn: Callable[[Pytree, Pytree], jax.Array]
    stage_fn: Callable[[Pytree, jax.Array], jax.Array]
    loss_fn: Callable[[Pytree, jax.Array, Pytree], jax.Array]


def split_batch_into_microbatches(batch: Pytree,
                                  num_microbatches: int) -> Pytree:
    """(B, ...) leaves -> (M, B//M, ...) (ref: the schedules' batch
    iterator; here a reshape so the microbatch loop can be a scan)."""
    def split(a):
        b = a.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch dim {b} not divisible by {num_microbatches} "
                "microbatches")
        return a.reshape((num_microbatches, b // num_microbatches)
                         + a.shape[1:])
    return jax.tree.map(split, batch)


def _num_microbatches(num_microbatches: Optional[int]) -> int:
    if num_microbatches is not None:
        return int(num_microbatches)
    return mb_calc.get_num_microbatches()


def _stage_apply(model: PipelineModel, checkpoint_stages: bool):
    fn = model.stage_fn
    return jax.checkpoint(fn) if checkpoint_stages else fn


# ---------------------------------------------------------------------------
# no pipelining
# ---------------------------------------------------------------------------

def forward_backward_no_pipelining(
    model: PipelineModel,
    params: Dict[str, Pytree],
    batch: Pytree,
    *,
    num_microbatches: Optional[int] = None,
    forward_only: bool = False,
    checkpoint_stages: bool = True,
    fp32_grad_accum: bool = True,
) -> Tuple[jax.Array, Optional[Pytree]]:
    """Grad accumulation over microbatches, no pipe collectives
    (ref: ``fwd_bwd_no_pipelining.py``). Usable with or without a mesh.

    ``fp32_grad_accum`` is the ``gradient_accumulation_fusion`` analogue
    (ref: ``fused_weight_gradient_mlp_cuda`` writing wgrads straight into
    fp32 ``main_grad`` buffers): the accumulator tree is fp32 regardless
    of param/compute dtype, so M bf16 microbatch grads don't lose low
    bits as they sum, and the fp32 result feeds the optimizer directly
    (every ``apex_tpu`` optimizer consumes fp32 grads natively — the
    TPU "fusion" is that XLA folds the widening cast into the bwd GEMM's
    epilogue rather than a separate kernel).
    """
    M = _num_microbatches(num_microbatches)
    mbs = split_batch_into_microbatches(batch, M)
    stage = _stage_apply(model, checkpoint_stages)

    def mb_loss(p, mb):
        x = model.embed_fn(p["embed"], mb)
        x, _ = lax.scan(lambda h, sp: (stage(sp, h), None), x, p["stages"])
        return model.loss_fn(p["head"], x, mb)

    zero = jnp.zeros((), jnp.float32)
    if forward_only:
        total, _ = lax.scan(
            lambda acc, mb: (acc + mb_loss(params, mb), None), zero, mbs)
        return total / M, None

    vg = jax.value_and_grad(mb_loss)
    acc_dtype = (lambda a: jnp.promote_types(a.dtype, jnp.float32)) \
        if fp32_grad_accum else (lambda a: a.dtype)

    def step(carry, mb):
        tot, g = carry
        loss, gi = vg(params, mb)
        g = jax.tree.map(lambda a, b: a + b.astype(a.dtype), g, gi)
        return (tot + loss, g), None

    zero_g = jax.tree.map(
        lambda a: jnp.zeros(a.shape, acc_dtype(a)), params)
    (total, grads), _ = lax.scan(step, (zero, zero_g), mbs)
    grads = jax.tree.map(lambda a: a / M, grads)
    return total / M, grads


# ---------------------------------------------------------------------------
# plain (non-interleaved) pipelining — 1F1B equivalent
# ---------------------------------------------------------------------------

def _mb_at(mbs: Pytree, i, M: int) -> Pytree:
    """Dynamic microbatch slice (clipped; callers mask invalid ticks)."""
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, jnp.clip(i, 0, M - 1), 0,
                                           keepdims=False), mbs)


def _hidden_proto(model: PipelineModel, embed_p, mb0):
    shape = jax.eval_shape(model.embed_fn, embed_p, mb0)
    return jnp.zeros(shape.shape, shape.dtype)


def _masked_axpy(acc: Pytree, upd: Pytree, valid) -> Pytree:
    return jax.tree.map(
        lambda a, b: a + jnp.where(valid, b, 0).astype(a.dtype), acc, upd)


def _zeros_f32_like(tree: Pytree) -> Pytree:
    return jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.promote_types(a.dtype,
                                                       jnp.float32)), tree)


def forward_backward_pipelining_without_interleaving(
    model: PipelineModel,
    params: Dict[str, Pytree],
    batch: Pytree,
    *,
    num_microbatches: Optional[int] = None,
    forward_only: bool = False,
    checkpoint_stages: bool = True,
) -> Tuple[jax.Array, Optional[Pytree]]:
    """Collective 1F1B (ref: ``fwd_bwd_pipelining_without_interleaving``).

    Call inside shard_map; ``params["stages"]`` leaves arrive as the
    local ``(1, ...)`` slice of the ``(pp, ...)`` stack.

    **Memory discipline** (the schedule's reason to exist — ref:
    ``deallocate_output_tensor`` + the warmup/steady/cooldown split).  The
    backward is written INTO the tick by hand with ``jax.vjp`` rather
    than differentiating the microbatch scan: tick ``t`` forwards
    microbatch ``t - d`` AND backwards microbatch ``t - 2(pp-1) + d``
    (1F1B steady state), with the forward's stage inputs kept in a
    circular buffer of depth ``2*pp - 1`` — the live-activation bound is
    therefore **O(pp × microbatch), independent of the global batch**,
    and the scan itself is never differentiated so no per-tick residuals
    accumulate (``tests/L0/run_transformer/test_pipeline_memory.py``
    asserts the compiled peak temp memory is flat in M).  The backward
    recomputes the stage forward from the saved input (the
    activation-recompute discipline the reference pairs with 1F1B);
    ``checkpoint_stages`` is accepted for API compatibility but the
    recompute is inherent here.

    Grad accumulation is fp32 regardless of param dtype (the schedule-
    level ``gradient_accumulation_fusion`` analogue).
    """
    del checkpoint_stages  # recompute-from-saved-input is inherent
    M = _num_microbatches(num_microbatches)
    mbs = split_batch_into_microbatches(batch, M)
    pp = axis_size(ps.PIPE_AXIS)
    d = lax.axis_index(ps.PIPE_AXIS)
    stage = model.stage_fn
    stage_p = jax.tree.map(lambda a: a[0], params["stages"])
    embed_p, head_p = params["embed"], params["head"]
    state0 = _hidden_proto(model, embed_p, _mb_at(mbs, 0, M))

    if forward_only:
        T = M + pp - 1

        def tick_f(carry, t):
            state, acc = carry
            x_in = jnp.where(d == 0,
                             model.embed_fn(embed_p, _mb_at(mbs, t, M)),
                             state)
            y = stage(stage_p, x_in)
            m_l = t - (pp - 1)
            l = model.loss_fn(head_p, y, _mb_at(mbs, m_l, M))
            acc = acc + jnp.where((m_l >= 0) & (d == pp - 1),
                                  l.astype(jnp.float32), 0.0)
            return (send_forward_recv_forward(y), acc), None

        (_, total), _ = lax.scan(tick_f, (state0, jnp.float32(0)),
                                 jnp.arange(T))
        return lax.psum(total / M, ps.PIPE_AXIS), None

    R = 2 * pp - 1          # residual-ring depth: max input residency
    T = M + 2 * (pp - 1)    # fill + steady 1F1B + drain

    def tick(carry, t):
        state, cot, ring, g_stage, g_embed, g_head, loss_acc = carry

        # -- forward half: stage d forwards microbatch t - d ------------
        m_f = t - d
        fwd_valid = (m_f >= 0) & (m_f < M)
        x_in = jnp.where(d == 0,
                         model.embed_fn(embed_p, _mb_at(mbs, t, M)),
                         state)
        y = stage(stage_p, x_in)
        slot_f = jnp.mod(m_f, R)
        old = lax.dynamic_index_in_dim(ring, slot_f, 0, keepdims=False)
        ring = lax.dynamic_update_index_in_dim(
            ring, jnp.where(fwd_valid, x_in, old), slot_f, 0)

        # -- loss half: last stage seeds the backward from this tick's y
        m_l = t - (pp - 1)
        loss_valid = (m_l >= 0) & (m_l < M)
        mb_l = _mb_at(mbs, m_l, M)
        l, loss_vjp = jax.vjp(
            lambda hp, yy: model.loss_fn(hp, yy, mb_l), head_p, y)
        seed = jnp.where(loss_valid & (d == pp - 1), 1.0 / M, 0.0)
        dhead, dy_loss = loss_vjp(seed.astype(l.dtype))
        loss_acc = loss_acc + jnp.where(loss_valid & (d == pp - 1),
                                        l.astype(jnp.float32), 0.0)
        g_head = _masked_axpy(g_head, dhead, True)  # seed already masks

        # -- backward half: stage d backwards microbatch t - 2(pp-1) + d
        m_b = t - 2 * (pp - 1) + d
        bwd_valid = (m_b >= 0) & (m_b < M)
        g_in = jnp.where(d == pp - 1, dy_loss, cot)
        x_saved = lax.dynamic_index_in_dim(ring, jnp.mod(m_b, R), 0,
                                           keepdims=False)
        _, stage_vjp = jax.vjp(stage, stage_p, x_saved)
        dstage, dx = stage_vjp(g_in)
        g_stage = _masked_axpy(g_stage, dstage, bwd_valid)
        mb_b = _mb_at(mbs, m_b, M)
        _, embed_vjp = jax.vjp(lambda ep: model.embed_fn(ep, mb_b),
                               embed_p)
        (dembed,) = embed_vjp(dx)
        g_embed = _masked_axpy(g_embed, dembed, bwd_valid & (d == 0))

        return (send_forward_recv_forward(y),
                send_backward_recv_backward(dx),
                ring, g_stage, g_embed, g_head, loss_acc), None

    carry0 = (state0, jnp.zeros_like(state0),
              jnp.zeros((R,) + state0.shape, state0.dtype),
              _zeros_f32_like(stage_p), _zeros_f32_like(embed_p),
              _zeros_f32_like(head_p), jnp.float32(0))
    (_, _, _, g_stage, g_embed, g_head, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(T))

    loss = lax.psum(loss_acc, ps.PIPE_AXIS) / M
    grads = {
        "stages": jax.tree.map(lambda a: a[None], g_stage),
        # embed grads live on stage 0 (injection), head grads on the last
        # stage (loss seed): replicate both — the analogue of the
        # reference's embedding-group allreduce
        "embed": lax.psum(g_embed, ps.PIPE_AXIS),
        "head": lax.psum(g_head, ps.PIPE_AXIS),
    }
    return loss, grads


# ---------------------------------------------------------------------------
# interleaved (virtual pipeline) — lanes of round-robin chunks
# ---------------------------------------------------------------------------

def forward_backward_pipelining_with_interleaving(
    model: PipelineModel,
    params: Dict[str, Pytree],
    batch: Pytree,
    *,
    num_microbatches: Optional[int] = None,
    forward_only: bool = False,
    checkpoint_stages: bool = True,
    virtual_pipeline_size: Optional[int] = None,
) -> Tuple[jax.Array, Optional[Pytree]]:
    """Interleaved schedule (ref: ``fwd_bwd_pipelining_with_interleaving``).

    Model chunk ``c`` (of ``pp*vpp``) lives on device ``c % pp`` —
    exactly the reference's round-robin assignment.  ``params["stages"]``
    leaves arrive as the local ``(vpp, 1, ...)`` slice of a
    ``(vpp, pp, ...)`` stack (``[l, dev]`` = chunk ``l*pp + dev``).
    Each device keeps ``vpp`` activation lanes; lane ``l`` holds the
    microbatch currently entering chunk ``l*pp + dev``.  One ppermute
    per tick rotates all lanes; the first stage additionally rolls
    lanes by one (a chunk boundary wraps from the last stage back to
    the first).
    """
    vpp = virtual_pipeline_size or \
        ps.get_virtual_pipeline_model_parallel_world_size()
    if vpp is None or vpp < 1:
        raise ValueError("interleaved schedule requires a virtual "
                         "pipeline size (initialize_model_parallel("
                         "virtual_pipeline_model_parallel_size_=...))")
    del checkpoint_stages  # recompute-from-saved-input is inherent
    M = _num_microbatches(num_microbatches)
    mbs = split_batch_into_microbatches(batch, M)
    pp = axis_size(ps.PIPE_AXIS)
    d = lax.axis_index(ps.PIPE_AXIS)
    stage = model.stage_fn
    stage_p = jax.tree.map(lambda a: a[:, 0], params["stages"])  # (vpp,...)
    embed_p, head_p = params["embed"], params["head"]
    n_chunks = pp * vpp
    # chunk ids this device hosts, one per lane: c(l) = l*pp + d
    chunk = jnp.arange(vpp) * pp + d
    state0 = _hidden_proto(model, embed_p, _mb_at(mbs, 0, M))
    lanes0 = jnp.zeros((vpp,) + state0.shape, state0.dtype)

    def fwd_lanes(t, lanes):
        """One tick of the forward wave: inject at chunk 0, apply every
        resident chunk, rotate +1 with the stage-0 lane roll (a chunk
        boundary wraps from the last stage back to the first)."""
        inject = model.embed_fn(embed_p, _mb_at(mbs, t, M))
        lane0 = jnp.where(d == 0, inject, lanes[0])
        x_in = jnp.concatenate([lane0[None], lanes[1:]], axis=0)
        ys = jax.vmap(stage)(stage_p, x_in)
        return x_in, ys

    def rotate_fwd(ys):
        recv = send_forward_recv_forward(ys)
        return jnp.where(d == 0, jnp.roll(recv, 1, axis=0), recv)

    if forward_only:
        T = M + n_chunks - 1

        def tick_f(carry, t):
            lanes, acc = carry
            _, ys = fwd_lanes(t, lanes)
            m_l = t - (n_chunks - 1)
            l = model.loss_fn(head_p, ys[vpp - 1], _mb_at(mbs, m_l, M))
            acc = acc + jnp.where((m_l >= 0) & (d == pp - 1),
                                  l.astype(jnp.float32), 0.0)
            return (rotate_fwd(ys), acc), None

        (_, total), _ = lax.scan(tick_f, (lanes0, jnp.float32(0)),
                                 jnp.arange(T))
        return lax.psum(total / M, ps.PIPE_AXIS), None

    # Backward written into the tick, as in the plain schedule: chunk c
    # forwards microbatch t-c and backwards microbatch t-2(N-1)+c, with
    # per-lane input rings of depth 2N-1 bounding live activations at
    # O(vpp * N * microbatch) — the interleaved schedule's higher
    # in-flight count, independent of M.
    R = 2 * n_chunks - 1
    T = M + 2 * (n_chunks - 1)

    def tick(carry, t):
        lanes, cot, ring, g_stage, g_embed, g_head, loss_acc = carry

        # -- forward half ----------------------------------------------
        m_f = t - chunk                       # (vpp,) microbatch per lane
        x_in, ys = fwd_lanes(t, lanes)
        slot_f = jnp.mod(m_f, R)
        fwd_valid = (m_f >= 0) & (m_f < M)

        def save(ring_l, x_l, slot_l, ok_l):
            old = lax.dynamic_index_in_dim(ring_l, slot_l, 0,
                                           keepdims=False)
            return lax.dynamic_update_index_in_dim(
                ring_l, jnp.where(ok_l, x_l, old), slot_l, 0)

        ring = jax.vmap(save)(ring, x_in, slot_f, fwd_valid)

        # -- loss half: chunk N-1 = lane vpp-1 on the last stage -------
        m_l = t - (n_chunks - 1)
        loss_valid = (m_l >= 0) & (m_l < M)
        mb_l = _mb_at(mbs, m_l, M)
        l, loss_vjp = jax.vjp(
            lambda hp, yy: model.loss_fn(hp, yy, mb_l), head_p,
            ys[vpp - 1])
        seed = jnp.where(loss_valid & (d == pp - 1), 1.0 / M, 0.0)
        dhead, dy_loss = loss_vjp(seed.astype(l.dtype))
        loss_acc = loss_acc + jnp.where(loss_valid & (d == pp - 1),
                                        l.astype(jnp.float32), 0.0)
        g_head = _masked_axpy(g_head, dhead, True)  # seed already masks

        # -- backward half ---------------------------------------------
        m_b = t - 2 * (n_chunks - 1) + chunk  # (vpp,)
        bwd_valid = (m_b >= 0) & (m_b < M)
        # chunk N-1 seeds from this tick's loss; every other chunk uses
        # the cotangent received from chunk c+1 (rotated in last tick)
        last = (jnp.arange(vpp) == vpp - 1) & (d == pp - 1)
        g_in = jnp.where(last.reshape((vpp,) + (1,) * dy_loss.ndim),
                         dy_loss[None], cot)
        x_saved = jax.vmap(
            lambda ring_l, slot_l: lax.dynamic_index_in_dim(
                ring_l, slot_l, 0, keepdims=False))(ring, jnp.mod(m_b, R))

        def lane_bwd(sp_l, x_l, g_l):
            _, vjp_l = jax.vjp(stage, sp_l, x_l)
            return vjp_l(g_l)

        dstage, dx = jax.vmap(lane_bwd)(stage_p, x_saved, g_in)
        g_stage = jax.tree.map(
            lambda a, b: a + jnp.where(
                bwd_valid.reshape((vpp,) + (1,) * (b.ndim - 1)), b, 0
            ).astype(a.dtype), g_stage, dstage)
        # chunk 0 (lane 0, stage 0) feeds the embed backward
        mb_b0 = _mb_at(mbs, m_b[0], M)
        _, embed_vjp = jax.vjp(lambda ep: model.embed_fn(ep, mb_b0),
                               embed_p)
        (dembed,) = embed_vjp(dx[0])
        g_embed = _masked_axpy(g_embed, dembed, bwd_valid[0] & (d == 0))

        # rotate: activations +1 with stage-0 roll; cotangents -1 with
        # the mirrored roll at the LAST stage (chunk (l+1)*pp flows back
        # to chunk l*pp + pp-1)
        cot_recv = send_backward_recv_backward(dx)
        cot_next = jnp.where(d == pp - 1, jnp.roll(cot_recv, -1, axis=0),
                             cot_recv)
        return (rotate_fwd(ys), cot_next, ring, g_stage, g_embed, g_head,
                loss_acc), None

    carry0 = (lanes0, jnp.zeros_like(lanes0),
              jnp.zeros((vpp, R) + state0.shape, state0.dtype),
              _zeros_f32_like(stage_p), _zeros_f32_like(embed_p),
              _zeros_f32_like(head_p), jnp.float32(0))
    (_, _, _, g_stage, g_embed, g_head, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(T))

    loss = lax.psum(loss_acc, ps.PIPE_AXIS) / M
    grads = {
        "stages": jax.tree.map(lambda a: a[:, None], g_stage),
        "embed": lax.psum(g_embed, ps.PIPE_AXIS),
        "head": lax.psum(g_head, ps.PIPE_AXIS),
    }
    return loss, grads


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------

def get_forward_backward_func() -> Callable[..., Tuple[jax.Array,
                                                       Optional[Pytree]]]:
    """Pick the schedule from the global parallel state (ref:
    ``schedules/__init__.py :: get_forward_backward_func``)."""
    if ps.get_pipeline_model_parallel_world_size() == 1:
        return forward_backward_no_pipelining
    if ps.get_virtual_pipeline_model_parallel_world_size() is not None:
        return forward_backward_pipelining_with_interleaving
    return forward_backward_pipelining_without_interleaving

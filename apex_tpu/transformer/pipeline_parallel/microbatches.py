"""Microbatch bookkeeping.

Reference: ``apex/transformer/microbatches.py`` +
``pipeline_parallel/utils.py`` — a module-global calculator created by
``setup_microbatch_calculator``; ``ConstantNumMicroBatches`` and
``RampupBatchsizeNumMicroBatches`` (linear global-batch ramp over
consumed samples, in ``batch_size_increment`` steps).
"""

from typing import List, Optional

from apex_tpu.utils.math import ensure_divisibility


class NumMicroBatchesCalculator:
    num_micro_batches: int
    current_global_batch_size: int

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        micro_times_dp = micro_batch_size * data_parallel_size
        ensure_divisibility(global_batch_size, micro_times_dp)
        self.num_micro_batches = global_batch_size // micro_times_dp
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear ramp: global batch grows from ``start_batch_size`` by
    ``batch_size_increment`` every ``rampup_samples /
    ((global-start)/increment)`` consumed samples (reference formula)."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = \
            micro_batch_size * data_parallel_size

        diff = global_batch_size - start_batch_size
        ensure_divisibility(diff, batch_size_increment)
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = ramup_samples / num_increments

        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples:
            current = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            current = self.start_batch_size \
                + steps * self.batch_size_increment
            assert current <= self.global_batch_size
        if consistency_check:
            ensure_divisibility(
                current, self.micro_batch_times_data_parallel_size)
        self.current_global_batch_size = current
        self.num_micro_batches = max(
            1, current // self.micro_batch_times_data_parallel_size)


_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def setup_microbatch_calculator(
        rank: int, rampup_batch_size: Optional[List[int]],
        global_batch_size: int, micro_batch_size: int,
        data_parallel_size: int) -> None:
    """ref: ``pipeline_parallel/utils.py :: setup_microbatch_calculator``.
    ``rampup_batch_size`` = [start, increment, samples] or None."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if rampup_batch_size is None:
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
    else:
        start, inc, samples = rampup_batch_size
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR = RampupBatchsizeNumMicroBatches(
            start, inc, samples, global_batch_size, micro_batch_size,
            data_parallel_size)


def get_num_microbatches() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def destroy_num_microbatches_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None

"""Logging helpers. Reference: ``apex/transformer/log_util.py ::
set_logging_level``."""

import logging

_LOGGER_NAME = "apex_tpu"


def get_transformer_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Set the apex_tpu logger level (int or logging level name)."""
    if isinstance(verbosity, str):
        verbosity = getattr(logging, verbosity.upper())
    logging.getLogger(_LOGGER_NAME).setLevel(verbosity)

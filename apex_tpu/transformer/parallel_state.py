"""Model- and data-parallel state over a ``jax.sharding.Mesh``.

TPU-native equivalent of the reference's global process-group registry
(ref: ``apex/transformer/parallel_state.py :: initialize_model_parallel``).
Where the reference builds NCCL process groups (DP / TP / PP / embedding)
with ``torch.distributed.new_group``, we build ONE device mesh with named
axes and treat each axis as the "group":

- ``data``    — data parallelism (gradient psum rides this axis)
- ``pipe``    — pipeline stages (ppermute of activations rides this axis)
- ``context`` — context/sequence-block parallelism for ring attention
  (not present in the reference — see SURVEY.md §2c — but first-class here)
- ``model``   — tensor parallelism (Megatron column/row sharding). The
  Megatron-style *sequence parallel* region also lives on this axis, exactly
  as in the reference (``sequence_parallel_enabled`` shards activations over
  the TP group).

Axis order is chosen so that ``model`` is innermost: adjacent device ids sit
on the same ICI link on a real pod slice, so the per-layer TP collectives
(the hottest comm in the stack, ref ``apex/transformer/tensor_parallel/
mappings.py``) ride ICI, while ``data``/``pipe`` traffic may cross DCN on
multi-slice topologies.

Rank accessors work both on the host (returning the static value for a
single-controller program: 0) and inside ``shard_map``/``jit`` where they
return the traced ``lax.axis_index``. "Groups" are just axis names; every
collective in this package takes the axis name from here.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh

# Canonical axis names. Other modules must use these constants rather than
# string literals so a future re-ordering stays local to this file.
DATA_AXIS = "data"
PIPE_AXIS = "pipe"
CONTEXT_AXIS = "context"
TENSOR_AXIS = "model"

MESH_AXIS_NAMES = (DATA_AXIS, PIPE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)

_MESH: Optional[Mesh] = None
# Virtual pipeline (interleaved 1F1B) bookkeeping, mirroring the reference's
# module-level globals.
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None


class ParallelStateError(RuntimeError):
    pass


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    context_parallel_size_: int = 1,
    data_parallel_size_: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and register the global mesh.

    Signature mirrors the reference (``parallel_state.py ::
    initialize_model_parallel``); data-parallel size is inferred as
    ``world // (tp * pp * cp)``. ``data_parallel_size_`` is a validation
    hook (used by ``partition.make_mesh``): when given, the inferred dp
    must equal it. Returns the mesh (also installed globally).
    """
    global _MESH
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK

    if devices is None:
        devices = jax.devices()
    world = len(devices)
    tp = int(tensor_model_parallel_size_)
    pp = int(pipeline_model_parallel_size_)
    cp = int(context_parallel_size_)
    denom = tp * pp * cp
    if denom <= 0 or world % denom != 0:
        raise ParallelStateError(
            f"world size {world} not divisible by tp*pp*cp = {tp}*{pp}*{cp}"
        )
    dp = world // denom
    if data_parallel_size_ is not None and dp != int(data_parallel_size_):
        raise ParallelStateError(
            f"requested data_parallel_size {data_parallel_size_} but world "
            f"{world} with tp*pp*cp = {tp}*{pp}*{cp} gives dp = {dp}"
        )
    if virtual_pipeline_model_parallel_size_ is not None and pp < 2:
        raise ParallelStateError(
            "virtual pipeline parallelism requires pipeline_model_parallel_size >= 2"
        )

    mesh_devices = np.asarray(devices, dtype=object).reshape(dp, pp, cp, tp)
    _MESH = Mesh(mesh_devices, MESH_AXIS_NAMES)
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = (
        virtual_pipeline_model_parallel_size_
    )
    # Reset (not leak) the virtual rank across re-initializations, matching
    # the reference which sets it to 0 whenever a virtual size is given.
    set_virtual_pipeline_model_parallel_rank(
        0 if virtual_pipeline_model_parallel_size_ is not None else None
    )
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank_
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def destroy_model_parallel() -> None:
    """Forget the global mesh (ref: ``destroy_model_parallel``)."""
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None


def get_mesh() -> Mesh:
    if _MESH is None:
        # Lazy default: a pure data-parallel mesh over all devices, so
        # single-chip flows work without an explicit initialize call.
        initialize_model_parallel()
    return _MESH


def shard_map(f, *, mesh: Optional[Mesh] = None, in_specs, out_specs,
              **kwargs):
    """``jax.shard_map`` over the global mesh with ``check_vma=False``.

    Two reasons this wrapper exists (use it for every mapped region in
    this package):

    - Pallas kernels in interpreter mode (the CPU test rig) reject mixed
      varying/unvarying operands under ``check_vma=True`` (JAX's own error
      suggests disabling it).
    - ``check_vma=False`` restores the classic semantics where ``jax.grad``
      inside the body yields LOCAL gradients (no implicit cross-axis psum
      for replicated params) — the torch model the reference's DDP and TP
      layers are written against; collectives stay explicit.
    """
    # jax promoted shard_map out of experimental and renamed check_rep ->
    # check_vma along the way; support both so this imports on every rig
    # (CI pins an older jax than the driver).
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
        kwargs["check_rep"] = kwargs.pop("check_vma", False)
    else:
        kwargs.setdefault("check_vma", False)
    return sm(f, mesh=mesh or get_mesh(), in_specs=in_specs,
              out_specs=out_specs, **kwargs)


# ---------------------------------------------------------------------------
# "Groups" — axis names.
# ---------------------------------------------------------------------------

def get_tensor_model_parallel_group() -> str:
    return TENSOR_AXIS


def get_pipeline_model_parallel_group() -> str:
    return PIPE_AXIS


def get_data_parallel_group() -> str:
    return DATA_AXIS


def get_context_parallel_group() -> str:
    return CONTEXT_AXIS


def get_embedding_group() -> str:
    # The reference builds a dedicated group of {first, last} pipeline stage
    # for embedding-weight allreduce. On a mesh that collective is a psum
    # over the pipe axis masked to those stages; callers use PIPE_AXIS.
    return PIPE_AXIS


# ---------------------------------------------------------------------------
# World sizes (static, from the mesh shape).
# ---------------------------------------------------------------------------

def _axis_size(name: str) -> int:
    return get_mesh().shape[name]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PIPE_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


# ---------------------------------------------------------------------------
# Ranks. Inside shard_map/jit over the mesh these are traced axis indices;
# on the host of a single-controller program they are 0 (every collective
# that cares about rank runs inside shard_map anyway).
# ---------------------------------------------------------------------------

def _axis_rank(name: str):
    try:
        return lax.axis_index(name)
    except NameError:
        return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPE_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS)


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_tensor_model_parallel_src_rank() -> int:
    """Index-0 position along the TP axis (broadcast source)."""
    return 0


def get_data_parallel_src_rank() -> int:
    return 0


def get_pipeline_model_parallel_first_rank() -> int:
    return 0


def get_pipeline_model_parallel_last_rank() -> int:
    return get_pipeline_model_parallel_world_size() - 1


def get_pipeline_model_parallel_next_rank():
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() + 1) % pp


def get_pipeline_model_parallel_prev_rank():
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() - 1) % pp


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """True on the first pipeline stage (traced inside shard_map).

    Mirrors the reference's virtual-pipeline handling: with interleaving,
    only virtual rank 0 on pipe rank 0 is "first".
    """
    if not ignore_virtual:
        vpp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vpp is not None and (_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK or 0) != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vpp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vpp is not None and (
            (_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK or 0) != vpp - 1
        ):
            return False
    return (
        get_pipeline_model_parallel_rank()
        == get_pipeline_model_parallel_world_size() - 1
    )


def get_model_parallel_world_size() -> int:
    """Deprecated-style accessor (reference keeps it for Megatron compat):
    tensor-parallel world size, valid when pp == 1."""
    return get_tensor_model_parallel_world_size()


def get_model_parallel_rank():
    return get_tensor_model_parallel_rank()

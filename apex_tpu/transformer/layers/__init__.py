"""LayerNorm re-export (ref: ``apex/transformer/layers/layer_norm.py``
bridges to ``fast_layer_norm`` when the hidden size has a persist kernel,
else ``fused_layer_norm``; on TPU there is one seqlen-generic Pallas LN, so
both names resolve to it)."""

from apex_tpu.normalization import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)

# the reference's persist-kernel alias
FastLayerNorm = FusedLayerNorm

"""Megatron-style model parallelism over a TPU device mesh.

Reference: ``apex/transformer/__init__.py`` — re-exports parallel_state,
tensor_parallel, pipeline_parallel and the AMP/functional helpers.
"""

from apex_tpu.transformer import functional  # noqa: F401
from apex_tpu.transformer import layers  # noqa: F401
from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType,
    AttnType,
    LayerType,
    ModelType,
)
from apex_tpu.transformer.log_util import (  # noqa: F401
    get_transformer_logger,
    set_logging_level,
)

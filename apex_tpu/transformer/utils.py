"""Transformer-layer utilities.

Reference: ``apex/transformer/utils.py`` (``divide``, ``ensure_divisibility``,
``split_tensor_along_last_dim``).
"""

import jax.numpy as jnp

from apex_tpu.utils.math import divide, ensure_divisibility  # noqa: F401


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Split a tensor along its last dimension into equal partitions.

    Returns a tuple of arrays (contiguity is a non-concept in XLA, so the
    reference's ``contiguous_split_chunks`` flag is dropped).
    """
    divide(tensor.shape[-1], num_partitions)
    return tuple(jnp.split(tensor, num_partitions, axis=-1))

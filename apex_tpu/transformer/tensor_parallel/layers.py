"""Megatron-style tensor-parallel layers.

Reference: ``apex/transformer/tensor_parallel/layers.py`` —
``ColumnParallelLinear`` (weight sharded on the output dim),
``RowParallelLinear`` (input dim), ``VocabParallelEmbedding`` (vocab-range
shard + allreduce), plus ``linear_with_grad_accumulation_and_async_allreduce``
(the ``gradient_accumulation_fusion`` wgrad path backed by
``fused_weight_gradient_mlp_cuda``).

Execution model: ``init`` builds FULL (unsharded) params on the host;
``apply`` runs INSIDE ``parallel_state.shard_map`` where each rank sees its
LOCAL shard (the shard_map in_specs — from ``partition_specs()`` — do the
splitting; GSPMD keeps the global array sharded at rest). Async-overlapped
grad allreduce and wgrad-accumulation fusion fall out of XLA's scheduler
rather than hand-rolled CUDA streams.

Sequence parallelism (``sequence_parallel_enabled``) follows the reference:
activations outside TP regions are sharded along the SEQUENCE dim (axis 0,
Megatron (s, b, h) layout) over the SAME model axis; Column gathers (fwd) /
reduce-scatters (bwd), Row reduce-scatters (fwd) / gathers (bwd).
"""

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.utils.math import divide

_AXIS = ps.TENSOR_AXIS


def _init_kernel(key, shape, dtype):
    fan_in = shape[0]
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)


@jax.custom_vjp
def linear_with_grad_accumulation(x, kernel):
    """``x @ kernel`` whose weight gradient leaves the layer in the
    KERNEL's dtype with fp32 GEMM accumulation and NO low-precision
    round-trip — the ``gradient_accumulation_fusion`` analogue (ref:
    ``fused_weight_gradient_mlp_cuda`` accumulating wgrads straight into
    fp32 ``main_grad`` buffers; consumer ``tensor_parallel/layers.py ::
    linear_with_grad_accumulation_and_async_allreduce``).

    With fp32 master weights and bf16 activations (amp O2), plain AD
    computes the wgrad GEMM, casts the cotangent DOWN to bf16 (the
    compute dtype at the cast site), then widens it again when it meets
    the fp32 accumulator — dropping the low bits every microbatch. Here
    the wgrad is emitted at fp32 directly, so any downstream accumulation
    (``lax.scan`` carry, user microbatch loop) stays exact; the "fusion"
    is XLA folding the widening into the bwd GEMM epilogue. ``kernel``
    should be fp32 for the property to bite (with a bf16 kernel the
    cotangent must match bf16 and nothing is gained, same as the
    reference's requirement that ``main_grad`` buffers exist).
    """
    return jnp.dot(x, kernel.astype(x.dtype))


def _lga_fwd(x, kernel):
    return linear_with_grad_accumulation(x, kernel), (x, kernel)


def _lga_bwd(res, dy):
    x, kernel = res
    batch_dims = tuple(range(x.ndim - 1))
    dx = jax.lax.dot_general(
        dy, kernel.astype(dy.dtype), (((dy.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dk = jax.lax.dot_general(
        x, dy, ((batch_dims, batch_dims), ((), ())),
        preferred_element_type=jnp.float32).astype(kernel.dtype)
    return dx, dk


linear_with_grad_accumulation.defvjp(_lga_fwd, _lga_bwd)


class ColumnParallelLinear:
    """Y = X @ A + b with A sharded column-wise: A = [A_1 .. A_p].

    ``gather_output=True`` all-gathers Y (each rank then holds the full
    output); otherwise the output stays sharded for a following
    RowParallelLinear.
    """

    def __init__(self, in_features: int, out_features: int, *,
                 bias: bool = True, gather_output: bool = True,
                 sequence_parallel_enabled: bool = False,
                 sequence_parallel_seq_dim: int = 0,
                 gradient_accumulation_fusion: bool = False,
                 params_dtype=jnp.float32, tp_size: Optional[int] = None):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.gather_output = gather_output
        self.gradient_accumulation_fusion = gradient_accumulation_fusion
        self.sequence_parallel_enabled = sequence_parallel_enabled
        self.sequence_parallel_seq_dim = sequence_parallel_seq_dim
        self.params_dtype = params_dtype
        if sequence_parallel_enabled and gather_output:
            raise ValueError(
                "sequence_parallel_enabled requires gather_output=False "
                "(the reference asserts the same)")
        # divisibility check against the mesh (init-time world size)
        tp = tp_size if tp_size is not None else \
            ps.get_tensor_model_parallel_world_size()
        divide(out_features, tp)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        p = {"kernel": _init_kernel(
            key, (self.in_features, self.out_features), self.params_dtype)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.out_features,), self.params_dtype)
        return p

    def partition_specs(self) -> Dict[str, P]:
        s = {"kernel": P(None, _AXIS)}
        if self.use_bias:
            s["bias"] = P(_AXIS)
        return s

    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        if self.sequence_parallel_enabled:
            # x arrives seq-sharded; gather the full sequence for the GEMM
            # (bwd: reduce-scatter)
            x = mappings.gather_from_sequence_parallel_region(
                x, True, self.sequence_parallel_seq_dim)
        else:
            # fwd identity / bwd allreduce of dX across TP ranks
            x = mappings.copy_to_tensor_model_parallel_region(x)
        if self.gradient_accumulation_fusion:
            y = linear_with_grad_accumulation(x, params["kernel"])
        else:
            y = jnp.dot(x, params["kernel"].astype(x.dtype))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        if self.gather_output:
            y = mappings.gather_from_tensor_model_parallel_region(y)
        return y

    __call__ = apply


class RowParallelLinear:
    """Y = X @ A + b with A sharded row-wise; X arrives split along its
    last dim (``input_is_parallel``, the output of a Column layer)."""

    def __init__(self, in_features: int, out_features: int, *,
                 bias: bool = True, input_is_parallel: bool = True,
                 sequence_parallel_enabled: bool = False,
                 sequence_parallel_seq_dim: int = 0,
                 gradient_accumulation_fusion: bool = False,
                 params_dtype=jnp.float32, tp_size: Optional[int] = None):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.input_is_parallel = input_is_parallel
        self.gradient_accumulation_fusion = gradient_accumulation_fusion
        self.sequence_parallel_enabled = sequence_parallel_enabled
        self.sequence_parallel_seq_dim = sequence_parallel_seq_dim
        self.params_dtype = params_dtype
        if sequence_parallel_enabled and not input_is_parallel:
            raise ValueError(
                "sequence_parallel_enabled requires input_is_parallel")
        tp = tp_size if tp_size is not None else \
            ps.get_tensor_model_parallel_world_size()
        divide(in_features, tp)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        p = {"kernel": _init_kernel(
            key, (self.in_features, self.out_features), self.params_dtype)}
        if self.use_bias:
            # bias is applied AFTER the reduction, replicated (ref keeps it
            # unsharded and adds on every rank post-allreduce)
            p["bias"] = jnp.zeros((self.out_features,), self.params_dtype)
        return p

    def partition_specs(self) -> Dict[str, P]:
        s = {"kernel": P(_AXIS, None)}
        if self.use_bias:
            s["bias"] = P()
        return s

    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        if not self.input_is_parallel:
            x = mappings.scatter_to_tensor_model_parallel_region(x)
        if self.gradient_accumulation_fusion:
            y = linear_with_grad_accumulation(x, params["kernel"])
        else:
            y = jnp.dot(x, params["kernel"].astype(x.dtype))
        if self.sequence_parallel_enabled:
            y = mappings.reduce_scatter_to_sequence_parallel_region(
                y, self.sequence_parallel_seq_dim)
        else:
            y = mappings.reduce_from_tensor_model_parallel_region(y)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y

    __call__ = apply


class VocabParallelEmbedding:
    """Embedding with the vocab dim sharded across TP ranks: each rank owns
    rows [rank·V/p, (rank+1)·V/p); out-of-range ids contribute zeros and
    the partial lookups are summed with psum."""

    def __init__(self, num_embeddings: int, embedding_dim: int, *,
                 params_dtype=jnp.float32, tp_size: Optional[int] = None):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.params_dtype = params_dtype
        tp = tp_size if tp_size is not None else \
            ps.get_tensor_model_parallel_world_size()
        divide(num_embeddings, tp)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        return {"embedding": jax.random.normal(
            key, (self.num_embeddings, self.embedding_dim),
            self.params_dtype) * 0.02}

    def partition_specs(self) -> Dict[str, P]:
        return {"embedding": P(_AXIS, None)}

    def apply(self, params: Dict[str, Any], ids: jax.Array) -> jax.Array:
        table = params["embedding"]          # local shard (V/p, H)
        per_rank = table.shape[0]
        rank = lax.axis_index(_AXIS)
        start = rank * per_rank
        local = ids - start
        in_range = (local >= 0) & (local < per_rank)
        safe = jnp.where(in_range, local, 0)
        out = jnp.take(table, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0)
        return mappings.reduce_from_tensor_model_parallel_region(out)

    __call__ = apply

"""RNG state management + activation checkpointing for model parallelism.

Reference: ``apex/transformer/tensor_parallel/random.py`` —
``CudaRNGStatesTracker`` keeps separate CUDA RNG streams so dropout inside
TP regions differs per rank while data-parallel regions agree;
``CheckpointFunction`` re-runs forward with saved/restored RNG states.

JAX has no mutable RNG streams: keys are values. The tracker API survives
as key derivation —

- ``model_parallel_rng_key(key)``: fold the TP rank in (dropout DIFFERS
  per TP rank — sharded activations need decorrelated masks);
- ``data_parallel_rng_key(key)``: fold nothing (replicated regions agree
  by construction, matching the reference's default stream).

``checkpoint`` is ``jax.checkpoint``: rematerialization replays the traced
computation with the SAME key values, so the save/restore dance is free.
"""

from typing import Optional

import jax
from jax import lax

from apex_tpu.transformer import parallel_state as ps

_MODEL_PARALLEL_RNG = "model-parallel-rng"  # tracker name in the reference


def model_parallel_rng_key(key: jax.Array) -> jax.Array:
    """Per-TP-rank key (ref: ``get_cuda_rng_tracker().fork()``); call
    inside shard_map."""
    return jax.random.fold_in(key, lax.axis_index(ps.TENSOR_AXIS))


def data_parallel_rng_key(key: jax.Array) -> jax.Array:
    """Key shared by all TP ranks (the reference's default stream)."""
    return key


def model_parallel_seed(seed: int) -> dict:
    """Mirror of ``model_parallel_cuda_manual_seed(seed)``: returns the two
    base keys the reference derives (data-parallel seed, model-parallel
    seed offset by 2718)."""
    return {
        "data_parallel": jax.random.PRNGKey(seed),
        "model_parallel": jax.random.PRNGKey(seed + 2718),
    }


class RNGStatesTracker:
    """API-shaped shim over key folding (ref: ``CudaRNGStatesTracker``).

    ``fork(name)`` returns a derived key instead of a context manager —
    functional code passes keys explicitly."""

    def __init__(self):
        self._keys = {}

    def add(self, name: str, seed: int) -> None:
        self._keys[name] = jax.random.PRNGKey(seed)

    def get_states(self) -> dict:
        return dict(self._keys)

    def set_states(self, states: dict) -> None:
        self._keys = dict(states)

    def reset(self) -> None:
        self._keys = {}

    def fork(self, name: str = _MODEL_PARALLEL_RNG) -> jax.Array:
        """Split off a fresh key (host-side). Inside shard_map, apply
        ``model_parallel_rng_key`` to the result to decorrelate TP ranks
        (the fold needs a bound mesh axis)."""
        key = self._keys[name]
        self._keys[name], sub = jax.random.split(key)
        return sub


_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """ref: ``get_cuda_rng_tracker`` (renamed: nothing CUDA about it)."""
    return _TRACKER


# Activation checkpointing: rematerialize in backward. RNG keys replay
# identically because they are values (ref CheckpointFunction's RNG
# save/restore is structural here).
checkpoint = jax.checkpoint


def checkpoint_policy(save_dots: bool = False):
    """Common remat policies: ``save_dots`` keeps matmul outputs (the
    reference's selective activation checkpointing analogue)."""
    if save_dots:
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return jax.checkpoint_policies.nothing_saveable

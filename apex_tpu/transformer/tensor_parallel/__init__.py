"""Tensor parallelism (ref: ``apex/transformer/tensor_parallel``)."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    linear_with_grad_accumulation,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RNGStatesTracker,
    checkpoint,
    checkpoint_policy,
    data_parallel_rng_key,
    get_rng_tracker,
    model_parallel_rng_key,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility,
    split_tensor_along_last_dim,
)

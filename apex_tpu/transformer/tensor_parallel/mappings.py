"""TP/SP region mappings — the collective autograd pairs.

Reference: ``apex/transformer/tensor_parallel/mappings.py`` — each mapping
is an ``autograd.Function`` whose forward/backward are a collective and its
dual. Here each is a ``jax.custom_vjp`` built on XLA collectives, to be
called INSIDE ``parallel_state.shard_map`` over the ``model`` axis:

=============================================  ==============  =============
mapping                                         forward         backward
=============================================  ==============  =============
``copy_to_tensor_model_parallel_region``        identity        psum
``reduce_from_tensor_model_parallel_region``    psum            identity
``scatter_to_tensor_model_parallel_region``     split last dim  all-gather
``gather_from_tensor_model_parallel_region``    all-gather      split
``scatter_to_sequence_parallel_region``         split seq dim   all-gather
``gather_from_sequence_parallel_region``        all-gather seq  reduce-scatter
``reduce_scatter_to_sequence_parallel_region``  reduce-scatter  all-gather
=============================================  ==============  =============

The sequence dim is axis 0 (Megatron's (s, b, h) layout is preserved so SP
semantics match the reference line for line).
"""

import functools

import jax
from jax import lax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size

_AXIS = ps.TENSOR_AXIS


def _tp_size():
    return axis_size(_AXIS)


def _split_along(x, dim):
    """Local chunk of dim for this TP rank (ref: ``_split_along_last_dim``)."""
    size = x.shape[dim] // _tp_size()
    idx = lax.axis_index(_AXIS)
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


def _gather_along(x, dim):
    return lax.all_gather(x, _AXIS, axis=dim, tiled=True)


def _reduce_scatter_along(x, dim):
    return lax.psum_scatter(x, _AXIS, scatter_dimension=dim, tiled=True)


# -- copy / reduce (last-dim free) ------------------------------------------

@jax.custom_vjp
def copy_to_tensor_model_parallel_region(x):
    return x

def _copy_fwd(x):
    return x, None

def _copy_bwd(_, g):
    return (lax.psum(g, _AXIS),)

copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@jax.custom_vjp
def reduce_from_tensor_model_parallel_region(x):
    return lax.psum(x, _AXIS)

def _reduce_fwd(x):
    return lax.psum(x, _AXIS), None

def _reduce_bwd(_, g):
    return (g,)

reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# -- scatter / gather over the LAST dim (tensor-parallel regions) -----------

@jax.custom_vjp
def scatter_to_tensor_model_parallel_region(x):
    return _split_along(x, -1)

def _scatter_fwd(x):
    return _split_along(x, -1), None

def _scatter_bwd(_, g):
    return (_gather_along(g, -1),)

scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@jax.custom_vjp
def gather_from_tensor_model_parallel_region(x):
    return _gather_along(x, -1)

def _gather_fwd(x):
    return _gather_along(x, -1), None

def _gather_bwd(_, g):
    return (_split_along(g, -1),)

gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel region mappings ---------------------------------------
# seq dim defaults to axis 0 (Megatron (s, b, h)); models in (b, s, h)
# layout pass seq_dim=1 — the collectives are dim-agnostic.

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, seq_dim: int = 0):
    return _split_along(x, seq_dim)

def _sp_scatter_fwd(x, seq_dim):
    return _split_along(x, seq_dim), None

def _sp_scatter_bwd(seq_dim, _, g):
    return (_gather_along(g, seq_dim),)

scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, to_model_parallel: bool = True,
                                         seq_dim: int = 0):
    return _gather_along(x, seq_dim)

def _sp_gather_fwd(x, to_model_parallel, seq_dim):
    return _gather_along(x, seq_dim), None

def _sp_gather_bwd(to_model_parallel, seq_dim, _, g):
    # entering a TP region: the dual is reduce-scatter (grads from all TP
    # ranks must be summed); leaving to a pure SP consumer: plain split
    if to_model_parallel:
        return (_reduce_scatter_along(g, seq_dim),)
    return (_split_along(g, seq_dim),)

gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, seq_dim: int = 0):
    return _reduce_scatter_along(x, seq_dim)

def _sp_rs_fwd(x, seq_dim):
    return _reduce_scatter_along(x, seq_dim), None

def _sp_rs_bwd(seq_dim, _, g):
    return (_gather_along(g, seq_dim),)

reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)

"""Batch broadcast across the TP group.

Reference: ``apex/transformer/tensor_parallel/data.py :: broadcast_data`` —
TP rank 0 loads the batch and broadcasts it (keys/dtype/shape handshake +
flatten + NCCL broadcast). On a mesh: a masked psum from index 0 of the
model axis; shapes/dtypes are static under jit so no handshake exists.
"""

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps

_AXIS = ps.TENSOR_AXIS


def broadcast_data(keys, data: dict, datatype=None) -> dict:
    """Broadcast ``{k: array}`` from TP rank 0 (call inside shard_map).

    ``keys`` selects which entries to broadcast; ``datatype`` optionally
    casts (the reference asserts a single dtype instead)."""
    rank = lax.axis_index(_AXIS)
    out = {}
    for k in keys:
        v = data[k]
        if datatype is not None:
            v = v.astype(datatype)
        masked = jnp.where(rank == 0, v, jnp.zeros_like(v))
        out[k] = lax.psum(masked, _AXIS)
    return out

"""Shard-math helpers (ref: ``apex/transformer/tensor_parallel/utils.py``)."""

from typing import Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.utils.math import divide


def split_tensor_along_last_dim(tensor, num_partitions: int,
                                contiguous_split_chunks: bool = False):
    """Split along the last dim (ref keeps a contiguity flag; moot here)."""
    last = tensor.shape[-1]
    size = divide(last, num_partitions)
    return [tensor[..., i * size:(i + 1) * size]
            for i in range(num_partitions)]


class VocabUtility:
    """Vocab range bookkeeping (ref: ``class VocabUtility``)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size: int, rank: int,
            world_size: int) -> Tuple[int, int]:
        f = rank * per_partition_vocab_size
        return f, f + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank: int,
                                           world_size: int) -> Tuple[int, int]:
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)

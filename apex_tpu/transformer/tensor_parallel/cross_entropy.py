"""Vocab-parallel cross entropy.

Reference: ``apex/transformer/tensor_parallel/cross_entropy.py ::
_VocabParallelCrossEntropy`` — logits arrive sharded on the vocab (last)
dim; the loss is computed with two allreduces (max, sum-exp) plus a masked
gather of the target logit from the owning shard, never materializing the
full softmax. Backward is (softmax - one_hot) computed shard-locally.
"""

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps

_AXIS = ps.TENSOR_AXIS


def _fwd_core(logits, target):
    """Returns (loss, (softmax_local, target_mask, target_local))."""
    per_rank = logits.shape[-1]
    rank = lax.axis_index(_AXIS)
    start = rank * per_rank

    # allreduce #1: global max for stability
    lmax = lax.pmax(jnp.max(logits, axis=-1), _AXIS)
    shifted = logits - lmax[..., None]
    exp = jnp.exp(shifted)
    # allreduce #2: global sum-exp
    sum_exp = lax.psum(jnp.sum(exp, axis=-1), _AXIS)

    # target logit: owning shard contributes, others add zero
    local = target - start
    in_range = (local >= 0) & (local < per_rank)
    safe = jnp.where(in_range, local, 0)
    tgt_shifted = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
    tgt_shifted = jnp.where(in_range, tgt_shifted, 0.0)
    tgt_shifted = lax.psum(tgt_shifted, _AXIS)

    loss = jnp.log(sum_exp) - tgt_shifted
    softmax_local = exp / sum_exp[..., None]
    return loss, (softmax_local, in_range, safe)


@jax.custom_vjp
def vocab_parallel_cross_entropy(logits, target):
    """Per-token loss (same shape as ``target``); call inside shard_map
    with logits sharded over the vocab dim."""
    loss, _ = _fwd_core(logits.astype(jnp.float32), target)
    return loss


def _vce_fwd(logits, target):
    loss, res = _fwd_core(logits.astype(jnp.float32), target)
    # zero-size sentinel carries the logits dtype (dtypes are not pytree
    # leaves)
    return loss, (res, jnp.zeros((0,), logits.dtype))


def _vce_bwd(resdt, g):
    # d logits = (softmax - one_hot(target)) * g, shard-locally: the
    # one-hot only lands on the owning rank's slice
    (softmax_local, in_range, safe), dtype_sentinel = resdt
    onehot = jax.nn.one_hot(safe, softmax_local.shape[-1],
                            dtype=softmax_local.dtype)
    onehot = onehot * jnp.where(in_range, 1.0, 0.0)[..., None]
    grad = softmax_local - onehot
    return (grad * g[..., None]).astype(dtype_sentinel.dtype), None


vocab_parallel_cross_entropy.defvjp(_vce_fwd, _vce_bwd)

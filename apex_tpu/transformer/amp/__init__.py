"""Grad scaler for TP/PP training (ref: ``apex/transformer/amp/grad_scaler.py``
— a Megatron-style GradScaler whose found_inf is allreduced across the
model-parallel group). The core ``LossScaler`` is shared with ``apex_tpu.amp``;
this wrapper adds the cross-rank OR of found_inf."""

from typing import Any, Tuple

import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler, LossScalerState  # noqa: F401
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size


def _axis_is_bound(name: str) -> bool:
    """True iff ``name`` is a mapped axis in the current trace context.

    Probes with ``utils.compat.axis_size`` (``lax.axis_size`` where it
    exists: pure trace-time metadata — unlike the earlier private
    ``jax._src.core.get_axis_env`` query it adds nothing to the jaxpr
    and touches no internals; the older-jax fallback is a psum-of-1
    probe that constant-folds at trace time). The unbound case is a
    trace-time ``NameError`` either way, so no runtime branch is
    compiled.
    """
    try:
        axis_size(name)
        return True
    except NameError:
        # the unbound-axis trace error; anything else must propagate —
        # failing open here would silently skip the cross-rank found_inf
        # OR and let optimizer states diverge across TP ranks
        return False


class GradScaler(LossScaler):
    """``unscale`` additionally ORs found_inf over the TP (and pipe) axes —
    a rank that overflowed must make EVERY rank skip the step (the
    reference allreduces found_inf over the model-parallel group). Call
    inside shard_map. Axes not bound by the enclosing mapped region (a
    tp-only or pp-only shard_map) are skipped rather than erroring."""

    def unscale(self, grads: Any, state: LossScalerState
                ) -> Tuple[Any, jnp.ndarray]:
        grads, found_inf = super().unscale(grads, state)
        for axis in (ps.TENSOR_AXIS, ps.PIPE_AXIS):
            if _axis_is_bound(axis):
                found_inf = lax.pmax(found_inf.astype(jnp.int32), axis) > 0
        return grads, found_inf

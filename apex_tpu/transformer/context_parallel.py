"""Context parallelism — ring attention over the ``context`` mesh axis.

Reference scope: the reference's long-sequence story is fused/flash
attention on one GPU plus Megatron sequence parallelism; it has no ring
attention. SURVEY §2c therefore lists CP as not-required — but
``parallel_state`` reserves a first-class ``context`` axis, and on TPU
ring attention is the natural long-context design (Liu et al., "Ring
Attention with Blockwise Transformers"; the public JAX implementations
in PAPERS.md/SNIPPETS.md follow the same shape): sequence-shard q/k/v,
rotate k/v shards around the ring with ``lax.ppermute`` while each rank
accumulates its queries' attention online, so no rank ever materializes
the full (s, s) score matrix OR the full k/v sequence.

Design:

- one ``lax.scan`` over the ``cp`` ring steps; the carry is the flash
  recurrence state (running max, running sum, output accumulator) plus
  the in-flight k/v block — compute on the current block overlaps the
  ppermute of the next by XLA's latency-hiding scheduler, the TPU
  analogue of the reference kernels' compute/NCCL overlap;
- blockwise math is the SAME fp32 online-softmax recurrence as the flash
  kernel (fully-masked rows return 0, additive -1e30 masking), so CP=1
  reproduces ``flash_attention`` numerics;
- causal masking uses GLOBAL positions derived from ``axis_index``, so
  the triangle is exact across shards;
- backward is plain autodiff: the transpose of a ppermute rotation is
  the reverse rotation, and ``jax.checkpoint`` around the per-step block
  keeps live memory at one block per step (blockwise-transformer remat).

Call inside ``parallel_state.shard_map`` with q/k/v (b, h, s_local, d)
sharded along seq over ``CONTEXT_AXIS`` (mask (b, s_local) likewise).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size

_NEG = -1e30


def _ring_perm(cp: int):
    # send to the NEXT rank: after j steps, rank i holds block (i - j) % cp
    return [(i, (i + 1) % cp) for i in range(cp)]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask: Optional[jax.Array] = None, *,
                   causal: bool = False,
                   softmax_scale: Optional[float] = None,
                   axis_name: str = ps.CONTEXT_AXIS,
                   checkpoint_blocks: bool = True) -> jax.Array:
    """Exact attention over a context-sharded sequence.

    Args:
      q, k, v: (b, h, s_local, d) — the rank's sequence shard.
      mask: optional (b, s_local) key-padding mask (1 = attend).
      causal: global upper-triangular masking.
      axis_name: the mesh axis the sequence is sharded over.

    Returns (b, h, s_local, d) in q's dtype — the rank's output shard.
    """
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)
    cp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    perm = _ring_perm(cp)

    q32 = q.astype(jnp.float32)
    q_pos = rank * s_loc + jnp.arange(s_loc)          # global q positions

    def block(carry_qstate, kv_block, src_rank):
        """One flash-recurrence update against the k/v block that
        originated on ``src_rank``."""
        m_run, l_run, acc = carry_qstate
        k_blk, v_blk, kmask_blk = kv_block
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k_blk.astype(jnp.float32)) * softmax_scale
        valid = None
        if kmask_blk is not None:
            valid = (kmask_blk[:, None, None, :] != 0)
        if causal:
            k_pos = src_rank * s_loc + jnp.arange(s_loc)
            tri = (k_pos[None, None, None, :]
                   <= q_pos[None, None, :, None])
            valid = tri if valid is None else (valid & tri)
        if valid is None:
            valid = jnp.ones(s.shape, bool)
        s = jnp.where(valid, s, _NEG)
        m_cur = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_run - m_cur)
        p = jnp.where(valid, jnp.exp(s - m_cur), 0.0)
        l_run = l_run * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return m_cur, l_run, acc

    if checkpoint_blocks:
        block = jax.checkpoint(block)

    # the mask rides the ring only when one exists (causal needs none)
    mask_loc = None if mask is None else mask.astype(jnp.int32)

    def step(carry, j):
        qstate, k_cur, v_cur, km_cur = carry
        src = (rank - j) % cp                 # who this block belongs to
        qstate = block(qstate, (k_cur, v_cur, km_cur), src)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        m_nxt = None if km_cur is None else \
            lax.ppermute(km_cur, axis_name, perm)
        return (qstate, k_nxt, v_nxt, m_nxt), None

    m0 = jnp.full((b, h, s_loc, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    # cp-1 rotate-and-consume steps, then the final block OUTSIDE the
    # scan — rotating after the last consume would send a full k/v/mask
    # round over ICI just to discard it
    carry = ((m0, l0, acc0), k, v, mask_loc)
    if cp > 1:
        carry, _ = lax.scan(step, carry, jnp.arange(cp - 1))
    qstate, k_last, v_last, km_last = carry
    qstate = block(qstate, (k_last, v_last, km_last),
                   (rank - (cp - 1)) % cp)
    _, l_run, acc = qstate
    out = jnp.where(l_run > 0, acc / jnp.where(l_run > 0, l_run, 1.0), 0.0)
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      mask: Optional[jax.Array] = None, *,
                      causal: bool = False,
                      softmax_scale: Optional[float] = None,
                      axis_name: str = ps.CONTEXT_AXIS,
                      attention_fn=None) -> jax.Array:
    """Ulysses-style sequence parallelism (DeepSpeed-Ulysses; see
    PAPERS.md): two ``all_to_all``s swap the sharded dimension so each
    rank runs EXACT attention over the FULL sequence for ``h/cp`` heads,
    then swap back. The alternative long-context strategy to
    :func:`ring_attention` — comm is exactly TWO all-to-alls per call
    (q/k/v ride one stacked collective in, the output one back; O(1)
    collectives vs the ring's cp-1 rotations of k/v), at the cost of
    requiring ``heads % cp == 0``.

    Args:
      q, k, v: (b, h, s_local, d) — the rank's sequence shard along the
        ``context`` axis (the same activation contract as ring).
      mask: optional (b, s_local) key-validity shard (1 = attend); it is
        all-gathered to the full sequence (tiny next to activations).
      attention_fn: the full-sequence attention to run per head group;
        defaults to :func:`...functional.flash_attention.flash_attention`
        (so the Pallas kernel serves long sequences, the XLA path short
        ones — the usual dispatch).

    Returns (b, h, s_local, d) in q's dtype.
    """
    cp = axis_size(axis_name)
    b, h, s_loc, d = q.shape
    if h % cp:
        raise ValueError(
            f"ulysses_attention needs heads % cp == 0, got {h} % {cp}")
    if attention_fn is None:
        from apex_tpu.transformer.functional.flash_attention import (
            flash_attention,
        )
        attention_fn = flash_attention

    # ONE stacked all-to-all for q/k/v: (3, b, h, s/cp, d) with head
    # shards scattering over ranks while the sequence gathers
    qkv = lax.all_to_all(jnp.stack([q, k, v]), axis_name, split_axis=2,
                         concat_axis=3, tiled=True)
    qf, kf, vf = qkv[0], qkv[1], qkv[2]
    full_mask = None if mask is None else \
        lax.all_gather(mask, axis_name, axis=1, tiled=True)
    out = attention_fn(qf, kf, vf, full_mask, causal=causal,
                       softmax_scale=softmax_scale)
    # inverse swap: heads gather back, the sequence re-shards
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True).astype(q.dtype)

"""Fused scale + mask + softmax — Pallas kernels + dispatcher.

Reference: ``apex/transformer/functional/fused_softmax.py ::
FusedScaleMaskSoftmax`` over the CUDA kernels
``csrc/megatron/scaled_masked_softmax_cuda.cu`` (additive/boolean padding
mask) and ``scaled_upper_triang_masked_softmax_cuda.cu`` (implicit causal
mask). The CUDA kernels are seqlen-templated (<= 2k/4k); the Pallas
kernels are seqlen-generic: the grid walks (batch*heads, q-tiles) with the
full key dim resident per tile, fp32 softmax arithmetic, and a fused
backward ``dx = scale * (dy - sum(dy*y)) * y``.

Masking follows the reference convention: ``mask == True`` (or 1) means
MASKED OUT, implemented additively with -10000 like the CUDA kernel.
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.utils.math import cdiv, round_up_to_multiple
from apex_tpu.utils.pallas import dimsem as _dimsem
from apex_tpu.utils.platform import pallas_interpret

_MASK_VALUE = -10000.0  # the reference kernels' masked-score constant
_TILE_Q = 128


def _pad_q(x, tile):
    q = x.shape[1]
    pq = round_up_to_multiple(q, tile)
    if pq != q:
        x = jnp.pad(x, ((0, 0), (0, pq - q), (0, 0)))
    return x


# -- forward kernels --------------------------------------------------------

def _softmax_rows(z):
    m = jnp.max(z, axis=-1, keepdims=True)
    e = jnp.exp(z - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)


def _masked_fwd_kernel(sc_ref, x_ref, m_ref, y_ref):
    z = x_ref[:].astype(jnp.float32) * sc_ref[0, 0]
    z = jnp.where(m_ref[:] != 0, _MASK_VALUE, z)
    y_ref[:] = _softmax_rows(z).astype(y_ref.dtype)


def _causal_fwd_kernel(sc_ref, x_ref, y_ref):
    _, tq, sk = x_ref.shape
    qt = pl.program_id(1)
    z = x_ref[:].astype(jnp.float32) * sc_ref[0, 0]
    qpos = qt * tq + jax.lax.broadcasted_iota(jnp.int32, (1, tq, sk), 1)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (1, tq, sk), 2)
    z = jnp.where(kpos > qpos, _MASK_VALUE, z)
    y_ref[:] = _softmax_rows(z).astype(y_ref.dtype)


def _bwd_kernel(sc_ref, y_ref, dy_ref, dx_ref):
    y = y_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    s = jnp.sum(y * dy, axis=-1, keepdims=True)
    dx_ref[:] = (sc_ref[0, 0] * (dy - s) * y).astype(dx_ref.dtype)


def _row_specs(tile, sk):
    return pl.BlockSpec((1, tile, sk), lambda i, j: (i, j, 0),
                        memory_space=pltpu.VMEM)


def _smem():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _bwd_call(y3, dy3, scale, interpret):
    batches, q, sk = y3.shape
    tile = min(_TILE_Q, round_up_to_multiple(q, 8))
    yp, dyp = _pad_q(y3, tile), _pad_q(dy3, tile)
    grid = (batches, yp.shape[1] // tile)
    sc = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    dx = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[_smem(), _row_specs(tile, sk), _row_specs(tile, sk)],
        out_specs=_row_specs(tile, sk),
        out_shape=jax.ShapeDtypeStruct(yp.shape, y3.dtype),
        compiler_params=_dimsem("parallel", "parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, yp, dyp)
    return dx[:, :q]


# -- scaled masked softmax (padding mask) -----------------------------------

def _sms_fwd(x, mask, scale, interpret):
    b, np_, sq, sk = x.shape
    # the mask stays (b, sq, sk) in HBM — identical across heads, so the
    # grid indexes it by i // np_ instead of replicating it per head (the
    # CUDA kernel does the same via its batch stride)
    m3 = jnp.broadcast_to(mask.astype(jnp.int32), (b, 1, sq, sk))[:, 0]
    x3 = x.reshape(b * np_, sq, sk)
    tile = min(_TILE_Q, round_up_to_multiple(sq, 8))
    xp, mp = _pad_q(x3, tile), _pad_q(m3, tile)
    grid = (b * np_, xp.shape[1] // tile)
    mask_spec = pl.BlockSpec((1, tile, sk), lambda i, j: (i // np_, j, 0),
                             memory_space=pltpu.VMEM)
    sc = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    y = pl.pallas_call(
        _masked_fwd_kernel,
        grid=grid,
        in_specs=[_smem(), _row_specs(tile, sk), mask_spec],
        out_specs=_row_specs(tile, sk),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        compiler_params=_dimsem("parallel", "parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, xp, mp)
    return y[:, :sq].reshape(b, np_, sq, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _scaled_masked_softmax_core(scale, interpret, x, mask):
    return _sms_fwd(x, mask, scale, interpret)


def _sms_fwd_vjp(scale, interpret, x, mask):
    y = _sms_fwd(x, mask, scale, interpret)
    return y, y


def _sms_bwd_vjp(scale, interpret, y, dy):
    b, np_, sq, sk = y.shape
    dx = _bwd_call(y.reshape(b * np_, sq, sk), dy.reshape(b * np_, sq, sk),
                   scale, interpret)
    return dx.reshape(b, np_, sq, sk), None


_scaled_masked_softmax_core.defvjp(_sms_fwd_vjp, _sms_bwd_vjp)


def scaled_masked_softmax(x, mask, scale=1.0,
                          interpret: Optional[bool] = None):
    """x: (b, np, sq, sk); mask: (b, 1, sq, sk) or broadcastable, nonzero =
    masked out (ref convention). Returns probabilities in x.dtype."""
    return _scaled_masked_softmax_core(float(scale), interpret, x, mask)


# -- scaled upper-triangular (causal) softmax -------------------------------

def _sut_fwd(x3, scale, interpret):
    batches, sq, sk = x3.shape
    tile = min(_TILE_Q, round_up_to_multiple(sq, 8))
    xp = _pad_q(x3, tile)
    grid = (batches, xp.shape[1] // tile)
    sc = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    y = pl.pallas_call(
        _causal_fwd_kernel,
        grid=grid,
        in_specs=[_smem(), _row_specs(tile, sk)],
        out_specs=_row_specs(tile, sk),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x3.dtype),
        compiler_params=_dimsem("parallel", "parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, xp)
    return y[:, :sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _scaled_upper_triang_core(scale, interpret, x3):
    return _sut_fwd(x3, scale, interpret)

def _sut_fwd_vjp(scale, interpret, x3):
    y = _sut_fwd(x3, scale, interpret)
    return y, y

def _sut_bwd_vjp(scale, interpret, y, dy):
    return (_bwd_call(y, dy, scale, interpret),)

_scaled_upper_triang_core.defvjp(_sut_fwd_vjp, _sut_bwd_vjp)


def scaled_upper_triang_masked_softmax(x, scale=1.0,
                                       interpret: Optional[bool] = None):
    """Causal softmax. x: (attn_batches, sq, sk) like the CUDA kernel, or
    (b, np, sq, sk) which is flattened."""
    if x.ndim == 4:
        b, np_, sq, sk = x.shape
        return _scaled_upper_triang_core(
            float(scale), interpret, x.reshape(b * np_, sq, sk)
        ).reshape(x.shape)
    return _scaled_upper_triang_core(float(scale), interpret, x)


# -- dispatcher (ref: class FusedScaleMaskSoftmax) --------------------------

class FusedScaleMaskSoftmax:
    """Picks the fused kernel when eligible, else the jnp fallback —
    mirroring the reference's ``is_kernel_available`` dispatch (dtype +
    fusion flag; the CUDA seqlen limits don't apply to Pallas)."""

    def __init__(self, input_in_fp16: bool = False,
                 input_in_bf16: bool = False,
                 attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func: Optional[Callable] = None,
                 softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags are set")
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """Mirrors the reference's gate with the gates that still apply.

        Kept from the reference (``fused_softmax.py ::
        is_kernel_available``): the user fusion flag and the
        input-in-float16 requirement — the fused path is specified for
        half-precision inputs (fp32 callers get the fp32-softmax fallback
        with identical numerics, as upstream).  Dropped, with reason: the
        CUDA tiling limits (16 < sk <= 16384, sq/sk % 4, attn_batches %
        batch_per_block) exist because the CUDA kernels are compiled for
        fixed tile geometries; the Pallas kernels pad to (8,128) lanes and
        take seqlen as a grid parameter, so every shape is eligible.
        Added: ``sq > 1`` — a single-query (decode) softmax is one VPU row
        where kernel dispatch is pure overhead.
        """
        return bool(self.fusion) and self.input_in_float16 and sq > 1

    def __call__(self, x, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        b, np_, sq, sk = x.shape
        if self.is_kernel_available(mask, b, np_, sq, sk):
            if self.attn_mask_type == AttnMaskType.causal:
                return scaled_upper_triang_masked_softmax(x, scale)
            if mask is not None:
                return scaled_masked_softmax(x, mask, scale)
            # no mask: scale-only softmax = masked kernel with a zero mask
            zero = jnp.zeros((b, 1, sq, sk), jnp.int32)
            return scaled_masked_softmax(x, zero, scale)
        return self.forward_torch_softmax(x, mask)

    forward_fused_softmax = __call__

    def forward_torch_softmax(self, x, mask=None):
        """jnp fallback (the reference's ``forward_torch_softmax``)."""
        z = x.astype(jnp.float32) if self.softmax_in_fp32 else x
        if self.scale is not None:
            z = z * self.scale
        if self.attn_mask_type == AttnMaskType.causal:
            sq, sk = z.shape[-2:]
            causal = jnp.tril(jnp.ones((sq, sk), bool))
            z = jnp.where(causal, z, _MASK_VALUE)
        elif mask is not None:
            f = self.mask_func or (lambda z, m: jnp.where(m != 0,
                                                          _MASK_VALUE, z))
            z = f(z, mask)
        y = jax.nn.softmax(z, axis=-1)
        return y.astype(x.dtype) if self.softmax_in_fp32 else y

"""Fused functional ops (ref: ``apex/transformer/functional``)."""

from apex_tpu.transformer.functional.flash_attention import (  # noqa: F401
    flash_attention,
)
from apex_tpu.transformer.functional.fused_rope import (  # noqa: F401
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_bhsd,
    fused_apply_rotary_pos_emb_bshd,
    fused_apply_rotary_pos_emb_cached,
    rope_cos_sin,
    rope_frequencies,
)
from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)

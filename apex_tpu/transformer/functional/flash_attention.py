"""Fused multi-head attention — flash-attention Pallas kernels.

Reference: ``apex/contrib/csrc/multihead_attn/*`` (fused QKV-softmax-
dropout-PV fwd/bwd, ~8k CUDA LoC) and ``apex/contrib/csrc/fmha/*``
(short-seqlen fused MHA) — SURVEY.md §2b calls this the largest single
kernel work item. Both are subsumed by one seqlen-generic flash-style
kernel pair:

- **forward**: grid ``(batch*heads, q_tiles, k_tiles)``; per q-tile a
  running (max, sum, acc) in VMEM scratch implements the online softmax
  (FlashAttention-2 recurrence); scores never touch HBM. Saves the
  per-row logsumexp for the backward.
- **backward**: the standard two-pass split — a dq kernel (k innermost)
  and a dk/dv kernel (q innermost) — recomputing score tiles from
  (q, k, lse) instead of materializing the (s, s) probability matrix,
  with ``D = rowsum(dout * out)`` precomputed outside.
- **dropout** follows the reference's saved-mask semantics
  (``masked_softmax_dropout_func``): probabilities are dropped AFTER
  normalization. The keep mask is never stored — it is regenerated in
  the backward from a counter-based hash of (seed, head, q, k), the
  TPU-friendly analogue of the CUDA kernels' saved-RNG-state replay.

Numerics: softmax in fp32 (scores masked to -1e30, matching the
``-10000``-additive convention of the fused softmax kernels for any
realistically-scaled logits); fully-masked rows return 0 (the
flash/fmha convention). ``mask`` is (b, s_k) with 1 = attend.

VPU diet (the d=64 lever — BERT-Large's own head shape ran at 18% of
peak while d=128 hit 38% at identical FLOPs, so the cost is per score
ELEMENT, not MXU occupancy):

- **base-2 online softmax** (``_EXP2``): ``log2(e)`` is folded into the
  q prescale that already exists, so every ``exp`` in the three kernels
  becomes the cheaper ``exp2`` (the hardware primitive ``exp`` lowers
  to — one fewer VPU multiply per score element per exponential) and
  the running max / logsumexp live in base 2 end to end. The backward
  kernels consume the base-2 lse directly (``exp2(s2 - lse2)`` is
  exactly the base-e probability); the only base conversion anywhere is
  ONE ln(2) multiply on the final dk tile (see ``_bwd_call`` — dk is
  ``ds^T @ (scale*log2e*q)``, i.e. log2e too big, and the fixup is
  d-sized, not s²-sized).
- **bf16 probability tiles** (``_P_BF16``): p / ds are consumed only by
  MXU ``dot_general``s, so they are cast to bf16 immediately after the
  fp32 (m, l) statistics are updated, and the dropout keep/scale ops run
  on the bf16 tile. m, l, lse, acc stay fp32. With the toggle off the
  tiles stay fp32 and the other operand is upcast — the measurement
  variant ``bench.py ab flash_d64_p32`` uses to price the bf16 path.
  fp32 inputs always keep fp32 tiles (golden-test tolerances are tight).

Dropout masks are position-hashed (``_hash_keep``) and therefore
bit-identical between forward and backward and across every variant
toggle — the toggles change arithmetic cost, never randomness.
"""

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.math import round_up_to_multiple
from apex_tpu.utils.pallas import (
    NEG_INF as _NEG,
    dimsem as _dimsem,
    pad_axis as _pad_axis,
)
from apex_tpu.utils.platform import pallas_interpret

def _block(s_padded: int, max_block: int = 512) -> int:
    """Largest of 512/256/128 (capped at ``max_block``) that divides the
    padded length — bigger blocks amortize grid overhead and feed the MXU
    larger matmuls. Causal kernels cap lower: the tile-skipping win grows
    as the diagonal gets thinner relative to the tile (at seq 2048,
    512-tiles keep 10/16 of the work, 256-tiles only 36/64)."""
    for cand in (512, 256, 128):
        if cand <= max_block and s_padded % cand == 0:
            return cand
    return 128


def _causal_live(qt, kt, bq, bk):
    """True iff tile (qt, kt) contains any unmasked position under the
    causal mask: its smallest k position <= its largest q position."""
    return kt * bk <= (qt + 1) * bq - 1


# Causal tile-skipping toggles. Measured on v5e (seq 2048, d 64, fwd+bwd,
# several same-process A/B sweeps; cross-process numbers drift +-20% with
# relay conditions): gating whole tiles behind pl.when costs MORE than the
# skipped matmuls save (the kernels are VPU-bound, and the per-tile
# control flow defeats Mosaic's copy/compute overlap), and index-map
# clamping adds further cost. The win that did land is the mask-free
# interior-tile path (_needs_mask). Defaults reflect the measurements;
# the toggles remain for re-tuning on other TPU generations.
_CAUSAL_MAX_BLOCK = 512
_CAUSAL_SKIP = False
_CAUSAL_CLAMP = False
_DIM_SEMANTICS = True

# VPU-diet toggles (see module docstring). Same contract as the causal
# toggles above: module-level so `bench.py ab` can trace a legacy-variant
# callable against the default one IN THE SAME PROCESS — the only
# comparison that resolves <20% effects on a relay-attached rig. Flip via
# `kernel_variant(...)`; the toggles are read at TRACE time, so a
# callable must be traced (first call / warmup) inside the context.
_EXP2 = True    # base-2 online softmax, log2e folded into the q prescale
_P_BF16 = True  # bf16 p/ds tiles into the MXU (bf16 operands only)

# Block cap for small head dims. The exp2/bf16-p diet shifts the VPU:MXU
# ratio at d<128 (the matmuls stay narrow while the per-score VPU cost
# drops), so the measured-best 512 tile of the pre-exp2 kernels may no
# longer be optimal — `bench.py ab flash_d64_block256` re-tunes this
# without a code edit. 512 (= no change) until the driver's A/B says
# otherwise; _SMALL_D gates which head dims the cap applies to.
_SMALL_D_MAX_BLOCK = 512
_SMALL_D = 128

_LOG2E = 1.4426950408889634  # log2(e): folded into the q prescale
_LN2 = 0.6931471805599453    # 1/log2(e): the one dk fixup multiply


@contextlib.contextmanager
def kernel_variant(**toggles):
    """Temporarily override module toggles (``exp2``, ``p_bf16``,
    ``small_d_max_block``, ``causal_skip``, ...). Trace-time only: jit a
    callable INSIDE the context (fwd and bwd together — e.g. warm a
    ``jax.grad`` under jit) and the variant is baked into the compiled
    program; already-compiled programs are unaffected. Used by the
    same-process A/B harness (``bench.py ab``) and the kernel-parity
    pinning checks."""
    mapping = {k: f"_{k.upper()}" for k in toggles}
    saved = {}
    for k, attr in mapping.items():
        if attr not in globals():
            raise ValueError(f"unknown kernel_variant toggle {k!r}")
        saved[attr] = globals()[attr]
        globals()[attr] = toggles[k]
    try:
        yield
    finally:
        globals().update(saved)


def _exp(x):
    return jnp.exp2(x) if _EXP2 else jnp.exp(x)


def _log(x):
    return jnp.log2(x) if _EXP2 else jnp.log(x)


def _mxu_dtype(operand_dtype):
    """dtype the probability/ds tiles take into an MXU dot against an
    operand of ``operand_dtype``. bf16 operands: bf16 (default) or fp32
    (the ``_P_BF16=False`` measurement variant, which upcasts the
    operand instead). fp32 operands always fp32 — golden-test parity."""
    if operand_dtype == jnp.bfloat16 and not _P_BF16:
        return jnp.dtype(jnp.float32)
    return jnp.dtype(operand_dtype)


def _cparams():
    """(batch*heads, outer, inner-reduction) -> the first two grid dims
    are parallel, the innermost accumulates into scratch."""
    if not _DIM_SEMANTICS:
        return None
    return _dimsem("parallel", "parallel", "arbitrary")


def _hash_keep(qpos, kpos, head, seed_lo, seed_hi, rate):
    """splitmix32-style integer mix over the GLOBAL (head, q, k) position so
    forward and backward regenerate bit-identical masks from the seed — no
    (s, s) mask tensor is ever materialized. 64 bits of PRNG-key entropy
    are folded in as two uint32 words (seed_lo, seed_hi) so per-call seeds
    do not birthday-collide at ~2^16 calls the way a single uint32 did.
    Pure jnp — usable both inside the Pallas kernels and on the unfused
    dispatch path (identical masks either way)."""
    x = (qpos * jnp.uint32(0x9E3779B9)) ^ (kpos * jnp.uint32(0x85EBCA6B))
    x = x ^ (seed_lo + head.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (seed_hi + (x >> 15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
    return x >= thresh  # keeps ~(1-rate) of positions


def _keep_mask(seed_ref, head, q0, k0, shape, rate):
    """Deterministic dropout keep-mask for a (TQ, TK) tile (kernel view)."""
    qpos = (q0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)).astype(
        jnp.uint32)
    kpos = (k0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)).astype(
        jnp.uint32)
    return _hash_keep(qpos, kpos, head, seed_ref[0, 0], seed_ref[0, 1],
                      rate)


def _score_mask(s, qt, kt, mask_row, sk, causal):
    """Validity mask for a score tile; every component is optional so the
    callers only pay for the masking a tile actually needs (``sk=None``
    skips the padding check, ``mask_row=None`` the user mask)."""
    tq, tk = s.shape
    kpos = kt * tk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = None
    if sk is not None:
        valid = kpos < sk
    if mask_row is not None:
        user = mask_row[None, :] != 0
        valid = user if valid is None else valid & user
    if causal:
        qpos = qt * tq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        tri = kpos <= qpos
        valid = tri if valid is None else valid & tri
    return valid


# -- forward ----------------------------------------------------------------

def _needs_mask(causal, pad, qt, kt, bq, bk, nk):
    """Traced predicate: does tile (qt, kt) need any masking? Only tiles
    crossing the causal diagonal and (under k-padding) the last k tile do;
    interior tiles take a mask-free path with roughly half the VPU work —
    which is the bound that matters (measured on v5e: causal tile-skipping
    alone moved the seq-2048 fwd+bwd bench <5%, because the kernels are
    VPU-bound on mask construction + softmax, not MXU-bound)."""
    needs = None
    if causal:
        needs = (kt + 1) * bk - 1 > qt * bq
    if pad:
        pad_t = kt == nk - 1
        needs = pad_t if needs is None else needs | pad_t
    return needs


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref,
                o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sk, causal, rate, has_mask, pad):
    i, qt, kt = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(kt == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal tile-skipping: tiles entirely above the diagonal contribute
    # nothing — gate ALL their compute (the index maps also clamp their
    # k/v fetches to an already-resident block, so a skipped tile costs
    # one grid tick and nothing else).
    run = _causal_live(qt, kt, bq, bk) if (causal and _CAUSAL_SKIP) \
        else True

    def tile(masked):
        def go():
            # q arrives PRE-SCALED by softmax_scale (*log2e under _EXP2)
            # — folded outside the kernel, so no per-score-element scale
            # op; scores are base-2 logits and every exp below is exp2
            q, k, v = q_ref[0], k_ref[0], v_ref[0]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if masked:
                valid = _score_mask(
                    s, qt, kt, mask_ref[0, 0, :] if has_mask else None,
                    sk if pad else None, causal)
                s = jnp.where(valid, s, _NEG)
            m_prev = m_ref[:, 0:1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = _exp(m_prev - m_cur)
            p = _exp(s - m_cur)
            if masked:
                p = jnp.where(valid, p, 0.0)
            # (m, l) statistics stay fp32: l sums the fp32 tile BEFORE
            # the bf16 cast so the normalizer keeps full precision
            l_ref[:, 0:1] = l_ref[:, 0:1] * alpha + jnp.sum(p, -1,
                                                            keepdims=True)
            m_ref[:, 0:1] = m_cur
            # p is consumed only by the PV matmul from here on — cast to
            # the MXU dtype now so the dropout keep/scale ops below run
            # on the narrow tile too (precision loss bounded by the fp32
            # matmul accumulate)
            p = p.astype(_mxu_dtype(v.dtype))
            if rate > 0.0:
                keep = _keep_mask(seed_ref, i, qt * bq, kt * bk,
                                  p.shape, rate)
                p = jnp.where(keep, p * p.dtype.type(1.0 / (1.0 - rate)),
                              p.dtype.type(0.0))
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p, v.astype(p.dtype), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return go

    @pl.when(run)
    def _():
        if has_mask:
            tile(True)()
        else:
            needs = _needs_mask(causal, pad, qt, kt, bq, bk, nk)
            if needs is None:
                tile(False)()
            else:
                jax.lax.cond(needs, tile(True), tile(False))

    @pl.when(kt == nk - 1)
    def _():
        l = l_ref[:, 0:1]
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = jnp.where(l > 0, acc_ref[:] / safe, 0.0).astype(
            o_ref.dtype)
        # lse block is (1, 1, bq) indexed BY qt — each qt owns its own
        # output block, so qt can stay 'parallel' in dimension_semantics
        # without megacore cores clobbering each other's slices of a
        # shared full-row block (a (1,1,sq_p) block indexed (i,0,0) is
        # revisited across qt; on v4/v5p each TensorCore's private copy
        # would lose the other core's rows on write-back).
        # Under _EXP2 the stored value is the BASE-2 logsumexp
        # (m2 + log2 l); the backward kernels consume it as-is — no
        # base conversion ever happens on an s²-sized tile.
        lse_ref[0, 0, :] = jnp.where(
            l[:, 0] > 0, m_ref[:, 0] + _log(l[:, 0]), jnp.inf)


# -- backward: dq -----------------------------------------------------------

def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, do_ref,
               lse_ref, delta_ref, dq_ref, dq_acc, *, sk, causal, rate,
               has_mask, pad):
    i, qt, kt = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(kt == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _causal_live(qt, kt, bq, bk) if (causal and _CAUSAL_SKIP) \
        else True

    def tile(masked):
        def go():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            lse_row = lse_ref[0, 0, pl.ds(qt * bq, bq)]
            delta_row = delta_ref[0, 0, pl.ds(qt * bq, bq)]
            # q pre-scaled; the kernel emits d(q*scale) and the caller
            # multiplies the final dq by softmax_scale once. Under _EXP2
            # s and lse_row are both base-2, so exp2(s - lse2) is the
            # base-e probability and ds needs NO base fixup here (dL/ds
            # is taken w.r.t. the base-e logit, whose gradient path the
            # caller's single scale multiply completes).
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = _exp(s - lse_row[:, None])
            if masked:
                valid = _score_mask(
                    s, qt, kt, mask_ref[0, 0, :] if has_mask else None,
                    sk if pad else None, causal)
                p = jnp.where(valid, p, 0.0)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            if rate > 0.0:
                keep = _keep_mask(seed_ref, i, qt * bq, kt * bk,
                                  p.shape, rate)
                dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
            ds = p * (dp - delta_row[:, None])
            dsd = _mxu_dtype(k.dtype)
            dq_acc[:] += jax.lax.dot_general(
                ds.astype(dsd), k.astype(dsd), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return go

    @pl.when(run)
    def _():
        if has_mask:
            tile(True)()
        else:
            needs = _needs_mask(causal, pad, qt, kt, bq, bk, nk)
            if needs is None:
                tile(False)()
            else:
                jax.lax.cond(needs, tile(True), tile(False))

    @pl.when(kt == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


# -- backward: dk, dv -------------------------------------------------------

def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, do_ref,
                lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                *, sk, causal, rate, has_mask, pad):
    i, kt, qt = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]

    @pl.when(qt == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _causal_live(qt, kt, bq, bk) if (causal and _CAUSAL_SKIP) \
        else True

    def tile(masked):
        def go():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            lse_row = lse_ref[0, 0, pl.ds(qt * bq, bq)]
            delta_row = delta_ref[0, 0, pl.ds(qt * bq, bq)]
            # q pre-scaled: dk = ds^T @ (scale*q); under _EXP2 the
            # prescale carries an extra log2e, so the caller multiplies
            # the FINAL dk tile by ln2 once (d-sized, not s²-sized)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = _exp(s - lse_row[:, None])
            if masked:
                valid = _score_mask(
                    s, qt, kt, mask_ref[0, 0, :] if has_mask else None,
                    sk if pad else None, causal)
                p = jnp.where(valid, p, 0.0)
            # p feeds only the dv matmul past this point (ds re-derives
            # from the fp32 copy below) — bf16 tile for keep/scale + MXU
            pd = _mxu_dtype(do.dtype)
            if rate > 0.0:
                keep = _keep_mask(seed_ref, i, qt * bq, kt * bk,
                                  p.shape, rate)
                p_drop = jnp.where(
                    keep, p.astype(pd) * pd.type(1.0 / (1.0 - rate)),
                    pd.type(0.0))
            else:
                p_drop = p.astype(pd)
            # dv += p_drop^T @ do
            dv_acc[:] += jax.lax.dot_general(
                p_drop, do.astype(pd), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            if rate > 0.0:
                dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
            ds = p * (dp - delta_row[:, None])
            dsd = _mxu_dtype(q.dtype)
            dk_acc[:] += jax.lax.dot_general(
                ds.astype(dsd), q.astype(dsd), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return go

    @pl.when(run)
    def _():
        if has_mask:
            tile(True)()
        else:
            needs = _needs_mask(causal, pad, qt, kt, bq, bk,
                                pl.num_programs(1))
            if needs is None:
                tile(False)()
            else:
                jax.lax.cond(needs, tile(True), tile(False))

    @pl.when(qt == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# -- padding / call plumbing ------------------------------------------------

def _smem():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _qkv_spec(tile, d):
    return pl.BlockSpec((1, tile, d), lambda i, q, k: (i, q, 0),
                        memory_space=pltpu.VMEM)


def _prep(q, k, v, mask, b, h):
    """Flatten (b,h,s,d) -> (b*h,s,d), pad s to tile multiples.

    head_dim is padded only to a sublane multiple (8), NOT to 128: a
    block whose last dim equals the array dim is legal, and padding
    d=64 to 128 would double the QK/PV matmul FLOPs for nothing.
    """
    _, _, sq, d = q.shape
    sk = k.shape[2]
    sq_p = round_up_to_multiple(sq, 128)
    sk_p = round_up_to_multiple(sk, 128)
    d_p = round_up_to_multiple(d, 8)

    def flat(x, s_p):
        x = x.reshape(b * h, x.shape[2], d)
        return _pad_axis(_pad_axis(x, s_p, 1), d_p, 2)

    q3, k3, v3 = flat(q, sq_p), flat(k, sk_p), flat(v, sk_p)
    if mask is None:
        m3 = jnp.ones((b, 1, sk_p), jnp.int32)
    else:
        m3 = _pad_axis(mask.astype(jnp.int32).reshape(b, 1, sk), sk_p, 2)
    return q3, k3, v3, m3, sq_p, sk_p, d_p


def _clamp_kt(causal, bq, bk):
    """k-tile index clamp for (i, qt, kt)-ordered causal grids: a tile
    above the diagonal re-requests the last live k-block instead of
    fetching one it will never read (the kernel's `run` gate skips the
    compute; this skips the copy)."""
    if not (causal and _CAUSAL_SKIP and _CAUSAL_CLAMP):
        return lambda kt, qt: kt
    return lambda kt, qt: jnp.minimum(kt, ((qt + 1) * bq - 1) // bk)


def _prescale_q(q3, scale):
    """Fold softmax_scale into q (fp32 multiply, one rounding back to
    the storage dtype) so no kernel pays a per-score-element scale op.
    Under _EXP2 the SAME multiply also carries log2(e): the kernels'
    score tiles come out as base-2 logits for free."""
    if _EXP2:
        scale = scale * _LOG2E
    return (q3.astype(jnp.float32) * jnp.float32(scale)).astype(q3.dtype)


def _maxb(causal, d):
    """Block-size cap: the causal-skip cap when tile skipping is on, the
    small-head-dim cap below _SMALL_D (see the toggle comments)."""
    maxb = _CAUSAL_MAX_BLOCK if (causal and _CAUSAL_SKIP) else 512
    if d < _SMALL_D:
        maxb = min(maxb, _SMALL_D_MAX_BLOCK)
    return maxb


def _fwd_call(q, k, v, mask, *, causal, scale, rate, seed, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    q3, k3, v3, m3, sq_p, sk_p, d_p = _prep(q, k, v, mask, b, h)
    q3 = _prescale_q(q3, scale)
    maxb = _maxb(causal, d)
    bq, bk = _block(sq_p, maxb), _block(sk_p, maxb)
    grid = (b * h, sq_p // bq, sk_p // bk)
    sd = jnp.asarray(seed, jnp.uint32).reshape(1, 2)
    ckt = _clamp_kt(causal, bq, bk)
    kv_spec = pl.BlockSpec((1, bk, d_p),
                           lambda i, qt, kt: (i, ckt(kt, qt), 0),
                           memory_space=pltpu.VMEM)
    mask_spec = pl.BlockSpec((1, 1, bk),
                             lambda i, qt, kt: (i // h, 0, ckt(kt, qt)),
                             memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, bq), lambda i, qt, kt: (i, 0, qt),
                            memory_space=pltpu.VMEM)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sk=sk, causal=causal, rate=rate,
                          has_mask=mask is not None, pad=sk != sk_p),
        grid=grid,
        in_specs=[_smem(), _qkv_spec(bq, d_p), kv_spec, kv_spec,
                  mask_spec],
        out_specs=(_qkv_spec(bq, d_p), lse_spec),
        out_shape=(jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype),
                   jax.ShapeDtypeStruct((b * h, 1, sq_p), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bq, d_p), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32)],
        compiler_params=_cparams(),
        interpret=pallas_interpret(interpret),
    )(sd, q3, k3, v3, m3)
    out = o[:, :sq, :d].reshape(b, h, sq, d)
    return out, lse  # lse stays padded (b*h, 1, sq_p)


def _bwd_call(q, k, v, mask, out, lse_p, do, *, causal, scale, rate, seed,
              interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    q3, k3, v3, m3, sq_p, sk_p, d_p = _prep(q, k, v, mask, b, h)
    q3 = _prescale_q(q3, scale)
    do3 = _pad_axis(_pad_axis(do.reshape(b * h, sq, d), sq_p, 1), d_p, 2)
    o3 = _pad_axis(_pad_axis(out.reshape(b * h, sq, d), sq_p, 1), d_p, 2)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    -1)[:, None, :]  # (bh, 1, sq_p) like lse
    sd = jnp.asarray(seed, jnp.uint32).reshape(1, 2)

    maxb = _maxb(causal, d)
    bq, bk = _block(sq_p, maxb), _block(sk_p, maxb)
    ckt = _clamp_kt(causal, bq, bk)
    row_spec = pl.BlockSpec((1, 1, sq_p), lambda i, qt, kt: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, d_p),
                           lambda i, qt, kt: (i, ckt(kt, qt), 0),
                           memory_space=pltpu.VMEM)
    mask_spec = pl.BlockSpec((1, 1, bk),
                             lambda i, qt, kt: (i // h, 0, ckt(kt, qt)),
                             memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sk=sk, causal=causal, rate=rate,
                          has_mask=mask is not None, pad=sk != sk_p),
        grid=(b * h, sq_p // bq, sk_p // bk),
        in_specs=[_smem(), _qkv_spec(bq, d_p), kv_spec, kv_spec,
                  mask_spec, _qkv_spec(bq, d_p), row_spec, row_spec],
        out_specs=_qkv_spec(bq, d_p),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d_p), jnp.float32)],
        compiler_params=_cparams(),
        interpret=pallas_interpret(interpret),
    )(sd, q3, k3, v3, m3, do3, lse_p, delta)

    # dkv: k outer / q inner — index maps swap roles; causal clamp
    # mirrors _clamp_kt (q tiles strictly above the diagonal are dead)
    if causal and _CAUSAL_SKIP and _CAUSAL_CLAMP:
        cqt = lambda qt, kt: jnp.maximum(qt, (kt * bk) // bq)
    else:
        cqt = lambda qt, kt: qt
    q_spec2 = pl.BlockSpec((1, bq, d_p),
                           lambda i, kt, qt: (i, cqt(qt, kt), 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, bk, d_p), lambda i, kt, qt: (i, kt, 0),
                            memory_space=pltpu.VMEM)
    mask_spec2 = pl.BlockSpec((1, 1, bk),
                              lambda i, kt, qt: (i // h, 0, kt),
                              memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, 1, sq_p), lambda i, kt, qt: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sk=sk, causal=causal, rate=rate,
                          has_mask=mask is not None, pad=sk != sk_p),
        grid=(b * h, sk_p // bk, sq_p // bq),
        in_specs=[_smem(), q_spec2, kv_spec2, kv_spec2, mask_spec2,
                  q_spec2, row_spec2, row_spec2],
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(jax.ShapeDtypeStruct((b * h, sk_p, d_p), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk_p, d_p), v.dtype)),
        scratch_shapes=[pltpu.VMEM((bk, d_p), jnp.float32),
                        pltpu.VMEM((bk, d_p), jnp.float32)],
        compiler_params=_cparams(),
        interpret=pallas_interpret(interpret),
    )(sd, q3, k3, v3, m3, do3, lse_p, delta)

    # dq kernel produced d(scale*q); one fused XLA multiply finishes it
    dq = (dq[:, :sq, :d].astype(jnp.float32) * jnp.float32(scale)
          ).astype(q.dtype).reshape(b, h, sq, d)
    dk = dk[:, :sk, :d]
    if _EXP2:
        # the dkv kernel's dk = ds^T @ (scale*log2e*q) — one ln(2)
        # multiply on the final (s, d) tile undoes the log2e (the ONLY
        # base-conversion cost of the base-2 softmax; it fuses with the
        # slice above)
        dk = (dk.astype(jnp.float32) * jnp.float32(_LN2)).astype(k.dtype)
    dk = dk.reshape(b, h, sk, d)
    dv = dv[:, :sk, :d].reshape(b, h, sk, d)
    return dq, dk, dv


# -- custom_vjp + public API ------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_core(cfg, q, k, v, mask, seed):
    causal, scale, rate, interpret = cfg
    out, _ = _fwd_call(q, k, v, mask, causal=causal, scale=scale, rate=rate,
                       seed=seed, interpret=interpret)
    return out


def _flash_fwd(cfg, q, k, v, mask, seed):
    causal, scale, rate, interpret = cfg
    out, lse_p = _fwd_call(q, k, v, mask, causal=causal, scale=scale,
                           rate=rate, seed=seed, interpret=interpret)
    return out, (q, k, v, mask, out, lse_p, seed)


def _flash_bwd(cfg, res, do):
    causal, scale, rate, interpret = cfg
    q, k, v, mask, out, lse_p, seed = res
    dq, dk, dv = _bwd_call(q, k, v, mask, out, lse_p, do, causal=causal,
                           scale=scale, rate=rate, seed=seed,
                           interpret=interpret)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


# Measured crossover on TPU v5e (b=16, h=16, d=64, fwd+bwd): at padded
# seq <= 256 XLA's single batched einsum+softmax beats the tiled kernel
# (the kernel degenerates to b*h sequential one-tile programs), while at
# >= 512 the kernel wins and at 2048 it is ~2x faster. Dispatch on size
# so every caller gets the better path at its shape.
_UNFUSED_MAX_SEQ = 256


def _unfused_attention(q, k, v, mask, seed, *, causal, scale, rate):
    """Mathematically-identical XLA path for short sequences.

    Same masking convention (fully-masked rows return 0) and the SAME
    ``_hash_keep`` dropout mask as the kernels, so dispatch never changes
    training randomness semantics; autodiff replays the mask bit-exactly
    in the backward because the hash is deterministic in its inputs.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is None:
        valid = jnp.ones((1, 1, 1, sk), bool)
    else:
        valid = (mask[:, None, None, :] != 0)
    if causal:
        tri = (jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None])
        valid = valid & tri[None, None]
    s = jnp.where(valid, s, _NEG)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    p = jnp.where(l > 0, p / jnp.where(l > 0, l, 1.0), 0.0)
    if rate > 0.0:
        # global (bh, q, k) positions — identical mask to the kernel's
        bh = jnp.arange(b * h, dtype=jnp.uint32).reshape(b, h, 1, 1)
        qpos = jnp.arange(sq, dtype=jnp.uint32).reshape(1, 1, sq, 1)
        kpos = jnp.arange(sk, dtype=jnp.uint32).reshape(1, 1, 1, sk)
        keep = _hash_keep(qpos, kpos, bh, seed[0], seed[1], rate)
        p = jnp.where(keep, p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None, *,
                    causal: bool = False,
                    softmax_scale: Optional[float] = None,
                    dropout_rate: float = 0.0,
                    dropout_rng: Optional[jax.Array] = None,
                    use_kernel: Optional[bool] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused scaled-dot-product attention.

    Args:
      q, k, v: (batch, heads, seq, head_dim).
      mask: optional (batch, s_k) with 1 = attend (BERT convention).
      causal: apply the implicit upper-triangular mask.
      softmax_scale: defaults to 1/sqrt(head_dim).
      dropout_rate: attention-probability dropout (after normalization,
        reference semantics); active only when ``dropout_rng`` is given.
      dropout_rng: PRNG key; 64 bits folded into the dropout-hash seed.
      use_kernel: force the Pallas kernel (True) or the XLA path (False);
        None auto-dispatches on sequence length (kernel when the padded
        seq exceeds ``_UNFUSED_MAX_SEQ`` — the measured v5e crossover).

    Returns (batch, heads, seq, head_dim) in q's dtype.
    """
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)
    rate = float(dropout_rate) if dropout_rng is not None else 0.0
    if rate > 0.0:
        seed = jax.random.bits(dropout_rng, (2,), jnp.uint32)
    else:
        seed = jnp.zeros((2,), jnp.uint32)
    if use_kernel is None:
        use_kernel = max(q.shape[2], k.shape[2]) > _UNFUSED_MAX_SEQ
    if not use_kernel:
        return _unfused_attention(q, k, v, mask, seed, causal=bool(causal),
                                  scale=float(softmax_scale), rate=rate)
    cfg = (bool(causal), float(softmax_scale), rate, interpret)
    return _flash_core(cfg, q, k, v, mask, seed)

"""Fused rotary positional embedding (RoPE).

Reference: ``apex/transformer/functional/fused_rope.py`` backed by
``csrc/megatron/fused_rotary_positional_embedding*`` — CUDA kernels whose
entire job is fusing the ``t*cos + rotate_half(t)*sin`` elementwise chain
into one pass and providing a hand-written backward.

TPU design: RoPE is purely elementwise over (seq, dim) broadcast factors.
XLA fuses elementwise chains into the surrounding matmuls natively, so a
Pallas kernel would only re-derive what the fusion pass already does (this
is the "let XLA fuse" rule, not a deferral). What the CUDA kernel's
hand-written backward DOES buy — computing dt as the rotation by ``-θ``
(the transpose of a rotation) instead of replaying the product rule, and
never materializing ``rotate_half(t)`` as a saved residual — is captured
here with a ``custom_vjp``. Unlike the reference kernel (whose backward
returns no gradient for freqs at all), the vjp also emits the true
cotangents for cos/sin so learned/scaled rotary tables train correctly
rather than silently receiving zeros. Internal math is fp32 (the CUDA
kernel computes in float internally too); the output is cast back once.

Conventions (reference parity):
- ``freqs`` is (s, 1, 1, d_rot) — position-outer-product-with-inv-freq,
  NOT yet cos/sin (``_cached`` takes precomputed cos/sin).
- tensors are sbhd (Megatron (seq, batch, head, dim)) unless the
  ``_bshd``/``_bhsd`` wrappers are used.
- when d_rot < d, the trailing ``d - d_rot`` channels pass through
  untouched (reference behavior for partial rotary).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rope_frequencies(dim: int, seq_len: int, base: float = 10000.0,
                     dtype=jnp.float32) -> jax.Array:
    """The (s, 1, 1, dim) angle tensor θ_{p,i} = p · base^(-2i/dim).

    Matches the reference testing helper (RotaryEmbedding in
    ``apex/transformer/testing``): inv_freq over even channels, angles
    duplicated across the two rotation halves.
    """
    inv_freq = 1.0 / (base ** (
        jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    ang = jnp.outer(pos, inv_freq)                     # (s, dim/2)
    ang = jnp.concatenate([ang, ang], axis=-1)          # (s, dim)
    return ang.astype(dtype)[:, None, None, :]


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _apply(t: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """t*cos + rotate_half(t)*sin on the leading d_rot channels, fp32
    internally, cast back to t's dtype once."""
    d_rot = cos.shape[-1]
    if d_rot < t.shape[-1]:
        rot, rest = t[..., :d_rot], t[..., d_rot:]
    else:
        rot, rest = t, None
    r32 = rot.astype(jnp.float32)
    out = (r32 * cos.astype(jnp.float32)
           + _rotate_half(r32) * sin.astype(jnp.float32)).astype(t.dtype)
    if rest is not None:
        out = jnp.concatenate([out, rest], axis=-1)
    return out


def _reduce_to(x: jax.Array, shape) -> jax.Array:
    """Sum ``x`` over the axes the (same-rank) target ``shape`` broadcasts."""
    axes = tuple(i for i, n in enumerate(shape) if n == 1 and x.shape[i] != 1)
    return jnp.sum(x, axis=axes, keepdims=True) if axes else x


@jax.custom_vjp
def _rope_core(t: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    return _apply(t, cos, sin)


def _rope_fwd(t, cos, sin):
    return _apply(t, cos, sin), (t, cos, sin)


def _rope_bwd(res, g):
    # dt: R(θ)ᵀ = R(−θ) — the same elementwise form with sin negated (no
    # product-rule replay, no saved rotate_half residual). dcos/dsin: the
    # product-rule factors, reduced over the axes cos/sin broadcast.
    t, cos, sin = res
    d_rot = cos.shape[-1]
    dt = _apply(g, cos, -sin)
    g32 = g[..., :d_rot].astype(jnp.float32)
    r32 = t[..., :d_rot].astype(jnp.float32)
    dcos = _reduce_to(g32 * r32, cos.shape).astype(cos.dtype)
    dsin = _reduce_to(g32 * _rotate_half(r32), sin.shape).astype(sin.dtype)
    return dt, dcos, dsin


_rope_core.defvjp(_rope_fwd, _rope_bwd)


def fused_apply_rotary_pos_emb(t: jax.Array, freqs: jax.Array) -> jax.Array:
    """Reference ``fused_apply_rotary_pos_emb``: t (s, b, h, d),
    freqs (s, 1, 1, d_rot) angles; returns t's dtype/shape."""
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    return _rope_core(t, cos, sin)


def fused_apply_rotary_pos_emb_cached(t: jax.Array, cos: jax.Array,
                                      sin: jax.Array) -> jax.Array:
    """Reference ``fused_apply_rotary_pos_emb_cached``: precomputed
    cos/sin (s, 1, 1, d_rot) — saves the transcendentals when the tables
    are reused across layers (GPT does this)."""
    return _rope_core(t, cos, sin)


def fused_apply_rotary_pos_emb_bshd(t: jax.Array,
                                    freqs: jax.Array) -> jax.Array:
    """(b, s, h, d) layout wrapper."""
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    return _rope_core(t, cos[None, :, 0], sin[None, :, 0])


def fused_apply_rotary_pos_emb_bhsd(t: jax.Array, freqs: jax.Array,
                                    positions: Optional[jax.Array] = None
                                    ) -> jax.Array:
    """(b, h, s, d) layout wrapper — the in-tree models' attention layout.

    ``positions`` (optional, traced is fine) selects ABSOLUTE rotation
    angles from the ``freqs`` table. A (b,) integer array rotates row
    ``i`` of ``t`` as if its ``s`` query positions were
    ``positions[i], positions[i]+1, ...`` — the incremental-decode
    entry point: a single-token query (s=1) at cache offset ``p`` must
    be rotated by θ_p, not θ_0, and the offset differs per batch slot.
    A (b, s) integer array gives every element its own position — the
    tree-verify entry point, where node j's angle is ``pos +
    depth[j]`` and depths are NOT consecutive. The default
    (``positions=None``) keeps the training convention — angles are
    rows ``0..s-1`` of the table, shared across the batch."""
    cos = jnp.cos(freqs).reshape(freqs.shape[0], freqs.shape[-1])
    sin = jnp.sin(freqs).reshape(freqs.shape[0], freqs.shape[-1])
    if positions is None:
        return _rope_core(t, cos[None, None], sin[None, None])
    # (b, s) absolute positions -> gathered (b, 1, s, d) angle factors
    # broadcasting over the head axis of t (b, h, s, d)
    if positions.ndim == 2:
        idx = positions
    else:
        idx = positions[:, None] + jnp.arange(t.shape[2])[None, :]
    return _rope_core(t, cos[idx][:, None], sin[idx][:, None])


def rope_cos_sin(dim: int, seq_len: int, base: float = 10000.0,
                 dtype=jnp.float32
                 ) -> Tuple[jax.Array, jax.Array]:
    """Precomputed (cos, sin) tables for the ``_cached`` entry point."""
    freqs = rope_frequencies(dim, seq_len, base, jnp.float32)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)

"""Transformer test rig (ref: ``apex/transformer/testing``).

The reference keeps Megatron-shaped test infrastructure here:
``standalone_bert.py``/``standalone_gpt.py`` (in-tree models exercising
the TP/PP stack), ``global_vars.py``/``arguments.py`` (the Megatron flag
system the schedules consult), and ``commons.py`` (distributed-test
helpers). The TPU equivalents:

- the standalone models live in the first-class zoo (``apex_tpu.models``:
  BERT and the TP/PP-ready GPT) — re-exported here under the reference
  names so reference-shaped test code finds them;
- ``global_vars``/``arguments`` are real (Megatron-style argparse +
  process-global args registry) for scripts written against that API.
"""

from apex_tpu.models.bert import (  # noqa: F401  (standalone_bert)
    BertConfig,
    apply_bert,
    bert_tiny,
    init_bert,
)
from apex_tpu.models.gpt import (  # noqa: F401  (standalone_gpt)
    GPTConfig,
    GPTModel,
    gpt_pipeline_model,
    gpt_tiny,
    init_gpt,
)
from apex_tpu.transformer.testing.arguments import (  # noqa: F401
    parse_args,
)
from apex_tpu.transformer.testing.global_vars import (  # noqa: F401
    get_args,
    set_args,
)

"""Megatron-style flag parsing (ref:
``apex/transformer/testing/arguments.py :: parse_args`` — the trimmed
Megatron argument set the reference's transformer tests consume).

Only the flags with a live consumer in this package are kept; each maps
onto the mesh/model config it drives. Unknown extra flags are tolerated
(``parse_known_args``) exactly because reference test scripts pass a
superset."""

import argparse
from typing import List, Optional


def parse_args(extra_args_provider=None,
               args: Optional[List[str]] = None,
               ignore_unknown_args: bool = True) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="apex_tpu transformer args",
                                allow_abbrev=False)
    g = p.add_argument_group("parallelism")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--sequence-parallel", action="store_true")

    g = p.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=4)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--num-attention-heads", type=int, default=8)
    g.add_argument("--seq-length", type=int, default=64)
    g.add_argument("--max-position-embeddings", type=int, default=64)
    g.add_argument("--padded-vocab-size", type=int, default=512)

    g = p.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=8)
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")

    if extra_args_provider is not None:
        p = extra_args_provider(p)
    if ignore_unknown_args:
        ns, _ = p.parse_known_args(args)
    else:
        ns = p.parse_args(args)
    return ns


def initialize_from_args(ns: argparse.Namespace):
    """Build the global mesh from parsed flags (the ``initialize_megatron``
    step of reference test scripts)."""
    from apex_tpu.transformer import parallel_state as ps

    return ps.initialize_model_parallel(
        tensor_model_parallel_size_=ns.tensor_model_parallel_size,
        pipeline_model_parallel_size_=ns.pipeline_model_parallel_size,
        virtual_pipeline_model_parallel_size_=(
            ns.virtual_pipeline_model_parallel_size),
        context_parallel_size_=ns.context_parallel_size)

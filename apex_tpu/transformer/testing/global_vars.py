"""Process-global args registry (ref:
``apex/transformer/testing/global_vars.py :: get_args/set_global_variables``
— Megatron keeps parsed flags in a module global that schedules and test
helpers read). Functional JAX code should thread config explicitly; this
exists for reference-shaped scripts."""

from typing import Optional

_GLOBAL_ARGS = None


def set_args(args) -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args():
    if _GLOBAL_ARGS is None:
        raise RuntimeError(
            "global args not initialized — call set_args(parse_args()) "
            "first (ref: Megatron's set_global_variables)")
    return _GLOBAL_ARGS


def unset_args() -> None:
    """Test teardown helper."""
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = None


def args_are_set() -> bool:
    return _GLOBAL_ARGS is not None

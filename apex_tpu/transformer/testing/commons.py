"""Shared test-rig helpers (ref: ``apex/transformer/testing/commons.py``
— ``initialize_distributed``, ``set_random_seed``, model builders the
reference's transformer tests share).

TPU translations: process-group bootstrap becomes mesh construction
(single-controller; multi-host via ``jax.distributed``); torch's global
RNG seeding becomes explicit key construction plus the TP RNG tracker."""

from typing import Optional

import jax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import random as tp_random


def initialize_distributed(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1,
                           virtual_pipeline_model_parallel_size:
                           Optional[int] = None,
                           context_parallel_size: int = 1):
    """Build the global mesh (ref: spawns/initializes the torch process
    group then calls ``parallel_state.initialize_model_parallel``)."""
    ps.destroy_model_parallel()
    return ps.initialize_model_parallel(
        tensor_model_parallel_size_=tensor_model_parallel_size,
        pipeline_model_parallel_size_=pipeline_model_parallel_size,
        virtual_pipeline_model_parallel_size_=(
            virtual_pipeline_model_parallel_size),
        context_parallel_size_=context_parallel_size)


def set_random_seed(seed: int) -> jax.Array:
    """Seed the TP RNG tracker and return a fresh root key (ref: seeds
    python/numpy/torch globals + the cuda-rng tracker; JAX has no global
    RNG — the returned key is the explicit equivalent)."""
    tracker = tp_random.get_rng_tracker()
    tracker.reset()
    tracker.add("model-parallel-rng", seed + 2718)
    return jax.random.PRNGKey(seed)


def print_separator(message: str) -> None:
    """The reference's test-section banner."""
    print("\n" + "-" * 20 + f" {message} " + "-" * 20, flush=True)

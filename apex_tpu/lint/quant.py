"""Quantization-contract checks: APX106.

The int8 inference tier (``apex_tpu.quant`` + the int8 paged KV pool)
rests on three numeric invariants that a type checker cannot see and a
tolerance test only catches after the fact:

1. **Scale tensors stay fp32.** A per-channel (or per-page-per-head)
   scale rounded through bf16 loses ~5 bits of mantissa and biases
   every dequantized element of its channel the same direction — the
   error is systematic, not noise, and teacher-forced logit drift
   explodes. Flags (a) stores into a ``*scale*``-stemmed ref/out that
   round through ``astype(bf16/f16)``, (b) ``pallas_call`` scratch /
   ``out_shape`` declarations that allocate a ``*scale*`` operand
   below fp32.

2. **Dequant accumulators are fp32.** Inside a dequant-fused matmul
   (any function whose name contains ``w8`` or ``dequant``) every
   ``dot``/``dot_general``/``matmul`` must pin
   ``preferred_element_type`` to fp32 (or wider) — the operands are
   fp32-dequantized in registers, but without the pin XLA may pick a
   narrower accumulator on bf16-native backends.

3. **int8 stores round to nearest.** ``astype(int8)`` truncates toward
   zero; round-to-nearest (RTN) is what makes whole-page requant
   idempotent at a fixed scale (untouched pages stay bit-identical —
   the paged COW/placement-independence tests rely on it). Flags any
   ``astype(int8)`` inside a function that contains no explicit
   rounding call (``round``/``rint``/``nearbyint``).

Like every apxlint check these are conventions over the repo's own
naming idioms (``X_ref``/``X_out`` kernel params, ``w8_*`` kernel
names); anything not statically readable is skipped, never guessed at.
"""

import ast
from typing import Dict, List, Optional

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import (
    attr_chain,
    call_name,
    kwarg,
    static_elements,
    static_len,
)

_LOW_PRECISION = {"bfloat16", "float16"}
_ACCUM_OK = {"float32", "float64"}
_DOT_NAMES = {"dot", "dot_general", "matmul"}
_ROUND_NAMES = {"round", "rint", "nearbyint"}
_DEQUANT_MARKERS = ("w8", "dequant")


def _stem(param: str) -> str:
    for suffix in ("_ref", "_out"):
        if param.endswith(suffix):
            return param[: -len(suffix)]
    return param


def _is_scale(param: str) -> bool:
    return "scale" in _stem(param)


def _dtype_name(node: Optional[ast.AST]) -> Optional[str]:
    """``jnp.float32`` -> "float32"; ``"int8"`` -> "int8"; else None."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    chain = attr_chain(node)
    return chain[-1] if chain else None


def _is_low_precision(node: Optional[ast.AST]) -> bool:
    return _dtype_name(node) in _LOW_PRECISION


def _downcasts(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "astype" and n.args
                and _is_low_precision(n.args[0])):
            return True
    return False


def _kernel_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call) and call_name(node) == "partial":
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
    return None


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    defs: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            defs.setdefault(n.name, n)

    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and call_name(node) == "pallas_call" and node.args):
            kname = _kernel_name(node.args[0])
            kernel = defs.get(kname) if kname else None
            if kernel is not None:
                findings.extend(_check_scale_decls(node, kernel, path))

    findings.extend(_check_scale_stores(tree, path))
    findings.extend(_check_functions(tree, path))
    return findings


def _check_scale_decls(node: ast.Call, kernel: ast.FunctionDef,
                       path: str) -> List[Finding]:
    """Rule 1(b): scale operands of a pallas_call declared below fp32.

    Same positional param mapping as APX101/103: inputs are the first
    ``len(in_specs)`` kernel params, outputs next, scratch last."""
    n_in = static_len(kwarg(node, "in_specs"))
    n_out = static_len(kwarg(node, "out_specs"))
    params = [a.arg for a in kernel.args.posonlyargs + kernel.args.args]
    if n_in is None:
        return []
    if n_out is None:
        if kwarg(node, "scratch_shapes") is not None:
            return []
        n_out = len(params) - n_in
    if n_out < 0 or len(params) < n_in + n_out:
        return []

    out_params = params[n_in:n_in + n_out]
    scratch_params = params[n_in + n_out:]

    findings = []
    scratch = static_elements(kwarg(node, "scratch_shapes")) or []
    for p, elem in zip(scratch_params, scratch):
        if not _is_scale(p):
            continue
        if (isinstance(elem, ast.Call) and len(elem.args) >= 2
                and _is_low_precision(elem.args[1])):
            findings.append(Finding(
                "APX106", path, elem.lineno,
                f"scale scratch '{p}' allocated in reduced precision — "
                "quantization scales must stay fp32"))
    outs = static_elements(kwarg(node, "out_shape")) or []
    for p, elem in zip(out_params, outs):
        if not _is_scale(p):
            continue
        if (isinstance(elem, ast.Call) and len(elem.args) >= 2
                and _is_low_precision(elem.args[1])):
            findings.append(Finding(
                "APX106", path, elem.lineno,
                f"scale output '{p}' declared in reduced precision — "
                "quantization scales must stay fp32"))
    return findings


def _check_scale_stores(tree: ast.Module, path: str) -> List[Finding]:
    """Rule 1(a): ``scale_ref[...] = (...).astype(bf16)`` anywhere —
    scale refs are unambiguous by naming convention, no call-site
    mapping needed."""
    findings = []
    seen = set()
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)):
                continue
            name = t.value.id
            if not name.endswith(("_ref", "_out")):
                continue
            if not _is_scale(name):
                continue
            if _downcasts(node.value) and node.lineno not in seen:
                seen.add(node.lineno)
                findings.append(Finding(
                    "APX106", path, node.lineno,
                    f"store into scale ref '{name}' rounds through a "
                    "reduced-precision astype — per-channel scales must "
                    "stay fp32"))
    return findings


def _check_functions(tree: ast.Module, path: str) -> List[Finding]:
    """Rules 2 and 3, both scoped to the innermost enclosing function."""
    findings: List[Finding] = []

    def visit(node: ast.AST, fn: Optional[ast.FunctionDef],
              fn_rounds: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef):
                visit(child, child, _has_round(child))
                continue
            if isinstance(child, ast.Call):
                findings.extend(_check_call(child, fn, fn_rounds, path))
            visit(child, fn, fn_rounds)

    visit(tree, None, False)
    return findings


def _has_round(fn: ast.FunctionDef) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and call_name(n) in _ROUND_NAMES:
            return True
    return False


def _check_call(node: ast.Call, fn: Optional[ast.FunctionDef],
                fn_rounds: bool, path: str) -> List[Finding]:
    name = call_name(node)
    findings = []
    # Rule 2: dot inside a dequant-fused body must pin fp32 accumulation
    if (fn is not None and name in _DOT_NAMES
            and any(m in fn.name for m in _DEQUANT_MARKERS)):
        pet = _dtype_name(kwarg(node, "preferred_element_type"))
        if pet not in _ACCUM_OK:
            what = (f"preferred_element_type={pet}" if pet
                    else "no preferred_element_type")
            findings.append(Finding(
                "APX106", path, node.lineno,
                f"{name} in dequant-fused '{fn.name}' has {what} — "
                "int8 dequant matmuls must accumulate in fp32"))
    # Rule 3: astype(int8) without an explicit round in the same function
    if (fn is not None and not fn_rounds and name == "astype"
            and node.args and _dtype_name(node.args[0]) in ("int8",)):
        findings.append(Finding(
            "APX106", path, node.lineno,
            f"astype(int8) in '{fn.name}' with no rounding call in "
            "scope — int8 quantization must round to nearest "
            "(truncation breaks requant idempotence)"))
    return findings

"""APX701/APX702 — partition-rule table coverage and cross-tree drift.

APX701 is the table's own contract: over the union of an entry's
registered abstract trees, every non-scalar leaf is matched by exactly
one rule, every matched spec fits its array (rank <= ndim), every mesh
axis a spec names exists on the canonical mesh and appears at most once
per spec, and every rule matches at least one leaf (a dead rule is a
typo'd pattern silently replicating whatever it was meant to shard —
the exact failure mode ``match_partition_rules``'s unmatched-leaf error
exists to kill, one step earlier).

APX702 is everything the repo *derives* from the table staying
identical per tensor family: optimizer moments / master weights
(re-matched under an ``m/``-, ``v/``-, ``master/``-prefixed path, so a
root-anchored pattern shows up as drift), the serving KV cache's head
axis against the attention qkv weights' tensor-parallel axis, and the
rule-derived spec tree against the hand-maintained reference
(``gpt_partition_specs``/``bert_partition_specs``) where one is
registered. A flipped axis in one rule fires here before it ever
reaches a pod slice.
"""

import re
from typing import List, Optional

from jax.sharding import PartitionSpec

from apex_tpu.lint import Finding


def _flat_specs(tree):
    import jax

    from apex_tpu.partition import tree_path_name

    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
    return [(tree_path_name(path), spec) for path, spec in flat]


def _safe_match(rules, tree) -> Optional[object]:
    from apex_tpu.partition import match_partition_rules

    try:
        return match_partition_rules(rules, tree)
    except ValueError:
        return None  # uncovered leaves: already an APX701 finding


def check(entry, path: str) -> List[Finding]:
    from apex_tpu.partition import (
        optimizer_state_specs, rule_match_table, spec_axis_names,
    )
    from apex_tpu.transformer import parallel_state as ps

    rules = tuple(entry.rules())
    findings: List[Finding] = []

    # -- APX701: per-rule spec sanity (tree-independent) ------------------
    known_axes = set(ps.MESH_AXIS_NAMES)
    for i, (pattern, spec) in enumerate(rules):
        try:
            re.compile(pattern)
        except re.error as exc:
            findings.append(Finding(
                "APX701", path, 1,
                f"entry '{entry.name}': rule {i} pattern {pattern!r} "
                f"is not a valid regex: {exc}"))
            continue
        axes = spec_axis_names(spec)
        unknown = [a for a in axes if a not in known_axes]
        if unknown:
            findings.append(Finding(
                "APX701", path, 1,
                f"entry '{entry.name}': rule {i} ({pattern!r}) names "
                f"mesh axes {unknown} that do not exist "
                f"(mesh axes: {sorted(known_axes)})"))
        dupes = sorted({a for a in axes if axes.count(a) > 1})
        if dupes:
            findings.append(Finding(
                "APX701", path, 1,
                f"entry '{entry.name}': rule {i} ({pattern!r}) repeats "
                f"mesh axes {dupes} within one spec — an array dim "
                f"cannot shard over the same axis twice"))

    trees = entry.trees() if entry.trees is not None else {}

    # -- APX701: coverage over the registered trees -----------------------
    live = set()
    for tree_name, tree in sorted(trees.items()):
        for leaf_path, leaf, hits in rule_match_table(rules, tree):
            live.update(hits)
            ndim = len(getattr(leaf, "shape", ()))
            if ndim == 0:
                continue  # scalars replicate without consulting the table
            if not hits:
                findings.append(Finding(
                    "APX701", path, 1,
                    f"entry '{entry.name}': no rule matches "
                    f"'{tree_name}' leaf '{leaf_path}' (shape "
                    f"{tuple(leaf.shape)}) — it would raise at shard "
                    f"time"))
                continue
            if len(hits) > 1:
                pats = [rules[i][0] for i in hits]
                findings.append(Finding(
                    "APX701", path, 1,
                    f"entry '{entry.name}': '{tree_name}' leaf "
                    f"'{leaf_path}' matched by {len(hits)} rules "
                    f"{pats} — first-match-wins hides all but "
                    f"{pats[0]!r}"))
                continue
            spec = rules[hits[0]][1]
            if len(tuple(spec)) > ndim:
                findings.append(Finding(
                    "APX701", path, 1,
                    f"entry '{entry.name}': rule {rules[hits[0]][0]!r} "
                    f"spec {spec} has rank {len(tuple(spec))} > array "
                    f"rank {ndim} of '{tree_name}' leaf '{leaf_path}'"))
    if trees:
        for i in sorted(set(range(len(rules))) - live):
            findings.append(Finding(
                "APX701", path, 1,
                f"entry '{entry.name}': rule {i} ({rules[i][0]!r}) "
                f"matches no leaf of any registered tree — dead rule "
                f"(typo'd pattern?)"))

    # -- APX702: derived trees must agree per tensor family ---------------
    params = trees.get("params")
    param_specs = _safe_match(rules, params) if params is not None else None

    if entry.optimizer_families and param_specs is not None:
        fams = optimizer_state_specs(rules, params,
                                     families=entry.optimizer_families)
        base = _flat_specs(param_specs)
        for fam in entry.optimizer_families:
            for (leaf_path, pspec), (_, fspec) in zip(
                    base, _flat_specs(fams[fam])):
                if pspec != fspec:
                    findings.append(Finding(
                        "APX702", path, 1,
                        f"entry '{entry.name}': optimizer family "
                        f"'{fam}' of param '{leaf_path}' derives spec "
                        f"{fspec} but the param derives {pspec} — "
                        f"state and weights would shard differently"))

    if entry.reference_specs is not None:
        refs = entry.reference_specs()
        for tree_name, ref_tree in sorted(refs.items()):
            if tree_name not in trees:
                continue
            derived = _safe_match(rules, trees[tree_name])
            if derived is None:
                continue
            for (leaf_path, dspec), (_, rspec) in zip(
                    _flat_specs(derived), _flat_specs(ref_tree)):
                if dspec != rspec:
                    findings.append(Finding(
                        "APX702", path, 1,
                        f"entry '{entry.name}': rule-derived spec "
                        f"{dspec} for '{tree_name}' leaf '{leaf_path}' "
                        f"!= hand-maintained reference {rspec}"))

    if entry.kv_cache_tree is not None and param_specs is not None:
        cache_specs = _safe_match(rules, trees[entry.kv_cache_tree])
        if cache_specs is not None:
            flat_cache = dict(_flat_specs(cache_specs))
            k_spec = next((s for p, s in flat_cache.items()
                           if p == "k" or p.endswith("/k")), None)
            v_spec = next((s for p, s in flat_cache.items()
                           if p == "v" or p.endswith("/v")), None)
            if k_spec != v_spec:
                findings.append(Finding(
                    "APX702", path, 1,
                    f"entry '{entry.name}': KV cache k spec {k_spec} "
                    f"!= v spec {v_spec}"))
            qkv_axes = set()
            for leaf_path, spec in _flat_specs(param_specs):
                if re.search(entry.qkv_kernel_re, leaf_path):
                    entries_ = tuple(spec)
                    last = entries_[-1] if entries_ else None
                    if last is not None:
                        qkv_axes.update(
                            last if isinstance(last, tuple) else (last,))
            head_axes = set(spec_axis_names(k_spec or PartitionSpec()))
            if head_axes != qkv_axes:
                findings.append(Finding(
                    "APX702", path, 1,
                    f"entry '{entry.name}': KV-cache head axes "
                    f"{sorted(head_axes)} != qkv output-dim axes "
                    f"{sorted(qkv_axes)} — decode would gather heads "
                    f"a rank's qkv shard never produced"))
    return findings

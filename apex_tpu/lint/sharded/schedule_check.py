"""APX704 — per-rank schedule + collective volume of rule-staged steps.

The sharded tier's last line of defense reuses the two interpreters the
earlier tiers already trust:

- the APX511 per-rank schedule simulator
  (:mod:`apex_tpu.lint.traced.schedule`) walks the rule-generated
  ``shard_map`` body once per rank of the staged mesh — dp-axis psums
  and tp-axis reduce-scatters must agree rank-pairwise, or the table
  generated a program that deadlocks a real slice. Those findings are
  re-issued under APX704 (the defect is in the *generated* program, so
  suppression and CI gating stay per-tier);
- the APX6xx collective-volume interpreter
  (:mod:`apex_tpu.lint.traced.cost`) prices the staged program's
  communication, which must equal the ``budgets.json`` record named by
  ``budget_name`` byte-for-byte — a rule-table change that moves
  collective volume is reviewable only through a budgets.json diff.
"""

from typing import Any, List, Optional

from apex_tpu.lint import Finding


def check(closed, path: str, entry,
          manifest: Optional[dict] = None) -> List[Finding]:
    from apex_tpu.lint.traced import cost, schedule

    findings: List[Finding] = []
    for f in schedule.check(closed, path, entry.name):
        findings.append(Finding(
            "APX704", f.path, f.line,
            f"rule-generated schedule: {f.message}"))

    if entry.budget_name is None:
        return findings
    try:
        report = cost.compute(closed, path, entry.name)
    except Exception as exc:  # noqa: BLE001 - surfaced as a finding
        findings.append(Finding(
            "APX100", path, 1,
            f"sharded entry '{entry.name}' collective pricing failed: "
            f"{type(exc).__name__}: {exc}"))
        return findings
    row = _budget_row(manifest, entry.budget_name)
    if row is None:
        findings.append(Finding(
            "APX704", path, 1,
            f"entry '{entry.name}': no budgets.json record "
            f"'{entry.budget_name}' to gate its collective volume — "
            f"seed it with `python -m apex_tpu.lint --write-budgets`"))
    elif report.collective_bytes != row.get("collective_bytes"):
        findings.append(Finding(
            "APX704", path, 1,
            f"entry '{entry.name}': staged collective volume "
            f"{report.collective_bytes} B != budgets.json record "
            f"{row.get('collective_bytes')} B for "
            f"'{entry.budget_name}' — the rule table changed the "
            f"communication schedule; regenerate budgets.json if "
            f"intentional"))
    return findings


def _budget_row(manifest: Optional[dict], name: str) -> Optional[Any]:
    if not isinstance(manifest, dict):
        return None
    row = manifest.get("entries", {}).get(name)
    return row if isinstance(row, dict) else None

"""Sharding-tier entry registry and driver (APX701-704).

A :class:`ShardedEntry` names one partition-rule table plus everything
the repo derives from it: the abstract trees it must cover (params,
optimizer families, the serving KV cache), the hand-maintained
reference spec trees it must reproduce, and — for train-step entries —
a builder staging the rule-derived ``shard_map`` program whose
``in_names`` and per-rank collective schedule are verified against the
table. The table is data; these entries are what make a wrong table a
lint finding instead of a silent mis-sharding on a pod slice.

Check dispatch per entry:

- ``rules`` + ``trees``            -> APX701 (coverage / spec sanity /
  dead rules, :mod:`rules_check`)
- ``optimizer_families`` /
  ``reference_specs`` / ``kv_*``   -> APX702 (cross-tree consistency)
- ``build``                        -> APX703 (in_names vs table,
  replicated-matmul floor, :mod:`propagation`) and APX704 (per-rank
  schedule + collective volume vs budgets.json,
  :mod:`schedule_check`)

The driver mirrors the trace tier's contract: entries trace under
``jax.make_jaxpr`` only (abstract, CPU-safe), the global parallel state
is snapshotted/restored around each entry, and an entry that fails to
evaluate is an APX100 finding, never a silent skip.
"""

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.traced.registry import (
    _mesh,
    _module_path,
    _restore_parallel_state,
    _sds,
    _snapshot_parallel_state,
    ensure_cpu_devices,
    zero_dp2xtp2_parts,
    zero_parts,
)

_REPLICATION_FLOOR = 1 << 20


@dataclass
class ShardedEntry:
    name: str
    module: str  # dotted module whose sharding contract this verifies
    rules: Callable[[], tuple]
    # name -> abstract tree (ShapeDtypeStructs); every rule must match
    # at least one leaf across the union of these trees
    trees: Optional[Callable[[], Dict[str, Any]]] = None
    # name -> hand-maintained spec tree the derived specs must equal
    reference_specs: Optional[Callable[[], Dict[str, Any]]] = None
    # optimizer-state families re-derived under a path prefix (APX702)
    optimizer_families: Tuple[str, ...] = ()
    # KV-cache consistency: tree name of the cache + regex of the
    # attention qkv kernel leaf whose output-dim axes the cache's head
    # axis must equal
    kv_cache_tree: Optional[str] = None
    qkv_kernel_re: str = r"qkv/kernel"
    # train-step staging: () -> (fn, args, in_specs)
    build: Optional[Callable[[], Tuple[Callable, tuple, Any]]] = None
    mesh: Optional[Callable[[], None]] = None
    min_devices: int = 1
    replication_floor: int = _REPLICATION_FLOOR
    budget_name: Optional[str] = None


def run_entries(entries: List[ShardedEntry], *,
                manifest: Any = "__load__") -> List[Finding]:
    """All sharding-tier findings; APX100 on any entry that fails to
    evaluate. ``manifest`` is the budgets.json dict (or the default
    sentinel to load the committed one) for APX704's volume gate."""
    ensure_cpu_devices()
    import jax

    from apex_tpu.lint.sharded import propagation, rules_check, schedule_check
    from apex_tpu.lint.traced import budgets

    if manifest == "__load__":
        manifest = budgets.load_manifest()

    findings: List[Finding] = []
    for e in entries:
        path = _module_path(e.module)
        try:
            findings.extend(rules_check.check(e, path))
        except Exception as exc:  # noqa: BLE001 - surfaced as a finding
            findings.append(Finding(
                "APX100", path, 1,
                f"sharded entry '{e.name}' rule checks failed to "
                f"evaluate: {type(exc).__name__}: {exc}"))
        if e.build is None:
            continue
        snap = _snapshot_parallel_state()
        try:
            try:
                have = jax.device_count()
                if have < e.min_devices:
                    raise RuntimeError(
                        f"needs {e.min_devices} devices, have {have} "
                        f"(backend initialized before ensure_cpu_devices)")
                if e.mesh is not None:
                    e.mesh()
                fn, args, in_specs = e.build()
                closed = jax.make_jaxpr(fn)(*args)
            finally:
                _restore_parallel_state(snap)
        except Exception as exc:  # noqa: BLE001 - surfaced as a finding
            findings.append(Finding(
                "APX100", path, 1,
                f"sharded entry '{e.name}' failed to trace: "
                f"{type(exc).__name__}: {exc}"))
            continue
        findings.extend(propagation.check(closed, in_specs, path, e))
        findings.extend(schedule_check.check(closed, path, e, manifest))
    return findings


# ---------------------------------------------------------------------------
# registered rule tables / sharded entrypoints
# ---------------------------------------------------------------------------

def _gpt_trees():
    import functools as ft

    import jax

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving.cache import init_cache, init_paged_cache

    cfg = gpt_tiny()
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(ft.partial(init_cache, cfg, 2, 32))
    # the paged layout keeps heads on axis 2 (same k/v rule) and adds
    # the replicated block tables — registering it keeps the
    # block_tables rule live for APX701 and its spec APX702-checked
    paged = jax.eval_shape(ft.partial(init_paged_cache, cfg, 2, 32, 6, 16))
    return {"params": params, "kv_cache": cache, "paged_kv_cache": paged}


def _gpt_reference():
    from apex_tpu.models.gpt import gpt_partition_specs, gpt_tiny
    from apex_tpu.partition import kv_cache_rules
    from apex_tpu.serving.cache import (
        cache_partition_specs, paged_cache_partition_specs,
    )

    return {"params": gpt_partition_specs(gpt_tiny()),
            "kv_cache": cache_partition_specs(kv_cache_rules()),
            "paged_kv_cache": paged_cache_partition_specs(kv_cache_rules())}


def _gpt_quant_trees():
    """The weight-only int8 tree (same kernel paths, sibling fp32
    scales) + the int8 page pool with its per-page-per-head scales —
    registering both keeps every gpt_quant_rules scale rule live for
    APX701 and the derived specs APX702-checked. gpt_tiny() default
    (learned positions) so the position-embedding rule stays live."""
    import functools as ft

    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.quant.params import quantize_params
    from apex_tpu.serving.cache import init_paged_cache

    cfg = gpt_tiny()
    params = quantize_params(jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0)))
    paged = jax.eval_shape(ft.partial(
        init_paged_cache, cfg, 2, 32, 6, 16, jnp.int8))
    return {"params": params, "paged_kv_cache": paged}


def _gpt_quant_reference():
    from apex_tpu.models.gpt import gpt_tiny
    from apex_tpu.partition import kv_cache_quant_rules
    from apex_tpu.quant.params import quant_partition_specs
    from apex_tpu.serving.cache import paged_cache_partition_specs

    return {"params": quant_partition_specs(gpt_tiny()),
            "paged_kv_cache": paged_cache_partition_specs(
                kv_cache_quant_rules(), quantized=True)}


def _draft_trees():
    """The speculative drafter's trees: a RoPE-only param tree (no
    position leaf) and the DENSE lockstep cache (engine max_len 32 plus
    DraftModel's catch-up chunk of 5) — exactly what draft_gpt_rules
    must cover with no dead rows."""
    import functools as ft

    import jax

    from apex_tpu.models.gpt import draft_gpt_tiny, init_gpt
    from apex_tpu.serving.cache import init_cache

    cfg = draft_gpt_tiny()
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(ft.partial(init_cache, cfg, 2, 37))
    return {"params": params, "kv_cache": cache}


def _draft_reference():
    from apex_tpu.models.gpt import draft_gpt_tiny, gpt_partition_specs
    from apex_tpu.partition import kv_cache_rules
    from apex_tpu.serving.cache import cache_partition_specs

    return {"params": gpt_partition_specs(draft_gpt_tiny()),
            "kv_cache": cache_partition_specs(kv_cache_rules())}


def _bert_trees():
    import jax

    from apex_tpu.models.bert import bert_tiny, init_bert

    params = jax.eval_shape(
        lambda k: init_bert(k, bert_tiny()), jax.random.PRNGKey(0))
    return {"params": params}


def _bert_reference():
    import jax

    from apex_tpu.models.bert import (
        bert_partition_specs, bert_tiny, init_bert,
    )

    params = jax.eval_shape(
        lambda k: init_bert(k, bert_tiny()), jax.random.PRNGKey(0))
    return {"params": bert_partition_specs(params)}


def repo_entries() -> List[ShardedEntry]:
    from apex_tpu.partition import (
        bert_rules, draft_gpt_rules, gpt_quant_rules, gpt_rules,
    )

    return [
        ShardedEntry(
            "gpt_tiny_rules", "apex_tpu.partition.tables",
            rules=gpt_rules, trees=_gpt_trees,
            reference_specs=_gpt_reference,
            optimizer_families=("m", "v", "master"),
            kv_cache_tree="kv_cache",
            qkv_kernel_re=r"layers/qkv/kernel"),
        # quantized tier: no optimizer families (int8 trees are
        # inference-only); the kv consistency check re-runs against the
        # int8 pool so its head axis stays pinned to the qkv tp axis
        ShardedEntry(
            "gpt_tiny_quant_rules", "apex_tpu.partition.tables",
            rules=gpt_quant_rules, trees=_gpt_quant_trees,
            reference_specs=_gpt_quant_reference,
            kv_cache_tree="paged_kv_cache",
            qkv_kernel_re=r"layers/qkv/kernel"),
        # the speculative drafter: same mesh and layout as the target
        # minus the rows its trees can never match (position table,
        # block tables); no optimizer families (inference-only). The kv
        # consistency check pins the lockstep cache's head axis to the
        # draft qkv column shard — the invariant that lets the drafter
        # run TP on the target's mesh without a resharding hop.
        ShardedEntry(
            "gpt_draft_rules", "apex_tpu.partition.tables",
            rules=draft_gpt_rules, trees=_draft_trees,
            reference_specs=_draft_reference,
            kv_cache_tree="kv_cache",
            qkv_kernel_re=r"layers/qkv/kernel"),
        ShardedEntry(
            "bert_tiny_rules", "apex_tpu.partition.tables",
            rules=bert_rules, trees=_bert_trees,
            reference_specs=_bert_reference,
            optimizer_families=("m", "v", "master")),
        # trace-staged: same builder as the gpt_tiny_dp2xtp2_zero
        # TraceEntry, so APX703/704 see exactly the program the APX5xx
        # and APX6xx tiers gate
        ShardedEntry(
            "gpt_tiny_dp2xtp2_zero",
            "apex_tpu.contrib.optimizers.distributed_fused_adam",
            rules=gpt_rules,
            build=zero_dp2xtp2_parts,
            mesh=_mesh(tp=2, n_devices=4), min_devices=4,
            budget_name="gpt_tiny_dp2xtp2_zero"),
        # the ROADMAP item-5 headline shape: the same rule-derived
        # builder at dp4 x tp2 on the full 8-device world, so APX703/704
        # verify in_names and the per-rank schedule at the shape the
        # training headline will actually run (the APX9xx scaling tier
        # additionally sweeps the whole grid)
        ShardedEntry(
            "gpt_tiny_dp4xtp2_zero",
            "apex_tpu.contrib.optimizers.distributed_fused_adam",
            rules=gpt_rules,
            build=lambda: zero_parts(dp=4, tp=2),
            mesh=_mesh(tp=2, n_devices=8), min_devices=8,
            budget_name="gpt_tiny_dp4xtp2_zero"),
    ]


def check_repo() -> List[Finding]:
    return run_entries(repo_entries())


__all__ = ["ShardedEntry", "repo_entries", "run_entries", "check_repo",
           "_sds"]

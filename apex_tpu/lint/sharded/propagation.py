"""APX703 — rule-derived specs must survive into the staged program.

A rule table can be internally consistent (APX701/702 clean) and still
never reach the compiler: a train step whose ``shard_map`` was wired
with stale hand-written ``in_specs`` shards nothing the table says it
should. This check stages the entry's builder under its mesh with
``jax.make_jaxpr`` (abstract — no compile, no devices touched beyond
the CPU world) and verifies, per flattened operand, that the traced
``shard_map`` equation's ``in_names`` equal the dim->axes mapping of
the expected ``PartitionSpec`` the builder derived from the table.

It also walks the shard_map body for the classic silent failure GSPMD
makes easy: an operand that arrives FULLY REPLICATED (empty
``in_names``), is at least ``replication_floor`` bytes, and flows into
a ``dot_general`` — i.e. a weight matrix every rank stores and
multiplies whole. Taint propagates only through layout-preserving ops
(convert/transpose/reshape/...) and inlined calls, so the finding
names an actual matmul operand, not everything downstream of it.
"""

from typing import Any, List

from jax.sharding import PartitionSpec

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl

# ops a replicated operand passes through without changing what it is
_TAINT_THROUGH = {
    "convert_element_type", "transpose", "reshape", "squeeze",
    "broadcast_in_dim", "copy", "stop_gradient", "expand_dims",
}


def spec_to_names(spec: PartitionSpec) -> dict:
    """``shard_map``'s ``in_names`` encoding of one spec:
    ``{dim: (axis, ...)}`` with replicated dims absent."""
    names = {}
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names[dim] = tuple(entry) if isinstance(entry, tuple) else (entry,)
    return names


def _flat_expected(in_specs: Any) -> List[PartitionSpec]:
    import jax

    return jax.tree_util.tree_leaves(
        in_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))


def _replicated_dot_operands(body, seeds) -> List[tuple]:
    """(label, nbytes) per tainted var consumed by a dot_general,
    recursing through inlined calls."""
    hits: List[tuple] = []
    jaxpr = jl.open_jaxpr(body)
    tainted = dict(seeds)  # var -> (label, nbytes)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            for v in eqn.invars:
                if not jl.is_literal(v) and v in tainted:
                    hits.append(tainted[v])
            continue
        if name in _TAINT_THROUGH and eqn.invars and not jl.is_literal(
                eqn.invars[0]) and eqn.invars[0] in tainted:
            tainted[eqn.outvars[0]] = tainted[eqn.invars[0]]
            continue
        for _, sub in jl.sub_jaxprs(eqn):
            sj = jl.open_jaxpr(sub)
            if len(sj.invars) != len(eqn.invars):
                continue
            sub_seeds = {sv: tainted[v]
                         for sv, v in zip(sj.invars, eqn.invars)
                         if not jl.is_literal(v) and v in tainted}
            if sub_seeds:
                hits.extend(_replicated_dot_operands(sub, sub_seeds))
    return hits


def check(closed, in_specs: Any, path: str, entry) -> List[Finding]:
    findings: List[Finding] = []
    expected = [spec_to_names(s) for s in _flat_expected(in_specs)]
    matched = False
    for eqn in jl.all_eqns(closed, into_pallas=False):
        if eqn.primitive.name != "shard_map":
            continue
        actual = eqn.params.get("in_names")
        if actual is None or len(actual) != len(expected):
            continue  # an inner shard_map with a different signature
        matched = True
        for i, (want, got) in enumerate(zip(expected, actual)):
            if dict(got) != want:
                aval = eqn.invars[i].aval
                findings.append(Finding(
                    "APX703", path, 1,
                    f"entry '{entry.name}': shard_map operand {i} "
                    f"(shape {tuple(getattr(aval, 'shape', ()))}) "
                    f"traced with in_names {dict(got)} but the rule "
                    f"table derives {want} — the staged program does "
                    f"not shard what the table says"))

        body = eqn.params["jaxpr"]
        bj = jl.open_jaxpr(body)
        floor = entry.replication_floor
        seeds = {}
        for i, (names, bv) in enumerate(zip(eqn.params["in_names"],
                                            bj.invars)):
            if dict(names):
                continue
            nbytes = jl.aval_bytes(bv.aval)
            if nbytes >= floor:
                shape = tuple(getattr(bv.aval, "shape", ()))
                seeds[bv] = (f"operand {i} (shape {shape})", nbytes)
        for label, nbytes in _replicated_dot_operands(body, seeds):
            findings.append(Finding(
                "APX703", path, 1,
                f"entry '{entry.name}': {label}, {nbytes} bytes, "
                f"enters the shard_map body fully replicated and is "
                f"consumed by a dot_general — every rank stores and "
                f"multiplies the whole matrix (silent replication "
                f"above the {floor}-byte floor)"))
    if not matched:
        findings.append(Finding(
            "APX703", path, 1,
            f"entry '{entry.name}': no shard_map equation with "
            f"{len(expected)} operands found in the staged program — "
            f"the rule-derived in_specs were never applied"))
    return findings

"""apxlint sharding tier (APX701-704) — ``--sharding``.

Static verification of the partition-rule engine
(:mod:`apex_tpu.partition`): rule-table coverage and spec sanity
(APX701), cross-tree per-tensor-family consistency — optimizer
moments, master weights, serving KV cache, hand-maintained references
(APX702), rule-derived ``shard_map`` in_specs surviving into the
staged dp x tp train step with no silently-replicated matmul operands
(APX703), and per-rank schedule agreement plus budgets.json-gated
collective volume for the generated bodies (APX704).
"""

from apex_tpu.lint.sharded.registry import (
    ShardedEntry,
    check_repo,
    repo_entries,
    run_entries,
)

__all__ = ["ShardedEntry", "check_repo", "repo_entries", "run_entries"]

"""APX805 — RNG key discipline on the tick path.

Sampling randomness in the serving engine must be a pure function of
``(request seed, position counter)`` — that is what makes a committed
stream replayable across restarts, failovers, and replica migrations:
the decode slot that picks up a preempted stream re-derives the exact
key the original slot would have used. The repo's idiom is

    key = jax.random.fold_in(jax.random.PRNGKey(req.seed), step)

and batched variants that ``jnp.stack`` per-slot keys. Two statically
detectable ways to break it:

**Raw PRNGKey on the tick path.** A ``PRNGKey(...)`` whose result is
consumed directly (not folded, not an element of a batched key stack)
gives every step of the stream the SAME key — identical draws at
every position, and no counter to re-derive after a migration. A
``PRNGKey`` call is fine when (a) some enclosing call in the same
expression is ``fold_in`` (it is the seed root of a fold chain), or
(b) it is an element of a list/tuple/comprehension that feeds a
``stack`` / ``concatenate`` / ``array`` / ``asarray`` call (the
batched-slot idiom — the fold already happened upstream or the slot
is inert/padding).

**Key reuse.** A local name bound to a ``fold_in`` / ``PRNGKey``
result and then passed as an argument to two or more distinct calls:
the second consumer sees correlated randomness. Deriving is not
consuming — passing the key to ``fold_in`` / ``split`` again is how
chains are built and does not count as a use.

``split`` is also flagged on the tick path when it is clearly
``jax.random.split`` (attribute chain mentioning ``random``, or a
name imported from ``jax.random``): split trees make the key at a
position depend on how many OTHER streams were scheduled that tick,
which is exactly the cross-request coupling fold_in chains avoid.
(``s.split(",")`` on strings has no ``random`` in its chain and is
never flagged.)
"""

import ast
from typing import Dict, List, Optional, Set

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import attr_chain, call_name
from apex_tpu.lint.determinism.reach import reachable_functions

_STACKERS = {"stack", "concatenate", "array", "asarray"}


def _parents(fn: ast.FunctionDef) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _random_split_names(tree: ast.Module) -> Set[str]:
    """Local names that are ``jax.random.split`` via from-import."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("random"):
            for a in node.names:
                if a.name == "split":
                    out.add(a.asname or "split")
    return out


def _key_ok(call: ast.Call, parents: Dict[int, ast.AST]) -> bool:
    """Is this PRNGKey(...) call blessed — under a fold_in, or an
    element of a batched key stack?"""
    node: ast.AST = call
    while id(node) in parents:
        parent = parents[id(node)]
        if isinstance(parent, ast.Call):
            pn = call_name(parent)
            if pn == "fold_in":
                return True
            if pn in _STACKERS:
                return True
        if isinstance(parent, (ast.stmt,)) and not isinstance(
                parent, ast.Expr):
            # climbed out of the expression without meeting a blesser
            # — except keep climbing through simple value statements
            # so `key = fold_in(PRNGKey(s), 0)` (Assign) still works:
            # the Call check above already fired before we got here.
            return False
        node = parent
    return False


def check_files(strees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []
    reach: Dict[str, List[ast.FunctionDef]] = {}
    for path, fn in reachable_functions(strees):
        reach.setdefault(path, []).append(fn)

    for path in sorted(reach):
        split_imports = _random_split_names(strees[path])
        for fn in reach[path]:
            parents = _parents(fn)
            # name -> line where bound to a key-producing call
            key_names: Dict[str, int] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call) and call_name(
                        node.value) in ("fold_in", "PRNGKey"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            key_names[t.id] = node.lineno

            uses: Dict[str, List[int]] = {}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn == "PRNGKey" and not _key_ok(node, parents):
                    findings.append(Finding(
                        "APX805", path, node.lineno,
                        f"raw PRNGKey on the tick path in '{fn.name}' "
                        "— a fixed key repeats the same draw at every "
                        "position; derive per-step keys as "
                        "fold_in(PRNGKey(request seed), counter)"))
                elif cn == "split":
                    chain = attr_chain(node.func)
                    is_random = (chain is not None and "random" in
                                 chain[:-1]) or (
                        isinstance(node.func, ast.Name)
                        and node.func.id in split_imports)
                    if is_random:
                        findings.append(Finding(
                            "APX805", path, node.lineno,
                            f"jax.random.split in '{fn.name}' on the "
                            "tick path — split trees couple a "
                            "stream's key to what else was scheduled "
                            "that tick; use fold_in(seed, counter) "
                            "chains"))
                # key reuse: a bound key passed as an argument to
                # distinct consumer calls (fold_in/split derive, they
                # don't consume)
                if cn in ("fold_in", "split"):
                    continue
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in key_names:
                        uses.setdefault(arg.id, []).append(node.lineno)
            for name, lines in sorted(uses.items()):
                if len(lines) > 1:
                    findings.append(Finding(
                        "APX805", path, lines[1],
                        f"key '{name}' (bound at line "
                        f"{key_names[name]}) consumed by "
                        f"{len(lines)} calls in '{fn.name}' — reusing "
                        "a key correlates draws; fold_in a fresh "
                        "counter per consumer"))
    return findings

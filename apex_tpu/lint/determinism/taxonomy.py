"""APX803 — error-taxonomy closure on the tick path.

The serving stack's failure handling is typed end-to-end: the
scheduler's degrade ladders (quarantine → retry → requeue → finish),
the router's failover picks, and the chaos tests' assertions all
dispatch on ``ServingError`` subclasses from ``serving.health``. An
untyped ``raise RuntimeError(...)`` on the tick path silently falls
through every one of those ladders — the stream dies wholesale
instead of degrading, and no chaos leg ever exercises the path
because nothing catches it to assert on. Two directions:

**Raise closure.** Every ``raise Cls(...)`` in a tick-reachable
function must name either a taxonomy class (a ClassDef in the serving
scope whose base chain reaches ``ServingError``, or ``InjectedFault``
— the fault hook's own typed carrier), a name imported from a serving
``health`` / ``faults`` module, or an allowlisted constructor-time
guard (``ValueError`` / ``TypeError`` / ``NotImplementedError`` /
``StopIteration`` — argument validation that fires on the caller's
stack before any stream state exists). Re-raises (``raise`` /
``raise err`` / ``raise self``) are flow, not new error types, and
never flag.

**Test coverage.** Every taxonomy class must appear by name in at
least one file under ``tests/`` — an error class no test references
is a degrade path that has never executed, which in this codebase
means its determinism contract is unverified. Checked only when the
serving scope declares the taxonomy (a ``health.py`` with
``ServingError``); fixture mini-repos without one skip it.
"""

import ast
import re
from typing import Dict, List, Optional, Set

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import call_name
from apex_tpu.lint.determinism import repofiles
from apex_tpu.lint.determinism.reach import reachable_functions, serving_dir

#: Builtin exceptions a tick-reachable function may raise directly:
#: constructor/argument-time guards that fire before any stream state
#: exists. Everything else on the tick path must be typed.
RAISE_ALLOWLIST = frozenset({
    "ValueError", "TypeError", "NotImplementedError", "StopIteration",
})


def _taxonomy_classes(trees: Dict[str, ast.Module]
                      ) -> Dict[str, "ast.ClassDef"]:
    """name -> ClassDef for every class in the scope whose base chain
    reaches ServingError (plus InjectedFault, the injector's typed
    carrier)."""
    defs: Dict[str, ast.ClassDef] = {}
    bases: Dict[str, Set[str]] = {}
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                defs[node.name] = node
                bases[node.name] = {
                    b.id for b in node.bases if isinstance(b, ast.Name)
                } | {b.attr for b in node.bases
                     if isinstance(b, ast.Attribute)}

    out: Dict[str, ast.ClassDef] = {}
    for name in defs:
        seen: Set[str] = set()
        frontier = [name]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if cur in ("ServingError", "InjectedFault"):
                out[name] = defs[name]
                break
            frontier.extend(bases.get(cur, ()))
    return out


def _serving_imports(trees: Dict[str, ast.Module]) -> Set[str]:
    """Names imported from a serving health/faults module anywhere in
    the scope — typed by construction even if the defining module is
    outside the linted file set."""
    out: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                tail = node.module.rsplit(".", 1)[-1]
                if tail in ("health", "faults", "serving"):
                    out.update(a.asname or a.name for a in node.names)
    return out


def check_files(strees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []

    scopes: Dict[str, Dict[str, ast.Module]] = {}
    for path, tree in strees.items():
        scopes.setdefault(serving_dir(path), {})[path] = tree

    for scope in sorted(scopes):
        trees = scopes[scope]
        taxonomy = _taxonomy_classes(trees)
        typed = set(taxonomy) | {"ServingError", "InjectedFault"} \
            | _serving_imports(trees) | RAISE_ALLOWLIST

        # -- raise closure over tick-reachable functions --------------
        for path, fn in reachable_functions(trees):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                if not isinstance(node.exc, ast.Call):
                    continue  # `raise err` / `raise self`: a re-raise
                name = call_name(node.exc)
                if name is None or name in typed:
                    continue
                findings.append(Finding(
                    "APX803", path, node.lineno,
                    f"'{fn.name}' raises untyped {name} on the tick "
                    "path — degrade ladders dispatch on ServingError "
                    "subclasses; raise a taxonomy class (or move "
                    "pure argument validation off the tick path)"))

        # -- taxonomy test coverage -----------------------------------
        declares = any(
            isinstance(n, ast.ClassDef) and n.name == "ServingError"
            for t in trees.values() for n in ast.walk(t))
        if not declares:
            continue
        texts = repofiles.test_texts(repofiles.repo_root(scope))
        if texts is None:
            findings.append(Finding(
                "APX803", sorted(trees)[0], 1,
                "serving scope declares an error taxonomy but the "
                "tree has no tests/ directory — every taxonomy class "
                "needs at least one test reference"))
            continue
        blob = "\n".join(texts.values())
        for name in sorted(taxonomy):
            if re.search(rf"\b{re.escape(name)}\b", blob):
                continue
            node = taxonomy[name]
            cpath = next(p for p, t in trees.items()
                         if node in ast.walk(t))
            findings.append(Finding(
                "APX803", cpath, node.lineno,
                f"taxonomy class {name} appears in no test under "
                "tests/ — its degrade path has never executed, so "
                "its determinism contract is unverified"))
    return findings

"""Repo-layout discovery shared by the cross-artifact determinism
checks (APX802 fault contracts, APX803 taxonomy test coverage).

Those checks compare the serving scope against artifacts OUTSIDE the
linted file set — the chaos tests under ``tests/`` and the CI chaos
matrix in ``.github/workflows/ci.yml``. The repo root is derived from
the serving directory itself (``<root>/apex_tpu/serving`` → two
levels up), which makes the same code work on the real repo, on the
fixture mini-repos (``<fixture>/apex_tpu/serving``), and on the
seeded-bug scratch copies the meta-tests build under a tmpdir.
"""

import os
from typing import Dict, Optional

_TEXT_CACHE: Dict[str, Dict[str, str]] = {}


def repo_root(serving_path: str) -> str:
    """``<root>/apex_tpu/serving`` (or any ``<root>/<pkg>/serving``)
    → ``<root>``. A bare ``serving/`` dir resolves to its parent."""
    parent = os.path.dirname(serving_path)
    return os.path.dirname(parent) if parent else os.curdir


def test_texts(root: str) -> Optional[Dict[str, str]]:
    """path -> source text for every ``.py`` under ``<root>/tests``;
    None when the tree has no tests directory at all (the caller
    decides whether that is itself a finding)."""
    key = os.path.abspath(root)
    if key in _TEXT_CACHE:
        return _TEXT_CACHE[key] or None
    tests = os.path.join(root, "tests")
    if not os.path.isdir(tests):
        _TEXT_CACHE[key] = {}
        return None
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(tests):
        dirnames.sort()
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path, encoding="utf-8") as fh:
                    out[path] = fh.read()
            except OSError:
                continue
    _TEXT_CACHE[key] = out
    return out


def ci_text(root: str) -> Optional[str]:
    """The CI workflow text, or None when the tree has none."""
    path = os.path.join(root, ".github", "workflows", "ci.yml")
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None

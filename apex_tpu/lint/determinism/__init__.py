"""apxlint determinism tier (APX8xx) — static race/nondeterminism
detection and fault-contract coverage for the serving stack.

The serving contract since PR 5 is that committed streams are
bit-identical to golden through every scheduling, speculation,
handoff, failover, and fault path — but it is enforced only
dynamically, by chaos tests that can miss a nondeterminism source
until a seed happens to hit it. This tier verifies the contract's
statically checkable preconditions the way APX511/704 verify
collective schedules: an AST pass over every file in a ``serving``
directory, scoped to functions reachable from the tick/admission
roots (:mod:`.reach`). Five codes:

- **APX801** (:mod:`.ordering`) — nondeterministic ordering on the
  tick path: set iteration flowing into scheduling/requeue/commit
  order, sets rendered into error text, unseeded stdlib RNG,
  ``hash()``/``id()`` ordering keys, wall-clock reads outside the
  Tracer's allowlisted wall-stamp sites.
- **APX802** (:mod:`.contracts`) — every ``faults.SITES`` entry
  carries its full five-artifact contract (consultation site, typed
  degrade error, chaos-test reference, CI sweep env) via the
  ``SITE_CONTRACTS`` table, with stale names flagged in both
  directions.
- **APX803** (:mod:`.taxonomy`) — tick-path raises are ServingError
  taxonomy classes (or allowlisted constructor guards), and every
  taxonomy class is referenced by at least one test.
- **APX804** (:mod:`.coherence`) — tracer span/instant names resolve
  against ``observe.PHASES``/``LIFECYCLE``, metric read-backs resolve
  against creation sites, no drifting dynamic names.
- **APX805** (:mod:`.rng`) — sampling keys derive via
  ``fold_in(seed, counter)`` chains: no raw ``PRNGKey`` consumption,
  no ``jax.random.split`` trees, no key reuse on the tick path.

Run with ``python -m apex_tpu.lint --determinism`` (or any
``--codes 'APX8*'`` selection, which enables the tier implicitly).
Pure-AST: no jax import, no execution of the linted code.
"""

import ast
from typing import Dict, List

from apex_tpu.lint import Finding
from apex_tpu.lint.determinism import (contracts, coherence, ordering,
                                       rng, taxonomy)
from apex_tpu.lint.determinism.reach import serving_trees


def check_files(trees: Dict[str, ast.Module]) -> List[Finding]:
    """All APX8xx findings over the serving-scope subset of ``trees``."""
    strees = serving_trees(trees)
    if not strees:
        return []
    findings: List[Finding] = []
    findings.extend(ordering.check_files(strees))
    findings.extend(contracts.check_files(strees))
    findings.extend(taxonomy.check_files(strees))
    findings.extend(coherence.check_files(strees))
    findings.extend(rng.check_files(strees))
    return findings


__all__ = ["check_files", "serving_trees"]

"""APX801 — nondeterministic ordering on the tick path.

The serving contract is that committed streams are bit-identical to
golden through every scheduling, speculation, handoff, failover, and
fault path. Every dynamic test of that contract assumes the host-side
scheduler makes the SAME decisions in the SAME order on every replay —
which a single ``for x in some_set:`` can silently break: CPython set
iteration order depends on insertion history and element hashes, and
str hashes are salted per process (PYTHONHASHSEED), so an order that
happens to be stable today ships a replay divergence the first time a
key type changes. This is exactly the bug class of the PR-8 unsorted
preemption requeue. The check is a small taint walk:

**Set-order taint.** An expression is set-typed when it is a ``set()``
/ ``frozenset()`` call, a set literal/comprehension, set algebra over a
set-typed operand (``| & - ^``, ``.union`` and friends), a local name
assigned one of those, or an attribute the module assigns one to
(``self._parked = set()``). Flagged consumers — the points where the
arbitrary order MATERIALIZES into scheduling, requeue, routing, or
commit order — inside tick-reachable functions
(:mod:`~apex_tpu.lint.determinism.reach`):

- ``for x in S:`` and comprehension sources (list/dict/generator —
  a SET comprehension over a set stays unordered and is fine);
- order-materializing calls: ``list(S)``, ``tuple(S)``,
  ``enumerate(S)``, ``iter(S)``, ``map(f, S)``, ``zip(.., S, ..)``,
  ``S.pop()``, ``sep.join(S)``;
- unpacking ``a, b = S``.

``sorted(S)`` / ``min`` / ``max`` / ``len`` / ``sum`` / ``any`` /
``all`` / membership consume a set without consuming its *order* and
never flag.

**Nondeterministic text.** A set interpolated into a string (f-string,
``str(S)``, ``format(S)``, ``repr(S)``, ``"%s" % S``) prints in
arbitrary order — an error message that names the same defect two
different ways on two runs breaks log diffing and golden-text tests.
Error text is usually raised OFF the tick path (constructor
validation), so this sub-check runs over every function in the serving
scope, reachable or not.

**Nondeterministic primitives**, tick-reachable functions only:
``hash(x)`` / ``id(x)`` (process-dependent values used as ordering or
routing keys — also flagged anywhere as a ``key=`` of
``sorted``/``min``/``max``), unseeded stdlib ``random.*`` and
``np.random.*`` calls, and wall-clock reads (``time.*``,
``perf_counter``) — the tick clock is the only clock scheduling may
consult. The one legitimate wall-clock surface is the Tracer's
dual-stamp sites in ``observe.py`` (``instant``/``begin``/``end``
stamp wall time for Perfetto, excluded from the replay contract by
``TraceEvent.tick_key``); those three methods are the explicit
allowlist (:data:`WALL_CLOCK_ALLOWLIST`).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import attr_chain, call_name
from apex_tpu.lint.determinism.reach import reachable_functions

#: (file basename, function name) pairs allowed to read the wall
#: clock: the Tracer's event-stamp sites, whose wall fields are
#: excluded from the deterministic tick stream by design.
WALL_CLOCK_ALLOWLIST = frozenset({
    ("observe.py", "instant"),
    ("observe.py", "begin"),
    ("observe.py", "end"),
})

_SET_METHODS = {"union", "difference", "intersection",
                "symmetric_difference", "copy"}
_ORDER_SINKS = {"list", "tuple", "enumerate", "iter", "map", "zip"}
_TEXT_SINKS = {"str", "format", "repr"}


def _attr_set_names(tree: ast.Module) -> Set[str]:
    """Attribute tails the module binds to a set anywhere
    (``self._parked = set()`` / ``x.pending: set = ...``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value, ann = node.targets, node.value, None
        elif isinstance(node, ast.AnnAssign):
            targets, value, ann = [node.target], node.value, node.annotation
        else:
            continue
        is_set = (value is not None and _is_set_expr(value, set(), set())) \
            or (ann is not None and isinstance(ann, ast.Name)
                and ann.id in ("set", "frozenset")) \
            or (ann is not None and isinstance(ann, ast.Subscript)
                and isinstance(ann.value, ast.Name)
                and ann.value.id in ("Set", "FrozenSet"))
        if not is_set:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute):
                out.add(t.attr)
    return out


def _is_set_expr(node: ast.AST, names: Set[str],
                 attrs: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in ("set", "frozenset"):
            return True
        if cn in _SET_METHODS and isinstance(node.func, ast.Attribute):
            return _is_set_expr(node.func.value, names, attrs)
        return False
    if isinstance(node, ast.Name):
        return node.id in names
    if isinstance(node, ast.Attribute):
        return node.attr in attrs
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return (_is_set_expr(node.left, names, attrs)
                or _is_set_expr(node.right, names, attrs))
    if isinstance(node, ast.IfExp):
        return (_is_set_expr(node.body, names, attrs)
                and _is_set_expr(node.orelse, names, attrs))
    return False


def _local_set_names(fn: ast.FunctionDef, attrs: Set[str]) -> Set[str]:
    """Fixpoint over the function's assignments: local names that hold
    a set at some point. One name, one taint — a name rebound to a
    list later stays tainted (conservative, but a finding there still
    reads correctly: don't reuse the name)."""
    names: Set[str] = set()
    for _ in range(4):
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
                targets, value = [node.target], node.value
            else:
                continue
            if not _is_set_expr(value, names, attrs) and not (
                    isinstance(node, ast.AugAssign)
                    and isinstance(node.target, ast.Name)
                    and node.target.id in names):
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in names:
                    names.add(t.id)
                    grew = True
        if not grew:
            break
    return names


def _host_modules(tree: ast.Module) -> Dict[str, str]:
    """Local alias -> module for time / random / numpy imports, plus
    names imported from ``time`` directly (``perf_counter``)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root in ("time", "random", "numpy"):
                    out[a.asname or root] = root
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root == "time":
                for a in node.names:
                    out[a.asname or a.name] = "time"
            elif root == "numpy" and any(a.name == "random"
                                         for a in node.names):
                for a in node.names:
                    if a.name == "random":
                        out[a.asname or "random"] = "numpy.random"
    return out


def check_files(strees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []
    reach: Dict[str, List[ast.FunctionDef]] = {}
    for path, fn in reachable_functions(strees):
        reach.setdefault(path, []).append(fn)

    for path in sorted(strees):
        tree = strees[path]
        attrs = _attr_set_names(tree)
        host = _host_modules(tree)
        reachable = {id(fn) for fn in reach.get(path, ())}
        all_fns = [n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)]
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, tag: str, msg: str) -> None:
            if (line, tag) not in seen:
                seen.add((line, tag))
                findings.append(Finding("APX801", path, line, msg))

        for fn in all_fns:
            names = _local_set_names(fn, attrs)
            on_tick = id(fn) in reachable

            def set_typed(node: ast.AST) -> bool:
                return _is_set_expr(node, names, attrs)

            for node in ast.walk(fn):
                # --- text sinks: every function in serving scope ----
                if isinstance(node, ast.FormattedValue) \
                        and set_typed(node.value):
                    emit(node.value.lineno, "text",
                         f"set interpolated into a string in "
                         f"'{fn.name}' prints in arbitrary order — "
                         "wrap it in sorted() so the text is "
                         "deterministic")
                    continue
                if isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Mod) \
                        and isinstance(node.left, ast.Constant) \
                        and isinstance(node.left.value, str):
                    rhs = (node.right.elts
                           if isinstance(node.right, ast.Tuple)
                           else [node.right])
                    if any(set_typed(r) for r in rhs):
                        emit(node.lineno, "text",
                             f"set formatted into a string in "
                             f"'{fn.name}' prints in arbitrary order "
                             "— wrap it in sorted()")
                    continue
                if isinstance(node, ast.Call):
                    cn = call_name(node)
                    if cn in _TEXT_SINKS and node.args \
                            and set_typed(node.args[0]):
                        emit(node.lineno, "text",
                             f"{cn}() of a set in '{fn.name}' renders "
                             "in arbitrary order — sorted() first")
                        continue
                    if cn == "join" and isinstance(node.func,
                                                  ast.Attribute) \
                            and node.args and set_typed(node.args[0]):
                        emit(node.lineno, "order",
                             f"str.join over a set in '{fn.name}' "
                             "concatenates in arbitrary order — "
                             "sorted() first")
                        continue
                    # hash/id as an ordering key, anywhere
                    if cn in ("sorted", "min", "max"):
                        for kw in node.keywords:
                            if kw.arg == "key" and isinstance(
                                    kw.value, ast.Name) \
                                    and kw.value.id in ("hash", "id"):
                                emit(node.lineno, "hash",
                                     f"{kw.value.id}() as a {cn} key "
                                     f"in '{fn.name}' orders by a "
                                     "process-dependent value")

                # --- tick-path-only rules ---------------------------
                if not on_tick:
                    continue
                if isinstance(node, ast.For) and set_typed(node.iter):
                    emit(node.iter.lineno, "iter",
                         f"iteration over a set in '{fn.name}' on the "
                         "tick path — the visit order flows into "
                         "scheduling/requeue/commit order; iterate "
                         "sorted(...) instead")
                elif isinstance(node, (ast.ListComp, ast.DictComp,
                                       ast.GeneratorExp)):
                    for gen in node.generators:
                        if set_typed(gen.iter):
                            emit(gen.iter.lineno, "iter",
                                 f"comprehension over a set in "
                                 f"'{fn.name}' on the tick path "
                                 "materializes an arbitrary order — "
                                 "iterate sorted(...) instead")
                elif isinstance(node, ast.Assign) and len(
                        node.targets) == 1 and isinstance(
                        node.targets[0], ast.Tuple) \
                        and set_typed(node.value):
                    emit(node.lineno, "iter",
                         f"unpacking a set in '{fn.name}' on the tick "
                         "path binds in arbitrary order")
                elif isinstance(node, ast.Call):
                    cn = call_name(node)
                    if cn in _ORDER_SINKS and node.args and any(
                            set_typed(a) for a in node.args):
                        emit(node.lineno, "order",
                             f"{cn}() over a set in '{fn.name}' on "
                             "the tick path materializes an "
                             "arbitrary order — sorted() instead")
                    elif cn == "pop" and isinstance(node.func,
                                                    ast.Attribute) \
                            and not node.args \
                            and set_typed(node.func.value):
                        emit(node.lineno, "order",
                             f"set.pop() in '{fn.name}' on the tick "
                             "path removes an arbitrary element")
                    elif cn in ("hash", "id") and isinstance(
                            node.func, ast.Name):
                        emit(node.lineno, "hash",
                             f"{cn}() in '{fn.name}' on the tick path "
                             "— process-dependent values must not "
                             "feed scheduling or routing keys")
                    elif cn is not None and isinstance(node.func,
                                                       ast.Attribute):
                        chain = attr_chain(node.func)
                        if chain and chain[0] in host:
                            root = host[chain[0]]
                            base = path.rsplit("/", 1)[-1]
                            if root == "time" and (
                                    base, fn.name
                            ) not in WALL_CLOCK_ALLOWLIST:
                                emit(node.lineno, "clock",
                                     f"wall-clock read "
                                     f"'{'.'.join(chain)}' in "
                                     f"'{fn.name}' on the tick path — "
                                     "the tick clock is the only "
                                     "clock scheduling may consult "
                                     "(Tracer wall stamps in "
                                     "observe.py are the allowlisted "
                                     "exception)")
                            elif root in ("random", "numpy.random") or (
                                    root == "numpy" and len(chain) > 2
                                    and chain[1] == "random"):
                                emit(node.lineno, "random",
                                     f"unseeded RNG "
                                     f"'{'.'.join(chain)}' in "
                                     f"'{fn.name}' on the tick path — "
                                     "derive randomness from the "
                                     "request seed via fold_in "
                                     "(APX805) or the FaultInjector "
                                     "hash draw")
                    elif cn is not None and isinstance(node.func,
                                                       ast.Name) \
                            and node.func.id in host \
                            and host[node.func.id] == "time":
                        base = path.rsplit("/", 1)[-1]
                        if (base, fn.name) not in WALL_CLOCK_ALLOWLIST:
                            emit(node.lineno, "clock",
                                 f"wall-clock read '{node.func.id}()' "
                                 f"in '{fn.name}' on the tick path — "
                                 "use the deterministic tick clock")
    return findings

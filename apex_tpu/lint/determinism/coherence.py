"""APX804 — observe/taxonomy coherence.

The observe layer's names are a contract surface twice over: the
deterministic replay tests compare ``tick_stream()`` tuples whose
first element is the event NAME, and the bench/export layer reads
metrics back by name (``registry.get`` / ``quantiles``). Both go
quietly wrong when an emit site drifts from the declared vocabulary —
a span opened under a name missing from ``PHASES`` still records, the
subset assertions in the observe tests still pass (they check against
the UNION of the tuples), and the drift surfaces much later as a
Perfetto track nobody categorised or a quantile read that silently
returns nothing. This check closes the loop statically:

- every ``tracer.begin(...)`` / ``tracer.end(...)`` name must be a
  string literal found in ``PHASES`` or an attribute read ending in
  ``.span`` (the transfer classes' declared span attribute); every
  ``span = "..."`` class attribute must itself be in ``PHASES``;
- every ``tracer.instant(...)`` name must be a literal in
  ``LIFECYCLE``;
- a non-literal name at any of those emit sites is flagged as a
  drifting dynamic name — the vocabulary tuples cannot vouch for a
  name computed at runtime;
- metric registry coherence: names created via ``.counter`` /
  ``.gauge`` / ``.histogram`` must be string literals or f-strings
  with literal structure (``f"{p}_src_bytes_total"`` declares the
  family ``*_src_bytes_total``); a fully dynamic name is flagged.
  Every literal ``registry.get("serving_...")`` /
  ``quantiles("serving_...")`` read-back must match a created literal
  or family — reading a never-created name returns nothing, silently.

The declared tuples are parsed from the serving scope's
``observe.py``; if the scope has none (a fixture mini-repo without an
observe module) the span/instant checks are skipped rather than
guessed at.
"""

import ast
import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import call_name
from apex_tpu.lint.determinism.reach import serving_dir


def _declared_tuples(tree: ast.Module) -> Dict[str, Tuple[str, ...]]:
    out: Dict[str, Tuple[str, ...]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in ("PHASES", "LIFECYCLE") \
                and isinstance(node.value, ast.Tuple):
            vals = []
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    vals.append(e.value)
            out[node.targets[0].id] = tuple(vals)
    return out


def _fstring_pattern(node: ast.JoinedStr) -> Optional[str]:
    """An f-string as an fnmatch pattern — interpolations become ``*``.
    None when there is no literal structure at all to anchor on."""
    parts: List[str] = []
    literal = False
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
            literal = True
        else:
            parts.append("*")
    return "".join(parts) if literal else None


def _name_arg(node: ast.Call) -> Optional[ast.AST]:
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def check_files(strees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []

    # group by serving scope so fixture mini-repos resolve against
    # their OWN observe.py, not the real one
    scopes: Dict[str, Dict[str, ast.Module]] = {}
    for path, tree in strees.items():
        scopes.setdefault(serving_dir(path), {})[path] = tree

    for scope in sorted(scopes):
        trees = scopes[scope]
        phases: Optional[Set[str]] = None
        lifecycle: Optional[Set[str]] = None
        for path, tree in trees.items():
            if path.rsplit("/", 1)[-1] == "observe.py":
                decl = _declared_tuples(tree)
                if "PHASES" in decl:
                    phases = set(decl["PHASES"])
                if "LIFECYCLE" in decl:
                    lifecycle = set(decl["LIFECYCLE"])

        created: Set[str] = set()
        families: List[str] = []
        lookups: List[Tuple[str, int, str, str]] = []

        for path in sorted(trees):
            tree = trees[path]
            for node in ast.walk(tree):
                # span = "..." class attributes
                if isinstance(node, ast.Assign) and phases is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "span" \
                                and isinstance(node.value, ast.Constant) \
                                and isinstance(node.value.value, str) \
                                and node.value.value not in phases:
                            findings.append(Finding(
                                "APX804", path, node.lineno,
                                f"span attribute "
                                f"'{node.value.value}' is not in "
                                f"observe.PHASES {sorted(phases)} — "
                                "declare the phase or rename the "
                                "span"))
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn in ("begin", "end", "instant") \
                        and isinstance(node.func, ast.Attribute) \
                        and node.args:
                    arg = node.args[0]
                    vocab = lifecycle if cn == "instant" else phases
                    vocab_name = "LIFECYCLE" if cn == "instant" \
                        else "PHASES"
                    if vocab is None:
                        continue
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        if arg.value not in vocab:
                            findings.append(Finding(
                                "APX804", path, node.lineno,
                                f"{cn}('{arg.value}') emits a name "
                                f"missing from observe."
                                f"{vocab_name} — the replay stream "
                                "and Perfetto tracks key on "
                                "declared names"))
                    elif isinstance(arg, ast.Attribute) \
                            and arg.attr == "span" and cn != "instant":
                        pass  # transfer classes' declared span attr
                    else:
                        findings.append(Finding(
                            "APX804", path, node.lineno,
                            f"dynamic name at a tracer.{cn}() emit "
                            "site — names must be literals from "
                            f"observe.{vocab_name} (or the declared "
                            "`span` attribute) so the vocabulary "
                            "can vouch for them"))
                elif cn in ("counter", "gauge", "histogram") \
                        and isinstance(node.func, ast.Attribute):
                    arg = _name_arg(node)
                    if arg is None:
                        continue
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        created.add(arg.value)
                    elif isinstance(arg, ast.JoinedStr):
                        pat = _fstring_pattern(arg)
                        if pat is None:
                            findings.append(Finding(
                                "APX804", path, node.lineno,
                                f"metric {cn}() name is an f-string "
                                "with no literal structure — "
                                "read-backs cannot be checked "
                                "against it"))
                        else:
                            families.append(pat)
                    else:
                        findings.append(Finding(
                            "APX804", path, node.lineno,
                            f"fully dynamic metric {cn}() name — "
                            "use a literal (or an f-string family "
                            "with literal structure) so read-back "
                            "sites can be verified against it"))
                elif cn in ("get", "quantiles") \
                        and isinstance(node.func, ast.Attribute) \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and node.args[0].value.startswith("serving_"):
                    lookups.append((path, node.lineno, cn,
                                    node.args[0].value))

        for path, line, cn, name in lookups:
            if name in created:
                continue
            if any(fnmatch.fnmatchcase(name, pat) for pat in families):
                continue
            findings.append(Finding(
                "APX804", path, line,
                f"registry.{cn}('{name}') reads a metric no serving "
                "module creates — a renamed or dropped metric here "
                "returns nothing, silently"))
    return findings

"""APX802 — fault-contract coverage for ``faults.SITES``.

A fault site is a five-artifact contract, and history says the
artifacts drift apart: a new site needs (a) a hook-consultation call
in the serving code, (b) a typed degrade path, (c) a chaos test that
actually schedules it, and (d) — for the swept families — a seed env
in the CI chaos matrix, or the site ships with a fault nobody can
inject and a recovery ladder nobody has run. Conversely a site
removed from ``SITES`` leaves stale names in tests and CI that keep
passing while testing nothing. This check makes the contract a single
declared table and cross-verifies every edge:

``faults.SITE_CONTRACTS`` maps every site to
``(error_class_or_None, sweep_env_or_None)`` — the typed error its
degrade path raises (``None`` for policy-only faults that alter a
decision instead of raising, e.g. ``pool_route`` falling back to
fixed-order routing), and the CI chaos-matrix env var that sweeps its
seed (``None`` for sites exercised by the default deterministic
schedules in the chaos tests rather than a matrix leg).

Per scope containing a ``faults.py`` that declares ``SITES``:

- ``SITE_CONTRACTS`` exists and its keys equal ``SITES`` exactly;
- every site has a consultation call site: a string literal argument
  to ``.draw(...)`` / ``.fire(...)`` / ``.calls(...)``, or a
  ``*_site = "..."`` class attribute (the transfer channels'
  indirection) somewhere in the scope;
- a declared error class resolves to a class defined or imported in
  the scope;
- every site is referenced by name in a test file that mentions
  ``chaos`` (the deterministic-replay suites);
- a declared sweep env appears in ``.github/workflows/ci.yml`` AND in
  at least one test (the test must read the env for the matrix leg to
  vary anything);
- reverse direction: every ``APEX_CHAOS_*SEED`` env in ci.yml is a
  declared sweep of some site and is read by some test — a matrix
  leg sweeping an env nobody reads is coverage theater.

Scopes without a ``faults.py``/``SITES`` (fixture mini-repos for the
other codes) are skipped silently.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import call_name
from apex_tpu.lint.determinism import repofiles
from apex_tpu.lint.determinism.reach import serving_dir

_SWEEP_RE = re.compile(r"APEX_CHAOS_[A-Z_]*SEED")


def _module_assign(tree: ast.Module, name: str) -> Optional[ast.Assign]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            return node
    return None


def _sites(node: ast.Assign) -> Optional[List[str]]:
    if not isinstance(node.value, (ast.Tuple, ast.List)):
        return None
    out = []
    for e in node.value.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append(e.value)
    return out


def _contracts(node: ast.Assign) -> Optional[
        Dict[str, Tuple[Optional[str], Optional[str], int]]]:
    """site -> (error, sweep, lineno); None if not a literal dict of
    2-tuples."""
    if not isinstance(node.value, ast.Dict):
        return None
    out: Dict[str, Tuple[Optional[str], Optional[str], int]] = {}
    for k, v in zip(node.value.keys, node.value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Tuple) and len(v.elts) == 2
                and all(isinstance(e, ast.Constant)
                        and (e.value is None or isinstance(e.value, str))
                        for e in v.elts)):
            return None
        out[k.value] = (v.elts[0].value, v.elts[1].value, k.lineno)
    return out


def _consulted(trees: Dict[str, ast.Module]) -> Set[str]:
    out: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in ("draw", "fire", "calls") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and t.id.endswith("_site"):
                        out.add(node.value.value)
    return out


def _known_classes(trees: Dict[str, ast.Module]) -> Set[str]:
    out: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                out.add(node.name)
            elif isinstance(node, ast.ImportFrom):
                out.update(a.asname or a.name for a in node.names)
    return out


def check_files(strees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []

    scopes: Dict[str, Dict[str, ast.Module]] = {}
    for path, tree in strees.items():
        scopes.setdefault(serving_dir(path), {})[path] = tree

    for scope in sorted(scopes):
        trees = scopes[scope]
        fpath = next((p for p in trees
                      if p.rsplit("/", 1)[-1] == "faults.py"), None)
        if fpath is None:
            continue
        ftree = trees[fpath]
        sites_node = _module_assign(ftree, "SITES")
        sites = _sites(sites_node) if sites_node is not None else None
        if sites is None:
            continue  # not a fault-registry module

        def emit(line: int, msg: str) -> None:
            findings.append(Finding("APX802", fpath, line, msg))

        contracts_node = _module_assign(ftree, "SITE_CONTRACTS")
        contracts = _contracts(contracts_node) \
            if contracts_node is not None else None
        if contracts is None:
            emit(sites_node.lineno,
                 "SITES has no literal SITE_CONTRACTS table mapping "
                 "every site to (typed error | None, sweep env | "
                 "None) — the fault contract must be declared to be "
                 "checkable")
            continue

        for name in sites:
            if name not in contracts:
                emit(contracts_node.lineno,
                     f"site '{name}' is in SITES but missing from "
                     "SITE_CONTRACTS")
        for name, (_, _, line) in contracts.items():
            if name not in sites:
                emit(line, f"SITE_CONTRACTS names '{name}' which is "
                           "not in SITES (stale entry)")

        consulted = _consulted(trees)
        known = _known_classes(trees)
        root = repofiles.repo_root(scope)
        texts = repofiles.test_texts(root)
        ci = repofiles.ci_text(root)
        chaos_blob = "" if texts is None else "\n".join(
            t for t in texts.values() if "chaos" in t)
        test_blob = "" if texts is None else "\n".join(texts.values())

        if texts is None:
            emit(sites_node.lineno,
                 "fault sites are declared but the tree has no "
                 "tests/ directory — every site needs a chaos-test "
                 "reference")
        declared_sweeps: Set[str] = set()
        for name in sites:
            err, sweep, line = contracts.get(name, (None, None,
                                                    sites_node.lineno))
            if name not in consulted:
                emit(line, f"site '{name}' has no consultation call "
                           "site (.draw/.fire/.calls literal or "
                           "*_site attribute) anywhere in the "
                           "serving scope — a fault nobody can "
                           "inject")
            if err is not None and err not in known:
                emit(line, f"site '{name}' declares degrade error "
                           f"'{err}' which is neither defined nor "
                           "imported in the serving scope")
            if texts is not None and not re.search(
                    rf"[\"']{re.escape(name)}[\"']", chaos_blob):
                emit(line, f"site '{name}' is referenced by no chaos "
                           "test under tests/ — its schedule has "
                           "never replayed")
            if sweep is not None:
                declared_sweeps.add(sweep)
                if ci is not None and sweep not in ci:
                    emit(line, f"site '{name}' declares sweep env "
                               f"{sweep} which is absent from the CI "
                               "chaos matrix (ci.yml)")
                if texts is not None and sweep not in test_blob:
                    emit(line, f"site '{name}' declares sweep env "
                               f"{sweep} which no test reads — the "
                               "matrix leg would vary nothing")
        if ci is None:
            if declared_sweeps:
                emit(sites_node.lineno,
                     "SITE_CONTRACTS declares CI sweep envs but the "
                     "tree has no .github/workflows/ci.yml")
        else:
            for env in sorted(set(_SWEEP_RE.findall(ci))):
                if env not in declared_sweeps:
                    emit(sites_node.lineno,
                         f"CI chaos matrix fans {env} which no "
                         "SITE_CONTRACTS entry declares (stale "
                         "matrix leg)")
                elif texts is not None and env not in test_blob:
                    emit(sites_node.lineno,
                         f"CI chaos matrix fans {env} which no test "
                         "reads — coverage theater")
    return findings

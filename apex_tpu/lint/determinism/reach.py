"""Tick-path reachability for the determinism tier (APX8xx).

The APX8xx checks care about one execution surface: the serving
engine's deterministic tick loop — everything that runs between a
``submit()`` and a committed token, because that is the code whose
ordering decisions flow into commit order and must replay bit-for-bit
under a pinned fault schedule (the chaos contract every serving PR
asserts dynamically). Host-side code OUTSIDE that surface — replica
validation in a constructor, a ``__repr__``, an export helper — is free
to iterate sets or read ``id()``; flagging it would be noise.

So, exactly like ``hygiene.py`` builds the set of functions reachable
from a *trace* root, this module builds the set of functions reachable
from the *tick/admission* roots:

- :data:`TICK_ROOTS` — ``run`` / ``step`` / ``submit`` (the
  ``ContinuousBatchingScheduler`` public drain surface) plus the
  router's per-tick admission hooks (``health_tick``,
  ``begin_admission_pass``). Every scheduler phase (``_tick``,
  ``_admit``, ``_decode_phase``, ``_prefill_phase``, ...), every
  engine wrapper (``prefill`` / ``chunk_prefill`` / ``decode`` /
  ``verify`` / ``tree_verify`` / ``draft*`` / ``sample`` /
  ``commit``), and every transfer/reshard/spill/promote path hangs off
  these by direct call.
- Closure is by *terminal identifier*, cross-module over the serving
  scope: ``self.engine.chunk_prefill(...)`` reaches every serving
  function named ``chunk_prefill`` regardless of which module defines
  it. This over-approximates (a shared method name anywhere in
  ``serving/`` joins the tick path) — deliberate: a reachability MISS
  would silently exempt a scheduling decision from APX801, while an
  over-approximation merely asks for a ``sorted()`` or a suppression
  comment in code that could plausibly be called from a tick.

Scope selection is by path: a file participates in the serving scope
when it sits in a directory named ``serving`` (the real package, a
fixture mini-repo, or a scratch copy under test — the seeded-bug
meta-tests copy ``scheduler.py`` into ``<tmp>/serving/`` and relint).
``tests/L0/run_serving`` does NOT match: the component is
``run_serving``, not ``serving``.
"""

import ast
import os
from typing import Dict, Iterable, List, Set, Tuple

#: The tick/admission roots: the public drain surface of the scheduler
#: plus the router hooks it invokes once per tick. Everything the
#: determinism tier scopes to is reachable from these by name.
TICK_ROOTS = frozenset({
    "run", "step", "submit", "health_tick", "begin_admission_pass",
})


def serving_trees(trees: Dict[str, ast.Module]) -> Dict[str, ast.Module]:
    """The subset of the linted file set that lives in a ``serving``
    directory — the only files the APX8xx checks look at."""
    out = {}
    for path, tree in trees.items():
        parts = os.path.normpath(path).split(os.sep)
        if "serving" in parts[:-1]:
            out[path] = tree
    return out


def serving_dir(path: str) -> str:
    """The ``.../serving`` directory that puts ``path`` in scope."""
    parts = os.path.normpath(path).split(os.sep)
    idx = len(parts) - 1 - parts[-2::-1].index("serving")
    return os.sep.join(parts[:idx])


class FnInfo:
    """One serving-scope function: its AST, its file, and the terminal
    identifiers it mentions (call targets, attribute tails, bare
    names) — the edges of the reachability graph."""

    __slots__ = ("path", "node", "mentions")

    def __init__(self, path: str, node: ast.FunctionDef):
        self.path = path
        self.node = node
        self.mentions = _mentions(node)


def _mentions(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute):
                out.add(f.attr)
            elif isinstance(f, ast.Name):
                out.add(f.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _function_defs(tree: ast.Module) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]


def reachable_functions(strees: Dict[str, ast.Module],
                        roots: Iterable[str] = TICK_ROOTS
                        ) -> List[Tuple[str, ast.FunctionDef]]:
    """All (path, FunctionDef) pairs reachable from the tick roots by
    cross-module terminal-name closure over the serving scope."""
    by_name: Dict[str, List[FnInfo]] = {}
    for path in sorted(strees):
        for fn in _function_defs(strees[path]):
            by_name.setdefault(fn.name, []).append(FnInfo(path, fn))

    seen: Set[int] = set()
    out: List[Tuple[str, ast.FunctionDef]] = []
    frontier = [n for n in roots if n in by_name]
    while frontier:
        name = frontier.pop()
        for info in by_name.get(name, ()):
            if id(info.node) in seen:
                continue
            seen.add(id(info.node))
            out.append((info.path, info.node))
            frontier.extend(m for m in info.mentions if m in by_name)
    return out

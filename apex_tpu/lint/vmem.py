"""Trace-time VMEM budget check (APX102).

A ``pallas_call`` whose resident blocks outgrow VMEM (~16 MiB per
TensorCore) fails at Mosaic compile time on hardware — but the CPU
test rig runs every kernel in interpret mode, where any block shape
"works", so an oversized retune only explodes on the TPU. This check
closes that gap without a TPU: ``pl.pallas_call`` is monkeypatched to
record (grid, block specs, scratch, out shapes) and return
correctly-shaped zeros, then each *registered configuration* — the
representative shapes of the kernels in ``multi_tensor_apply/
kernels.py``, ``flash_attention.py`` and ``fused_layer_norm.py``,
forward and backward — is traced under ``jax.eval_shape`` (abstract
only: no compile, no execution, CPU-safe, milliseconds per config).

The budget model per recorded call:

    2 x (sum of VMEM input blocks + sum of VMEM output blocks)
      + SMEM blocks + scratch bytes   <=  16 MiB

The 2x is Pallas' double buffering of streamed blocks; scratch and
SMEM are single-resident. Block dims of ``None`` take the operand's
full dimension. This deliberately overcounts revisited blocks — a
conservative estimator that passes is a real guarantee, one that
undercounts is noise.

A config that fails to trace at all is reported as APX100: an
unverifiable kernel is a lint failure, not a skip.
"""

import contextlib
import functools
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from apex_tpu.lint import Finding

BUDGET_BYTES = 16 * 1024 * 1024


@dataclass
class CallRecord:
    kernel: str
    grid: Tuple
    in_bytes: int = 0
    out_bytes: int = 0
    smem_bytes: int = 0
    scratch_bytes: int = 0

    @property
    def total(self) -> int:
        return (2 * (self.in_bytes + self.out_bytes)
                + self.smem_bytes + self.scratch_bytes)

    def describe(self) -> str:
        mib = 1024 * 1024
        return (f"2x({self.in_bytes / mib:.2f}+{self.out_bytes / mib:.2f})"
                f" + smem {self.smem_bytes / mib:.3f}"
                f" + scratch {self.scratch_bytes / mib:.2f}"
                f" = {self.total / mib:.2f} MiB (grid {self.grid})")


@dataclass
class Config:
    """One registered kernel configuration: ``build()`` returns
    ``(fn, args)`` to run under ``jax.eval_shape``."""
    name: str
    module: str  # dotted module whose kernels this config exercises
    build: Callable[[], Tuple[Callable, tuple]]
    budget: int = BUDGET_BYTES


def _kernel_name(kernel) -> str:
    if isinstance(kernel, functools.partial):
        kernel = kernel.func
    return getattr(kernel, "__name__", repr(kernel))


def _is_smem(spec) -> bool:
    return "smem" in str(getattr(spec, "memory_space", "")).lower()


def _block_bytes(spec, operand) -> int:
    import numpy as np

    shape = getattr(operand, "shape", ())
    dtype = getattr(operand, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    block = getattr(spec, "block_shape", None) if spec is not None else None
    if block is None:
        dims = shape
    else:
        dims = [s if b is None else b for b, s in zip(block, shape)]
    n = 1
    for d in dims:
        n *= int(d)
    return n * itemsize


@contextlib.contextmanager
def capture_calls(records: List[CallRecord]):
    """Swap ``pl.pallas_call`` for a recorder returning shaped zeros."""
    from jax.experimental import pallas as pl

    real = pl.pallas_call

    def fake(kernel, *, out_shape, grid=None, in_specs=None,
             out_specs=None, scratch_shapes=None, **_kw):
        def runner(*operands):
            import jax.numpy as jnp

            rec = CallRecord(_kernel_name(kernel),
                             grid if isinstance(grid, tuple) else (grid,))
            specs = in_specs if in_specs is not None else [None] * len(
                operands)
            for spec, op in zip(specs, operands):
                b = _block_bytes(spec, op)
                if _is_smem(spec):
                    rec.smem_bytes += b
                else:
                    rec.in_bytes += b
            out_leaves = (list(out_shape)
                          if isinstance(out_shape, (list, tuple))
                          else [out_shape])
            ospecs = (list(out_specs)
                      if isinstance(out_specs, (list, tuple))
                      else [out_specs] * len(out_leaves))
            for spec, leaf in zip(ospecs, out_leaves):
                rec.out_bytes += _block_bytes(spec, leaf)
            for s in scratch_shapes or []:
                rec.scratch_bytes += _block_bytes(None, s)
            records.append(rec)
            outs = [jnp.zeros(l.shape, l.dtype) for l in out_leaves]
            if isinstance(out_shape, (list, tuple)):
                return type(out_shape)(outs)
            return outs[0]

        return runner

    pl.pallas_call = fake
    try:
        yield
    finally:
        pl.pallas_call = real


def run_configs(configs: List[Config]) -> List[Finding]:
    import jax

    findings: List[Finding] = []
    for cfg in configs:
        records: List[CallRecord] = []
        path = _module_path(cfg.module)
        try:
            with capture_calls(records):
                fn, args = cfg.build()
                jax.eval_shape(fn, *args)
        except Exception as e:  # noqa: BLE001 - surfaced as a finding
            findings.append(Finding(
                "APX100", path, 1,
                f"config '{cfg.name}' failed to trace: "
                f"{type(e).__name__}: {e}"))
            continue
        for rec in records:
            if rec.total > cfg.budget:
                findings.append(Finding(
                    "APX102", path, 1,
                    f"config '{cfg.name}' kernel '{rec.kernel}': "
                    f"estimated VMEM residency {rec.describe()} exceeds "
                    f"the {cfg.budget // (1024 * 1024)} MiB budget"))
    return findings


def _module_path(dotted: str) -> str:
    import importlib

    try:
        return importlib.import_module(dotted).__file__ or dotted
    except Exception:  # noqa: BLE001
        return dotted


# -- registered repo configurations -----------------------------------------

def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))


def _flash_cfg(d, dtype, seq):
    def build():
        import jax
        import jax.numpy as jnp

        from apex_tpu.transformer.functional.flash_attention import (
            flash_attention,
        )

        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=True, use_kernel=True)
            return jnp.sum(out.astype(jnp.float32))

        grads = lambda q, k, v: jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
        shape = (1, 2, seq, d)
        return grads, (_sds(shape, dtype),) * 3

    return build


def _ln_cfg(h, rms=False):
    def build():
        import importlib

        import jax
        import jax.numpy as jnp

        # the package __init__ re-exports a function of the same name,
        # so the submodule must be imported by dotted path
        fln = importlib.import_module(
            "apex_tpu.normalization.fused_layer_norm")

        if rms:
            def loss(x, w):
                y = fln.fused_rms_norm_affine(x, w, (h,))
                return jnp.sum(y.astype(jnp.float32))
            argnums = (0, 1)
            args = (_sds((4096, h), "float32"), _sds((h,), "float32"))
        else:
            def loss(x, w, b):
                y = fln.fused_layer_norm_affine(x, w, b, (h,))
                return jnp.sum(y.astype(jnp.float32))
            argnums = (0, 1, 2)
            args = (_sds((4096, h), "float32"), _sds((h,), "float32"),
                    _sds((h,), "float32"))
        return (lambda *a: jax.value_and_grad(loss, argnums)(*a)), args

    return build


def _flat_cfg(which):
    rows = 8192  # 8192x128 fp32 = 4 MiB flat buffer, 32 grid tiles

    def build():
        import functools as ft

        from apex_tpu.multi_tensor_apply import kernels as K

        buf = _sds((rows, 128), "float32")
        m16 = _sds((rows, 128), "bfloat16")
        ids = _sds((rows // 8,), "int32")
        if which == "adam":
            fn = ft.partial(K.flat_adam, lr=1e-3, beta1=0.9, beta2=0.99,
                            eps=1e-8, step=1, weight_decay=0.01,
                            emit_compute_dtype="bfloat16", interpret=True)
            return fn, (buf, buf, m16, buf)
        if which == "sgd":
            fn = ft.partial(K.flat_sgd, lr=1e-3, momentum=0.9,
                            dampening=0.0, weight_decay=0.0,
                            nesterov=False, wd_after_momentum=False,
                            first_run=True, interpret=True)
            return fn, (buf, buf, m16)
        if which == "lamb":
            fn = ft.partial(K.flat_lamb, lr=1e-3, beta1=0.9, beta2=0.99,
                            eps=1e-8, step=1, weight_decay=0.01,
                            num_tensors=4, interpret=True)
            return fn, (buf, buf, m16, buf, ids)
        if which == "adagrad":
            fn = ft.partial(K.flat_adagrad, lr=1e-3, eps=1e-8,
                            weight_decay=0.0, interpret=True)
            return fn, (buf, buf, buf)
        if which == "novograd":
            fn = ft.partial(K.flat_novograd, lr=1e-3, beta1=0.9,
                            beta2=0.99, eps=1e-8, step=1,
                            weight_decay=0.0, num_tensors=4,
                            interpret=True)
            return fn, (buf, buf, m16, _sds((4,), "float32"), ids)
        if which == "scale":
            fn = ft.partial(K.flat_scale, scale=0.5, interpret=True)
            return fn, (buf,)
        if which == "axpby":
            fn = (lambda x, y: K.flat_axpby(1.0, x, 2.0, y,
                                            interpret=True))
            return fn, (buf, buf)
        fn = ft.partial(K.flat_l2norm_partials, interpret=True)
        return fn, (buf,)

    return build


def _xentropy_cfg():
    def build():
        import jax

        from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

        def loss(logits, labels):
            return softmax_cross_entropy_loss(logits, labels).mean()

        fn = lambda lg, lb: jax.value_and_grad(loss)(lg, lb)
        return fn, (_sds((1024, 512), "float32"), _sds((1024,), "int32"))

    return build


def _fused_softmax_cfg():
    """Both fused-softmax families (masked 4D + causal 3D) fwd+bwd at
    full 128-row tiles."""
    def build():
        import jax
        import jax.numpy as jnp

        from apex_tpu.transformer.functional import fused_softmax as fs

        def loss(x, mask, x3):
            y = fs.scaled_masked_softmax(x, mask, scale=0.5)
            z = fs.scaled_upper_triang_masked_softmax(x3, scale=0.5)
            return (jnp.sum(y.astype(jnp.float32))
                    + jnp.sum(z.astype(jnp.float32)))

        fn = lambda *a: jax.value_and_grad(loss, (0, 2))(*a)
        return fn, (_sds((2, 2, 128, 128), "bfloat16"),
                    _sds((2, 1, 128, 128), "int32"),
                    _sds((4, 128, 128), "bfloat16"))

    return build


def _bottleneck_cfg():
    """Halo'd 3x3-conv spatial bottleneck, H sharded over ``context``.

    The block's compute is XLA convs today, so the capture records no
    pallas calls — registering it pins the *trace* (a halo-exchange or
    conv regression surfaces as APX100) and budget-checks any Pallas
    kernel that later lands in the halo path. Uses an explicit local
    2-device mesh so the global parallel state is untouched; on a
    single-device rig it degrades to the unsharded reference block
    (same convs, no exchange).
    """
    def build():
        import jax

        from apex_tpu.contrib.bottleneck import (
            spatial_bottleneck, spatial_parallel_bottleneck,
        )

        params = {"w1": _sds((1, 1, 8, 4), "float32"),
                  "w2": _sds((3, 3, 4, 4), "float32"),
                  "w3": _sds((1, 1, 4, 8), "float32")}
        x = _sds((2, 16, 5, 8), "float32")
        if len(jax.devices()) < 2:
            return spatial_bottleneck, (params, x)

        import numpy as np
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state as ps

        mesh = Mesh(np.array(jax.devices()[:2]), (ps.CONTEXT_AXIS,))
        fn = ps.shard_map(spatial_parallel_bottleneck, mesh=mesh,
                          in_specs=(P(), P(None, ps.CONTEXT_AXIS)),
                          out_specs=P(None, ps.CONTEXT_AXIS))
        return fn, (params, x)

    return build


def _w8_matmul_cfg():
    """The dequant-fused int8 matmul family at serving-like shapes: a
    column/row-style ``w8_matmul`` (K x N weight, per-N scale, bias)
    chained into the output-channel-major logits head ``w8_matmul_nk``
    (V x h table, per-V scale). The N grid streams one (K, block_n)
    int8 tile + its fp32 dequant in registers — the resident blocks are
    what the budget prices."""
    def build():
        from apex_tpu.quant.kernels import w8_matmul, w8_matmul_nk

        def fn(x, wq, scale, bias, tq, tscale):
            h = w8_matmul(x, wq, scale, bias, out_dtype=x.dtype)
            return w8_matmul_nk(h, tq, tscale)

        return fn, (_sds((32, 1024), "bfloat16"),
                    _sds((1024, 4096), "int8"), _sds((4096,), "float32"),
                    _sds((4096,), "float32"),
                    _sds((50304, 4096), "int8"), _sds((50304,), "float32"))

    return build


def _paged_serving_cfg(which):
    """Paged serving steps under the recorder: prefill runs flash
    attention over the prompt bucket (its pallas blocks are what the
    budget prices); decode's gather/scatter is XLA math today, so — as
    with the bottleneck config — registering it pins the trace and
    covers any Pallas paged-attention kernel that lands later."""
    def build():
        import dataclasses
        import functools as ft

        import jax

        from apex_tpu.models.gpt import gpt_tiny, init_gpt
        from apex_tpu.serving.cache import init_paged_cache
        from apex_tpu.serving.decode import (
            make_paged_decode_fn, make_paged_prefill_fn,
        )

        cfg = dataclasses.replace(gpt_tiny(), use_rope=True)
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, 2, 32, 6, 16))
        if which == "prefill":
            fn = make_paged_prefill_fn(cfg)
            return fn, (params, cache, _sds((1, 16), "int32"),
                        _sds((16,), "int32"), _sds((), "int32"),
                        _sds((1,), "int32"), _sds((2,), "int32"))
        if which == "chunk_prefill":
            from apex_tpu.serving.decode import make_paged_chunk_prefill_fn

            fn = make_paged_chunk_prefill_fn(cfg)
            return fn, (params, cache, _sds((1, 16), "int32"),
                        _sds((16,), "int32"), _sds((), "int32"),
                        _sds((), "int32"), _sds((1,), "int32"),
                        _sds((2,), "int32"), _sds((2,), "int32"))
        if which == "verify":
            from apex_tpu.serving.decode import make_paged_verify_fn

            fn = make_paged_verify_fn(cfg)
            return fn, (params, cache, _sds((2, 4), "int32"))
        if which == "tree_verify":
            from apex_tpu.serving.decode import make_paged_tree_verify_fn

            fn = make_paged_tree_verify_fn(cfg)
            return fn, (params, cache, _sds((2, 4), "int32"),
                        _sds((2, 4), "int32"), _sds((2, 4, 4), "bool"))
        fn = make_paged_decode_fn(cfg)
        return fn, (params, cache, _sds((2,), "int32"),
                    _sds((2,), "bool"))

    return build


def _draft_forward_cfg():
    """The model drafter's per-token forward (``draft_gpt_tiny`` over
    its dense lockstep cache): XLA math today, so — like the paged
    steps — registering it pins the trace and budget-checks any Pallas
    kernel that later lands in the draft path."""
    def build():
        import functools as ft

        import jax

        from apex_tpu.models.gpt import draft_gpt_tiny, init_gpt
        from apex_tpu.serving.cache import init_cache
        from apex_tpu.serving.decode import make_decode_fn

        cfg = draft_gpt_tiny()
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
        # 32 + 5: the engine max_len plus DraftModel's catch-up chunk
        cache = jax.eval_shape(ft.partial(init_cache, cfg, 2, 37))
        fn = make_decode_fn(cfg)
        return fn, (params, cache, _sds((2,), "int32"),
                    _sds((2,), "bool"))

    return build


def repo_configs() -> List[Config]:
    flat = "apex_tpu.multi_tensor_apply.kernels"
    flash = "apex_tpu.transformer.functional.flash_attention"
    ln = "apex_tpu.normalization.fused_layer_norm"
    cfgs = [
        Config("flash_d64_bf16_s2048", flash,
               _flash_cfg(64, "bfloat16", 2048)),
        Config("flash_d128_f32_s2048", flash,
               _flash_cfg(128, "float32", 2048)),
        Config("ln_h1024_fwd_bwd", ln, _ln_cfg(1024)),
        Config("ln_h4096_fwd_bwd_colsplit", ln, _ln_cfg(4096)),
        Config("rms_h4096_fwd_bwd", ln, _ln_cfg(4096, rms=True)),
    ]
    for which in ("adam", "sgd", "lamb", "adagrad", "novograd", "scale",
                  "axpby", "l2norm"):
        cfgs.append(Config(f"flat_{which}", flat, _flat_cfg(which)))
    cfgs.append(Config("xentropy_fwd_bwd", "apex_tpu.contrib.xentropy",
                       _xentropy_cfg()))
    cfgs.append(Config("fused_softmax_fwd_bwd",
                       "apex_tpu.transformer.functional.fused_softmax",
                       _fused_softmax_cfg()))
    cfgs.append(Config("bottleneck_spatial_cp2",
                       "apex_tpu.contrib.bottleneck.bottleneck",
                       _bottleneck_cfg()))
    cfgs.append(Config("w8_matmul_suite", "apex_tpu.quant.kernels",
                       _w8_matmul_cfg()))
    cfgs.append(Config("gpt_paged_prefill_step", "apex_tpu.serving.decode",
                       _paged_serving_cfg("prefill")))
    cfgs.append(Config("gpt_paged_chunk_prefill_step",
                       "apex_tpu.serving.decode",
                       _paged_serving_cfg("chunk_prefill")))
    cfgs.append(Config("gpt_paged_decode_step", "apex_tpu.serving.decode",
                       _paged_serving_cfg("decode")))
    cfgs.append(Config("gpt_spec_verify_step", "apex_tpu.serving.decode",
                       _paged_serving_cfg("verify")))
    cfgs.append(Config("gpt_tree_verify_step", "apex_tpu.serving.decode",
                       _paged_serving_cfg("tree_verify")))
    cfgs.append(Config("gpt_draft_forward_step",
                       "apex_tpu.serving.draft_model",
                       _draft_forward_cfg()))
    return cfgs


def check_repo() -> List[Finding]:
    return run_configs(repo_configs())

"""Small shared AST helpers for the apxlint checkers.

Everything here is deliberately conservative: helpers return ``None``
for anything they cannot resolve statically, and every checker treats
``None`` as "skip, don't guess" — a lint finding must never rest on a
heuristic that could misread the program.
"""

import ast
from typing import Any, Iterator, List, Optional


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called function: ``pl.pallas_call`` ->
    ``pallas_call``, ``psum`` -> ``psum``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``np.random.rand`` -> ["np", "random", "rand"]; None if the chain
    is rooted in anything but a plain name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def kwarg(node: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def static_len(node: Optional[ast.AST]) -> Optional[int]:
    """Length of a list/tuple expression when statically countable.

    Handles the spec-building idioms of the kernel call sites:
    ``[a] + [b] * 3`` and a bare ``BlockSpec(...)`` call (a single
    spec counts as length 1). Anything else -> None.
    """
    if node is None:
        return None
    if isinstance(node, (ast.List, ast.Tuple)):
        if any(isinstance(e, ast.Starred) for e in node.elts):
            return None
        return len(node.elts)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            left, right = static_len(node.left), static_len(node.right)
            if left is None or right is None:
                return None
            return left + right
        if isinstance(node.op, ast.Mult):
            seq, mult = node.left, node.right
            if isinstance(seq, ast.Constant):
                seq, mult = mult, seq
            n = static_len(seq)
            if (n is not None and isinstance(mult, ast.Constant)
                    and isinstance(mult.value, int)):
                return n * mult.value
            return None
    if isinstance(node, ast.Call):
        return 1  # a single BlockSpec(...) / ShapeDtypeStruct(...)
    return None


def static_elements(node: Optional[ast.AST]) -> Optional[List[ast.AST]]:
    """The element expressions of a statically countable sequence, with
    ``[x] * 3`` expanded by repetition. None if not countable."""
    if node is None:
        return None
    if isinstance(node, (ast.List, ast.Tuple)):
        if any(isinstance(e, ast.Starred) for e in node.elts):
            return None
        return list(node.elts)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            left = static_elements(node.left)
            right = static_elements(node.right)
            if left is None or right is None:
                return None
            return left + right
        if isinstance(node.op, ast.Mult):
            seq, mult = node.left, node.right
            if isinstance(seq, ast.Constant):
                seq, mult = mult, seq
            elems = static_elements(seq)
            if (elems is not None and isinstance(mult, ast.Constant)
                    and isinstance(mult.value, int)):
                return elems * mult.value
            return None
    if isinstance(node, ast.Call):
        return [node]
    return None


def literal_strings(node: ast.AST) -> Optional[Any]:
    """Evaluate an expression built of string literals and set algebra:
    set/frozenset/list/tuple literals, ``frozenset({...})``, and ``|`` /
    ``-`` over those. Returns a frozenset of strings, or None."""
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return frozenset(vals)
    if isinstance(node, ast.Call) and call_name(node) in ("frozenset", "set"):
        if len(node.args) == 1 and not node.keywords:
            return literal_strings(node.args[0])
        if not node.args and not node.keywords:
            return frozenset()
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitOr,
                                                            ast.Sub)):
        left = literal_strings(node.left)
        right = literal_strings(node.right)
        if left is None or right is None:
            return None
        return left | right if isinstance(node.op, ast.BitOr) else \
            left - right
    return None


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Like ``ast.walk`` but does not descend into nested function or
    class scopes (their statements execute elsewhere, if at all)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def functions_in(tree: ast.AST) -> List[ast.FunctionDef]:
    """Every FunctionDef in the module, including nested ones."""
    return [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]

"""APX105 — trace-tier coverage meta-lint.

The trace-time tiers only verify what is *registered*: APX102 evaluates
the ``apex_tpu.lint.vmem`` Config list, the APX5xx/APX6xx tiers walk
the ``apex_tpu.lint.traced`` TraceEntry registry. A brand-new pallas
kernel family that registers in neither is invisible to all of them —
its VMEM residency, accumulator dtypes, and byte budgets are simply
unchecked, with no finding to say so. This check closes that hole:
every file under ``apex_tpu/`` that actually *calls*
``pl.pallas_call`` must be named (as a dotted ``module``) by at least
one VMEM Config AND at least one TraceEntry.

Scoping: only files with an ``apex_tpu`` path component are examined
(test fixtures opt in by living under a ``.../apex_tpu/`` fixture
directory), and only ``ast.Call`` nodes count — modules that merely
mention ``pallas_call`` in strings, attribute references, or the vmem
monkeypatch itself are not kernel families. Coverage is resolved by
path-suffix matching the registries' dotted module names, so no
imports of the covered modules happen here.
"""

import ast
import os
from typing import Dict, Iterable, List, Optional

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import call_name


def _module_suffixes(dotted: str):
    rel = dotted.replace(".", os.sep)
    return (os.sep + rel + ".py", os.sep + rel + os.sep + "__init__.py")


def _covered(path: str, modules: Iterable[str]) -> bool:
    return any(path.endswith(_module_suffixes(m)) for m in modules)


def _first_pallas_call(tree: ast.Module) -> Optional[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) == "pallas_call":
            return node
    return None


def check_files(trees: Dict[str, ast.Module], *,
                vmem_modules: Optional[Iterable[str]] = None,
                trace_modules: Optional[Iterable[str]] = None
                ) -> List[Finding]:
    """APX105 findings over the linted file set.

    ``vmem_modules`` / ``trace_modules`` are injectable for tests; by
    default they come from the two live registries (pure-python
    imports — the Config/TraceEntry builders stay lazy).
    """
    marker = os.sep + "apex_tpu" + os.sep
    interesting = {}
    for path, tree in trees.items():
        if marker not in path:
            continue
        node = _first_pallas_call(tree)
        if node is not None:
            interesting[path] = node
    if not interesting:
        return []

    if vmem_modules is None:
        from apex_tpu.lint import vmem
        vmem_modules = {c.module for c in vmem.repo_configs()}
    if trace_modules is None:
        from apex_tpu.lint.traced.registry import repo_entries
        trace_modules = {e.module for e in repo_entries()}

    findings: List[Finding] = []
    for path, node in sorted(interesting.items()):
        missing = []
        if not _covered(path, vmem_modules):
            missing.append("APX102 VMEM Config (apex_tpu/lint/vmem.py)")
        if not _covered(path, trace_modules):
            missing.append(
                "TraceEntry (apex_tpu/lint/traced/registry.py)")
        if missing:
            findings.append(Finding(
                "APX105", path, node.lineno,
                "pallas_call kernel family is missing a registered "
                + " and a ".join(missing)
                + " — unregistered kernels dodge the VMEM, APX5xx, and "
                  "cost tiers entirely"))
    return findings

"""apxlint trace tier — jaxpr-level verifiers (APX5xx).

The AST tier (``apex_tpu.lint.checks``) sees source; this tier sees
*programs*. A registry of traceable entrypoints (``registry.py``) is
walked under ``jax.make_jaxpr`` — abstract shapes only, no compile, no
accelerator — and each traced jaxpr is handed to the verifiers:

- ``precision``  — APX501 sub-fp32 reduction/loop accumulators,
                   APX502 loss-scale unscale/overflow-check placement;
- ``memory``     — APX503 broadcast/materialization blowup;
- ``schedule``   — APX511 per-rank SPMD collective-schedule simulation;
- ``aliases``    — APX512 declared ``input_output_aliases`` survival;
- ``cost``       — APX6xx abstract HBM-traffic / collective-volume /
                   peak-live interpreter, gated by ``budgets`` against
                   the committed ``budgets.json`` manifest.

Run via ``python -m apex_tpu.lint --trace`` (APX5xx) and/or ``--cost``
(APX6xx; both tiers share one ``jax.make_jaxpr`` pass per entry).
Import side effects are kept minimal: jax is only imported when a
check actually runs.
"""

from apex_tpu.lint.traced.registry import (  # noqa: F401
    TraceEntry,
    check_repo,
    ensure_cpu_devices,
    repo_entries,
    run_entries,
)

"""APX511 — SPMD communication-schedule simulation (static deadlock
detector).

Collectives are rendezvous points: on a real pod slice every rank must
issue the *same collectives in the same order* along each mesh axis, or
the mesh hangs. APX201 (the AST pass) catches rank-divergent branches
it can see in source; this check abstract-interprets the *traced*
``shard_map`` body once per rank instead, so divergence hidden behind
helper functions, ``lax.cond`` lowering, or schedule arithmetic is
caught too.

Model: for every ``shard_map`` equation in the entry's jaxpr, the body
is walked once per rank assignment (the cartesian product over mesh
axes with size > 1). A tiny concrete interpreter propagates scalar
integer/boolean values that derive from ``axis_index`` and literals
through arithmetic/comparison primitives; everything else is Unknown.
The walk emits an ordered *footprint* of nested tuples:

- ``("coll", prim, axes, extra, nbytes)`` for each collective —
  ``ppermute`` includes its full permutation, ``all_to_all``/
  ``all_gather`` their axis params; ``nbytes`` is the per-rank operand
  byte count (aval-derived, so rank-independent — the APX6xx cost tier
  prices communication volume from it without changing the equality
  semantics here);
- ``("scan", length, body_footprint)`` / ``("while", cond_fp,
  body_fp)`` for loops (collectives inside a loop rendezvous once per
  iteration, so the loop structure is part of the schedule);
- a ``cond`` with a per-rank *concrete* predicate descends the chosen
  branch (this is where rank-divergent schedules become per-rank
  differences); with an Unknown predicate, all branches must have
  identical footprints, else the schedule is unverifiable and flagged.

Checks: all per-rank footprints must be pairwise equal, and every
``ppermute`` permutation must be well-formed (no duplicated source or
destination — a duplicated endpoint is a double-send that deadlocks
its peer).
"""

import itertools
from typing import List, Optional, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl

_COLLECTIVES = {
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather", "axis_all_gather",
}

_MAX_RANKS = 64

# Scalar primitives the concrete interpreter evaluates. Anything else
# produces Unknown (None) values.
_EVAL = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "rem": lambda a, b: a % b,
    "div": lambda a, b: a // b if isinstance(a, int) and isinstance(b, int)
    else a / b,
    "max": lambda a, b: max(a, b),
    "min": lambda a, b: min(a, b),
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "and": lambda a, b: bool(a) and bool(b),
    "or": lambda a, b: bool(a) or bool(b),
    "xor": lambda a, b: bool(a) != bool(b),
    "not": lambda a: not a,
    "neg": lambda a: -a,
    "convert_element_type": lambda a: a,
    "stop_gradient": lambda a: a,
    "broadcast_in_dim": lambda a: a,  # scalar-to-scalar only (guarded)
    "reshape": lambda a: a,
    "squeeze": lambda a: a,
}


class _ScheduleError(Exception):
    """An Unknown-predicate cond whose branches disagree."""


def _is_scalar(v) -> bool:
    return getattr(v.aval, "shape", None) == ()


def _read(env, v):
    lit = jl.scalar_literal(v)
    if lit is not None:
        return lit
    if jl.is_literal(v):
        return None
    return env.get(v)


def _footprint(jaxpr_like, env, rank) -> Tuple:
    """Ordered collective footprint of one jaxpr for one rank."""
    jaxpr = jl.open_jaxpr(jaxpr_like)
    out: List[Tuple] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        vals = [_read(env, v) for v in eqn.invars]

        if name == "axis_index":
            ax = jl.axis_names(eqn.params)
            env[eqn.outvars[0]] = rank.get(ax[0], 0) if ax else None
            continue

        if name in _COLLECTIVES:
            axes = jl.axis_names(eqn.params)
            extra: Tuple = ()
            if name == "ppermute":
                perm = tuple(tuple(p) for p in eqn.params.get("perm", ()))
                extra = (perm,)
            nbytes = sum(jl.aval_bytes(v.aval) for v in eqn.invars
                         if not jl.is_literal(v))
            out.append(("coll", name, axes, extra, nbytes))
            continue

        if name == "scan":
            sub_env = {}
            body = eqn.params["jaxpr"]
            nc = eqn.params.get("num_consts", 0)
            bj = jl.open_jaxpr(body)
            for bv, val in zip(bj.invars[:nc], vals[:nc]):
                sub_env[bv] = val
            fp = _footprint(body, sub_env, rank)
            if fp:
                out.append(("scan", eqn.params.get("length"), fp))
            continue

        if name == "while":
            cc = eqn.params.get("cond_nconsts", 0)
            bc = eqn.params.get("body_nconsts", 0)
            cfp = _footprint(eqn.params["cond_jaxpr"], {}, rank)
            benv = {}
            bj = jl.open_jaxpr(eqn.params["body_jaxpr"])
            for bv, val in zip(bj.invars[:bc], vals[cc:cc + bc]):
                benv[bv] = val
            bfp = _footprint(eqn.params["body_jaxpr"], benv, rank)
            if cfp or bfp:
                out.append(("while", cfp, bfp))
            continue

        if name == "cond":
            branches = eqn.params["branches"]
            pred = vals[0]
            if pred is not None:
                idx = int(bool(pred)) if isinstance(pred, bool) else int(pred)
                idx = max(0, min(idx, len(branches) - 1))
                sub_env = {}
                bj = jl.open_jaxpr(branches[idx])
                for bv, val in zip(bj.invars, vals[1:]):
                    sub_env[bv] = val
                out.extend(_footprint(branches[idx], sub_env, rank))
                continue
            fps = []
            for br in branches:
                sub_env = {}
                bj = jl.open_jaxpr(br)
                for bv, val in zip(bj.invars, vals[1:]):
                    sub_env[bv] = val
                fps.append(_footprint(br, sub_env, rank))
            if any(fp != fps[0] for fp in fps[1:]):
                raise _ScheduleError(
                    "a cond with an unresolvable predicate has branches "
                    f"with different collective footprints: {fps[0]!r} "
                    f"vs {fps[1]!r}")
            out.extend(fps[0])
            continue

        # generic call (pjit/remat/...): inline with value propagation
        handled = False
        for _, sub in jl.sub_jaxprs(eqn):
            sj = jl.open_jaxpr(sub)
            if len(sj.invars) == len(eqn.invars):
                sub_env = dict(zip(sj.invars, vals))
                out.extend(_footprint(sub, sub_env, rank))
                # propagate concrete scalar results back out
                if len(sj.outvars) == len(eqn.outvars):
                    for ov, sv in zip(eqn.outvars, sj.outvars):
                        env[ov] = _read(sub_env, sv)
                handled = True
                break
        if handled:
            continue

        # scalar concrete interpretation
        fn = _EVAL.get(name)
        if (fn is not None and all(val is not None for val in vals)
                and all(_is_scalar(ov) for ov in eqn.outvars)
                and all(_is_scalar(v) or jl.is_literal(v)
                        for v in eqn.invars)):
            try:
                env[eqn.outvars[0]] = fn(*vals)
            except Exception:  # noqa: BLE001 - Unknown on any failure
                pass
    return tuple(out)


def _perm_findings(fp, path: str, entry: str,
                   findings: List[Finding]) -> None:
    for item in fp:
        if item[0] == "coll" and item[1] == "ppermute" and item[3]:
            perm = item[3][0]
            srcs = [p[0] for p in perm]
            dsts = [p[1] for p in perm]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                findings.append(Finding(
                    "APX511", path, 1,
                    f"entry '{entry}': ppermute permutation {perm} has a "
                    f"duplicated source or destination — a double "
                    f"send/recv endpoint deadlocks its peer"))
        elif item[0] == "scan":
            _perm_findings(item[2], path, entry, findings)
        elif item[0] == "while":
            _perm_findings(item[1], path, entry, findings)
            _perm_findings(item[2], path, entry, findings)


def _first_divergence(a, b, prefix="") -> str:
    for i, (x, y) in enumerate(itertools.zip_longest(a, b)):
        if x != y:
            return (f"{prefix}step {i}: {x!r} vs {y!r}")
    return f"{prefix}lengths {len(a)} vs {len(b)}"


def check(closed, path: str, entry: str,
          max_ranks: int = _MAX_RANKS) -> List[Finding]:
    findings: List[Finding] = []
    for eqn in jl.all_eqns(closed, into_pallas=False):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        try:
            axis_sizes = dict(mesh.shape)
        except Exception:  # noqa: BLE001
            axis_sizes = {}
        active = [(ax, n) for ax, n in axis_sizes.items() if n > 1]
        n_ranks = 1
        for _, n in active:
            n_ranks *= n
        if n_ranks > max_ranks:
            active = active[:1]  # degrade to one axis rather than skip

        rank_fps = []
        body = eqn.params["jaxpr"]
        for combo in itertools.product(*[range(n) for _, n in active]):
            rank = {ax: idx for (ax, _), idx in zip(active, combo)}
            try:
                fp = _footprint(body, {}, rank)
            except _ScheduleError as e:
                findings.append(Finding(
                    "APX511", path, 1, f"entry '{entry}': {e}"))
                rank_fps = []
                break
            rank_fps.append((rank, fp))
        if not rank_fps:
            continue

        _perm_findings(rank_fps[0][1], path, entry, findings)
        rank0, fp0 = rank_fps[0]
        for rank, fp in rank_fps[1:]:
            if fp != fp0:
                findings.append(Finding(
                    "APX511", path, 1,
                    f"entry '{entry}': collective schedule diverges "
                    f"between rank {rank0} and rank {rank} — "
                    f"{_first_divergence(fp0, fp)} (multi-chip "
                    f"deadlock)"))
                break
    return findings

"""APX6xx cost tier — abstract HBM-traffic / communication / FLOP
interpreter over registered trace entries.

Every headline claim in BASELINE.md is a roofline argument: r7 prices
the optimizer ladder in GB/step, r8 derives the decode tokens/s ceiling
from a ~2.3 GB/step HBM read. A jaxpr is a complete statement of what a
step reads, writes, and communicates, so this module *computes* those
bytes per registered entrypoint and ``budgets.py`` gates them against a
committed manifest (APX601-604).

The cost model, per entry (all numbers static, from abstract shapes):

- **read bytes** — the sum over the traced program's top-level inputs
  (invars + closed-over consts). This is the roofline convention: each
  operand is charged ONCE per step, regardless of how many equations
  touch it (XLA re-reads inside a step are a fusion question, not a
  footprint question).
- **write bytes** — the sum over top-level outputs, EXCEPT outputs
  absorbed by a ``pjit`` donation (``donate_argnums``): donation is
  what lets XLA lower a cache update in place, so a donated output is
  charged only its *delta* — the bytes of ``dynamic_update_slice``/
  ``scatter`` update operands inside donated bodies, times loop trip
  counts. A donated KV cache therefore counts once (its read), not
  twice. Pallas ``input_output_aliases`` outputs deliberately still
  charge the full write: the kernel physically rewrites every byte of
  the aliased buffer (r7's flat-optimizer hand math reads g+p+m+v and
  writes p+m+v — aliasing saves the *allocation*, not the traffic).
- **peak live bytes** — a liveness walk over equation order: inputs
  start resident, each equation's outputs join the live set (donation-
  absorbed outputs are free — they land in the donated input's buffer,
  which is kept resident instead), operands are released after their
  last use. Sub-jaxprs (scan/cond/pjit bodies) contribute their inner
  peak minus their inputs as a transient. An upper-ish bound under the
  no-rematerialization schedule XLA actually emits for these programs.
- **collective bytes** — per collective primitive, reusing APX511's
  per-rank schedule simulator: the rank-0 footprint of each
  ``shard_map`` body (which already resolves loop structure and
  per-rank conds) now carries each collective's operand bytes, and the
  fold prices ``bytes x mesh-axis size`` for psum/all_gather/
  reduce_scatter-style rendezvous and ``bytes x hop count`` (the
  permutation's pair count) for ``ppermute``, times loop trip counts.
- **flops** — ``dot_general`` (2·batch·M·N·K from the dimension
  numbers) and ``conv_general_dilated`` (2·out_elems·kernel_window),
  times loop trip counts and pallas grid sizes; everything else is
  free. Arithmetic intensity = flops / total HBM bytes.

Loop conventions: ``scan`` multiplies by its static length; ``while``
counts one iteration (trip counts are dynamic — the manifest pins the
per-iteration cost); ``cond`` takes the most expensive branch.
"""

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from apex_tpu.lint.traced import jaxprlib as jl
from apex_tpu.lint.traced.aliases import _LAYOUT_PRESERVING

# update-primitive -> index of the update operand whose bytes are the
# in-place write delta (operand layouts: dus(operand, update, *starts),
# scatter(operand, indices, updates))
_UPDATE_OPERAND = {
    "dynamic_update_slice": 1,
    "scatter": 2,
    "scatter-add": 2,
    "scatter-mul": 2,
    "scatter-min": 2,
    "scatter-max": 2,
}


@dataclass
class CostReport:
    """Static per-entry cost summary; all byte counts are per step."""
    entry: str
    module: str  # file path of the module the entry exercises
    read_bytes: int = 0
    write_bytes: int = 0        # full-charged (non-donated) outputs
    delta_write_bytes: int = 0  # in-place update traffic under donation
    peak_live_bytes: int = 0
    flops: int = 0
    per_collective: Dict[str, int] = field(default_factory=dict)

    @property
    def collective_bytes(self) -> int:
        return sum(self.per_collective.values())

    @property
    def hbm_total_bytes(self) -> int:
        return self.read_bytes + self.write_bytes + self.delta_write_bytes

    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / max(self.hbm_total_bytes, 1)

    def as_dict(self) -> dict:
        return {
            "entry": self.entry,
            "module": self.module,
            "read_bytes": int(self.read_bytes),
            "write_bytes": int(self.write_bytes),
            "delta_write_bytes": int(self.delta_write_bytes),
            "hbm_total_bytes": int(self.hbm_total_bytes),
            "peak_live_bytes": int(self.peak_live_bytes),
            "collective_bytes": int(self.collective_bytes),
            "per_collective": {k: int(v)
                               for k, v in sorted(self.per_collective.items())},
            "flops": int(self.flops),
            "arithmetic_intensity": round(self.arithmetic_intensity, 3),
        }


def _donation_pairs(eqn) -> List[tuple]:
    """(in_idx, out_idx) pairs a pjit donation actually lands in — the
    same greedy shape/dtype matching XLA (and APX512) applies: each
    output absorbs at most one donated input."""
    donated = eqn.params.get("donated_invars") or ()
    pairs: List[tuple] = []
    if not any(donated):
        return pairs
    taken = [False] * len(eqn.outvars)
    for in_idx, is_donated in enumerate(donated):
        if not is_donated:
            continue
        op_aval = eqn.invars[in_idx].aval
        for out_idx, out in enumerate(eqn.outvars):
            if taken[out_idx]:
                continue
            if (getattr(out.aval, "shape", None) == getattr(
                    op_aval, "shape", None)
                    and getattr(out.aval, "dtype", None) == getattr(
                        op_aval, "dtype", None)):
                taken[out_idx] = True
                pairs.append((in_idx, out_idx))
                break
    return pairs


def _scan_length(eqn) -> int:
    try:
        return max(1, int(eqn.params.get("length")))
    except (TypeError, ValueError):
        return 1


def _pallas_grid(eqn) -> int:
    """Total grid size of a pallas_call (the kernel body runs once per
    grid point); 1 when the traced params don't expose it."""
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", None) if gm is not None else None
    if grid is None:
        grid = eqn.params.get("grid")
    n = 1
    try:
        for d in tuple(grid):
            n *= int(d)
    except (TypeError, ValueError):
        return 1
    return max(1, n)


def _dot_flops(eqn) -> int:
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lshape = tuple(eqn.invars[0].aval.shape)
    rshape = tuple(eqn.invars[1].aval.shape)
    batch = 1
    for d in lb:
        batch *= int(lshape[d])
    k = 1
    for d in lc:
        k *= int(lshape[d])
    m = 1
    for i, d in enumerate(lshape):
        if i not in lc and i not in lb:
            m *= int(d)
    n = 1
    for i, d in enumerate(rshape):
        if i not in rc and i not in rb:
            n *= int(d)
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    out_elems = 1
    for d in eqn.outvars[0].aval.shape:
        out_elems *= int(d)
    rhs_elems = 1
    for d in eqn.invars[1].aval.shape:
        rhs_elems *= int(d)
    dn = eqn.params.get("dimension_numbers")
    out_feature_dim = getattr(dn, "rhs_spec", (0,))[0] if dn else 0
    try:
        out_ch = int(eqn.invars[1].aval.shape[out_feature_dim])
    except (IndexError, TypeError):
        out_ch = 1
    # window per output element = kernel elems per output channel
    window = rhs_elems // max(out_ch, 1)
    return 2 * out_elems * window


def _fold_footprint(fp, mult: int, axis_sizes: Dict[str, int],
                    coll: Dict[str, int]) -> None:
    """Price an APX511 footprint: each collective carries its operand
    bytes (item[4]); rendezvous collectives scale by the product of
    their mesh-axis sizes, ppermute by its hop count."""
    for item in fp:
        if item[0] == "coll":
            name, axes, extra = item[1], item[2], item[3]
            nbytes = item[4] if len(item) > 4 else 0
            if name == "ppermute" and extra:
                vol = nbytes * len(extra[0])
            else:
                size = 1
                for ax in axes:
                    size *= int(axis_sizes.get(ax, 1))
                vol = nbytes * size
            coll[name] = coll.get(name, 0) + mult * vol
        elif item[0] == "scan":
            length = item[1]
            try:
                length = max(1, int(length))
            except (TypeError, ValueError):
                length = 1
            _fold_footprint(item[2], mult * length, axis_sizes, coll)
        elif item[0] == "while":
            _fold_footprint(item[1], mult, axis_sizes, coll)
            _fold_footprint(item[2], mult, axis_sizes, coll)


def _collective_volume(eqn, mult: int, acc: dict) -> None:
    from apex_tpu.lint.traced import schedule

    mesh = eqn.params.get("mesh")
    try:
        axis_sizes = dict(mesh.shape)
    except Exception:  # noqa: BLE001 - abstract mesh; price axes at 1
        axis_sizes = {}
    rank0 = {ax: 0 for ax in axis_sizes}
    try:
        fp = schedule._footprint(eqn.params["jaxpr"], {}, rank0)
    except Exception:  # noqa: BLE001 - unverifiable body prices at 0
        return
    _fold_footprint(fp, mult, axis_sizes, acc["coll"])


def _walk(jaxpr_like, mult: int, in_donated: bool, in_shard_map: bool,
          acc: dict) -> None:
    """Accumulate flops, in-place update deltas, and collective volume
    over one jaxpr, scaled by the enclosing loop multiplier."""
    jaxpr = jl.open_jaxpr(jaxpr_like)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            acc["flops"] += mult * _dot_flops(eqn)
            continue
        if name == "conv_general_dilated":
            acc["flops"] += mult * _conv_flops(eqn)
            continue
        if name in _UPDATE_OPERAND:
            if in_donated:
                idx = _UPDATE_OPERAND[name]
                if idx < len(eqn.invars):
                    acc["delta"] += mult * jl.aval_bytes(
                        eqn.invars[idx].aval)
            continue
        if name == "shard_map":
            if not in_shard_map:
                _collective_volume(eqn, mult, acc)
            _walk(eqn.params["jaxpr"], mult, in_donated, True, acc)
            continue
        if name == "scan":
            _walk(eqn.params["jaxpr"], mult * _scan_length(eqn),
                  in_donated, in_shard_map, acc)
            continue
        if name == "cond":
            best: Optional[dict] = None
            for _, sub in jl.sub_jaxprs(eqn):
                branch = {"flops": 0, "delta": 0, "coll": {}}
                _walk(sub, mult, in_donated, in_shard_map, branch)
                if best is None or (branch["flops"] + branch["delta"]
                                    > best["flops"] + best["delta"]):
                    best = branch
            if best is not None:
                acc["flops"] += best["flops"]
                acc["delta"] += best["delta"]
                for k, v in best["coll"].items():
                    acc["coll"][k] = acc["coll"].get(k, 0) + v
            continue
        if name == "pjit":
            donated = in_donated or any(
                eqn.params.get("donated_invars") or ())
            for _, sub in jl.sub_jaxprs(eqn):
                _walk(sub, mult, donated, in_shard_map, acc)
            continue
        if name == "pallas_call":
            grid = _pallas_grid(eqn)
            for _, sub in jl.sub_jaxprs(eqn):
                _walk(sub, mult * grid, in_donated, in_shard_map, acc)
            continue
        for _, sub in jl.sub_jaxprs(eqn):
            _walk(sub, mult, in_donated, in_shard_map, acc)


def _peak_live(jaxpr_like, inplace_out=frozenset(), depth: int = 0) -> int:
    """Liveness walk over equation order; see module doc."""
    if depth > 16:
        return 0
    jaxpr = jl.open_jaxpr(jaxpr_like)
    producers = {ov: e for e in jaxpr.eqns for ov in e.outvars}

    # outputs backed by a donated input's buffer are free: chase each
    # back through layout-preserving views to the var that fills it
    credit = set()
    for ov in inplace_out:
        v, hops = ov, 0
        while True:
            credit.add(v)
            e = producers.get(v)
            if (e is None or e.primitive.name not in _LAYOUT_PRESERVING
                    or not e.invars or jl.is_literal(e.invars[0])):
                break
            v = e.invars[0]
            hops += 1
            if hops > 32:
                break

    immortal = {v for v in jaxpr.outvars if not jl.is_literal(v)}
    for e in jaxpr.eqns:
        if e.primitive.name == "pjit":
            for in_idx, _ in _donation_pairs(e):
                if not jl.is_literal(e.invars[in_idx]):
                    # the donated buffer IS the output: never released
                    immortal.add(e.invars[in_idx])

    last_use: Dict[object, int] = {}
    for i, e in enumerate(jaxpr.eqns):
        for v in e.invars:
            if not jl.is_literal(v):
                last_use[v] = i

    start = {v for v in list(jaxpr.invars) + list(jaxpr.constvars)}
    cur = sum(jl.aval_bytes(v.aval) for v in start)
    peak = cur
    released = set()
    for i, e in enumerate(jaxpr.eqns):
        inplace_idx = set()
        extra = 0
        if e.primitive.name == "pjit":
            pairs = _donation_pairs(e)
            inplace_idx = {oi for _, oi in pairs}
            body = e.params.get("jaxpr")
            if body is not None:
                bj = jl.open_jaxpr(body)
                inner_inplace = frozenset(
                    bj.outvars[oi] for _, oi in pairs
                    if oi < len(bj.outvars)
                    and not jl.is_literal(bj.outvars[oi]))
                inner = _peak_live(body, inner_inplace, depth + 1)
                inputs = sum(jl.aval_bytes(v.aval) for v in e.invars
                             if not jl.is_literal(v))
                extra = max(0, inner - inputs)
        else:
            inputs = sum(jl.aval_bytes(v.aval) for v in e.invars
                         if not jl.is_literal(v))
            for _, sub in jl.sub_jaxprs(e):
                extra = max(extra,
                            _peak_live(sub, frozenset(), depth + 1)
                            - inputs)
            extra = max(0, extra)
        produced = 0
        for oi, ov in enumerate(e.outvars):
            if ov in credit or oi in inplace_idx:
                continue
            produced += jl.aval_bytes(ov.aval)
        cur += produced
        peak = max(peak, cur + extra)
        for v in {v for v in e.invars if not jl.is_literal(v)}:
            if v in immortal or v in released or v in credit:
                continue
            if last_use.get(v) == i:
                released.add(v)
                cur -= jl.aval_bytes(v.aval)
    return peak


def compute(closed, path: str, entry: str) -> CostReport:
    """Cost report for one traced entry (output of jax.make_jaxpr)."""
    jaxpr = jl.open_jaxpr(closed)

    seen = set()
    read = 0
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if v in seen:
            continue
        seen.add(v)
        read += jl.aval_bytes(v.aval)

    # top-level outputs absorbed by a donation, propagated forward
    # through layout-preserving views to the jaxpr outvars
    inplace = set()
    for e in jaxpr.eqns:
        if e.primitive.name == "pjit":
            for _, out_idx in _donation_pairs(e):
                inplace.add(e.outvars[out_idx])
    changed = True
    while changed:
        changed = False
        for e in jaxpr.eqns:
            if (e.primitive.name in _LAYOUT_PRESERVING and e.invars
                    and not jl.is_literal(e.invars[0])
                    and e.invars[0] in inplace):
                for ov in e.outvars:
                    if ov not in inplace:
                        inplace.add(ov)
                        changed = True

    write = 0
    for v in jaxpr.outvars:
        if jl.is_literal(v) or v in inplace:
            continue
        write += jl.aval_bytes(v.aval)

    acc = {"flops": 0, "delta": 0, "coll": {}}
    _walk(jaxpr, 1, False, False, acc)
    peak = _peak_live(jaxpr)

    return CostReport(
        entry=entry, module=path, read_bytes=read, write_bytes=write,
        delta_write_bytes=acc["delta"], peak_live_bytes=peak,
        flops=acc["flops"], per_collective=acc["coll"])


def render_table(reports: List[CostReport]) -> str:
    """The ``--cost --report`` JSON payload."""
    return json.dumps(
        {"entries": [r.as_dict() for r in
                     sorted(reports, key=lambda r: r.entry)]},
        indent=2, sort_keys=True)

"""Trace-tier entry registry and driver.

A :class:`TraceEntry` names a *traceable entrypoint* — a representative
invocation of a kernel, an optimizer, an amp-wrapped train step, or a
parallel schedule — and the jaxpr-level verifiers to run over it. The
driver traces each entry under ``jax.make_jaxpr`` (abstract only, no
compile, CPU-safe) and dispatches to the APX5xx checkers; an entry that
fails to trace at all is an APX100 finding, never a silent skip (same
contract as the APX102 VMEM registry).

Builder conventions:

- ``build()`` returns ``(fn, args)`` where args are
  ``jax.ShapeDtypeStruct`` trees — nothing is materialized;
- entries with the ``amp`` check make ``fn``'s FIRST flat argument the
  loss-scale scalar and return ``(protected_state, aux)`` where
  ``protected_state`` is the tree of optimizer-state writes (new
  params + optimizer state) — :func:`precision.check_amp` seeds and
  reads taint by those positions;
- entries that need the global mesh set ``mesh`` to a thunk calling
  ``parallel_state.initialize_model_parallel``; the driver snapshots
  and restores the parallel state around every entry.

The registry needs the 8-virtual-device CPU world the test rig uses
(pipeline/TP/context entries shard over it); ``ensure_cpu_devices``
arranges that BEFORE first backend use, falling back to ``XLA_FLAGS``
on older jax, and degrades to APX100 findings for mesh entries when the
backend was already initialized too small.
"""

import functools
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from apex_tpu.lint import Finding

_DEFAULT_DEVICES = 8


@dataclass
class TraceEntry:
    name: str
    module: str  # dotted module whose contract this entry exercises
    build: Callable[[], Tuple[Callable, tuple]]
    checks: Tuple[str, ...] = ("precision", "memory")
    mesh: Optional[Callable[[], None]] = None
    min_devices: int = 1
    min_alias_pairs: int = 0
    blowup_factor: float = 8.0
    blowup_floor: int = 1 << 20


def ensure_cpu_devices(n: int = _DEFAULT_DEVICES) -> int:
    """Best-effort: give this process an ``n``-device CPU world.

    Only effective before the jax backend initializes (the lint CLI
    calls it first thing; under pytest the conftest has already done
    the equivalent). Afterwards it is a no-op and the caller sees the
    actual device count.
    """
    import os

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up; keep going
        pass
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:  # noqa: BLE001 - older jax: XLA flag, read at init
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}")
    return jax.device_count()


def _snapshot_parallel_state():
    from apex_tpu.transformer import parallel_state as ps

    return (ps._MESH,
            ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE,
            ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK,
            ps._PIPELINE_MODEL_PARALLEL_SPLIT_RANK)


def _restore_parallel_state(snap) -> None:
    from apex_tpu.transformer import parallel_state as ps

    (ps._MESH,
     ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE,
     ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK,
     ps._PIPELINE_MODEL_PARALLEL_SPLIT_RANK) = snap


def _module_path(dotted: str) -> str:
    import importlib

    try:
        return importlib.import_module(dotted).__file__ or dotted
    except Exception:  # noqa: BLE001
        return dotted


def run_entries(entries: List[TraceEntry], *, run_checks: bool = True,
                cost_out: Optional[list] = None) -> List[Finding]:
    """Trace every entry and run its checks; APX100 on trace failure.

    Each entry is traced exactly once. With ``run_checks`` the APX5xx
    verifiers run over the jaxpr; with ``cost_out`` a
    :class:`~apex_tpu.lint.traced.cost.CostReport` per entry is
    appended to that list (APX100 if cost analysis itself fails) — the
    ``--trace --cost`` CLI combination shares the single trace.
    """
    ensure_cpu_devices()
    import jax

    from apex_tpu.lint.traced import aliases, memory, precision, schedule

    findings: List[Finding] = []
    for e in entries:
        path = _module_path(e.module)
        snap = _snapshot_parallel_state()
        try:
            try:
                have = jax.device_count()
                if have < e.min_devices:
                    raise RuntimeError(
                        f"needs {e.min_devices} devices, have {have} "
                        f"(backend initialized before ensure_cpu_devices)")
                if e.mesh is not None:
                    e.mesh()
                fn, args = e.build()
                closed, out_shape = jax.make_jaxpr(
                    fn, return_shape=True)(*args)
            finally:
                _restore_parallel_state(snap)
        except Exception as exc:  # noqa: BLE001 - surfaced as a finding
            findings.append(Finding(
                "APX100", path, 1,
                f"trace entry '{e.name}' failed to trace: "
                f"{type(exc).__name__}: {exc}"))
            continue

        if cost_out is not None:
            from apex_tpu.lint.traced import cost

            try:
                cost_out.append(cost.compute(closed, path, e.name))
            except Exception as exc:  # noqa: BLE001 - surfaced
                findings.append(Finding(
                    "APX100", path, 1,
                    f"trace entry '{e.name}' cost analysis failed: "
                    f"{type(exc).__name__}: {exc}"))

        if not run_checks:
            continue
        if "precision" in e.checks:
            findings.extend(precision.check_reductions(closed, path, e.name))
        if "amp" in e.checks:
            prot = out_shape[0] if isinstance(out_shape, tuple) else out_shape
            n_prot = len(jax.tree_util.tree_leaves(prot))
            findings.extend(precision.check_amp(closed, path, e.name,
                                                n_prot))
        if "memory" in e.checks:
            findings.extend(memory.check(closed, path, e.name,
                                         factor=e.blowup_factor,
                                         floor=e.blowup_floor))
        if "schedule" in e.checks:
            findings.extend(schedule.check(closed, path, e.name))
        if "aliases" in e.checks:
            findings.extend(aliases.check(
                closed, path, e.name, min_alias_pairs=e.min_alias_pairs))
    return findings


# ---------------------------------------------------------------------------
# registered repo entrypoints
# ---------------------------------------------------------------------------

def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _flash_entry(d, dtype, seq):
    def build():
        import jax
        import jax.numpy as jnp

        from apex_tpu.transformer.functional.flash_attention import (
            flash_attention,
        )

        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=True, use_kernel=True)
            # squared so the cotangent is data-dependent, not a
            # broadcast-of-ones (which would trip APX503 on the harness)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        fn = lambda q, k, v: jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
        shape = (1, 2, seq, d)
        return fn, (_sds(shape, dtype),) * 3

    return build


def _ln_entry(h, rms=False):
    def build():
        import importlib

        import jax
        import jax.numpy as jnp

        fln = importlib.import_module(
            "apex_tpu.normalization.fused_layer_norm")

        if rms:
            def loss(x, w):
                y = fln.fused_rms_norm_affine(x, w, (h,))
                return jnp.sum(y.astype(jnp.float32) ** 2)
            args = (_sds((2048, h), "float32"), _sds((h,), "float32"))
            return (lambda *a: jax.value_and_grad(loss, (0, 1))(*a)), args

        def loss(x, w, b):
            y = fln.fused_layer_norm_affine(x, w, b, (h,))
            return jnp.sum(y.astype(jnp.float32) ** 2)
        args = (_sds((2048, h), "float32"), _sds((h,), "float32"),
                _sds((h,), "float32"))
        return (lambda *a: jax.value_and_grad(loss, (0, 1, 2))(*a)), args

    return build


def _xentropy_entry():
    def build():
        import jax

        from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

        def loss(logits, labels):
            return softmax_cross_entropy_loss(logits, labels).mean()

        fn = lambda lg, lb: jax.value_and_grad(loss)(lg, lb)
        return fn, (_sds((1024, 512), "float32"), _sds((1024,), "int32"))

    return build


def _flat_entry(which):
    rows = 8192  # aligned to the block multiple: no pad, alias survives

    def build():
        import functools as ft

        from apex_tpu.multi_tensor_apply import kernels as K

        buf = _sds((rows, 128), "float32")
        m16 = _sds((rows, 128), "bfloat16")
        ids = _sds((rows // 8,), "int32")
        if which == "adam":
            fn = ft.partial(K.flat_adam, lr=1e-3, beta1=0.9, beta2=0.99,
                            eps=1e-8, step=1, weight_decay=0.01,
                            interpret=True)
            return fn, (buf, buf, buf, buf)
        if which == "sgd":
            fn = ft.partial(K.flat_sgd, lr=1e-3, momentum=0.9,
                            dampening=0.0, weight_decay=0.0,
                            nesterov=False, wd_after_momentum=False,
                            first_run=True, interpret=True)
            return fn, (buf, buf, m16)
        if which == "lamb":
            fn = ft.partial(K.flat_lamb, lr=1e-3, beta1=0.9, beta2=0.99,
                            eps=1e-8, step=1, weight_decay=0.01,
                            num_tensors=4, interpret=True)
            return fn, (buf, buf, m16, buf, ids)
        if which == "adagrad":
            fn = ft.partial(K.flat_adagrad, lr=1e-3, eps=1e-8,
                            weight_decay=0.0, interpret=True)
            return fn, (buf, buf, buf)
        fn = ft.partial(K.flat_novograd, lr=1e-3, beta1=0.9,
                        beta2=0.99, eps=1e-8, step=1, weight_decay=0.0,
                        num_tensors=4, interpret=True)
        return fn, (buf, buf, m16, _sds((4,), "float32"), ids)

    return build


def _fused_adam_tree_entry():
    def build():
        import jax

        from apex_tpu.optimizers.fused_adam import FusedAdam

        opt = FusedAdam(lr=1e-3, use_flat_kernel=False)
        params = {"w": _sds((256, 128), "float32"),
                  "b": _sds((128,), "float32")}
        state = jax.eval_shape(opt.init, params)

        def step(grads, params, state):
            return opt.step(grads, params, state)

        return step, (params, params, state)

    return build


def _amp_o2_step_entry(model):
    """O2 amp train step over a tiny model; the APX502 subject.

    fn layout (the check_amp convention): first arg = loss-scale
    scalar, first output = (new master params, new optimizer state).
    """
    def build():
        import jax
        import jax.numpy as jnp

        from apex_tpu import amp
        from apex_tpu.amp.scaler import LossScalerState
        from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam

        h = amp.initialize("O2", verbosity=0, loss_scale="dynamic")
        opt = FusedAdam(lr=1e-3, use_flat_kernel=False)

        if model == "bert":
            from apex_tpu.models.bert import (
                apply_bert, bert_tiny, init_bert, mlm_loss,
            )

            cfg = bert_tiny()
            master = jax.eval_shape(
                lambda k: init_bert(k, cfg), jax.random.PRNGKey(0))
            batch = {"ids": _sds((2, 32), "int32"),
                     "labels": _sds((2, 32), "int32")}

            def loss_fn(p, b):
                out = apply_bert(p, cfg, b["ids"])
                mask = jnp.ones_like(b["labels"], jnp.float32)
                return mlm_loss(out["mlm_logits"], b["labels"], mask)
        else:
            from apex_tpu.models.gpt import (
                gpt_loss_unsharded, gpt_tiny, init_gpt,
            )

            cfg = gpt_tiny()
            master = jax.eval_shape(
                lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
            batch = {"ids": _sds((2, 32), "int32"),
                     "labels": _sds((2, 32), "int32")}

            def loss_fn(p, b):
                return gpt_loss_unsharded(p, cfg, b["ids"], b["labels"])

        mstate = jax.eval_shape(opt.init, master)

        def step(loss_scale, master, m, v, stepc, batch):
            state = LossScalerState(
                loss_scale=loss_scale,
                unskipped=jnp.zeros((), jnp.int32),
                overflows=jnp.zeros((), jnp.int32))
            params = h.cast_model(master)
            loss, grads, found_inf, new_state = h.value_and_grad(
                loss_fn)(params, state, batch)
            new_master, new_mstate = opt.step(
                grads, master, AdamState(stepc, m, v),
                found_inf=found_inf)
            return (new_master, new_mstate), (loss, new_state.loss_scale)

        args = (_sds((), "float32"), master, mstate.m, mstate.v,
                _sds((), mstate.step.dtype), batch)
        return step, args

    return build


# --- tiny pipeline harness (mirrors tests/L0/run_transformer) ---------------

_PP_VOCAB, _PP_SEQ, _PP_HIDDEN, _PP_FF = 64, 8, 16, 32


def _pp_model():
    import jax
    import jax.numpy as jnp

    from apex_tpu.transformer.pipeline_parallel import PipelineModel

    def embed_fn(p, mb):
        x = p["word"][mb["ids"]]
        return x + p["pos"][None, : x.shape[1]]

    def stage_fn(p, x):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        h = (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["ln_w"] + p["ln_b"]
        h = jax.nn.gelu(h @ p["fc1"] + p["b1"]) @ p["fc2"] + p["b2"]
        return x + h

    def loss_fn(p, x, mb):
        logits = x @ p["proj"] + p["bias"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, mb["labels"][..., None], -1)[..., 0]
        return -ll.mean()

    return PipelineModel(embed_fn, stage_fn, loss_fn)


def _pp_args(n_stages, batch, stage_lead=()):
    v, s, hd, ff = _PP_VOCAB, _PP_SEQ, _PP_HIDDEN, _PP_FF
    params = {
        "embed": {"word": _sds((v, hd), "float32"),
                  "pos": _sds((s, hd), "float32")},
        "stages": {
            "ln_w": _sds(stage_lead + (n_stages, hd), "float32"),
            "ln_b": _sds(stage_lead + (n_stages, hd), "float32"),
            "fc1": _sds(stage_lead + (n_stages, hd, ff), "float32"),
            "b1": _sds(stage_lead + (n_stages, ff), "float32"),
            "fc2": _sds(stage_lead + (n_stages, ff, hd), "float32"),
            "b2": _sds(stage_lead + (n_stages, hd), "float32"),
        },
        "head": {"proj": _sds((hd, v), "float32"),
                 "bias": _sds((v,), "float32")},
    }
    mb = {"ids": _sds((batch, s), "int32"),
          "labels": _sds((batch, s), "int32")}
    return params, mb


def _pp_1f1b_entry(pp, n_mb):
    def build():
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state as ps
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_without_interleaving,
        )

        model = _pp_model()
        params, mb = _pp_args(pp, 2 * n_mb)
        tree_spec = {"embed": P(), "stages": P(ps.PIPE_AXIS), "head": P()}
        fn = ps.shard_map(
            lambda p, b: forward_backward_pipelining_without_interleaving(
                model, p, b, num_microbatches=n_mb),
            in_specs=(tree_spec, P()),
            out_specs=(P(), tree_spec))
        return fn, (params, mb)

    return build


def _pp_interleaved_entry(pp, vpp, n_mb):
    def build():
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state as ps
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving,
        )

        model = _pp_model()
        params, mb = _pp_args(pp, 2 * n_mb, stage_lead=(vpp,))
        tree_spec = {"embed": P(), "stages": P(None, ps.PIPE_AXIS),
                     "head": P()}
        fn = ps.shard_map(
            lambda p, b: forward_backward_pipelining_with_interleaving(
                model, p, b, num_microbatches=n_mb),
            in_specs=(tree_spec, P()),
            out_specs=(P(), tree_spec))
        return fn, (params, mb)

    return build


def _pp_sequential_entry():
    def build():
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_no_pipelining,
        )

        model = _pp_model()
        params, mb = _pp_args(3, 4)
        fn = lambda p, b: forward_backward_no_pipelining(
            model, p, b, num_microbatches=2)
        return fn, (params, mb)

    return build


def _tp_block_entry(tp):
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state as ps
        from apex_tpu.transformer import tensor_parallel as tpmod

        col = tpmod.ColumnParallelLinear(32, 64, gather_output=False)
        row = tpmod.RowParallelLinear(64, 32, input_is_parallel=True)

        def loss(cp, rp, x):
            y = row.apply(rp, jax.nn.gelu(col.apply(cp, x)))
            return jnp.sum((y.astype(jnp.float32)) ** 2)

        fn = ps.shard_map(
            lambda cp, rp, x: jax.value_and_grad(loss, (0, 1))(cp, rp, x),
            in_specs=(col.partition_specs(), row.partition_specs(), P()),
            out_specs=(P(), (col.partition_specs(),
                             row.partition_specs())))
        cp = jax.eval_shape(lambda k: col.init(k), jax.random.PRNGKey(0))
        rp = jax.eval_shape(lambda k: row.init(k), jax.random.PRNGKey(1))
        return fn, (cp, rp, _sds((4, 32), "float32"))

    return build


def bottleneck_parts():
    """The spatial-parallel bottleneck halo exchange: conv stack whose
    width dim shards over ``context``, ring-ppermute halos at the shard
    edges. Returns ``(fn, args, in_specs)`` so the APX9xx scaling tier
    can re-stage it across swept ``cp`` sizes (the width of 16 divides
    every swept context size); the caller's mesh sets the ``context``
    axis size."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.bottleneck import spatial_parallel_bottleneck
    from apex_tpu.transformer import parallel_state as ps

    params = {"w1": _sds((1, 1, 8, 4), "float32"),
              "w2": _sds((3, 3, 4, 4), "float32"),
              "w3": _sds((1, 1, 4, 8), "float32")}
    # one spec per flattened operand (not a pytree-prefix P()) so the
    # APX703/903 taint walk sees the same operand count shard_map does
    in_specs = ({k: P() for k in sorted(params)},
                P(None, ps.CONTEXT_AXIS))
    fn = ps.shard_map(
        spatial_parallel_bottleneck,
        in_specs=in_specs,
        out_specs=P(None, ps.CONTEXT_AXIS))
    return fn, (params, _sds((2, 16, 5, 8), "float32")), in_specs


def _bottleneck_entry():
    def build():
        fn, args, _ = bottleneck_parts()
        return fn, args

    return build


def _serving_cfg():
    import dataclasses

    from apex_tpu.models.gpt import gpt_tiny

    return dataclasses.replace(gpt_tiny(), use_rope=True)


def _serving_args(cfg, num_slots=2, max_len=32):
    import functools as ft

    import jax

    from apex_tpu.models.gpt import init_gpt
    from apex_tpu.serving.cache import init_cache

    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(ft.partial(init_cache, cfg, num_slots, max_len))
    return params, cache


def _prefill_step_entry():
    def build():
        from apex_tpu.serving.decode import make_prefill_fn

        cfg = _serving_cfg()
        params, cache = _serving_args(cfg)
        fn = make_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 16), "int32"),
                    _sds((16,), "int32"), _sds((), "int32"))

    return build


def _decode_step_entry(tp=None):
    def build():
        from apex_tpu.serving.decode import make_decode_fn, make_tp_decode_fn

        cfg = _serving_cfg()
        params, cache = _serving_args(cfg)
        if tp is None:
            fn = make_decode_fn(cfg)
        else:
            from apex_tpu.models.gpt import GPTModel

            fn = make_tp_decode_fn(GPTModel(cfg, tp_size=tp))
        return fn, (params, cache, _sds((2,), "int32"), _sds((2,), "bool"))

    return build


def _prefill_step_bucketed_entry():
    """The ContinuousBatchingScheduler prefill path: a prompt padded up
    to the 32-token bucket rung, 4-slot pool (scheduler.pad_to_bucket
    + DecodeEngine per-bucket jitted step)."""
    def build():
        from apex_tpu.serving.decode import make_prefill_fn

        cfg = _serving_cfg()
        params, cache = _serving_args(cfg, num_slots=4, max_len=64)
        fn = make_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 32), "int32"),
                    _sds((32,), "int32"), _sds((), "int32"))

    return build


def _decode_step_learned_pos_entry():
    """Decode without RoPE — the learned-position-table gather variant
    of _block_decode (gpt_tiny defaults to use_rope=False)."""
    def build():
        from apex_tpu.models.gpt import gpt_tiny
        from apex_tpu.serving.decode import make_decode_fn

        cfg = gpt_tiny()
        params, cache = _serving_args(cfg)
        fn = make_decode_fn(cfg)
        return fn, (params, cache, _sds((2,), "int32"), _sds((2,), "bool"))

    return build


def _paged_serving_args(cfg, num_slots=2, max_len=32, num_pages=6,
                        page_size=16):
    import functools as ft

    import jax

    from apex_tpu.models.gpt import init_gpt
    from apex_tpu.serving.cache import init_paged_cache

    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(ft.partial(
        init_paged_cache, cfg, num_slots, max_len, num_pages, page_size))
    return params, cache


def _paged_prefill_step_entry():
    """Paged prefill: one 16-token bucket = one page tile scattered to
    ``write_pages`` plus the slot's block-table row — all four cache
    leaves (pool k/v, lengths, block tables) written in place."""
    def build():
        from apex_tpu.serving.decode import make_paged_prefill_fn

        cfg = _serving_cfg()
        params, cache = _paged_serving_args(cfg)
        fn = make_paged_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 16), "int32"),
                    _sds((16,), "int32"), _sds((), "int32"),
                    _sds((1,), "int32"), _sds((2,), "int32"))

    return build


def _chunk_prefill_step_entry():
    """Dense chunked prefill: one 16-token prompt chunk written at a
    dynamic start row (the scheduler's chunk_tokens bucket — exactly
    one executable per chunk size). Same 3-leaf cache donation as the
    monolithic prefill step."""
    def build():
        from apex_tpu.serving.decode import make_chunk_prefill_fn

        cfg = _serving_cfg()
        params, cache = _serving_args(cfg)
        fn = make_chunk_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 16), "int32"),
                    _sds((16,), "int32"), _sds((), "int32"),
                    _sds((), "int32"))

    return build


def _paged_chunk_prefill_step_entry():
    """Paged chunked prefill: a 16-token = one-page chunk scattered to
    ``write_pages`` while attention gathers through the slot's real
    ``gather_row`` (earlier chunks + shared prefix visible) and
    ``store_row`` lands in the block table — the same 4-leaf donated
    cache as monolithic paged prefill."""
    def build():
        from apex_tpu.serving.decode import make_paged_chunk_prefill_fn

        cfg = _serving_cfg()
        params, cache = _paged_serving_args(cfg)
        fn = make_paged_chunk_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 16), "int32"),
                    _sds((16,), "int32"), _sds((), "int32"),
                    _sds((), "int32"), _sds((1,), "int32"),
                    _sds((2,), "int32"), _sds((2,), "int32"))

    return build


def _paged_chunk_prefill_step_medium_entry():
    """r14 cost anchor: one 256-token chunk of a long prompt at the
    ragged medium pool shape (32 slots, s_max 512, page 64, bf16
    params). Its budgets.json row against the monolithic-prefill read
    pins the chunking price: ~chunk/S of the parameter+activation work
    plus the re-read of the cache written so far — the bytes the
    scheduler trades for bounded p99 inter-token latency."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig, init_gpt
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.decode import make_paged_chunk_prefill_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        fn = make_paged_chunk_prefill_fn(cfg)
        return fn, (params, cache, _sds((1, 256), "int32"),
                    _sds((256,), "int32"), _sds((), "int32"),
                    _sds((), "int32"), _sds((4,), "int32"),
                    _sds((8,), "int32"), _sds((8,), "int32"))

    return build


def _page_handoff_medium_entry():
    """r15 cost anchor: the receiver half of a disaggregated page
    handoff — ``serving.transfer.make_insert_pages_fn`` scattering one
    full prompt's tiles (8 pages x 64 tokens = a 512-token prompt)
    into the ragged medium pool (32 slots, s_max 512, page 64, bf16).
    The donated in-place scatter prices the handoff at ~the shipped
    tile bytes (2 x L x H x page x head_dim x 2 per page), which is
    what the BASELINE r15 verdict compares against a decode step's
    parameter read — the bytes disaggregation moves once per prompt to
    unblock every co-tenant decode tick."""
    def build():
        import functools as ft

        import jax

        from apex_tpu.models.gpt import GPTConfig
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.transfer import make_insert_pages_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        n = s_max // page  # one max-length prompt's page tile
        tile = _sds((cfg.num_layers, n, cfg.num_heads, page,
                     cfg.head_dim), "bfloat16")
        fn = make_insert_pages_fn()
        return fn, (cache, _sds((n,), "int32"), tile, tile)

    return build


def _page_reshard_medium_entry():
    """r17 cost anchor: the sender half of a DEVICE-TO-DEVICE page
    reshard — ``serving.transfer.make_reshard_extract_fn`` gathering
    one full prompt's tiles (8 pages x 64 tokens = a 512-token prompt)
    out of the ragged medium pool (32 slots, s_max 512, page 64, bf16)
    with the head axis sharded tp=2 over ``model``. The explicit tiled
    ``all_gather`` is the whole point of the entry: APX511's per-rank
    simulator verifies both ranks run the identical collective, and
    budgets.json pins the per-prompt collective volume ((tp-1)/tp of
    the tile bytes per rank on the ICI/DCN wire) that the pool
    router's per-link clock prices at ``ici_ticks_per_page`` /
    ``dcn_ticks_per_page`` — the spec-to-spec alternative to the host
    bounce's full gather + re-placement budgeted by
    ``gpt_page_handoff_medium``."""
    def build():
        import functools as ft

        import jax

        from apex_tpu.models.gpt import GPTConfig
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.transfer import make_reshard_extract_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        n = s_max // page  # one max-length prompt's page tile
        fn = make_reshard_extract_fn()
        return fn, (cache, _sds((n,), "int32"))

    return build


def _page_spill_extract_medium_entry():
    """r16 cost anchor: the sender half of a host-tier spill —
    ``serving.transfer.make_extract_pages_fn`` gathering one full
    prompt's tiles (8 pages x 64 tokens) out of the ragged medium pool
    (32 slots, s_max 512, page 64, bf16) on their way to the
    :class:`~apex_tpu.serving.paging.PrefixRegistry`. The gather
    prices a spill at ~the page tile bytes, the same per-page unit the
    r15 handoff pins — BASELINE r16 compares this against a decode
    step's parameter read to justify ``promote_ticks_per_page``."""
    def build():
        import functools as ft

        import jax

        from apex_tpu.models.gpt import GPTConfig
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.transfer import make_extract_pages_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        n = s_max // page
        fn = make_extract_pages_fn()
        return fn, (cache, _sds((n,), "int32"))

    return build


def _page_promote_insert_quant_medium_entry():
    """r16 cost anchor: a host-tier promotion into the INT8 pool —
    ``serving.transfer.make_insert_pages_quant_fn`` scattering one
    prompt's quantized tiles plus their per-page-per-head scale planes
    back into HBM. The int8 payload is half the bf16 handoff's bytes
    (the scale planes are noise: L x n x H fp32 values per side), which
    is the capacity-doubling arithmetic BASELINE r16 banks for BOTH
    tiers — the registry budgets bytes, so kv8 doubles its page count
    exactly as it does HBM's."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.transfer import make_insert_pages_quant_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page,
            jnp.int8))
        n = s_max // page
        tile = _sds((cfg.num_layers, n, cfg.num_heads, page,
                     cfg.head_dim), "int8")
        scale = _sds((cfg.num_layers, n, cfg.num_heads), "float32")
        fn = make_insert_pages_quant_fn()
        return fn, (cache, _sds((n,), "int32"), tile, tile, scale,
                    scale)

    return build


def _paged_decode_step_entry(tp=None):
    """Paged decode: scatter the new row through the block table, then
    gather each slot's pages and attend (APX105 pins this file's
    registration for the new gather/scatter entrypoints)."""
    def build():
        from apex_tpu.serving.decode import (
            make_paged_decode_fn, make_tp_paged_decode_fn,
        )

        cfg = _serving_cfg()
        params, cache = _paged_serving_args(cfg)
        if tp is None:
            fn = make_paged_decode_fn(cfg)
        else:
            from apex_tpu.models.gpt import GPTModel

            fn = make_tp_paged_decode_fn(GPTModel(cfg, tp_size=tp))
        return fn, (params, cache, _sds((2,), "int32"), _sds((2,), "bool"))

    return build


def _paged_decode_step_medium_ragged_entry():
    """The r10 paged counterpart of ``gpt_decode_step_medium``: same r8
    model shape and 32 slots, but the pool is sized to a RAGGED length
    ladder (uniform 32..512, page size 64) — Σ ceil(len/64) pages plus
    the two reserved ones — so the cost tier's K/V read term is
    proportional to tokens actually held instead of slots x S_max.
    Cost-tier only, like the dense medium entry."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig, init_gpt
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.decode import make_paged_decode_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        fn = make_paged_decode_fn(cfg)
        return fn, (params, cache, _sds((slots,), "int32"),
                    _sds((slots,), "bool"))

    return build


def _spec_verify_step_entry(tp=None):
    """Speculative verify: k+1 = 4 candidate positions per slot against
    the paged pool — k1 unrolled row scatters through the block table,
    then gather + per-query masked attend. Same 4-leaf cache donation
    as paged decode (lengths/block tables come back via the self-row
    rewrite, since verify leaves them numerically untouched)."""
    def build():
        from apex_tpu.serving.decode import (
            make_paged_verify_fn, make_tp_paged_verify_fn,
        )

        cfg = _serving_cfg()
        params, cache = _paged_serving_args(cfg)
        if tp is None:
            fn = make_paged_verify_fn(cfg)
        else:
            from apex_tpu.models.gpt import GPTModel

            fn = make_tp_paged_verify_fn(GPTModel(cfg, tp_size=tp))
        return fn, (params, cache, _sds((2, 4), "int32"))

    return build


def _spec_verify_step_medium_ragged_entry():
    """The verify step at the r10 ragged medium shape (32 slots, bf16
    params, uniform 32..512 ladder), k+1 = 4 positions per slot —
    cost-tier only. Its budgets.json row divided by the expected
    committed tokens per slot at the bench acceptance rate is the
    bytes/accepted-token headline BASELINE.md r11 prices against the
    plain-decode ``model_bytes_per_token``."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig, init_gpt
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.decode import make_paged_verify_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page))
        fn = make_paged_verify_fn(cfg)
        return fn, (params, cache, _sds((slots, 4), "int32"))

    return build


def _tree_verify_step_entry(tp=None):
    """Tree-attention verify: a k1 = 4-node draft grid per slot against
    the paged pool — the per-query linear mask of the spec verify
    replaced by the grid's ancestor-matrix columns. Same 4-leaf cache
    donation as the linear verify (lengths/block tables come back via
    the self-row rewrite)."""
    def build():
        from apex_tpu.serving.decode import (
            make_paged_tree_verify_fn, make_tp_paged_tree_verify_fn,
        )

        cfg = _serving_cfg()
        params, cache = _paged_serving_args(cfg)
        if tp is None:
            fn = make_paged_tree_verify_fn(cfg)
        else:
            from apex_tpu.models.gpt import GPTModel

            fn = make_tp_paged_tree_verify_fn(GPTModel(cfg, tp_size=tp))
        return fn, (params, cache, _sds((2, 4), "int32"),
                    _sds((2, 4), "int32"), _sds((2, 4, 4), "bool"))

    return build


def _draft_forward_step_entry():
    """The r13 draft-forward anchor: ``draft_gpt_medium`` decoding one
    greedy token per slot through its dense lockstep cache — 32 slots
    at the target's s_max = 512 plus DraftModel's chunk = 5 catch-up
    headroom, bf16 params. Its budgets.json row is the ``draft_bytes``
    numerator of the BASELINE r13 break-even condition; the ceiling is
    hand-tightened to < 3% of the target's per-step parameter read
    (the ``gpt_paged_decode_step_medium_ragged`` row)."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import draft_gpt_medium, init_gpt
        from apex_tpu.serving.cache import init_cache
        from apex_tpu.serving.decode import make_decode_fn

        cfg = draft_gpt_medium()
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(init_cache, cfg, 32, 512 + 5))
        fn = make_decode_fn(cfg)
        return fn, (params, cache, _sds((32,), "int32"),
                    _sds((32,), "bool"))

    return build


def _w8_matmul_entry():
    """The dequant-fused int8 matmul family (column/row apply + the
    output-channel-major logits head) traced standalone — APX501 proves
    the fp32 accumulation survives into the jaxpr, APX503 that the
    register dequant never materializes a blown-up fp32 weight copy."""
    def build():
        from apex_tpu.quant.kernels import w8_matmul, w8_matmul_nk

        def fn(x, wq, scale, bias, tq, tscale):
            h = w8_matmul(x, wq, scale, bias, out_dtype=x.dtype)
            return w8_matmul_nk(h, tq, tscale)

        return fn, (_sds((8, 256), "bfloat16"),
                    _sds((256, 512), "int8"), _sds((512,), "float32"),
                    _sds((512,), "float32"),
                    _sds((1024, 512), "int8"), _sds((1024,), "float32"))

    return build


def _quant_paged_serving_args(cfg, num_slots=2, max_len=32, num_pages=6,
                              page_size=16):
    """Weight-only int8 params (same tree paths, int8 kernels + fp32
    scales) over an int8 page pool with per-page-per-head scales."""
    import functools as ft

    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import init_gpt
    from apex_tpu.quant.params import quantize_params
    from apex_tpu.serving.cache import init_paged_cache

    params = quantize_params(jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0)))
    cache = jax.eval_shape(ft.partial(
        init_paged_cache, cfg, num_slots, max_len, num_pages, page_size,
        jnp.int8))
    return params, cache


def _quant_paged_step_entry(which):
    """w8 + kv8 paged serving steps: the int8 pool donates SIX leaves
    (pool k/v, lengths, block tables, k/v scales) — min_alias_pairs=6
    pins the widened donation."""
    def build():
        from apex_tpu.serving.decode import (
            make_paged_decode_fn, make_paged_prefill_fn,
            make_paged_verify_fn,
        )

        cfg = _serving_cfg()
        params, cache = _quant_paged_serving_args(cfg)
        if which == "prefill":
            fn = make_paged_prefill_fn(cfg, quantized=True)
            return fn, (params, cache, _sds((1, 16), "int32"),
                        _sds((16,), "int32"), _sds((), "int32"),
                        _sds((1,), "int32"), _sds((2,), "int32"))
        if which == "verify":
            fn = make_paged_verify_fn(cfg, quantized=True)
            return fn, (params, cache, _sds((2, 4), "int32"))
        fn = make_paged_decode_fn(cfg, quantized=True)
        return fn, (params, cache, _sds((2,), "int32"),
                    _sds((2,), "bool"))

    return build


def _w8_decode_step_tp2_entry():
    """Dense-cache decode under tp2 with int8 weights: the quantized
    tree shards by ``quant_partition_specs`` (scale specs derived from
    the bf16 table), the schedule check pins the collective order of
    the dequant-fused column/row/logits applies."""
    def build():
        import jax

        from apex_tpu.models.gpt import GPTModel, init_gpt
        from apex_tpu.quant.params import quantize_params
        from apex_tpu.serving.decode import make_tp_decode_fn

        cfg = _serving_cfg()
        params = quantize_params(jax.eval_shape(
            lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0)))
        _, cache = _serving_args(cfg)
        fn = make_tp_decode_fn(GPTModel(cfg, tp_size=2), quantized=True)
        return fn, (params, cache, _sds((2,), "int32"), _sds((2,), "bool"))

    return build


def _quant_paged_decode_medium_ragged_entry():
    """The r12 quantized twin of the ragged medium paged decode: int8
    params (fp32 scales) + int8 page pool at the identical ladder —
    its budgets.json row pins the halved byte claim (≤ 0.95 GB/step vs
    1.68 GB bf16, BASELINE.md r12). Cost-tier only."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig, init_gpt
        from apex_tpu.quant.params import quantize_params
        from apex_tpu.serving.cache import RESERVED_PAGES, init_paged_cache
        from apex_tpu.serving.decode import make_paged_decode_fn

        cfg = GPTConfig(use_rope=True)
        slots, s_max, page = 32, 512, 64
        lengths = [32 + round(i * (s_max - 32) / (slots - 1))
                   for i in range(slots)]
        num_pages = RESERVED_PAGES + sum(-(-l // page) for l in lengths)
        params = quantize_params(jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16),
            jax.random.PRNGKey(0)))
        cache = jax.eval_shape(ft.partial(
            init_paged_cache, cfg, slots, s_max, num_pages, page,
            jnp.int8))
        fn = make_paged_decode_fn(cfg, quantized=True)
        return fn, (params, cache, _sds((slots,), "int32"),
                    _sds((slots,), "bool"))

    return build


def _decode_step_medium_entry():
    """The BASELINE.md r8 roofline shape: gpt_medium-class decode, bf16
    params, 32 slots parked at depth 512 (the steady-state mid-cache
    occupancy the hand derivation prices). Cost-tier only — APX5xx
    already runs on the tiny-shape decode entries."""
    def build():
        import functools as ft

        import jax
        import jax.numpy as jnp

        from apex_tpu.models.gpt import GPTConfig, init_gpt
        from apex_tpu.serving.cache import init_cache
        from apex_tpu.serving.decode import make_decode_fn

        cfg = GPTConfig(use_rope=True)
        params = jax.eval_shape(
            lambda k: init_gpt(k, cfg, jnp.bfloat16), jax.random.PRNGKey(0))
        cache = jax.eval_shape(ft.partial(init_cache, cfg, 32, 512))
        fn = make_decode_fn(cfg)
        return fn, (params, cache, _sds((32,), "int32"),
                    _sds((32,), "bool"))

    return build


def _fused_softmax_entry():
    """Both fused-softmax pallas families (masked 4D + causal 3D),
    fwd+bwd through the custom_vjp."""
    def build():
        import jax
        import jax.numpy as jnp

        from apex_tpu.transformer.functional import fused_softmax as fs

        def loss(x, mask, x3):
            y = fs.scaled_masked_softmax(x, mask, scale=0.5)
            z = fs.scaled_upper_triang_masked_softmax(x3, scale=0.5)
            return (jnp.sum(y.astype(jnp.float32) ** 2)
                    + jnp.sum(z.astype(jnp.float32) ** 2))

        fn = lambda *a: jax.value_and_grad(loss, (0, 2))(*a)
        return fn, (_sds((2, 2, 128, 128), "bfloat16"),
                    _sds((2, 1, 128, 128), "int32"),
                    _sds((4, 128, 128), "bfloat16"))

    return build


def _flat_simple_entry(which):
    """The three non-optimizer flat kernels (scale / axpby / l2norm):
    pure streaming, no input_output_aliases, so no aliases check."""
    rows = 8192

    def build():
        import functools as ft

        from apex_tpu.multi_tensor_apply import kernels as K

        buf = _sds((rows, 128), "float32")
        if which == "scale":
            return ft.partial(K.flat_scale, scale=0.5,
                              interpret=True), (buf,)
        if which == "axpby":
            return (lambda x, y: K.flat_axpby(1.0, x, 2.0, y,
                                              interpret=True)), (buf, buf)
        return ft.partial(K.flat_l2norm, interpret=True), (buf,)

    return build


def _local_shapes(tree, specs, axis_sizes):
    """TP-local ShapeDtypeStructs: divide each dim of each leaf by the
    product of the mesh-axis sizes its spec entry names (the shard a
    rank sees inside shard_map)."""
    import jax

    def one(leaf, spec):
        shape = list(leaf.shape)
        for dim, entry in enumerate(tuple(spec)):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                shape[dim] //= axis_sizes.get(ax, 1)
        return jax.ShapeDtypeStruct(tuple(shape), leaf.dtype)

    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(one, tree, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def zero_parts(dp: int = 2, tp: int = 2):
    """The ROADMAP item-3 headline config at a parametric mesh shape:
    rule-table-sharded GPT train step, dp x tp, ZeRO optimizer state
    (bf16 m) row-sharded over ``(model, data)`` jointly. Returns
    ``(fn, args, in_specs)`` — the spec tree is consumed by the APX7xx
    sharded tier (APX703 checks the shard_map in_names against it), the
    ``(fn, args)`` pair by the plain trace/cost tiers, and the APX9xx
    scaling tier re-stages this builder at every swept ``(dp, tp)``
    shape. Everything sharded here derives from
    ``partition.gpt_rules()``; nothing is hand-specified — the caller's
    mesh must carry ``data`` axis size ``dp`` and ``model`` axis size
    ``tp``."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistributedAdamState, DistributedFusedAdam,
    )
    from apex_tpu.models.gpt import GPTModel, gpt_tiny, init_gpt
    from apex_tpu.partition import gpt_rules, match_partition_rules
    from apex_tpu.transformer import parallel_state as ps

    cfg = gpt_tiny()
    model = GPTModel(cfg, tp_size=tp)
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    specs = match_partition_rules(gpt_rules(), params)
    local_params = _local_shapes(params, specs, {ps.TENSOR_AXIS: tp})

    opt = DistributedFusedAdam(lr=1e-4, weight_decay=0.01, dp_size=dp,
                               m_dtype=jnp.bfloat16)
    # Flat ZeRO buffers are built from the TP-LOCAL param shard (each tp
    # rank optimizes only its own rows); at rest the global buffer
    # stacks the tp segments, hence leading rows tp * R_local and the
    # joint (model, data) row sharding from partition_spec().
    local_state = jax.eval_shape(opt.init, local_params)
    r_local = local_state.master.shape[0]
    state = DistributedAdamState(
        step=_sds((), local_state.step.dtype),
        master=_sds((tp * r_local, 128), local_state.master.dtype),
        m=_sds((tp * r_local, 128), local_state.m.dtype),
        v=_sds((tp * r_local, 128), local_state.v.dtype))
    zero_spec = opt.partition_spec(tensor_axis=ps.TENSOR_AXIS)

    def train_step(p, st, ids, labels):
        # local grads (check_vma=False): TP grads are already correct
        # per-shard, dp reduction happens in the optimizer's
        # psum_scatter; no separate DDP allreduce.
        loss, grads = jax.value_and_grad(model.loss)(p, ids, labels)
        new_p, new_st = opt.step(grads, p, st)
        return lax.pmean(loss, ps.DATA_AXIS), new_p, new_st

    in_specs = (specs, zero_spec, P(ps.DATA_AXIS), P(ps.DATA_AXIS))
    fn = ps.shard_map(train_step, in_specs=in_specs,
                      out_specs=(P(), specs, zero_spec))
    args = (params, state, _sds((2 * dp, 32), "int32"),
            _sds((2 * dp, 32), "int32"))
    return fn, args, in_specs


def zero_dp2xtp2_parts():
    """The dp2 x tp2 anchor shape of :func:`zero_parts` (the original
    ROADMAP item-3 headline config)."""
    return zero_parts(dp=2, tp=2)


def _zero_entry(dp, tp):
    def build():
        fn, args, _ = zero_parts(dp=dp, tp=tp)
        return fn, args

    return build


def _mesh(pp=1, vpp=None, tp=1, cp=1, n_devices=None):
    def setup():
        import jax

        from apex_tpu.transformer import parallel_state as ps

        devs = jax.devices()
        if n_devices is not None:
            devs = devs[:n_devices]
        ps.initialize_model_parallel(
            tensor_model_parallel_size_=tp,
            pipeline_model_parallel_size_=pp,
            virtual_pipeline_model_parallel_size_=vpp,
            context_parallel_size_=cp,
            devices=devs)

    return setup


def repo_entries() -> List[TraceEntry]:
    flash = "apex_tpu.transformer.functional.flash_attention"
    ln = "apex_tpu.normalization.fused_layer_norm"
    flat = "apex_tpu.multi_tensor_apply.kernels"
    sched = "apex_tpu.transformer.pipeline_parallel.schedules"
    entries = [
        TraceEntry("flash_d64_bf16_s512_fwd_bwd", flash,
                   _flash_entry(64, "bfloat16", 512)),
        TraceEntry("flash_d128_f32_s512_fwd_bwd", flash,
                   _flash_entry(128, "float32", 512)),
        TraceEntry("ln_h1024_fwd_bwd", ln, _ln_entry(1024)),
        TraceEntry("rms_h4096_fwd_bwd", ln, _ln_entry(4096, rms=True)),
        TraceEntry("xentropy_fwd_bwd", "apex_tpu.contrib.xentropy",
                   _xentropy_entry()),
        TraceEntry("flat_adam", flat, _flat_entry("adam"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        TraceEntry("flat_sgd", flat, _flat_entry("sgd"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=2),
        TraceEntry("flat_lamb", flat, _flat_entry("lamb"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=2),
        TraceEntry("flat_adagrad", flat, _flat_entry("adagrad"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=2),
        TraceEntry("flat_novograd", flat, _flat_entry("novograd"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=2),
        # tree path is per-leaf XLA math (no pallas kernels), so there
        # is deliberately no aliases check here — the flat_* entries
        # above carry the APX512 coverage
        TraceEntry("fused_adam_tree_step",
                   "apex_tpu.optimizers.fused_adam",
                   _fused_adam_tree_entry()),
        TraceEntry("amp_o2_bert_step", "apex_tpu.amp.frontend",
                   _amp_o2_step_entry("bert"),
                   checks=("precision", "amp", "memory")),
        TraceEntry("amp_o2_gpt_step", "apex_tpu.amp.frontend",
                   _amp_o2_step_entry("gpt"),
                   checks=("precision", "amp", "memory")),
        TraceEntry("tp_block_tp2", "apex_tpu.transformer.tensor_parallel",
                   _tp_block_entry(2),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(tp=2), min_devices=2),
        TraceEntry("pp_1f1b_pp4", sched, _pp_1f1b_entry(4, 8),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(pp=4, n_devices=4), min_devices=4),
        TraceEntry("pp_interleaved_pp2_vpp2", sched,
                   _pp_interleaved_entry(2, 2, 4),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(pp=2, vpp=2, n_devices=2), min_devices=2),
        TraceEntry("pp_no_pipelining_fp32_accum", sched,
                   _pp_sequential_entry()),
        # ROADMAP item 3 headline: dp2 x tp2 ZeRO train step, every
        # sharding derived from partition.gpt_rules(); the APX7xx tier
        # re-traces the same builder for its in_specs/schedule checks
        TraceEntry("gpt_tiny_dp2xtp2_zero",
                   "apex_tpu.contrib.optimizers.distributed_fused_adam",
                   _zero_entry(2, 2),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(tp=2, n_devices=4), min_devices=4),
        # ROADMAP item 5 payoff: the same rule-derived ZeRO step at the
        # dp4 x tp2 headline shape (the full 8-device world) — the
        # APX9xx scaling tier sweeps the builder across the whole
        # (dp, tp) grid; this entry pins the headline shape in the
        # APX5xx/6xx tiers too, with its own budgets.json row
        TraceEntry("gpt_tiny_dp4xtp2_zero",
                   "apex_tpu.contrib.optimizers.distributed_fused_adam",
                   _zero_entry(4, 2),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(tp=2, n_devices=8), min_devices=8),
        TraceEntry("bottleneck_spatial_cp2",
                   "apex_tpu.contrib.bottleneck.bottleneck",
                   _bottleneck_entry(),
                   checks=("precision", "memory", "schedule"),
                   mesh=_mesh(cp=2, n_devices=2), min_devices=2),
        # serving: the KV cache (k, v, lengths) is DONATED into both
        # jitted steps — min_alias_pairs=3 pins the donation (APX512's
        # pjit branch); a dropped donate_argnums re-allocates the whole
        # cache every decoded token
        TraceEntry("gpt_prefill_step", "apex_tpu.serving.decode",
                   _prefill_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        TraceEntry("gpt_decode_step", "apex_tpu.serving.decode",
                   _decode_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        TraceEntry("gpt_decode_step_tp2", "apex_tpu.serving.decode",
                   _decode_step_entry(tp=2),
                   checks=("precision", "memory", "schedule", "aliases"),
                   mesh=_mesh(tp=2), min_devices=2, min_alias_pairs=3),
        TraceEntry("gpt_prefill_step_bucketed", "apex_tpu.serving.decode",
                   _prefill_step_bucketed_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        TraceEntry("gpt_decode_step_learned_pos", "apex_tpu.serving.decode",
                   _decode_step_learned_pos_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        # paged serving: 4 donated leaves (pool k/v, lengths, block
        # tables) — min_alias_pairs=4 pins the whole-cache donation
        TraceEntry("gpt_paged_prefill_step", "apex_tpu.serving.decode",
                   _paged_prefill_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=4),
        # chunked prefill: the same donations as the monolithic steps
        # (3 dense leaves / 4 paged leaves) — a dropped pair would
        # re-allocate the whole cache EVERY CHUNK, multiplying the
        # admission cost by the chunk count
        TraceEntry("gpt_chunk_prefill_step", "apex_tpu.serving.decode",
                   _chunk_prefill_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        TraceEntry("gpt_paged_chunk_prefill_step",
                   "apex_tpu.serving.decode",
                   _paged_chunk_prefill_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=4),
        TraceEntry("gpt_paged_decode_step", "apex_tpu.serving.decode",
                   _paged_decode_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=4),
        TraceEntry("gpt_paged_decode_step_tp2", "apex_tpu.serving.decode",
                   _paged_decode_step_entry(tp=2),
                   checks=("precision", "memory", "schedule", "aliases"),
                   mesh=_mesh(tp=2), min_devices=2, min_alias_pairs=4),
        # speculative verify: same donated 4-leaf paged cache as the
        # decode step, k+1 query positions per slot
        TraceEntry("gpt_spec_verify_step", "apex_tpu.serving.decode",
                   _spec_verify_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=4),
        TraceEntry("gpt_spec_verify_step_tp2", "apex_tpu.serving.decode",
                   _spec_verify_step_entry(tp=2),
                   checks=("precision", "memory", "schedule", "aliases"),
                   mesh=_mesh(tp=2), min_devices=2, min_alias_pairs=4),
        # tree-attention verify: one forward over a k1-node draft grid
        # per slot (ancestor-matrix mask in place of the linear one);
        # the donated 4-leaf paged cache is unchanged
        TraceEntry("gpt_tree_verify_step", "apex_tpu.serving.decode",
                   _tree_verify_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=4),
        TraceEntry("gpt_tree_verify_step_tp2", "apex_tpu.serving.decode",
                   _tree_verify_step_entry(tp=2),
                   checks=("precision", "memory", "schedule", "aliases"),
                   mesh=_mesh(tp=2), min_devices=2, min_alias_pairs=4),
        # cost-tier anchor for the BASELINE r8/r9 decode roofline; no
        # APX5xx checks (the tiny-shape decode entries above carry them
        # — this one exists so budgets.json pins the headline bytes)
        TraceEntry("gpt_decode_step_medium", "apex_tpu.serving.decode",
                   _decode_step_medium_entry(), checks=()),
        # r10: ragged-length paged pool at the same model shape — its
        # budgets.json row demonstrates the K/V-read cut vs the dense
        # slots x S_max charge above (BASELINE.md r10)
        TraceEntry("gpt_paged_decode_step_medium_ragged",
                   "apex_tpu.serving.decode",
                   _paged_decode_step_medium_ragged_entry(), checks=()),
        # r11: the verify step at the same ragged shape — one parameter
        # read priced over k+1 candidate positions; budgets.json pins
        # the bytes/accepted-token headline (BASELINE.md r11)
        TraceEntry("gpt_spec_verify_step_medium_ragged",
                   "apex_tpu.serving.decode",
                   _spec_verify_step_medium_ragged_entry(), checks=()),
        # r14: one chunk of a chunked prefill at the same ragged
        # medium shape — budgets.json pins the per-chunk HBM bytes
        # (~chunk/S of the monolithic read plus the cache re-read)
        TraceEntry("gpt_paged_chunk_prefill_step_medium",
                   "apex_tpu.serving.decode",
                   _paged_chunk_prefill_step_medium_entry(), checks=()),
        # r15: the disaggregated handoff's receiver scatter at the same
        # ragged medium shape — budgets.json pins the per-prompt-page
        # handoff bytes the router ships between replicas
        TraceEntry("gpt_page_handoff_medium",
                   "apex_tpu.serving.transfer",
                   _page_handoff_medium_entry(), checks=()),
        # r17: the reshard tier's sender collective at the same ragged
        # medium shape — the explicit tiled all_gather over the tp=2
        # model axis that APX511's per-rank simulator verifies and
        # budgets.json prices as the per-prompt ICI/DCN collective
        # volume behind ici_ticks_per_page / dcn_ticks_per_page
        TraceEntry("gpt_page_reshard_medium",
                   "apex_tpu.serving.transfer",
                   _page_reshard_medium_entry(),
                   checks=("schedule",),
                   mesh=_mesh(tp=2), min_devices=2),
        # r16: the KV-cache hierarchy's two data movers at the same
        # ragged medium shape — the spill-side page gather (bf16) and
        # the promote-side quantized scatter (int8 + scale planes);
        # budgets.json pins the per-page bytes a spill/promote moves,
        # the denominator behind promote_ticks_per_page
        TraceEntry("gpt_page_spill_extract_medium",
                   "apex_tpu.serving.transfer",
                   _page_spill_extract_medium_entry(), checks=()),
        TraceEntry("gpt_page_promote_insert_quant_medium",
                   "apex_tpu.serving.transfer",
                   _page_promote_insert_quant_medium_entry(),
                   checks=()),
        # r13: the model drafter's per-token forward at the medium
        # shape — the draft_bytes numerator of the break-even condition
        # (BASELINE.md r13); its hand-tightened ceiling pins the draft
        # under 3% of the target parameter read. The dense-cache
        # donation (3 leaves) rides along.
        TraceEntry("gpt_draft_forward_step",
                   "apex_tpu.serving.draft_model",
                   _draft_forward_step_entry(),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=3),
        # int8 tier: the standalone dequant-fused matmuls, the w8+kv8
        # paged serving steps (6 donated cache leaves — pool k/v,
        # lengths, block tables, k/v scales), a tp2 dense-decode with
        # the quantized tree sharded by quant_partition_specs, and the
        # r12 cost anchor at the ragged medium shape
        TraceEntry("w8_matmul_fused", "apex_tpu.quant.kernels",
                   _w8_matmul_entry()),
        TraceEntry("gpt_paged_prefill_step_w8kv8",
                   "apex_tpu.serving.decode",
                   _quant_paged_step_entry("prefill"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=6),
        TraceEntry("gpt_paged_decode_step_w8kv8",
                   "apex_tpu.serving.decode",
                   _quant_paged_step_entry("decode"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=6),
        TraceEntry("gpt_spec_verify_step_w8kv8",
                   "apex_tpu.serving.decode",
                   _quant_paged_step_entry("verify"),
                   checks=("precision", "memory", "aliases"),
                   min_alias_pairs=6),
        TraceEntry("gpt_decode_step_w8_tp2", "apex_tpu.serving.decode",
                   _w8_decode_step_tp2_entry(),
                   checks=("precision", "memory", "schedule", "aliases"),
                   mesh=_mesh(tp=2), min_devices=2, min_alias_pairs=3),
        TraceEntry("gpt_paged_decode_step_medium_ragged_w8kv8",
                   "apex_tpu.serving.decode",
                   _quant_paged_decode_medium_ragged_entry(), checks=()),
        TraceEntry("fused_softmax_fwd_bwd",
                   "apex_tpu.transformer.functional.fused_softmax",
                   _fused_softmax_entry()),
        TraceEntry("flat_scale", flat, _flat_simple_entry("scale")),
        TraceEntry("flat_axpby", flat, _flat_simple_entry("axpby")),
        TraceEntry("flat_l2norm", flat, _flat_simple_entry("l2norm")),
    ]
    return entries


def check_repo() -> List[Finding]:
    return run_entries(repo_entries())

"""APX601-604 — per-entrypoint byte budgets over the cost tier.

``budgets.json`` (committed next to this module) is the reviewed
contract: for every registered trace entry it pins the expected HBM
traffic, the collective volume, and the peak-live estimate, plus two
*hand-ownable* knobs — an ``hbm_ceiling`` and a ``peak_live_cap``
(seeded at 1.25x measured by ``--write-budgets``, preserved verbatim
on regeneration so a reviewer-tightened ceiling survives).

Findings:

- **APX601** — an entry's total HBM bytes exceed its ceiling: a real
  traffic regression (e.g. a dropped ``donate_argnums`` doubling the
  KV-cache bytes).
- **APX602** — an entry drifted outside the +-tolerance band around
  the recorded ``hbm_bytes`` without a manifest update (or the entry /
  manifest is missing, or the manifest lists an entry that no longer
  exists). This is the "say so in the diff" check: a PR that changes
  traffic must regenerate budgets.json so the byte delta is reviewable.
- **APX603** — collective volume differs from the manifest (exact:
  communication schedules are deterministic, so any change is a
  schedule change).
- **APX604** — peak-live estimate exceeds the per-entry cap.

Update workflow (also in docs/source/static_analysis.rst): run
``python -m apex_tpu.lint --write-budgets``, eyeball the JSON diff,
and commit it with the PR that moved the numbers.
"""

import json
import os
from typing import Dict, List, Optional

from apex_tpu.lint import Finding

DEFAULT_TOLERANCE = 0.10
_HEADROOM = 1.25

_REQUIRED_ENTRY_KEYS = (
    "hbm_bytes", "hbm_ceiling", "collective_bytes",
    "peak_live_bytes", "peak_live_cap",
)


def manifest_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "budgets.json")


def validate(manifest) -> List[str]:
    """Schema errors as strings; empty means well-formed."""
    errs: List[str] = []
    if not isinstance(manifest, dict):
        return ["manifest is not a JSON object"]
    if manifest.get("version") != 1:
        errs.append("missing or unsupported 'version' (expected 1)")
    tol = manifest.get("tolerance")
    if not isinstance(tol, (int, float)) or not 0 < tol < 1:
        errs.append("'tolerance' must be a fraction in (0, 1)")
    entries = manifest.get("entries")
    if not isinstance(entries, dict):
        errs.append("'entries' must be an object keyed by entry name")
        return errs
    for name, row in sorted(entries.items()):
        if not isinstance(row, dict):
            errs.append(f"entry '{name}' is not an object")
            continue
        for key in _REQUIRED_ENTRY_KEYS:
            v = row.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(
                    f"entry '{name}' key '{key}' must be a"
                    " non-negative integer")
    return errs


def load_manifest(path: Optional[str] = None) -> Optional[dict]:
    """The committed manifest, or None when it doesn't exist yet."""
    path = path or manifest_path()
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _from_report(report) -> Dict[str, int]:
    return {
        "hbm_bytes": int(report.hbm_total_bytes),
        "collective_bytes": int(report.collective_bytes),
        "peak_live_bytes": int(report.peak_live_bytes),
    }


def build_manifest(reports, previous: Optional[dict] = None,
                   tolerance: Optional[float] = None,
                   prune: bool = False) -> dict:
    """Manifest dict from fresh reports. Hand-ownable knobs (ceilings,
    caps, tolerance) carry over from ``previous``; new entries get
    1.25x-measured headroom.

    ``previous`` entries with no fresh report are carried over verbatim
    (a partial retrace must not silently drop reviewed budgets); pass
    ``prune=True`` to drop them instead — the fix for a renamed or
    deleted TraceEntry whose stale row otherwise keeps an APX602
    finding alive. :func:`pruned_names` reports what ``prune`` removes.
    """
    prev_entries = (previous or {}).get("entries", {})
    if tolerance is None:
        tolerance = (previous or {}).get("tolerance", DEFAULT_TOLERANCE)
    entries: Dict[str, dict] = {}
    for rep in reports:
        row = _from_report(rep)
        old = prev_entries.get(rep.entry, {})
        row["hbm_ceiling"] = int(old.get(
            "hbm_ceiling", row["hbm_bytes"] * _HEADROOM))
        row["peak_live_cap"] = int(old.get(
            "peak_live_cap", row["peak_live_bytes"] * _HEADROOM))
        entries[rep.entry] = {k: row[k] for k in _REQUIRED_ENTRY_KEYS}
    if not prune:
        for name, row in prev_entries.items():
            entries.setdefault(name, row)
    return {"version": 1, "tolerance": tolerance, "entries": entries}


def pruned_names(reports, previous: Optional[dict]) -> List[str]:
    """Manifest entries that ``prune=True`` would drop: present in
    ``previous`` but with no fresh report."""
    prev = (previous or {}).get("entries", {})
    return sorted(set(prev) - {rep.entry for rep in reports})


def write_manifest(reports, path: Optional[str] = None,
                   previous: Optional[dict] = "__load__",
                   prune: bool = False) -> dict:
    path = path or manifest_path()
    if previous == "__load__":
        previous = load_manifest(path)
    manifest = build_manifest(reports, previous=previous, prune=prune)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return manifest


def _gb(n: int) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    return f"{n} B"


def check(reports, manifest: Optional[dict],
          path: Optional[str] = None) -> List[Finding]:
    """APX601-604 findings for fresh reports vs the committed manifest.

    Entry-level findings land on the entry's module path (line 1) so
    file-level suppressions apply; manifest-level problems (missing
    file, schema, stale entries) land on budgets.json itself.
    """
    path = path or manifest_path()
    findings: List[Finding] = []
    if manifest is None:
        findings.append(Finding(
            "APX602", path, 1,
            "budgets.json does not exist — seed it with"
            " `python -m apex_tpu.lint --write-budgets`"))
        return findings
    errs = validate(manifest)
    if errs:
        findings.append(Finding(
            "APX602", path, 1,
            "budgets.json fails schema validation: " + "; ".join(errs)))
        return findings

    tol = float(manifest["tolerance"])
    entries: Dict[str, dict] = manifest["entries"]
    seen = set()
    for rep in reports:
        seen.add(rep.entry)
        row = entries.get(rep.entry)
        if row is None:
            findings.append(Finding(
                "APX602", rep.module, 1,
                f"trace entry '{rep.entry}' has no budget in"
                " budgets.json — regenerate with"
                " `python -m apex_tpu.lint --write-budgets`"))
            continue
        total = rep.hbm_total_bytes
        if total > row["hbm_ceiling"]:
            findings.append(Finding(
                "APX601", rep.module, 1,
                f"entry '{rep.entry}' HBM traffic {_gb(total)} exceeds"
                f" its budget ceiling {_gb(row['hbm_ceiling'])} — a"
                " memory-traffic regression (check donation/aliasing"
                " before raising the ceiling)"))
        expected = row["hbm_bytes"]
        if abs(total - expected) > tol * max(expected, 1):
            findings.append(Finding(
                "APX602", rep.module, 1,
                f"entry '{rep.entry}' HBM traffic {_gb(total)} drifted"
                f" outside the +-{tol:.0%} band around the recorded"
                f" {_gb(expected)} — if intentional, regenerate"
                " budgets.json in this PR so the delta is reviewed"))
        if rep.collective_bytes != row["collective_bytes"]:
            findings.append(Finding(
                "APX603", rep.module, 1,
                f"entry '{rep.entry}' collective volume"
                f" {_gb(rep.collective_bytes)} != recorded"
                f" {_gb(row['collective_bytes'])} — the communication"
                " schedule changed; regenerate budgets.json if"
                " intentional"))
        if rep.peak_live_bytes > row["peak_live_cap"]:
            findings.append(Finding(
                "APX604", rep.module, 1,
                f"entry '{rep.entry}' peak-live estimate"
                f" {_gb(rep.peak_live_bytes)} exceeds its cap"
                f" {_gb(row['peak_live_cap'])}"))
    for name in sorted(set(entries) - seen):
        if "@" in name:
            # per-mesh scaling rows ('<entry>@<tag>') are owned by the
            # APX9xx tier, which sweeps them against its own grid
            continue
        findings.append(Finding(
            "APX602", path, 1,
            f"budgets.json lists entry '{name}' which is no longer"
            " registered — regenerate with"
            " `python -m apex_tpu.lint --write-budgets`"))
    return findings

"""APX503 — broadcast/materialization blowup.

The classic mixed-precision OOM is not a big *input*, it is a big
*intermediate*: an attention backward that re-materializes the S x S
fp32 score matrix, a one-hot expansion of a label vector against the
vocabulary, a broadcast that XLA cannot fuse because its consumer is a
contraction. None of these are visible in source — the shapes only
exist in the traced program.

The check walks every equation (including scan/cond/pjit sub-jaxprs
and Pallas kernel bodies, where block shapes keep tile-local dot
products under the floor) and flags producers whose output abstract
value is more than ``factor`` times the sum of all operand sizes AND at
least ``floor`` bytes. Two classes of producers are charged:

- contraction/layout primitives that always materialize their output
  (``dot_general``, ``conv_general_dilated``, ``gather``,
  ``concatenate``, ``pad``);
- pure-expansion primitives (``broadcast_in_dim``, ``iota``) only when
  some consumer in the same jaxpr *materializes* them (a contraction, a
  stacked loop, a Pallas call, a jaxpr output). A broadcast feeding
  only elementwise math fuses into its consumer and costs nothing, so
  charging it would flag every ``(h,) -> (b, s, h)`` affine weight.

The ``floor`` (default 1 MiB) keeps tile-sized intermediates, ring
buffers and tiny-model test entries out of scope: a 16x blowup to
200 KiB is not an OOM.
"""

from typing import List

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl

DEFAULT_FACTOR = 8.0
DEFAULT_FLOOR = 1 << 20  # 1 MiB

# Producers whose output always occupies real memory.
_MATERIALIZING_PRODUCERS = {
    "dot_general", "conv_general_dilated", "gather", "concatenate", "pad",
}

# Expansion producers charged only when materialized by a consumer.
_EXPANSION_PRODUCERS = {"broadcast_in_dim", "iota"}


def _mib(n: int) -> str:
    return f"{n / (1 << 20):.2f} MiB"


def _check_one(jaxpr_like, path: str, entry: str, factor: float,
               floor: int, findings: List[Finding]) -> None:
    jaxpr = jl.open_jaxpr(jaxpr_like)
    consumers = {}
    out_set = {v for v in jaxpr.outvars if not jl.is_literal(v)}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not jl.is_literal(v):
                consumers.setdefault(v, set()).add(eqn.primitive.name)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        for _, sub in jl.sub_jaxprs(eqn):
            _check_one(sub, path, entry, factor, floor, findings)
        if name in _EXPANSION_PRODUCERS:
            # materialized = escapes the jaxpr, or has any consumer
            # that is not a known-fusible elementwise/reduce/shape op
            # (scan, dot_general, pallas_call, scatter, ... all count)
            materialized = any(
                (v in out_set)
                or any(c not in _FUSIBLE for c in consumers.get(v, set()))
                for v in eqn.outvars)
            if not materialized:
                continue
        elif name not in _MATERIALIZING_PRODUCERS:
            continue
        in_bytes = sum(jl.aval_bytes(v.aval) for v in eqn.invars)
        out_bytes = max((jl.aval_bytes(v.aval) for v in eqn.outvars),
                        default=0)
        if out_bytes >= floor and out_bytes > factor * max(in_bytes, 1):
            findings.append(Finding(
                "APX503", path, 1,
                f"entry '{entry}': {name} materializes "
                f"{_mib(out_bytes)} from {_mib(in_bytes)} of operands "
                f"(> {factor:g}x blowup, shape "
                f"{tuple(eqn.outvars[0].aval.shape)} "
                f"{eqn.outvars[0].aval.dtype}) — a fused/blocked "
                f"formulation keeps this intermediate tile-sized"))


# Consumers known to fuse an expansion producer away: elementwise math,
# reductions, and shape-only ops. Anything NOT in this set counts as
# materializing (conservative for new primitives).
_FUSIBLE = {
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "integer_pow",
    "neg", "abs", "sign", "exp", "exp2", "log", "log1p", "expm1", "tanh",
    "logistic", "erf", "erf_inv", "erfc", "rsqrt", "sqrt", "cbrt", "sin",
    "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "floor",
    "ceil", "round", "clamp", "is_finite", "not", "and", "or", "xor",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "convert_element_type",
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min", "reduce_and",
    "reduce_or", "cumsum", "cumprod", "cumlogsumexp", "argmax", "argmin",
    "reduce_precision", "broadcast_in_dim", "reshape", "squeeze",
    "expand_dims", "transpose", "rev", "slice", "dynamic_slice", "copy",
    "stop_gradient", "pjit", "remat", "remat2", "checkpoint", "nextafter",
    "square", "add_any", "mul_add", "real", "imag", "device_put",
}


def check(closed, path: str, entry: str, *,
          factor: float = DEFAULT_FACTOR,
          floor: int = DEFAULT_FLOOR) -> List[Finding]:
    findings: List[Finding] = []
    _check_one(closed, path, entry, factor, floor, findings)
    return findings

"""APX501/APX502 — jaxpr-level precision-flow verifiers.

APX501 (reduction accumulators) is the dynamic complement of the
AST-only APX103: instead of pattern-matching stats-named tiles in
source, it walks the *traced* program — through ``scan``/``cond``/
``pjit`` sub-jaxprs and Pallas kernel bodies — and flags any summing
reduction whose operand is a sub-fp32 float. A bf16 ``reduce_sum`` over
more than a few hundred elements loses mantissa bits every step (bf16
has 8); the mixed-precision recipe (Micikevicius et al., 2018) keeps
all accumulations fp32. ``dot_general``/``conv`` are exempt — the MXU
accumulates fp32 internally regardless of operand dtype — and so are
order-insensitive reductions (max/min/and/or). A scan whose *carry* is
a sub-fp32 float updated by an ``add`` on the carried value is the same
bug spelled as a loop (a bf16 gradient accumulator), and is flagged too.

APX502 (unscale/overflow-check placement) is a forward taint
interpreter over the traced amp step. Abstract tags per variable:

- ``scale``    — data-derived from the loss-scale scalar (the entry's
  first flat input): the scaled loss, the gradients of the scaled loss,
  anything computed from them;
- ``unscaled`` — passed through a division by a scale-tainted value
  (``1/loss_scale`` then multiply, or a direct divide);
- ``finite``   — derived from an ``is_finite`` reduction (the overflow
  flag);
- ``guarded``  — selected by a ``select_n`` whose *predicate* is
  finite-tainted (``apply_if_finite`` / ``select_finite``).

The two contract checks over the entry's declared optimizer-state
outputs: every state write influenced by traced inputs must be
``guarded`` (the overflow check dominates the write), and no state
write may carry ``scale`` without ``unscaled`` (the loss-scale division
dominates the write). Predicate tags are deliberately *not* unioned
into ``select_n``'s data tags, so the step counter selected by the
overflow flag does not spuriously inherit ``scale``.
"""

from typing import List, Sequence, Set

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl

# Reductions that accumulate (order- and precision-sensitive).
_SUM_REDUCES = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
    "reduce_window_sum",
}

_ACCUM_PRIMS = {"add", "add_any"}

# Minimum per-output accumulation length before a sub-fp32 reduction is
# flagged. bf16 carries 8 mantissa bits, so magnitude-1 contributions
# stop registering after a few hundred additions; below this length the
# error is bounded and ubiquitous (every bias wgrad in a bf16 backward
# is a short bf16 reduce_sum) — flagging those would force fp32 casts
# that change nothing.
_MIN_ACCUM = 512


def _accum_length(eqn, operand) -> int:
    """Elements folded into each output of a summing reduction."""
    name = eqn.primitive.name
    shape = getattr(operand.aval, "shape", ())
    if name in ("cumsum", "cumprod", "cumlogsumexp"):
        axis = eqn.params.get("axis")
        if axis is not None and shape:
            return int(shape[axis])
        return max([int(d) for d in shape] or [1])
    in_elems = 1
    for d in shape:
        in_elems *= int(d)
    out_elems = 1
    for d in getattr(eqn.outvars[0].aval, "shape", ()):
        out_elems *= int(d)
    return in_elems // max(out_elems, 1)


# ---------------------------------------------------------------------------
# APX501 — sub-fp32 reduction / scan-carried accumulator
# ---------------------------------------------------------------------------

def check_reductions(closed, path: str, entry: str) -> List[Finding]:
    findings: List[Finding] = []
    for eqn in jl.all_eqns(closed):
        name = eqn.primitive.name
        if name in _SUM_REDUCES:
            for v in eqn.invars:
                if jl.is_literal(v) or not jl.is_sub_fp32(v.aval):
                    continue
                length = _accum_length(eqn, v)
                if length < _MIN_ACCUM:
                    continue
                dtype = v.aval.dtype
                findings.append(Finding(
                    "APX501", path, 1,
                    f"entry '{entry}': {name} folds {length} {dtype} "
                    f"elements (operand shape {tuple(v.aval.shape)}) "
                    f"into each output — reductions of this length "
                    f"must run on an fp32 (or wider) accumulator"))
        elif name == "scan":
            findings.extend(_check_scan_carry(eqn, path, entry))
    return findings


def _depends_on(var, target, producers, _cache=None) -> bool:
    """Does ``var`` transitively depend on ``target`` inside one body?

    Equations are treated as opaque (any tainted invar taints every
    outvar), which is conservative through nested pjit/remat calls.
    """
    if _cache is None:
        _cache = {}
    stack, seen = [var], set()
    while stack:
        v = stack.pop()
        if v is target:
            return True
        if jl.is_literal(v) or id(v) in seen:
            continue
        seen.add(id(v))
        eqn = producers.get(v)
        if eqn is not None:
            stack.extend(eqn.invars)
    return False


def _check_scan_carry(eqn, path: str, entry: str) -> List[Finding]:
    body = jl.open_jaxpr(eqn.params["jaxpr"])
    nc = eqn.params.get("num_consts", 0)
    ncar = eqn.params.get("num_carry", 0)
    findings: List[Finding] = []
    producers = {ov: e for e in body.eqns for ov in e.outvars}
    for i in range(ncar):
        carry_in = body.invars[nc + i]
        if not jl.is_sub_fp32(carry_in.aval):
            continue
        carry_out = body.outvars[i]
        prod = producers.get(carry_out)
        if prod is None or prod.primitive.name not in _ACCUM_PRIMS:
            continue
        operands = [v for v in prod.invars if not jl.is_literal(v)]
        if carry_in not in operands:
            continue
        # residual discriminator: ``x + f(x)`` (the other addend derives
        # from the carry) is a per-step residual, not an accumulator —
        # only ``acc + g(xs)`` with g independent of the carry compounds
        # rounding error every iteration
        others = [v for v in operands if v is not carry_in]
        if others and all(_depends_on(v, carry_in, producers)
                          for v in others):
            continue
        findings.append(Finding(
            "APX501", path, 1,
            f"entry '{entry}': scan carries a "
            f"{carry_in.aval.dtype} accumulator of shape "
            f"{tuple(carry_in.aval.shape)} updated by "
            f"{prod.primitive.name} — loop-carried accumulation "
            f"must be fp32 (fp32_grad_accum)"))
    return findings


# ---------------------------------------------------------------------------
# APX502 — taint propagation
# ---------------------------------------------------------------------------

_FIXPOINT_CAP = 8


def _read(env, v) -> Set[str]:
    if jl.is_literal(v):
        return set()
    return env.get(v, set())


def _prop(jaxpr_like, in_tags: Sequence[Set[str]]) -> List[Set[str]]:
    """Forward tag propagation through one (possibly closed) jaxpr."""
    jaxpr = jl.open_jaxpr(jaxpr_like)
    env = {}
    for v, t in zip(jaxpr.invars, in_tags):
        env[v] = set(t)
    for v in jaxpr.constvars:
        env[v] = set()
    for eqn in jaxpr.eqns:
        outs = _prop_eqn(eqn, [_read(env, v) for v in eqn.invars])
        for ov, t in zip(eqn.outvars, outs):
            env[ov] = t
    return [_read(env, v) for v in jaxpr.outvars]


def _prop_eqn(eqn, in_t: List[Set[str]]) -> List[Set[str]]:
    name = eqn.primitive.name
    n_out = len(eqn.outvars)

    if name == "scan":
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        consts, carry = in_t[:nc], [set(t) for t in in_t[nc:nc + ncar]]
        xs = in_t[nc + ncar:]
        out = [set() for _ in range(n_out)]
        for _ in range(_FIXPOINT_CAP):
            out = _prop(eqn.params["jaxpr"], consts + carry + xs)
            new_carry = [c | o for c, o in zip(carry, out[:ncar])]
            if new_carry == carry:
                break
            carry = new_carry
        return carry + [set(t) for t in out[ncar:]]

    if name == "while":
        cc = eqn.params.get("cond_nconsts", 0)
        bc = eqn.params.get("body_nconsts", 0)
        body_consts = in_t[cc:cc + bc]
        carry = [set(t) for t in in_t[cc + bc:]]
        for _ in range(_FIXPOINT_CAP):
            out = _prop(eqn.params["body_jaxpr"], body_consts + carry)
            new_carry = [c | o for c, o in zip(carry, out)]
            if new_carry == carry:
                break
            carry = new_carry
        return carry

    if name == "cond":
        ops = in_t[1:]
        merged = [set() for _ in range(n_out)]
        for branch in eqn.params["branches"]:
            for acc, t in zip(merged, _prop(branch, ops)):
                acc |= t
        return merged

    # generic sub-jaxpr call (pjit, remat, shard_map, custom_vjp, ...):
    # recurse when the arity matches; pallas_call's kernel jaxpr takes
    # refs for outputs too, so it falls through to the union rule.
    for _, sub in jl.sub_jaxprs(eqn):
        sj = jl.open_jaxpr(sub)
        if (len(sj.invars) == len(eqn.invars)
                and len(sj.outvars) == n_out):
            return [set(t) for t in _prop(sub, in_t)]

    base: Set[str] = set()
    for t in in_t:
        base |= t

    if name == "div" and len(in_t) >= 2 and "scale" in in_t[1]:
        base = base | {"unscaled"}
    elif name == "is_finite":
        base = base | {"finite"}
    elif name == "select_n" and in_t:
        pred = in_t[0]
        base = set()
        for t in in_t[1:]:
            base |= t
        if "finite" in pred or "guarded" in pred:
            base |= {"guarded"}
    return [set(base) for _ in range(n_out)]


def check_amp(closed, path: str, entry: str,
              n_protected: int) -> List[Finding]:
    """Contract check over the entry's flat outputs.

    Convention (enforced by the registry builders): the entry fn's first
    flat input is the loss-scale scalar, and its first ``n_protected``
    flat outputs are the optimizer-state writes (new params + optimizer
    state).
    """
    jaxpr = closed.jaxpr
    in_tags: List[Set[str]] = [set() for _ in jaxpr.invars]
    if not in_tags:
        return []
    in_tags[0] = {"scale"}
    out_tags = _prop(jaxpr, in_tags)
    protected = out_tags[:n_protected]

    findings: List[Finding] = []
    unguarded = sum(1 for t in protected if t and "guarded" not in t)
    if unguarded:
        findings.append(Finding(
            "APX502", path, 1,
            f"entry '{entry}': {unguarded} of {n_protected} optimizer-"
            f"state writes are not dominated by the overflow check (no "
            f"finite-flag select guards the write — an inf/nan step is "
            f"applied instead of skipped)"))
    scaled = sum(1 for t in protected
                 if "scale" in t and "unscaled" not in t)
    if scaled:
        findings.append(Finding(
            "APX502", path, 1,
            f"entry '{entry}': {scaled} of {n_protected} optimizer-"
            f"state writes consume loss-scaled gradients with no "
            f"loss-scale division on the path (missing unscale — the "
            f"update is wrong by the loss-scale factor)"))
    return findings

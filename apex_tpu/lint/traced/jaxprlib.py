"""Shared jaxpr-walking tools for the trace tier.

Every APX5xx verifier operates on the output of ``jax.make_jaxpr`` over
a registered entrypoint and has to see through the same set of
higher-order primitives: ``pjit`` (closed sub-jaxpr), ``scan``/``while``
(ClosedJaxpr body + carry structure), ``cond`` (tuple of branch
ClosedJaxprs), ``shard_map`` (open Jaxpr body), ``remat``/``custom_vjp``
wrappers, and ``pallas_call`` (the kernel body itself). This module
centralizes that traversal so each checker only writes its per-equation
logic.

``sub_jaxprs(eqn)`` is deliberately generic — any equation parameter
that *is* a Jaxpr/ClosedJaxpr (or a tuple/list of them) is yielded — so
a new higher-order primitive degrades to "recursed into" rather than
"silently skipped".
"""

from typing import Iterator, List, Tuple


def _jaxpr_types():
    from jax.core import ClosedJaxpr, Jaxpr

    return Jaxpr, ClosedJaxpr


def open_jaxpr(j):
    """Jaxpr from either a Jaxpr or a ClosedJaxpr."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def sub_jaxprs(eqn) -> List[Tuple[str, object]]:
    """``[(param_name, jaxpr-or-closed), ...]`` for one equation."""
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    out = []
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, (Jaxpr, ClosedJaxpr)):
                out.append((name, v))
    return out


def all_eqns(jaxpr, *, into_pallas: bool = True) -> Iterator[object]:
    """Depth-first over every equation, recursing into sub-jaxprs."""
    for eqn in open_jaxpr(jaxpr).eqns:
        yield eqn
        if not into_pallas and eqn.primitive.name == "pallas_call":
            continue
        for _, sub in sub_jaxprs(eqn):
            yield from all_eqns(sub, into_pallas=into_pallas)


def is_literal(v) -> bool:
    from jax.core import Literal

    return isinstance(v, Literal)


def aval_bytes(aval) -> int:
    """Byte size of an abstract value; 0 when it has no shape/dtype
    (tokens, refs without inner avals, effects)."""
    import numpy as np

    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic dim
            return 0
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        return 0


def is_sub_fp32(aval) -> bool:
    """True for float dtypes narrower than 32 bits (bf16/f16/fp8)."""
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        np_dtype = np.dtype(dtype)
    except TypeError:
        return False
    # bfloat16/fp8 are ml_dtypes extension types: np.issubdtype sees
    # them as void, so classify by jax's own lattice instead.
    import jax.numpy as jnp

    return bool(jnp.issubdtype(dtype, jnp.floating)) and np_dtype.itemsize < 4


def scalar_literal(v):
    """Python value of a scalar Literal, else None."""
    if not is_literal(v):
        return None
    if getattr(v.aval, "shape", None) not in ((), None):
        return None
    try:
        return v.val.item() if hasattr(v.val, "item") else v.val
    except (ValueError, AttributeError):
        return None


def axis_names(params, key: str = "axis_name"):
    """Normalize a collective's axis-name param to a tuple of names.

    jax stores it as a bare name, a tuple, or (psum) under ``axes``.
    """
    ax = params.get(key, params.get("axes", params.get("axis_name")))
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list)):
        return tuple(ax)
    return (ax,)

"""APX512 — declared kernel aliasing must survive into the traced
program.

The flat optimizer kernels declare ``input_output_aliases`` so a step
is one read-modify-write pass over HBM. That declaration is only worth
anything if the aliased *operand* of the lowered ``pallas_call`` is
still the caller's buffer: an intervening copy-producing equation — a
dtype cast, a pad to the block multiple, an arithmetic touch-up —
silently inserts a second buffer, the alias binds to the *copy*, and
HBM traffic doubles with bit-identical numerics. No runtime test can
see it; the traced jaxpr can.

For every ``pallas_call`` equation in the entry's jaxpr, each declared
``(operand, output)`` alias pair is verified:

- the operand and output abstract values agree in shape and dtype
  (an alias between mismatched buffers is rejected by XLA at compile
  time on hardware — on the interpret-mode CPU rig it is ignored);
- the operand's provenance chain, followed through layout-preserving
  equations only (``reshape``/``squeeze``/``expand_dims``), terminates
  at an *invar* of the jaxpr the call sits in — i.e. the caller's
  buffer, not a fresh intermediate.

The same contract covers jit DONATIONS (``donate_argnums``): a traced
``pjit`` equation carries ``donated_invars``, and the serving KV cache
depends on its donation surviving — a dropped donation turns every
decode step's cache update into a fresh ``O(L·B·H·S·d)`` allocation.
Each donated invar must have a shape/dtype-matching output to land in
(XLA only reuses buffers between compatible avals; a donation with no
matching output is silently discarded and the HBM win evaporates).
Donated invars count toward ``min_alias_pairs`` alongside pallas pairs.

Each entry declares ``min_alias_pairs``: if fewer pairs survive into
the trace than the kernel registry promises (e.g. a refactor dropped
the parameter), that is a finding too.
"""

from typing import List

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl

# Producers an alias legitimately traces through: pure layout views.
_LAYOUT_PRESERVING = {"reshape", "squeeze", "expand_dims"}


def _normalize_pairs(raw):
    """``input_output_aliases`` appears as a dict at the pallas API and
    as a tuple of (in_idx, out_idx) pairs in the traced params."""
    if raw is None:
        return []
    if isinstance(raw, dict):
        return sorted(raw.items())
    return sorted((int(i), int(o)) for i, o in raw)


def _trace_to_invar(var, producers, invars) -> str:
    """'' when ``var`` reaches an invar through layout-preserving eqns,
    else the name of the first severing primitive."""
    seen = 0
    while True:
        if jl.is_literal(var):
            return "literal"
        if var in invars:
            return ""
        eqn = producers.get(var)
        if eqn is None:
            return "constvar"  # a closed-over constant, not a live buffer
        if eqn.primitive.name not in _LAYOUT_PRESERVING:
            return eqn.primitive.name
        var = eqn.invars[0]
        seen += 1
        if seen > 32:
            return "cycle"


def _check_jaxpr(jaxpr_like, path, entry, counts, findings):
    jaxpr = jl.open_jaxpr(jaxpr_like)
    producers = {ov: e for e in jaxpr.eqns for ov in e.outvars}
    invars = set(jaxpr.invars)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name != "pallas_call":
            if eqn.primitive.name == "pjit":
                _check_donations(eqn, path, entry, counts, findings)
            for _, sub in jl.sub_jaxprs(eqn):
                _check_jaxpr(sub, path, entry, counts, findings)
            continue
        pairs = _normalize_pairs(eqn.params.get("input_output_aliases"))
        counts[0] += len(pairs)
        for in_idx, out_idx in pairs:
            if in_idx >= len(eqn.invars) or out_idx >= len(eqn.outvars):
                findings.append(Finding(
                    "APX512", path, 1,
                    f"entry '{entry}': alias pair ({in_idx}, {out_idx}) "
                    f"is out of range for a pallas_call with "
                    f"{len(eqn.invars)} operands / "
                    f"{len(eqn.outvars)} outputs"))
                continue
            op, out = eqn.invars[in_idx], eqn.outvars[out_idx]
            op_aval, out_aval = op.aval, out.aval
            if (getattr(op_aval, "shape", None) != getattr(
                    out_aval, "shape", None)
                    or getattr(op_aval, "dtype", None) != getattr(
                        out_aval, "dtype", None)):
                findings.append(Finding(
                    "APX512", path, 1,
                    f"entry '{entry}': alias pair ({in_idx}, {out_idx}) "
                    f"binds mismatched buffers {op_aval} -> {out_aval} "
                    f"— XLA rejects the donation and doubles HBM"))
                continue
            sever = _trace_to_invar(op, producers, invars)
            if sever:
                findings.append(Finding(
                    "APX512", path, 1,
                    f"entry '{entry}': aliased operand {in_idx} of "
                    f"'{_kernel_of(eqn)}' is produced by '{sever}', not "
                    f"the caller's buffer — the declared in-place "
                    f"update writes to a copy and HBM traffic doubles"))


def _check_donations(eqn, path, entry, counts, findings):
    """``pjit`` donations (``donate_argnums``): each donated invar needs
    a shape/dtype-matching output for XLA to land the reuse in — each
    output can absorb at most one donation."""
    donated = eqn.params.get("donated_invars") or ()
    if not any(donated):
        return
    taken = [False] * len(eqn.outvars)
    for in_idx, is_donated in enumerate(donated):
        if not is_donated:
            continue
        op_aval = eqn.invars[in_idx].aval
        for out_idx, out in enumerate(eqn.outvars):
            if taken[out_idx]:
                continue
            if (getattr(out.aval, "shape", None) == getattr(
                    op_aval, "shape", None)
                    and getattr(out.aval, "dtype", None) == getattr(
                        op_aval, "dtype", None)):
                taken[out_idx] = True
                counts[0] += 1
                break
        else:
            findings.append(Finding(
                "APX512", path, 1,
                f"entry '{entry}': donated operand {in_idx} of "
                f"'{_kernel_of(eqn)}' ({op_aval}) has no shape/dtype-"
                f"matching output to reuse — XLA discards the donation "
                f"and the update allocates a fresh buffer"))


def _kernel_of(eqn) -> str:
    name = eqn.params.get("name")
    if name:
        return str(name)
    j = eqn.params.get("jaxpr")
    return getattr(j, "name", None) or "pallas_call"


def check(closed, path: str, entry: str, *,
          min_alias_pairs: int = 0) -> List[Finding]:
    findings: List[Finding] = []
    counts = [0]
    _check_jaxpr(closed, path, entry, counts, findings)
    if counts[0] < min_alias_pairs:
        findings.append(Finding(
            "APX512", path, 1,
            f"entry '{entry}': expected at least {min_alias_pairs} "
            f"input_output_aliases pair(s) in the traced program, found "
            f"{counts[0]} — the declared in-place aliasing was dropped "
            f"before lowering"))
    return findings

"""AMP op-list coherence checks (APX301-APX304).

The O1 policy is a three-way partition: every op name consulted through
``amp.autocast.cast_args(op, ...)`` must appear in exactly one of
``FP16_FUNCS`` / ``FP32_FUNCS`` / ``CASTS`` in ``amp/lists.py``, and
every listed op should correspond to an interception site — otherwise
the table silently stops describing the code (the reference repo's
op lists and its monkey-patch sites have exactly this drift failure
mode). Ops carried over from the reference tables that are not yet
routed through ``cast_args`` are declared in an explicit ``UNWIRED``
frozenset in the same module; APX303 fires for any listed op that is
neither wired nor declared, and APX304 fires when a declared-unwired
op gains a call site (the exemption went stale), so drift is loud in
both directions.

Mechanics: any linted file that assigns all three list names with
literal-evaluable sets is treated as a policy module; the intercepted
set is gathered from ``cast_args("<literal>", ...)`` calls in linted
files under the same package root (two directory levels above the
policy module), so test helpers exercising ``cast_args`` directly don't
count as wiring.
"""

import ast
import os
from typing import Dict, List, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import literal_strings

_LIST_NAMES = ("FP16_FUNCS", "FP32_FUNCS", "CASTS")


def _extract_sets(tree: ast.Module):
    """{list_name: {op: lineno}} for literal-evaluable assigns."""
    out: Dict[str, Dict[str, int]] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name not in _LIST_NAMES + ("UNWIRED",):
            continue
        ops = literal_strings(node.value)
        if ops is None:
            continue
        lines: Dict[str, int] = {}
        for n in ast.walk(node.value):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and n.value in ops:
                lines.setdefault(n.value, n.lineno)
        for op in ops:
            lines.setdefault(op, node.lineno)
        out[name] = lines
    return out


def _intercepted(trees: Dict[str, ast.Module],
                 root: str) -> Dict[str, Tuple[str, int]]:
    """op -> (path, line) of a cast_args("op", ...) call under root."""
    out: Dict[str, Tuple[str, int]] = {}
    for path, tree in trees.items():
        if not os.path.abspath(path).startswith(root):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if name != "cast_args" or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                             str):
                out.setdefault(first.value, (path, node.lineno))
    return out


def check_files(trees: Dict[str, ast.Module]) -> List[Finding]:
    findings: List[Finding] = []
    for path, tree in trees.items():
        sets = _extract_sets(tree)
        if not all(n in sets for n in _LIST_NAMES):
            continue
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(path)))
        wired = _intercepted(trees, pkg_root)
        unwired = sets.get("UNWIRED", {})
        listed: Dict[str, List[Tuple[str, int]]] = {}
        for lname in _LIST_NAMES:
            for op, line in sets[lname].items():
                listed.setdefault(op, []).append((lname, line))

        for op, homes in sorted(listed.items()):
            if len(homes) > 1:
                names = "/".join(h[0] for h in homes)
                findings.append(Finding(
                    "APX301", path, homes[0][1],
                    f"op '{op}' appears in multiple policy lists "
                    f"({names}) — policy_for() resolves them in "
                    "declaration order, hiding the later entries"))
            if op not in wired and op not in unwired:
                findings.append(Finding(
                    "APX303", path, homes[0][1],
                    f"op '{op}' is listed but never intercepted via "
                    "cast_args() and not declared in UNWIRED — the "
                    "policy table has drifted from the code"))
        for op, (cpath, cline) in sorted(wired.items()):
            if op not in listed:
                findings.append(Finding(
                    "APX302", cpath, cline,
                    f"cast_args('{op}', ...) has no entry in "
                    "FP16_FUNCS/FP32_FUNCS/CASTS — the op silently "
                    "falls through to 'passthrough'"))
            if op in unwired:
                findings.append(Finding(
                    "APX304", path, unwired[op],
                    f"op '{op}' is declared UNWIRED but is intercepted "
                    f"at {os.path.relpath(cpath)}:{cline} — remove the "
                    "stale exemption"))
    return findings

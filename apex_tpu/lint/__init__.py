"""apxlint — static contract checker for apex_tpu.

The repo's load-bearing invariants (in-place ``input_output_aliases`` on
the optimizer kernels, fp32 flash-attention softmax statistics, VMEM
block budgets, deterministic collective ordering inside shard_map
bodies, the O1 autocast op lists) are enforced at runtime only by tests
that happen to execute the right branch. This package checks them at
review time instead: an AST pass over every module plus a trace-time
abstract evaluation of the registered kernel configurations.

Run it as ``python -m apex_tpu.lint apex_tpu/ tests/``. Each check has
an error code (catalogue below, details in
``docs/source/static_analysis.rst``) and can be suppressed on a single
line with ``# apxlint: disable=CODE`` (on the flagged line or on a
standalone comment line directly above it). Files whose first lines
contain ``# apxlint: fixture`` are test fixtures: directory walks skip
them, explicit paths lint them.
"""

from dataclasses import dataclass

#: code -> one-line contract description. The docstring of each checker
#: module carries the full rationale.
CODES = {
    "APX100": "lint internal: a registered trace config failed to "
              "evaluate (the kernel it covers is unverifiable)",
    "APX101": "pallas kernel updates an input operand in place "
              "(stem-matched X_ref -> X_out pair) without the matching "
              "input_output_aliases entry",
    "APX102": "pallas_call VMEM block residency (2x streaming blocks "
              "+ scratch) exceeds the per-kernel budget",
    "APX103": "flash/softmax statistics tile (m, l, lse, mean, rstd) "
              "stored or allocated below fp32",
    "APX105": "pallas_call kernel family has no APX102 VMEM registry "
              "config and/or no TraceEntry in the trace registry (new "
              "kernels must register in both trace-time tiers)",
    "APX106": "quantization contract: scale tensor stored or allocated "
              "below fp32, dequant-fused matmul without an fp32 "
              "preferred_element_type, or astype(int8) with no "
              "round-to-nearest in scope",
    "APX201": "collective sequence diverges across the branches of a "
              "rank-dependent conditional (multi-chip deadlock)",
    "APX202": "collective axis name does not resolve to a "
              "parallel_state mesh axis",
    "APX301": "op appears in more than one AMP policy list "
              "(FP16_FUNCS / FP32_FUNCS / CASTS)",
    "APX302": "op intercepted by cast_args() appears in no AMP policy "
              "list",
    "APX303": "op listed in an AMP policy list is neither intercepted "
              "by cast_args() nor declared in UNWIRED",
    "APX304": "op declared UNWIRED is actually intercepted by "
              "cast_args() (stale exemption)",
    "APX401": "host-state read (time.*, np.random.*, random.*, or the "
              "registered serving fault/stats state) in a function "
              "reachable from a jit/custom_vjp/kernel body",
    "APX402": "global-statement write in a function reachable from a "
              "jit/custom_vjp/kernel body",
    "APX501": "traced program accumulates (reduce_sum/cumsum/scan "
              "carry add) on a sub-fp32 operand — reductions must run "
              "on an fp32 accumulator",
    "APX502": "amp train step writes optimizer state not dominated by "
              "the loss-scale division and the overflow check "
              "(missing unscale or unguarded update)",
    "APX503": "traced equation materializes an intermediate more than "
              "8x larger than its operands (broadcast/one-hot/score-"
              "matrix blowup)",
    "APX511": "per-rank simulation of a shard_map body yields "
              "divergent collective schedules or a malformed ppermute "
              "(multi-chip deadlock)",
    "APX512": "declared input_output_aliases pair does not survive "
              "into the traced jaxpr (severed provenance, dtype/shape "
              "mismatch, or dropped pair) — HBM traffic doubles",
    "APX601": "entry's static HBM traffic exceeds its budgets.json "
              "ceiling (memory-traffic regression)",
    "APX602": "entry's static HBM traffic drifted outside the "
              "tolerance band without a budgets.json update (or the "
              "manifest is missing/stale)",
    "APX603": "entry's static collective volume differs from the "
              "budgets.json record (communication schedule changed)",
    "APX604": "entry's peak-live-bytes estimate exceeds its "
              "budgets.json cap",
    "APX701": "partition-rule table defect: a registered tree leaf is "
              "matched by zero or multiple rules, a spec outranks its "
              "array / names an unknown or repeated mesh axis, or a "
              "rule matches nothing (dead rule)",
    "APX702": "cross-tree sharding drift: optimizer moments / master "
              "weights carry a different spec than their param, the "
              "KV-cache head axis disagrees with the qkv weights' tp "
              "axis, or rule-derived specs diverge from the "
              "hand-maintained reference",
    "APX703": "rule-derived shard_map in_specs disagree with the "
              "partition table under the staged mesh, or a matmul "
              "operand above the byte floor enters the body fully "
              "replicated (silent GSPMD fallback)",
    "APX704": "rule-generated shard_map body fails per-rank schedule "
              "agreement (APX511 simulator) or its collective volume "
              "diverges from the budgets.json record",
    "APX801": "nondeterministic ordering on the serving tick path: "
              "set iteration flowing into scheduling/requeue/commit "
              "order, a set rendered into error text, unseeded "
              "random, hash()/id() ordering keys, or a wall-clock "
              "read outside the Tracer wall-stamp allowlist",
    "APX802": "fault-site contract incomplete or stale: a "
              "faults.SITES entry missing its consultation call "
              "site, typed degrade error, chaos-test reference, or "
              "CI sweep env — or a stale name in SITE_CONTRACTS, "
              "tests, or the ci.yml chaos matrix",
    "APX803": "error-taxonomy closure: a tick-path raise that is not "
              "a ServingError taxonomy class (or allowlisted "
              "constructor-time guard), or a taxonomy class no test "
              "references",
    "APX804": "observe-name drift: a tracer span/instant name "
              "missing from PHASES/LIFECYCLE, a dynamic name at an "
              "emit site, or a metric read-back no creation site "
              "matches",
    "APX805": "RNG key indiscipline on the tick path: raw PRNGKey "
              "consumption, jax.random.split trees, or a key "
              "consumed by more than one call instead of fold_in("
              "seed, counter) chains",
    "APX901": "collective schedule is not scale-invariant: the APX511 "
              "rank simulator fails at a swept mesh shape, or the "
              "normalized schedule structure differs between swept "
              "shapes (a schedule must be a function of axis names, "
              "not axis sizes)",
    "APX902": "collective volume off the declared scaling law: a "
              "swept shape's bytes miss its pinned <entry>@<tag> "
              "budgets.json row, deviate from the least-squares fit "
              "of the entry's declared model, or an unmodeled "
              "collective scales super-linearly along a mesh axis",
    "APX903": "per-device memory grows with the mesh: optimizer-state "
              "or peak-live bytes increase along the data axis, or "
              "the APX703 replication taint walk fails at a swept "
              "shape",
    "APX904": "rule table unsafe under the sweep: APX701 coverage "
              "fails for a scaling-registered table, or a sharded "
              "dim does not divide its mesh-axis size product at a "
              "swept shape",
}


@dataclass(frozen=True)
class Finding:
    """One lint violation, addressable by (path, line) for suppression."""
    code: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


__all__ = ["CODES", "Finding"]
